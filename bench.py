"""Headline benchmark: ResNet-50 training throughput, images/sec/chip.

Emit contract (on BOTH success and failure — a crashed backend must
still produce a machine-readable record; round-1 lesson: rc=1 with no
JSON is zero evidence): the LAST stdout line is always a compact
(<~500 byte) headline JSON {"metric", "value", "unit", "vs_baseline",
...} sized for the driver's tail-window capture (BENCH_r04 lesson: one
fat line parsed as null).  The FULL record — per-config tree, embedded
last_known_tpu on fallback — is persisted to ``FULL_EMIT_PATH`` and
additionally printed as a preceding JSON line when it fits within
``_MAX_FULL_LINE`` (tools/chip_hunter.py prefers the richest line, and
falls back to the persisted file, for its merge).

Hardening:
- A host-wide flock (runtime/chip_lock.py) serializes every framework
  process that touches the single-chip tunnel — concurrent use corrupts
  timings (observed 460% "MFU") and can wedge the backend.
- The TPU backend is probed in a SUBPROCESS with a timeout (observed
  failure mode is a hang inside backend init, not an exception), inside a
  patient time-budgeted acquire loop (``--acquire-timeout``, default
  10 min) with exponential backoff — the chip is known to be held
  transiently.  Probe errors distinguish "chip held by framework pid N"
  (lock diagnosis) from "tunnel unresponsive" (dead tunnel / non-framework
  holder).
- Even after a successful probe, the in-process init runs under a watchdog
  that emits the failure record and exits if init wedges.
- ``--allow-cpu-fallback`` (default on) benches on the host CPU when the
  chip is unreachable, recording ``"backend": "cpu", "fallback": true`` so
  the number is never mistaken for a TPU result. ``--no-cpu-fallback``
  restores hard-fail-with-record.

Benched families (``--families``): ``resnet`` (both ``resnet50`` and
``resnet50_s2d``, the MXU-friendly space-to-depth stem — the headline is
the faster one), plus on TPU ``lm`` (llama_125m decoder, tools/bench_lm)
and ``bert`` (bert_base MLM, tools/bench_bert) so the persisted record
carries every driver-designated metric, not just ResNet; ``input``
(tools/bench_input, pure host — runs even on a CPU fallback) records the
JPEG-ingest pipeline incl. the ship-raw-uint8 and native-libjpeg modes;
``gen`` (opt-in, tools/bench_generate) adds KV-cache decode throughput
+ MBU; ``vit`` (tools/bench_vit, in the default list) the
transformer-vision throughput.  The lm/bert
families run as subprocesses: allocator isolation (a fresh HBM heap per
family — in-process leftovers could push a fitting config over the
budget) while inheriting the chip lock.  A jax.profiler trace is captured
per ResNet config into ``--profile-dir`` (default ``profiles/bench``).

Baseline: the reference publishes no numbers (BASELINE.json "published":
{}), so ``vs_baseline`` is computed against TARGET_IMG_PER_SEC_PER_CHIP —
v5e peak ≈ 197 bf16 TFLOP/s; ResNet-50 fwd+bwd ≈ 3 × 4.1 ≈ 12.3
GFLOP/image → ~16k img/s roofline; a well-tuned conv pipeline sustaining
~17% of peak gives ~2800 img/s/chip, and target = 0.9 × 2800 ≈ 2500
(≥90%-of-MLPerf-class, BASELINE.md).  vs_baseline ≥ 1.0 meets the goal.

Measures true end-to-end step time: jitted train step (bf16 policy, label
smoothing, weight decay, SGD momentum), synthetic device-resident input
(the input pipeline is measured separately in tests).
"""

import argparse
import json
import os
import subprocess
import sys
import threading
import time

TARGET_IMG_PER_SEC_PER_CHIP = 2500.0
GFLOP_PER_IMAGE = 12.3            # ResNet-50 fwd+bwd ≈ 3 × 4.1 GFLOP
PEAK_TFLOPS = {"tpu": 197.0}      # v5e bf16 peak; MFU reported on TPU only
HEADLINE_METRIC = "resnet50_train_images_per_sec_per_chip"
# Successful TPU runs persist their record here; a CPU-fallback record
# embeds it as "last_known_tpu" so a transiently-dead chip tunnel (it
# happens — see PROFILE.md) never erases the real measurement.
LAST_TPU_RESULT = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "profiles", "bench", "last_tpu_result.json")

_PROBE_SRC = (
    "import json, jax; ds = jax.devices(); "
    "print(json.dumps({'n': len(ds), 'platform': ds[0].platform}))"
)


# Full records can be large (the fallback path embeds last_known_tpu,
# ~20 configs).  BENCH_r04 proved a single fat line overflows the
# driver's tail-window capture → "parsed": null, so the driver recorded
# NO metric despite a same-day silicon measurement.  The emit contract
# is therefore: full record → persisted file (+ printed only if short),
# compact bounded headline → ALWAYS the last stdout line.
FULL_EMIT_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "profiles", "bench", "last_emit.json")
_MAX_FULL_LINE = 4096
_HEADLINE_KEYS = ("metric", "value", "unit", "vs_baseline", "backend",
                  "config", "mfu_pct", "fallback", "measured_at")


def _headline(record: dict) -> dict:
    h = {k: record[k] for k in _HEADLINE_KEYS if k in record}
    err = record.get("error")
    if err is not None:
        err = str(err)
        h["error"] = err if len(err) <= 160 else err[:157] + "..."
    lk = record.get("last_known_tpu")
    if isinstance(lk, dict):
        h["last_known_tpu"] = {k: lk[k] for k in _HEADLINE_KEYS
                               if k in lk}
    return h


def _emit(record: dict) -> None:
    """Print the record; the LAST stdout line is always a compact
    (<~500 byte) headline JSON the driver's tail capture can parse,
    whatever the backend outcome.  The full record goes to
    ``FULL_EMIT_PATH`` and is printed too when it fits on a sane line
    (tools/chip_hunter.py prefers the richest line for its merge)."""
    try:
        os.makedirs(os.path.dirname(FULL_EMIT_PATH), exist_ok=True)
        with open(FULL_EMIT_PATH, "w") as f:
            json.dump(record, f)
    except OSError:
        pass
    full = json.dumps(record)
    if len(full) <= _MAX_FULL_LINE:
        print(full, flush=True)
    else:
        print(f"# full record ({len(full)} bytes) -> {FULL_EMIT_PATH}",
              flush=True)
    print(json.dumps(_headline(record)), flush=True)


def _base_record() -> dict:
    return {
        "metric": HEADLINE_METRIC,
        "value": 0.0,
        "unit": "images/sec/chip",
        "vs_baseline": 0.0,
    }


def _probe_backend(timeout_s: float):
    """Check backend health in a subprocess (init hangs can't be caught
    in-process). Returns {'n', 'platform'} or an error string."""
    try:
        out = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        # We hold the framework chip lock here, so a hang is NOT another
        # framework process — it is the tunnel itself (dead, or held by
        # something outside this repo's tooling).
        return (f"tunnel unresponsive: probe hung {timeout_s:.0f}s with "
                f"the framework chip lock held (tunnel dead, or chip held "
                f"by a non-framework process)")
    if out.returncode != 0:
        tail = (out.stderr or out.stdout).strip().splitlines()
        return "backend probe failed: " + (tail[-1] if tail else
                                           f"rc={out.returncode}")
    try:
        return json.loads(out.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return f"backend probe printed no JSON: {out.stdout[-200:]!r}"


def _acquire_backend(acquire_timeout: float, probe_timeout: float):
    """Patient acquire: probe with exponential backoff until the time
    budget runs out.  (info_dict | None, [attempt error strings])."""
    errors = []
    t0 = time.monotonic()
    backoff = 15.0
    attempt = 0
    while True:
        attempt += 1
        info = _probe_backend(probe_timeout)
        elapsed = time.monotonic() - t0
        if isinstance(info, dict):
            return info, errors
        errors.append(f"attempt {attempt} (t+{elapsed:.0f}s): {info}")
        remaining = acquire_timeout - (time.monotonic() - t0)
        if remaining <= probe_timeout * 0.5:
            return None, errors  # not enough budget for a useful retry
        time.sleep(min(backoff, max(remaining - probe_timeout, 1.0)))
        backoff = min(backoff * 2, 120.0)


def _watchdog(seconds: float, record: dict, what: str = "backend init"):
    """Emit the failure record and hard-exit if not cancelled in time —
    the last line of defense when init/compile wedges after a healthy
    probe.  ``record`` is read at fire time, so mutable fields (partial
    per-config results) reflect progress made before the hang."""
    def _fire():
        out = dict(record)
        out.setdefault("backend", "none")
        out["error"] = f"in-process {what} exceeded {seconds:.0f}s"
        _emit(out)
        os._exit(1)

    t = threading.Timer(seconds, _fire)
    t.daemon = True
    t.start()
    return t


def bench_config(preset_name: str, batch_per_chip: int, warmup: int,
                 iters: int, profile_dir=None):
    """Train-step throughput for one ResNet preset on the live backend."""
    import jax
    import numpy as np
    import optax

    from tensorflow_train_distributed_tpu.models import resnet
    from tensorflow_train_distributed_tpu.parallel.sharding import shard_batch
    from tensorflow_train_distributed_tpu.runtime.mesh import (
        MeshConfig, build_mesh,
    )
    from tensorflow_train_distributed_tpu.training import (
        Policy, Trainer, TrainerConfig,
    )

    mesh = build_mesh(MeshConfig(data=-1))
    n_chips = mesh.devices.size
    platform_hint = mesh.devices.flat[0].platform
    batch_size = batch_per_chip * n_chips
    preset = resnet.RESNET_PRESETS[preset_name]
    task = resnet.make_task(preset)
    trainer = Trainer(
        task,
        optax.sgd(0.1, momentum=0.9, nesterov=True),
        mesh,
        policy=Policy.from_name("mixed_bfloat16"),
        config=TrainerConfig(log_every=1_000_000),
    )
    rng = np.random.default_rng(0)
    if preset.space_to_depth:
        # Host pipelines deliver s2d layout (datasets.SyntheticImageNet
        # space_to_depth=True); the device never sees the 3-channel tensor.
        img = rng.standard_normal((batch_size, 112, 112, 12),
                                  dtype=np.float32)
    else:
        img = rng.standard_normal((batch_size, 224, 224, 3),
                                  dtype=np.float32)
    batch = {"image": img,
             "label": rng.integers(0, 1000, batch_size).astype(np.int32)}
    state = trainer.create_state(batch)
    step = trainer._compiled_train_step()
    dev_batch = shard_batch(mesh, batch)
    for _ in range(warmup):
        state, m = step(state, dev_batch)
    jax.block_until_ready(state)
    # Plausibility guard: a timed window faster than the compute roofline
    # (all FLOPs at 100% peak) is a measurement artifact, not throughput —
    # observed once on a flaky chip tunnel (73k img/s ≈ 460% MFU).
    # Re-time once on the SAME compiled step (recompiling could blow the
    # bench watchdog); a persistent artifact is reported but flagged so it
    # can never become the headline.
    roofline_dt = (batch_size * GFLOP_PER_IMAGE
                   / (PEAK_TFLOPS.get(platform_hint, 1e9) * 1e3 * n_chips))
    for _ in range(2):
        t0 = time.perf_counter()
        for _ in range(iters):
            state, m = step(state, dev_batch)
        jax.block_until_ready(m)
        dt = (time.perf_counter() - t0) / iters
        if dt >= roofline_dt:
            break
    if profile_dir is not None:
        # Short profiled window, separate from the timed one: traces are
        # evidence for PROFILE.md, not part of the measurement.
        try:
            with jax.profiler.trace(os.path.join(profile_dir, preset_name)):
                for _ in range(3):
                    state, m = step(state, dev_batch)
                jax.block_until_ready(m)
        except Exception as e:  # profiling must never kill the bench
            print(f"# profiler trace failed: {e}", file=sys.stderr)
    img_per_sec_per_chip = batch_size / dt / n_chips
    platform = platform_hint
    result = {
        "images_per_sec_per_chip": round(img_per_sec_per_chip, 1),
        "step_time_ms": round(dt * 1e3, 2),
        "batch_per_chip": batch_per_chip,
        "n_chips": n_chips,
    }
    if dt < roofline_dt:
        result["implausible"] = True
    if platform in PEAK_TFLOPS:
        mfu = (img_per_sec_per_chip * GFLOP_PER_IMAGE
               / (PEAK_TFLOPS[platform] * 1e3))
        result["mfu_pct"] = round(100 * mfu, 2)
    return result


# Non-ResNet model families folded into the persisted emit (VERDICT r2:
# the record must carry ≥2 model families).  Subprocesses: fresh HBM heap
# per family; the chip lock is inherited via TTD_CHIP_LOCK_HELD.
_HERE = os.path.dirname(os.path.abspath(__file__))
FAMILY_CMDS = {
    "lm": ([sys.executable, os.path.join(_HERE, "tools", "bench_lm.py"),
            "--preset", "llama_125m", "--batch-per-chip", "8",
            "--seq", "2048", "--no-remat", "--warmup", "3",
            "--iters", "10"], "llama_125m"),
    "bert": ([sys.executable, os.path.join(_HERE, "tools", "bench_bert.py"),
              "--preset", "bert_base", "--batch-per-chip", "32",
              "--seq", "128", "--warmup", "3", "--iters", "20"],
             "bert_base"),
    # Opt-in (not in the default list — the driver window is budgeted for
    # the three training families): KV-cache decode throughput + MBU.
    "gen": ([sys.executable, os.path.join(_HERE, "tools",
                                          "bench_generate.py"),
             "--preset", "llama_125m", "--batch", "8",
             "--prompt-len", "128", "--max-new", "256"],
            "llama_125m_decode"),
    # Opt-in: transformer-vision throughput beside ResNet's.
    "vit": ([sys.executable, os.path.join(_HERE, "tools", "bench_vit.py"),
             "--preset", "vit_b16", "--batch-per-chip", "64",
             "--warmup", "3", "--iters", "10"],
            "vit_b16"),
    # Pure host (never touches the tunnel): JPEG decode+augment pipeline
    # throughput incl. the ship-raw-uint8 and native-libjpeg modes.  Runs
    # even on a CPU fallback, so a dead-tunnel record still carries real
    # measurements.
    "input": ([sys.executable, os.path.join(_HERE, "tools",
                                            "bench_input.py"),
               "--records", "128", "--image-hw", "192", "--size", "160",
               "--batch", "32", "--workers", "2"],
              "host_input"),
}

# Families that never touch the device — they survive the CPU-fallback
# family cull and run outside any chip concern.
HOST_ONLY_FAMILIES = ("input",)


def _run_family(cmd, timeout_s: float):
    """(record | None, error | None) from a family bench subprocess."""
    from tensorflow_train_distributed_tpu.runtime import chip_lock as _cl

    # Pass the held lock fd through: if THIS process is killed mid-family
    # (driver timeout), the child's inherited open file description keeps
    # the flock held until the child exits — no concurrent acquirer can
    # race the orphan on the chip.
    fd = _cl.held_fd()
    kw = {"pass_fds": (fd,)} if fd is not None else {}
    try:
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=timeout_s, **kw)
    except subprocess.TimeoutExpired:
        return None, f"family bench timed out after {timeout_s:.0f}s"
    lines = [ln for ln in out.stdout.strip().splitlines()
             if ln.startswith("{")]
    if not lines:
        tail = (out.stderr or out.stdout).strip().splitlines()
        return None, ("family bench printed no JSON: "
                      + (tail[-1][-200:] if tail else
                         f"rc={out.returncode}"))
    try:
        rec = json.loads(lines[-1])
    except ValueError:
        return None, f"unparseable family JSON: {lines[-1][:200]!r}"
    if out.returncode != 0 or rec.get("error"):
        return None, rec.get("error", f"rc={out.returncode}")
    return rec, None


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--configs",
                   default="resnet50,resnet50_s2d",
                   help="comma-separated RESNET_PRESETS names to bench. "
                        "resnet50_s2d_bnsub exists but was MEASURED AND "
                        "REJECTED on silicon (-12%%: the strided stats "
                        "gather costs more than the stats reads it "
                        "saves, PROFILE.md) — not worth chip-window "
                        "time by default")
    p.add_argument("--families", default="resnet,lm,bert,vit,input",
                   help="model families in the emit: resnet (in-process "
                        "headline) plus lm/bert/vit subprocess benches "
                        "(TPU only); opt-in: gen (decode); "
                        "'input' = host JPEG-pipeline throughput "
                        "(pure CPU, runs even on fallback); 'gen' "
                        "(opt-in) adds KV-cache decode throughput + MBU")
    p.add_argument("--batch-per-chip", type=int, default=256)
    p.add_argument("--warmup", type=int, default=5)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--acquire-timeout", type=float, default=600.0,
                   help="total time budget for acquiring a live TPU "
                        "backend (probe + backoff loop)")
    p.add_argument("--probe-timeout", type=float, default=120.0,
                   help="seconds per subprocess backend probe")
    p.add_argument("--lock-timeout", type=float, default=900.0,
                   help="how long to wait for the host-wide chip lock "
                        "when another framework process holds the chip")
    p.add_argument("--init-timeout", type=float, default=300.0,
                   help="watchdog on in-process backend init")
    p.add_argument("--bench-timeout", type=float, default=1200.0,
                   help="watchdog on the ResNet compile+measure phase")
    p.add_argument("--family-timeout", type=float, default=900.0,
                   help="timeout per non-resnet family subprocess")
    fb = p.add_mutually_exclusive_group()
    fb.add_argument("--allow-cpu-fallback", dest="cpu_fallback",
                    action="store_true", default=True)
    fb.add_argument("--no-cpu-fallback", dest="cpu_fallback",
                    action="store_false",
                    help="emit a failure record instead of benching on CPU")
    p.add_argument("--profile-dir", default="profiles/bench",
                   help="jax.profiler trace output ('' disables)")
    p.add_argument("--no-persist", dest="persist", action="store_false",
                   default=True,
                   help="don't overwrite the last-known-TPU record (for "
                        "sweeps/experiments; the default headline run "
                        "persists)")
    args = p.parse_args(argv)

    record = _base_record()
    try:
        return _run(args, record)
    except SystemExit:
        raise
    except Exception as e:
        # The one-JSON-line-on-any-outcome contract holds even for
        # failures nothing below anticipated (round-1 lesson).
        _emit(dict(record, error=f"{type(e).__name__}: {e}",
                   backend="none"))
        return 1


def _run(args, record) -> int:
    from tensorflow_train_distributed_tpu.runtime.chip_lock import chip_lock

    errors: list[str] = []
    try:
        with chip_lock(
                timeout=args.lock_timeout,
                on_wait=lambda pid, w: print(
                    f"# waiting for chip lock"
                    + (f" (held by framework pid {pid})" if pid else "")
                    + f", {w:.0f}s", file=sys.stderr)):
            info, perrors = _acquire_backend(args.acquire_timeout,
                                             args.probe_timeout)
            errors += perrors
            if info is not None:
                rc = _bench_phase(args, record, errors, want_tpu=True)
                if rc is not None:
                    return rc
                # else: in-process TPU init failed after a healthy probe —
                # fall through to the CPU path OUTSIDE the lock (this
                # process has no further use for the chip).
    except TimeoutError as e:
        # Another framework process owns the chip for longer than our
        # budget — a definitive "chip held" diagnosis, distinct from a
        # dead tunnel.
        errors.append(f"chip held: {e}")
    except OSError as e:
        errors.append(f"chip lock error: {type(e).__name__}: {e}")

    if not args.cpu_fallback:
        _emit(dict(record, error="; ".join(errors), backend="none"))
        return 1
    # Re-target CPU *before* any further in-process backend use.
    # force_platform clears any backend a launcher's sitecustomize already
    # pinned — a bare jax.config.update would be silently ignored in
    # exactly the wedged-TPU case that got us here.
    from tensorflow_train_distributed_tpu.runtime.mesh import force_platform

    force_platform("cpu")
    rc = _bench_phase(args, record, errors, want_tpu=False)
    return 1 if rc is None else rc


def _bench_phase(args, record, errors, want_tpu: bool):
    """Init the backend and measure.  Returns an exit code, or None when
    a TPU init failed and the caller should fall back on CPU."""
    import jax

    wd = _watchdog(args.init_timeout, record)
    try:
        platform = jax.devices()[0].platform
    except Exception as e:
        # Init can *raise* as well as hang (chip grabbed between probe and
        # here).
        errors.append(f"in-process init: {e}")
        if want_tpu and args.cpu_fallback:
            return None  # caller benches on CPU, outside the chip lock
        _emit(dict(record, error="; ".join(errors), backend="none"))
        return 1
    finally:
        wd.cancel()

    if want_tpu and platform != "tpu" and not args.cpu_fallback:
        _emit(dict(record, error=f"expected tpu backend, got {platform}",
                   backend=platform))
        return 1
    # Any non-TPU number is a fallback result by definition — flag it even
    # when the probe "succeeded" because the host simply has no TPU.
    fallback = platform != "tpu"

    # CPU can't push MLPerf-sized batches through ResNet-50 in useful time;
    # shrink the workload (one config, tiny batch) and say so in the
    # record — a fallback exists to land a parseable record before any
    # driver timeout, not to measure the CPU.
    batch_per_chip = args.batch_per_chip
    warmup, iters = args.warmup, args.iters
    configs = [c for c in args.configs.split(",") if c]
    families = [f for f in args.families.split(",") if f]
    skipped_configs = []
    if platform != "tpu":
        batch_per_chip = min(batch_per_chip, 8)
        warmup, iters = min(warmup, 1), min(iters, 2)
        configs, skipped_configs = configs[:1], configs[1:]
        keep = ("resnet",) + HOST_ONLY_FAMILIES
        skipped_configs += [f for f in families if f not in keep]
        families = [f for f in families if f in keep]

    # The DEFAULT trace dir holds committed TPU evidence; a CPU fallback
    # must not bury it under CPU traces.  An explicitly chosen dir is
    # honored on any backend.
    profile_dir = args.profile_dir or None
    if platform != "tpu" and args.profile_dir == "profiles/bench":
        profile_dir = None
    results = {}
    failures = {}
    # Compile or the first step can wedge just like init — keep a watchdog
    # armed through the whole measure phase so a JSON record always lands.
    skip_note = ({"skipped_configs": skipped_configs}
                 if skipped_configs else {})
    wd = _watchdog(args.bench_timeout,
                   dict(record, backend=platform, configs=results,
                        failed_configs=failures, **skip_note),
                   what="compile/measure")
    try:
        if "resnet" in families:
            for name in configs:
                try:
                    results[name] = bench_config(
                        name, batch_per_chip, warmup, iters, profile_dir)
                except Exception as e:
                    failures[name] = f"{type(e).__name__}: {e}"
    finally:
        wd.cancel()
    # Non-ResNet families: bounded subprocesses, lock inherited.  They
    # enrich the record but never sink the headline — a family failure is
    # recorded, not fatal.
    family_results = {}
    for fam in families:
        if fam == "resnet":
            continue
        if fam not in FAMILY_CMDS:
            failures[fam] = f"unknown family {fam!r}"
            continue
        cmd, key = FAMILY_CMDS[fam]
        rec_f, err = _run_family(cmd, args.family_timeout)
        if err:
            failures[fam] = err
        else:
            family_results[key] = rec_f
    if not results and not family_results:
        _emit(dict(record, error=f"all configs failed: {failures}",
                   backend=platform, probe_errors=errors, **skip_note))
        return 1

    if results:
        plausible = {n: r for n, r in results.items()
                     if not r.get("implausible")}
        if not plausible:
            _emit(dict(record, backend=platform,
                       configs={**results, **family_results},
                       error="all measurements exceeded the hardware "
                             "roofline (timing artifact; see bench_config "
                             "guard)", **skip_note))
            return 1
        best_name = max(plausible, key=lambda n:
                        plausible[n]["images_per_sec_per_chip"])
        best = results[best_name]
        record.update(
            value=best["images_per_sec_per_chip"],
            vs_baseline=round(best["images_per_sec_per_chip"]
                              / TARGET_IMG_PER_SEC_PER_CHIP, 3),
            backend=platform,
            config=best_name,
            configs={**results, **family_results},
        )
        if "mfu_pct" in best:
            record["mfu_pct"] = best["mfu_pct"]
    else:
        # Families-only run (--families lm / bert): the first successful
        # family carries the headline; there is no ResNet target to
        # compare against, so vs_baseline stays 0.0 by convention.
        first = next(iter(family_results.values()))
        record.update(
            metric=first.get("metric", record["metric"]),
            value=first.get("value", 0.0),
            unit=first.get("unit", record["unit"]),
            backend=platform,
            configs=family_results,
        )
    record["measured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                          time.gmtime())
    if fallback:
        record["fallback"] = True
        if errors:
            record["probe_errors"] = errors
        try:
            with open(LAST_TPU_RESULT) as f:
                record["last_known_tpu"] = json.load(f)
        except (OSError, ValueError):
            pass
    if failures:
        record["failed_configs"] = failures
    if skipped_configs:
        record["skipped_configs"] = skipped_configs
    if profile_dir:
        record["profile_dir"] = profile_dir
    if platform == "tpu" and args.persist:
        try:
            os.makedirs(os.path.dirname(LAST_TPU_RESULT), exist_ok=True)
            with open(LAST_TPU_RESULT, "w") as f:
                json.dump(record, f)
        except OSError as e:
            print(f"# could not persist TPU result: {e}", file=sys.stderr)
    _emit(record)
    return 0


if __name__ == "__main__":
    sys.exit(main())
