"""Headline benchmark: ResNet-50 training throughput, images/sec/chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

This is the reference's own headline config (BASELINE.md: ResNet-50/
ImageNet, target ≥90% of MLPerf TPU-ref images/sec/chip).  No published
reference number is recoverable (BASELINE.json "published": {}), so
``vs_baseline`` is computed against TARGET_IMG_PER_SEC_PER_CHIP — a
documented stand-in derived as follows: v5e peak ≈ 197 bf16 TFLOP/s;
ResNet-50 fwd+bwd ≈ 3 × 4.1 ≈ 12.3 GFLOP/image, so the compute roofline is
~16k img/s and a well-tuned conv pipeline sustaining ~17% of peak (convs
tile the MXU far worse than big matmuls) gives ~2800 img/s/chip as the
MLPerf-class estimate; target = 0.9 × 2800 ≈ 2500 img/s/chip.
vs_baseline ≥ 1.0 means the ≥90%-of-reference goal is met.

Measures true end-to-end step time on the real chip: jitted train step
(bf16 policy, label smoothing, weight decay, SGD momentum), synthetic
device-resident input (input pipeline measured separately in tests).
"""

import json
import time

import jax
import numpy as np
import optax

TARGET_IMG_PER_SEC_PER_CHIP = 2500.0
BATCH_PER_CHIP = 256
WARMUP = 5
ITERS = 20


def main():
    from tensorflow_train_distributed_tpu.models import resnet
    from tensorflow_train_distributed_tpu.runtime.mesh import (
        MeshConfig, build_mesh,
    )
    from tensorflow_train_distributed_tpu.training import (
        Policy, Trainer, TrainerConfig,
    )

    mesh = build_mesh(MeshConfig(data=-1))
    n_chips = mesh.devices.size
    batch_size = BATCH_PER_CHIP * n_chips  # constant per-chip batch
    task = resnet.make_task(resnet.RESNET_PRESETS["resnet50"])
    trainer = Trainer(
        task,
        optax.sgd(0.1, momentum=0.9, nesterov=True),
        mesh,
        policy=Policy.from_name("mixed_bfloat16"),
        config=TrainerConfig(log_every=1000),
    )
    rng = np.random.default_rng(0)
    batch = {
        "image": rng.standard_normal((batch_size, 224, 224, 3),
                                     dtype=np.float32),
        "label": rng.integers(0, 1000, batch_size).astype(np.int32),
    }
    state = trainer.create_state(batch)
    step = trainer._compiled_train_step()
    from tensorflow_train_distributed_tpu.parallel.sharding import shard_batch

    dev_batch = shard_batch(mesh, batch)
    for _ in range(WARMUP):
        state, m = step(state, dev_batch)
    jax.block_until_ready(m)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        state, m = step(state, dev_batch)
    jax.block_until_ready(m)
    dt = (time.perf_counter() - t0) / ITERS
    img_per_sec_per_chip = batch_size / dt / n_chips
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(img_per_sec_per_chip, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_per_sec_per_chip
                             / TARGET_IMG_PER_SEC_PER_CHIP, 3),
    }))


if __name__ == "__main__":
    main()
