"""Headline benchmark: ResNet-50 training throughput, images/sec/chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...} on
BOTH success and failure — a crashed backend must still produce a
machine-readable record (round-1 lesson: rc=1 with no JSON is zero
evidence).

Hardening:
- The TPU backend is probed in a SUBPROCESS with a timeout (observed
  failure mode is a hang inside backend init, not an exception), with
  bounded retries + backoff.
- Even after a successful probe, the in-process init runs under a watchdog
  that emits the failure record and exits if init wedges.
- ``--allow-cpu-fallback`` (default on) benches on the host CPU when the
  chip is unreachable, recording ``"backend": "cpu", "fallback": true`` so
  the number is never mistaken for a TPU result. ``--no-cpu-fallback``
  restores hard-fail-with-record.

Benched configs: both ``resnet50`` and ``resnet50_s2d`` (the MXU-friendly
space-to-depth stem, models/resnet.py) — the headline is the faster one,
with per-config results and derived MFU% in the record.  A jax.profiler
trace is captured per config into ``--profile-dir`` (default
``profiles/bench``).

Baseline: the reference publishes no numbers (BASELINE.json "published":
{}), so ``vs_baseline`` is computed against TARGET_IMG_PER_SEC_PER_CHIP —
v5e peak ≈ 197 bf16 TFLOP/s; ResNet-50 fwd+bwd ≈ 3 × 4.1 ≈ 12.3
GFLOP/image → ~16k img/s roofline; a well-tuned conv pipeline sustaining
~17% of peak gives ~2800 img/s/chip, and target = 0.9 × 2800 ≈ 2500
(≥90%-of-MLPerf-class, BASELINE.md).  vs_baseline ≥ 1.0 meets the goal.

Measures true end-to-end step time: jitted train step (bf16 policy, label
smoothing, weight decay, SGD momentum), synthetic device-resident input
(the input pipeline is measured separately in tests).
"""

import argparse
import json
import os
import subprocess
import sys
import threading
import time

TARGET_IMG_PER_SEC_PER_CHIP = 2500.0
GFLOP_PER_IMAGE = 12.3            # ResNet-50 fwd+bwd ≈ 3 × 4.1 GFLOP
PEAK_TFLOPS = {"tpu": 197.0}      # v5e bf16 peak; MFU reported on TPU only
HEADLINE_METRIC = "resnet50_train_images_per_sec_per_chip"
# Successful TPU runs persist their record here; a CPU-fallback record
# embeds it as "last_known_tpu" so a transiently-dead chip tunnel (it
# happens — see PROFILE.md) never erases the real measurement.
LAST_TPU_RESULT = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "profiles", "bench", "last_tpu_result.json")

_PROBE_SRC = (
    "import json, jax; ds = jax.devices(); "
    "print(json.dumps({'n': len(ds), 'platform': ds[0].platform}))"
)


def _emit(record: dict) -> None:
    print(json.dumps(record), flush=True)


def _base_record() -> dict:
    return {
        "metric": HEADLINE_METRIC,
        "value": 0.0,
        "unit": "images/sec/chip",
        "vs_baseline": 0.0,
    }


def _probe_backend(timeout_s: float):
    """Check backend health in a subprocess (init hangs can't be caught
    in-process). Returns {'n', 'platform'} or an error string."""
    try:
        out = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return f"backend probe timed out after {timeout_s:.0f}s"
    if out.returncode != 0:
        tail = (out.stderr or out.stdout).strip().splitlines()
        return "backend probe failed: " + (tail[-1] if tail else
                                           f"rc={out.returncode}")
    try:
        return json.loads(out.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return f"backend probe printed no JSON: {out.stdout[-200:]!r}"


def _acquire_backend(retries: int, probe_timeout: float):
    """(info_dict | None, [attempt error strings])."""
    errors = []
    for attempt in range(retries):
        info = _probe_backend(probe_timeout)
        if isinstance(info, dict):
            return info, errors
        errors.append(f"attempt {attempt + 1}: {info}")
        if attempt + 1 < retries:
            time.sleep(5 * (attempt + 1))  # 5s, 10s, ... backoff
    return None, errors


def _watchdog(seconds: float, record: dict, what: str = "backend init"):
    """Emit the failure record and hard-exit if not cancelled in time —
    the last line of defense when init/compile wedges after a healthy
    probe.  ``record`` is read at fire time, so mutable fields (partial
    per-config results) reflect progress made before the hang."""
    def _fire():
        out = dict(record)
        out.setdefault("backend", "none")
        out["error"] = f"in-process {what} exceeded {seconds:.0f}s"
        _emit(out)
        os._exit(1)

    t = threading.Timer(seconds, _fire)
    t.daemon = True
    t.start()
    return t


def bench_config(preset_name: str, batch_per_chip: int, warmup: int,
                 iters: int, profile_dir=None):
    """Train-step throughput for one ResNet preset on the live backend."""
    import jax
    import numpy as np
    import optax

    from tensorflow_train_distributed_tpu.models import resnet
    from tensorflow_train_distributed_tpu.parallel.sharding import shard_batch
    from tensorflow_train_distributed_tpu.runtime.mesh import (
        MeshConfig, build_mesh,
    )
    from tensorflow_train_distributed_tpu.training import (
        Policy, Trainer, TrainerConfig,
    )

    mesh = build_mesh(MeshConfig(data=-1))
    n_chips = mesh.devices.size
    platform_hint = mesh.devices.flat[0].platform
    batch_size = batch_per_chip * n_chips
    preset = resnet.RESNET_PRESETS[preset_name]
    task = resnet.make_task(preset)
    trainer = Trainer(
        task,
        optax.sgd(0.1, momentum=0.9, nesterov=True),
        mesh,
        policy=Policy.from_name("mixed_bfloat16"),
        config=TrainerConfig(log_every=1_000_000),
    )
    rng = np.random.default_rng(0)
    if preset.space_to_depth:
        # Host pipelines deliver s2d layout (datasets.SyntheticImageNet
        # space_to_depth=True); the device never sees the 3-channel tensor.
        img = rng.standard_normal((batch_size, 112, 112, 12),
                                  dtype=np.float32)
    else:
        img = rng.standard_normal((batch_size, 224, 224, 3),
                                  dtype=np.float32)
    batch = {"image": img,
             "label": rng.integers(0, 1000, batch_size).astype(np.int32)}
    state = trainer.create_state(batch)
    step = trainer._compiled_train_step()
    dev_batch = shard_batch(mesh, batch)
    for _ in range(warmup):
        state, m = step(state, dev_batch)
    jax.block_until_ready(state)
    # Plausibility guard: a timed window faster than the compute roofline
    # (all FLOPs at 100% peak) is a measurement artifact, not throughput —
    # observed once on a flaky chip tunnel (73k img/s ≈ 460% MFU).
    # Re-time once on the SAME compiled step (recompiling could blow the
    # bench watchdog); a persistent artifact is reported but flagged so it
    # can never become the headline.
    roofline_dt = (batch_size * GFLOP_PER_IMAGE
                   / (PEAK_TFLOPS.get(platform_hint, 1e9) * 1e3 * n_chips))
    for _ in range(2):
        t0 = time.perf_counter()
        for _ in range(iters):
            state, m = step(state, dev_batch)
        jax.block_until_ready(m)
        dt = (time.perf_counter() - t0) / iters
        if dt >= roofline_dt:
            break
    if profile_dir is not None:
        # Short profiled window, separate from the timed one: traces are
        # evidence for PROFILE.md, not part of the measurement.
        try:
            with jax.profiler.trace(os.path.join(profile_dir, preset_name)):
                for _ in range(3):
                    state, m = step(state, dev_batch)
                jax.block_until_ready(m)
        except Exception as e:  # profiling must never kill the bench
            print(f"# profiler trace failed: {e}", file=sys.stderr)
    img_per_sec_per_chip = batch_size / dt / n_chips
    platform = platform_hint
    result = {
        "images_per_sec_per_chip": round(img_per_sec_per_chip, 1),
        "step_time_ms": round(dt * 1e3, 2),
        "batch_per_chip": batch_per_chip,
        "n_chips": n_chips,
    }
    if dt < roofline_dt:
        result["implausible"] = True
    if platform in PEAK_TFLOPS:
        mfu = (img_per_sec_per_chip * GFLOP_PER_IMAGE
               / (PEAK_TFLOPS[platform] * 1e3))
        result["mfu_pct"] = round(100 * mfu, 2)
    return result


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--configs", default="resnet50,resnet50_s2d",
                   help="comma-separated RESNET_PRESETS names to bench")
    p.add_argument("--batch-per-chip", type=int, default=256)
    p.add_argument("--warmup", type=int, default=5)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--retries", type=int, default=2,
                   help="backend probe attempts before fallback/failure")
    p.add_argument("--probe-timeout", type=float, default=120.0,
                   help="seconds per subprocess backend probe")
    p.add_argument("--init-timeout", type=float, default=300.0,
                   help="watchdog on in-process backend init")
    p.add_argument("--bench-timeout", type=float, default=1200.0,
                   help="watchdog on the whole compile+measure phase")
    fb = p.add_mutually_exclusive_group()
    fb.add_argument("--allow-cpu-fallback", dest="cpu_fallback",
                    action="store_true", default=True)
    fb.add_argument("--no-cpu-fallback", dest="cpu_fallback",
                    action="store_false",
                    help="emit a failure record instead of benching on CPU")
    p.add_argument("--profile-dir", default="profiles/bench",
                   help="jax.profiler trace output ('' disables)")
    p.add_argument("--no-persist", dest="persist", action="store_false",
                   default=True,
                   help="don't overwrite the last-known-TPU record (for "
                        "sweeps/experiments; the default headline run "
                        "persists)")
    args = p.parse_args(argv)

    record = _base_record()
    info, errors = _acquire_backend(args.retries, args.probe_timeout)
    fallback = False
    if info is None:
        if not args.cpu_fallback:
            _emit(dict(record, error="; ".join(errors), backend="none"))
            return 1
        fallback = True

    import jax

    if fallback:
        # Probe exhausted retries: re-target CPU *before* any in-process
        # backend init.  force_platform clears any backend a launcher's
        # sitecustomize already pinned — a bare jax.config.update would be
        # silently ignored in exactly the wedged-TPU case that got us here.
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            force_platform,
        )

        force_platform("cpu")

    wd = _watchdog(args.init_timeout, record)
    try:
        platform = jax.devices()[0].platform
    except Exception as e:
        # Init can *raise* as well as hang (chip grabbed between probe and
        # here).  With fallback enabled this is just another reason to
        # bench on CPU; without it, the record must still land.
        errors.append(f"in-process init: {e}")
        if not args.cpu_fallback:
            _emit(dict(record, error="; ".join(errors), backend="none"))
            return 1
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            force_platform,
        )

        fallback = True
        force_platform("cpu")
        platform = jax.devices()[0].platform
    finally:
        wd.cancel()

    if platform != "tpu" and not fallback and not args.cpu_fallback:
        _emit(dict(record, error=f"expected tpu backend, got {platform}",
                   backend=platform))
        return 1
    # Any non-TPU number is a fallback result by definition — flag it even
    # when the probe "succeeded" because the host simply has no TPU.
    fallback = fallback or platform != "tpu"

    # CPU can't push MLPerf-sized batches through ResNet-50 in useful time;
    # shrink the workload (one config, tiny batch) and say so in the
    # record — a fallback exists to land a parseable record before any
    # driver timeout, not to measure the CPU.
    batch_per_chip = args.batch_per_chip
    warmup, iters = args.warmup, args.iters
    configs = [c for c in args.configs.split(",") if c]
    skipped_configs = []
    if platform != "tpu":
        batch_per_chip = min(batch_per_chip, 8)
        warmup, iters = min(warmup, 1), min(iters, 2)
        configs, skipped_configs = configs[:1], configs[1:]

    # The DEFAULT trace dir holds committed TPU evidence; a CPU fallback
    # must not bury it under CPU traces.  An explicitly chosen dir is
    # honored on any backend.
    profile_dir = args.profile_dir or None
    if platform != "tpu" and args.profile_dir == "profiles/bench":
        profile_dir = None
    results = {}
    failures = {}
    # Compile or the first step can wedge just like init — keep a watchdog
    # armed through the whole measure phase so a JSON record always lands.
    skip_note = ({"skipped_configs": skipped_configs}
                 if skipped_configs else {})
    wd = _watchdog(args.bench_timeout,
                   dict(record, backend=platform, configs=results,
                        failed_configs=failures, **skip_note),
                   what="compile/measure")
    try:
        for name in configs:
            try:
                results[name] = bench_config(
                    name, batch_per_chip, warmup, iters, profile_dir)
            except Exception as e:
                failures[name] = f"{type(e).__name__}: {e}"
    finally:
        wd.cancel()
    if not results:
        _emit(dict(record, error=f"all configs failed: {failures}",
                   backend=platform, probe_errors=errors, **skip_note))
        return 1

    plausible = {n: r for n, r in results.items()
                 if not r.get("implausible")}
    if not plausible:
        _emit(dict(record, backend=platform, configs=results,
                   error="all measurements exceeded the hardware roofline "
                         "(timing artifact; see bench_config guard)",
                   **skip_note))
        return 1
    best_name = max(plausible, key=lambda n:
                    plausible[n]["images_per_sec_per_chip"])
    best = results[best_name]
    record.update(
        value=best["images_per_sec_per_chip"],
        vs_baseline=round(best["images_per_sec_per_chip"]
                          / TARGET_IMG_PER_SEC_PER_CHIP, 3),
        backend=platform,
        config=best_name,
        configs=results,
    )
    if "mfu_pct" in best:
        record["mfu_pct"] = best["mfu_pct"]
    record["measured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                          time.gmtime())
    if fallback:
        record["fallback"] = True
        if errors:
            record["probe_errors"] = errors
        try:
            with open(LAST_TPU_RESULT) as f:
                record["last_known_tpu"] = json.load(f)
        except (OSError, ValueError):
            pass
    if failures:
        record["failed_configs"] = failures
    if skipped_configs:
        record["skipped_configs"] = skipped_configs
    if profile_dir:
        record["profile_dir"] = profile_dir
    if platform == "tpu" and args.persist:
        try:
            os.makedirs(os.path.dirname(LAST_TPU_RESULT), exist_ok=True)
            with open(LAST_TPU_RESULT, "w") as f:
                json.dump(record, f)
        except OSError as e:
            print(f"# could not persist TPU result: {e}", file=sys.stderr)
    _emit(record)
    return 0


if __name__ == "__main__":
    sys.exit(main())
