#!/usr/bin/env python
"""Run any command under the self-healing training supervisor.

``launch.py --supervise`` covers the common case (supervising this
repo's own CLI); this tool supervises an ARBITRARY training command —
a shell script, a different entry point, a container runner — with the
same exit-code contract (``runtime.preemption.PREEMPTION_EXIT_CODE``
relaunches without consuming the crash budget) and the same JSON-lines
attempt journal::

    tools/train_supervisor.py --max-restarts 5 \
        --journal /ckpt/supervisor.jsonl -- \
        python -m tensorflow_train_distributed_tpu \
        --config mnist --steps 2000 --checkpoint-dir /ckpt

Everything after ``--`` is the child argv, launched verbatim with
``TTD_SUPERVISE_ATTEMPT`` exported per attempt.
"""

import argparse
import logging
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))  # repo root (the package)


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    p = argparse.ArgumentParser(
        prog="train_supervisor",
        description="self-healing relaunch loop for a training command",
    )
    p.add_argument("--max-restarts", type=int, default=3,
                   help="crash restart budget (preemption exits are "
                        "free)")
    p.add_argument("--backoff", type=float, default=1.0,
                   help="base crash-relaunch delay; doubles per "
                        "consecutive crash")
    p.add_argument("--backoff-max", type=float, default=60.0)
    p.add_argument("--no-restart-on-preemption", action="store_true",
                   help="return the preemption exit code instead of "
                        "relaunching")
    p.add_argument("--journal", default=None,
                   help="append one JSON line per attempt to this file")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   metavar="-- COMMAND ...",
                   help="child argv (prefix with --)")
    args = p.parse_args(argv)
    child = args.command
    if child and child[0] == "--":
        child = child[1:]
    if not child:
        p.error("no child command given (put it after --)")

    from tensorflow_train_distributed_tpu.runtime.supervisor import (
        TrainSupervisor,
    )

    result = TrainSupervisor(
        child,
        max_restarts=args.max_restarts,
        backoff_s=args.backoff,
        backoff_max_s=args.backoff_max,
        restart_on_preemption=not args.no_restart_on_preemption,
        journal_path=args.journal,
    ).run()
    logging.getLogger("train_supervisor").info(
        "attempts=%d crashes=%d preemptions=%d gave_up=%s rc=%d",
        result.attempts, result.crashes, result.preemptions,
        result.gave_up, result.returncode)
    return result.returncode


if __name__ == "__main__":
    sys.exit(main())
