"""Continuous-batching engine throughput vs static-batch generate.

Serves a mixed-length synthetic request stream through
``serving.ServingEngine`` (slot-refill decode) and reports GENERATED
tokens/sec plus p50 TTFT and mean inter-token latency.  By default the
run is an A/B over async decode pipelining — overlap ON (the headline
numbers) vs OFF (``no_overlap`` sub-record) — with the engine's
``overlap_ratio`` (host-harvest share hidden under device compute)
committed alongside; ``--no-ab`` skips the OFF leg.  ``--baseline``
also times the static-batch path the engine replaces — same requests
grouped into arrival-order batches of ``--slots``, each batch padded to
its longest prompt and decoded for its largest max_new (what
``generate()`` forces) — so the engine's win IS the padding/straggler
waste it removes.

``--mixed`` instead runs the tail-latency workload the interleaved
prefill scheduler exists for: short requests decode on most lanes while
one LONG prompt (spanning several ``--prefill-chunk`` budget
installments) is injected mid-stream, A/B'ing interleave ON vs the
atomic-admission kill switch (``prefill_budget=0``) — reported are the
active lanes' p99 inter-token latency during the admission window, the
long and trailing-short TTFTs, and the engine's prefill-stall seconds.

``--trace-ab`` instead A/Bs the always-on flight recorder
(``runtime.events``) against its ``TTD_NO_TRACE=1`` kill switch on
identical passes of one engine, reporting the tok/s overhead
percentage — the committed proof the recorder is cheap enough to leave
on (``profiles/bench/trace_overhead_ab.jsonl``).

``--fused-ab`` runs the fused paged-attention decode push's three
stacked A/Bs (fused kernel vs the ``TTD_NO_FUSED_ATTN`` XLA
block-gather leg, int8 KV pool vs fp, and the ``--sweep-slots``
capacity-growth curve) — committed to
``profiles/bench/fused_attn_ab.jsonl``.

Every decode record carries ``mbu_pct`` (model-bandwidth utilization,
the serving analog of training MFU — null off-TPU where no bandwidth
table exists) beside tok/s, so the metric decode optimization is
judged by lands in every committed record.

Prints one JSON line per run (bench_lm.py conventions).
"""

import argparse
import contextlib
import json
import os
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))  # repo root (the package)
sys.path.insert(0, _HERE)                   # tools/ siblings

from bench_gateway import (  # noqa: E402 (shared helpers)
    _percentile,
    decode_mbu_fields,
)


def _requests(n, plo, phi, glo, ghi, vocab, seed):
    import numpy as np

    rng = np.random.default_rng(seed)
    return [(list(rng.integers(1, vocab, int(rng.integers(plo, phi + 1)))),
             int(rng.integers(glo, ghi + 1))) for _ in range(n)]


def _run_engine_timed(eng, reqs):
    """One timed pass: submit everything, drive ``serve_step``, record
    per-request first-token and completion times (the serving-latency
    view ``run()`` cannot give).  Returns ``(wall_s, ttfts, itls,
    total_tokens_out)`` — ``itls`` are per-request mean inter-token
    gaps (completion-first)/(generated-1), requests with >1 generated
    token only."""
    ids = [eng.submit(p, m) for p, m in reqs]
    plens = {rid: len(p) for rid, (p, _) in zip(ids, reqs)}
    first, done_at, out = {}, {}, {}
    t0 = time.perf_counter()
    while eng.pending():
        done = eng.serve_step()
        now = time.perf_counter()
        for rid, toks in done.items():
            out[rid] = toks
            done_at[rid] = now
            if rid not in first and len(toks) > plens[rid]:
                first[rid] = now
        for rid, n in eng.progress().items():
            if rid not in first and n > plens[rid]:
                first[rid] = now
    wall = time.perf_counter() - t0
    ttfts = sorted(first[r] - t0 for r in ids if r in first)
    itls = []
    for rid in ids:
        gen = len(out[rid]) - plens[rid]
        if rid in first and rid in done_at and gen > 1:
            itls.append((done_at[rid] - first[rid]) / (gen - 1))
    return wall, ttfts, itls, sum(len(v) for v in out.values())


def _mixed_pass(eng, active_prompts, active_new, long_prompt, long_new,
                tail_prompt, tail_new):
    """One mixed-workload pass: fill ``len(active_prompts)`` lanes,
    wait until every lane is decoding, then inject one LONG prompt
    plus one short prompt queued behind it.  Measures the active
    lanes' per-token gaps during the long admission window
    (submit → long's first token) — the head-of-line stall interleaved
    prefill removes — plus both injected requests' TTFTs and the
    engine's prefill-stall delta."""
    ids = [eng.submit(p, active_new) for p in active_prompts]
    plens = {rid: len(p) for rid, p in zip(ids, active_prompts)}
    done: dict = {}
    while not all(rid in done
                  or eng.progress().get(rid, 0) > plens[rid]
                  for rid in ids):
        done.update(eng.serve_step())
    stall0 = eng.prefill_stall_s()
    counts = {rid: (len(done[rid]) if rid in done
                    else eng.progress().get(rid, plens[rid]))
              for rid in ids}
    t_inject = time.perf_counter()
    long_id = eng.submit(long_prompt, long_new)
    tail_id = eng.submit(tail_prompt, tail_new)
    gaps: list = []        # active-lane per-token gaps while admitting
    ttft_long = ttft_tail = None
    last = t_inject
    while eng.pending():
        step_done = eng.serve_step()
        now = time.perf_counter()
        done.update(step_done)
        prog = eng.progress()
        admitting = ttft_long is None
        for rid in ids:
            n_now = (len(done[rid]) if rid in done
                     else prog.get(rid, counts[rid]))
            d = n_now - counts[rid]
            if d > 0 and admitting:
                gaps.extend([(now - last) / d] * d)
            counts[rid] = n_now
        if ttft_long is None:
            n = (len(done[long_id]) if long_id in done
                 else prog.get(long_id, 0))
            if n > len(long_prompt):
                ttft_long = now - t_inject
        if ttft_tail is None:
            n = (len(done[tail_id]) if tail_id in done
                 else prog.get(tail_id, 0))
            if n > len(tail_prompt):
                ttft_tail = now - t_inject
        last = now
    gaps.sort()
    return {
        "p99_inter_token_ms_active": round(
            1e3 * _percentile(gaps, 0.99), 3),
        "max_gap_ms_active": round(1e3 * gaps[-1], 3) if gaps else 0.0,
        "ttft_long_ms": round(1e3 * ttft_long, 2),
        "ttft_short_behind_long_ms": round(1e3 * ttft_tail, 2),
        "prefill_stall_s": round(eng.prefill_stall_s() - stall0, 4),
    }


def bench_serving_mixed(preset, slots, chunk, cache_len, seed,
                        prefill_chunk, long_pieces, reps=3):
    """The --mixed A/B: long prompts arriving during active decode,
    interleaved prefill ON (the headline) vs the atomic-admission kill
    switch (``no_interleave`` sub-record).  The long prompt spans
    ``long_pieces`` budget installments (``prefill_chunk`` tokens
    each), so the OFF leg's admission blocks active lanes for the
    whole prompt while the ON leg bounds each gap by one installment."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tensorflow_train_distributed_tpu.models.llama import (
        LLAMA_PRESETS, LlamaModel,
    )
    from tensorflow_train_distributed_tpu.serving import ServingEngine

    cfg = LLAMA_PRESETS[preset]
    params = LlamaModel(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    vocab = min(cfg.vocab_size, 30_000)
    rng = np.random.default_rng(seed)
    # Two lanes stay free: one for the long admission, one for the
    # tail short — so the tail's TTFT measures queueing behind the
    # long prefill, not waiting for an active lane to retire.
    lanes = max(1, slots - 2)
    active_prompts = [list(rng.integers(1, vocab, 8))
                      for _ in range(lanes)]
    long_len = prefill_chunk * long_pieces
    long_prompt = list(rng.integers(1, vocab, long_len))
    tail_prompt = list(rng.integers(1, vocab, 8))
    # Active lanes must outlive the admission window (~long_pieces
    # steps of chunk tokens each) with margin.
    active_new = chunk * (long_pieces + 6)
    cache_len = cache_len or max(long_len + 16,
                                 8 + active_new + 8)
    if cache_len > cfg.max_positions:
        raise ValueError(
            f"mixed workload needs cache_len {cache_len} but the "
            f"preset caps max_positions at {cfg.max_positions} — "
            f"shrink --long-pieces/--prefill-chunk/--chunk")

    def one_mode(interleave):
        eng = ServingEngine(
            cfg, params, slots=slots, chunk=chunk, cache_len=cache_len,
            prefill_chunk=prefill_chunk,
            prefill_budget=None if interleave else 0)
        args = (eng, active_prompts, active_new, long_prompt, 8,
                tail_prompt, 8)
        _mixed_pass(*args)                  # warmup: compiles
        best = None
        for _ in range(max(1, reps)):
            rec = _mixed_pass(*args)
            if (best is None or rec["p99_inter_token_ms_active"]
                    < best["p99_inter_token_ms_active"]):
                best = rec
        return best

    on = one_mode(True)
    off = one_mode(False)
    dev = jax.devices()[0]
    rec = {
        "metric": f"{preset}_serving_mixed_p99_inter_token_ms",
        "value": on["p99_inter_token_ms_active"],
        "unit": "ms p99 active-lane inter-token during long admission",
        "slots": slots,
        "chunk": chunk,
        "prefill_chunk": prefill_chunk,
        "long_prompt_len": long_len,
        "long_pieces": long_pieces,
        "interleave": on,
        "no_interleave": off,
        "backend": dev.platform,
        "device_kind": dev.device_kind,
    }
    if on["p99_inter_token_ms_active"]:
        rec["p99_improvement"] = round(
            off["p99_inter_token_ms_active"]
            / on["p99_inter_token_ms_active"], 3)
    if on["max_gap_ms_active"]:
        rec["max_gap_improvement"] = round(
            off["max_gap_ms_active"] / on["max_gap_ms_active"], 3)
    return rec


def bench_trace_ab(preset, slots, chunk, n_requests, prompt_range,
                   new_range, cache_len, seed, reps=3):
    """The flight-recorder overhead A/B: identical engine passes with
    the recorder ON (the always-on default) vs ``TTD_NO_TRACE=1`` (the
    kill switch).  ONE engine serves both legs — the jitted programs
    are shared, so the measured delta is purely the host-side
    span/instant recording the tentpole claims is ≤ 2 % tok/s.

    Noise discipline: single-pass walls on a shared host swing far
    more than the effect being measured, so the legs run as
    BACK-TO-BACK PAIRS (on, off) and the headline is the MEDIAN of the
    per-pair wall ratios — a scheduler spike inflates one pair's both
    legs (ratio survives) or one leg of one pair (median discards it),
    where min-wall-per-leg across minutes compares walls from
    different load regimes."""
    import jax
    import jax.numpy as jnp

    from tensorflow_train_distributed_tpu.models.llama import (
        LLAMA_PRESETS, LlamaModel,
    )
    from tensorflow_train_distributed_tpu.runtime import events
    from tensorflow_train_distributed_tpu.serving import ServingEngine

    cfg = LLAMA_PRESETS[preset]
    params = LlamaModel(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    reqs = _requests(n_requests, *prompt_range, *new_range,
                     min(cfg.vocab_size, 30_000), seed)
    gen_tokens = sum(m for _, m in reqs)
    eng = ServingEngine(cfg, params, slots=slots, chunk=chunk,
                        cache_len=cache_len)
    for p, m in reqs:                              # warmup: compiles
        eng.submit(p, m)
    eng.run()
    had_kill = os.environ.get("TTD_NO_TRACE")
    best = {True: None, False: None}
    ratios = []
    try:
        for i in range(max(1, reps)):
            walls = {}
            # Leg order alternates per pair ((on, off), (off, on), ...):
            # whatever systematic advantage the second-run leg of a
            # pair has (cache warmth, allocator state) cancels in the
            # median instead of biasing every ratio the same way.
            for trace_on in ((True, False) if i % 2 == 0
                             else (False, True)):
                if trace_on:
                    os.environ.pop("TTD_NO_TRACE", None)
                else:
                    os.environ["TTD_NO_TRACE"] = "1"
                rec = _run_engine_timed(eng, reqs)
                walls[trace_on] = rec[0]
                if best[trace_on] is None or rec[0] < best[trace_on][0]:
                    best[trace_on] = rec
            ratios.append(walls[True] / walls[False])
    finally:
        if had_kill is None:
            os.environ.pop("TTD_NO_TRACE", None)
        else:
            os.environ["TTD_NO_TRACE"] = had_kill
    ratios.sort()
    median_ratio = ratios[len(ratios) // 2]
    tps_on = gen_tokens / best[True][0]
    tps_off = gen_tokens / best[False][0]
    dev = jax.devices()[0]
    return {
        "metric": f"{preset}_serving_trace_overhead_pct",
        "value": round(100.0 * (median_ratio - 1.0), 3),
        "unit": "% tok/s lost, flight recorder on vs TTD_NO_TRACE=1 "
                "(median of per-pair wall ratios)",
        "pair_wall_ratios": [round(r, 4) for r in ratios],
        "trace_on_tokens_per_sec": round(tps_on, 1),
        "trace_off_tokens_per_sec": round(tps_off, 1),
        "trace_on_wall_s": round(best[True][0], 3),
        "trace_off_wall_s": round(best[False][0], 3),
        "events_in_ring": len(events.get_recorder()),
        "ring_capacity": events.get_recorder().capacity,
        "slots": slots,
        "chunk": chunk,
        "n_requests": n_requests,
        "gen_tokens": gen_tokens,
        "reps": reps,
        "backend": dev.platform,
        "device_kind": dev.device_kind,
    }


def bench_trace_fleet_ab(preset, slots, chunk, n_requests, prompt_range,
                         new_range, cache_len, seed, reps=3,
                         replicas=2):
    """The FLEET observability overhead A/B: a real subprocess pool
    (parent gateway process + ``replicas`` llama workers over the
    frame protocol) serving the same request set under THREE legs —
    ``off`` (``TTD_NO_TRACE=1`` + ``TTD_NO_CLOCK_SYNC=1``, no spool),
    ``trace`` (the pre-fleet flight recorder alone: rings on, relay
    on, sync killed, no spool), and ``full`` (the whole plane:
    PING/PONG clock sync on the stats heartbeat plus the
    crash-durable trace spool writing in parent and workers).  Two
    headlines fall out: ``full/off`` is the total cost of always-on
    fleet observability, and ``full/trace`` is the MARGINAL cost of
    what this plane added on top of the recorder the repo already
    shipped — the "spool+sync overhead" the tentpole's ≤2% bar
    names.

    Workers read their kill switches from their own environment, so
    each leg is its own pool spawned with the leg's env overlaid on
    the child; all pools are built and warmed up-front and the timed
    passes run as leg-order-rotating rounds with the parent-side env
    flipped around each pass, median of per-round wall ratios — the
    --trace-ab noise discipline.  During a pass the other pools'
    workers are idle (heartbeats only), which costs every leg the
    same.  NOTE the observer and the observed share cores: on a
    small host (the committed record's 1-CPU container) flusher and
    relay threads displace decode compute directly, so these numbers
    are an upper bound — on a multi-core host the plane rides spare
    cores and only the serving-thread ring appends remain."""
    import shutil
    import tempfile

    import jax

    from tensorflow_train_distributed_tpu.models.llama import (
        LLAMA_PRESETS,
    )
    from tensorflow_train_distributed_tpu.runtime import events
    from tensorflow_train_distributed_tpu.server.procpool import (
        ProcPool, WorkerSpec,
    )

    cfg = LLAMA_PRESETS[preset]
    reqs = _requests(n_requests, *prompt_range, *new_range,
                     min(cfg.vocab_size, 30_000), seed)
    gen_tokens = sum(m for _, m in reqs)
    factory_json = dict(preset=preset, init_seed=0, slots=slots,
                        chunk=chunk)
    if cache_len:
        factory_json["cache_len"] = cache_len
    spool_dir = tempfile.mkdtemp(prefix="ttd-fleet-ab-spool-")
    worker_env = {
        "off": {"TTD_NO_TRACE": "1", "TTD_NO_CLOCK_SYNC": "1"},
        "trace": {"TTD_NO_CLOCK_SYNC": "1"},
        "full": {"TTD_TRACE_SPOOL": spool_dir},
    }
    saved = {k: os.environ.get(k) for k in
             ("TTD_NO_TRACE", "TTD_NO_CLOCK_SYNC", "TTD_TRACE_SPOOL")}
    # Spawn every pool from a NEUTRAL parent env: WorkerSpec.env
    # OVERLAYS the inherited environment (it cannot unset keys), so a
    # leak from the parent would silently arm the wrong leg's workers.
    for k in saved:
        os.environ.pop(k, None)

    def arm(leg):
        """Parent-side leg flip: recording, the ping mint, and the
        parent spool all live in this process and re-read env (or are
        armed explicitly) around each pass."""
        for k in saved:
            os.environ.pop(k, None)
        os.environ.update(worker_env[leg])
        if leg == "full":
            events.get_recorder().start_spool(spool_dir)
            # Drain the ring backlog NOW: re-arming resets the spool
            # cursor, and the backlog serialize belongs to no leg.
            events.get_recorder().flush_spool()
        else:
            events.get_recorder().stop_spool()

    def timed_pass(pool):
        t0 = time.perf_counter()
        hs = [pool.submit(p, m) for p, m in reqs]
        for h in hs:
            h.result(timeout=600)
        return time.perf_counter() - t0

    legs = ("off", "trace", "full")
    pools = {}
    best = {leg: None for leg in legs}
    rounds = []
    sync_state = None
    spool_files = 0
    try:
        for leg in legs:
            spec = WorkerSpec(factory="llama", factory_json=factory_json,
                              env=worker_env[leg])
            pools[leg] = ProcPool(spec, replicas=replicas,
                                  max_queue=4 * n_requests,
                                  watchdog_timeout_s=300.0).start()
        for leg in legs:                    # warmup: worker compiles
            if not pools[leg].wait_ready(timeout=600):
                raise RuntimeError("fleet AB pool never became ready")
            arm(leg)
            timed_pass(pools[leg])
        for i in range(max(1, reps)):
            walls = {}
            for leg in (legs if i % 2 == 0 else legs[::-1]):
                arm(leg)
                w = timed_pass(pools[leg])
                walls[leg] = w
                if best[leg] is None or w < best[leg]:
                    best[leg] = w
            rounds.append(walls)
        # Committed proof the full leg really ran the plane: clocks
        # synced on every full-leg worker, spool segments on disk.
        sync_state = [s.get("clock") for s in
                      pools["full"].replica_states()]
        events.get_recorder().flush_spool()
        spool_files = len([n for n in os.listdir(spool_dir)
                           if n.startswith("spool-")])
    finally:
        for pool in pools.values():
            try:
                pool.join(timeout=60)
            except Exception:   # noqa: BLE001 — teardown best-effort
                pass
        events.get_recorder().stop_spool()
        shutil.rmtree(spool_dir, ignore_errors=True)
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    def med(pairs):
        rs = sorted(pairs)
        return rs[len(rs) // 2]

    total = med([w["full"] / w["off"] for w in rounds])
    trace_only = med([w["trace"] / w["off"] for w in rounds])
    marginal = med([w["full"] / w["trace"] for w in rounds])
    dev = jax.devices()[0]
    return {
        "metric": f"{preset}_serving_trace_fleet_overhead_pct",
        "value": round(100.0 * (marginal - 1.0), 3),
        "unit": "% tok/s lost to clock sync + crash-durable spool on "
                "top of the flight recorder (full/trace, median of "
                "per-round wall ratios over a subprocess worker pool)",
        "fleet_total_overhead_pct":
            round(100.0 * (total - 1.0), 3),
        "trace_only_overhead_pct":
            round(100.0 * (trace_only - 1.0), 3),
        "round_wall_ratios_full_vs_trace":
            sorted(round(w["full"] / w["trace"], 4) for w in rounds),
        "round_wall_ratios_full_vs_off":
            sorted(round(w["full"] / w["off"], 4) for w in rounds),
        "fleet_full_tokens_per_sec": round(gen_tokens / best["full"], 1),
        "fleet_off_tokens_per_sec": round(gen_tokens / best["off"], 1),
        "fleet_full_wall_s": round(best["full"], 3),
        "fleet_trace_wall_s": round(best["trace"], 3),
        "fleet_off_wall_s": round(best["off"], 3),
        "workers_synced": sum(1 for c in (sync_state or [])
                              if c and c.get("synced")),
        "spool_segments": spool_files,
        "replicas": replicas,
        "slots": slots,
        "chunk": chunk,
        "n_requests": n_requests,
        "gen_tokens": gen_tokens,
        "reps": reps,
        "backend": dev.platform,
        "device_kind": dev.device_kind,
    }


def bench_paged_kv_ab(preset, slots, chunk, n_requests, prefix_len,
                      cache_len, seed, kv_block_size, reps=3):
    """The --shared-prefix A/B: every request = one shared system
    prompt + a distinct short tail, served with the paged KV cache's
    radix prefix sharing ON (the default engine) vs the linear cache
    (the ``TTD_NO_PAGED_KV`` kill switch path — every request
    re-prefills the prefix).  Legs run as leg-order-alternating pairs
    (the --trace-ab noise discipline) on TWO warmed engines; the
    headline is the shared-prefix TTFT p50 improvement, with the
    engine's ``prefix_hit_tokens`` committed alongside so the
    prefill-compute saving is a counter, not an inference.

    A second, NON-SHARED pair (disjoint random prompts, same shapes)
    pins the paged gather/scatter overhead: its tok/s ratio is the
    "no regression" guard — block indirection must not tax plain
    decode."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tensorflow_train_distributed_tpu.models.llama import (
        LLAMA_PRESETS, LlamaModel,
    )
    from tensorflow_train_distributed_tpu.serving import ServingEngine

    cfg = LLAMA_PRESETS[preset]
    params = LlamaModel(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    vocab = min(cfg.vocab_size, 30_000)
    rng = np.random.default_rng(seed)
    # new=32: the no-regression guard is about steady-state DECODE
    # tok/s, so decode must dominate the pass — with tiny generations
    # the fixed per-admission work (claim + insert + reset programs,
    # identical at any model size) masquerades as a decode tax.
    tail, new = 8, 32
    prefix = list(rng.integers(1, vocab, prefix_len))
    cache_len = cache_len or min(cfg.max_positions,
                                 prefix_len + tail + new + 8)
    if prefix_len + tail + new > cache_len:
        raise ValueError(f"--prefix-len {prefix_len} + tail {tail} + "
                         f"{new} new exceeds cache_len {cache_len}")

    # EVERY pass serves FRESH prompts (lengths fixed — compiles
    # reuse): the engines persist across passes, and the radix caches
    # every retired request, so reusing prompts would let pass 2+ of
    # the DISJOINT pair prefix-hit its own pass-1 history — crediting
    # prefix-cache wins to the "pure layout overhead" guard.  Fresh
    # tails keep the shared pair honest too: its hits measure the
    # SHARED PREFIX only.
    def shared_pass():
        return [(prefix + list(rng.integers(1, vocab, tail)), new)
                for _ in range(n_requests)]

    def disjoint_pass():
        return [(list(rng.integers(1, vocab, prefix_len + tail)), new)
                for _ in range(n_requests)]

    def warm(paged, reqs):
        e = ServingEngine(cfg, params, slots=slots, chunk=chunk,
                          cache_len=cache_len, paged=paged,
                          kv_block_size=kv_block_size)
        for p, m in reqs:                          # warmup: compiles
            e.submit(p, m)
        e.run()
        return e

    def ab(make_pass):
        """Leg-order-alternating BACK-TO-BACK pairs; besides best-leg
        stats, collect each pair's wall ratio (linear/paged) — the
        trace-ab noise discipline: on a shared 1-core host, single
        walls swing far more than a few-percent effect, min-wall
        compares different load regimes, and the MEDIAN of per-pair
        ratios is the estimator that survives scheduler spikes."""
        eng = {True: warm(True, make_pass()),
               False: warm(False, make_pass())}
        best = {True: None, False: None}
        hits = {True: 0, False: 0}
        ratios = []
        for i in range(max(1, reps)):
            # Both legs of a pair serve the SAME fresh request list.
            pass_reqs = make_pass()
            walls = {}
            for paged in ((True, False) if i % 2 == 0
                          else (False, True)):
                e = eng[paged]
                h0 = e.kv_prefix_hit_tokens()
                rec = _run_engine_timed(e, pass_reqs)
                walls[paged] = rec[0]
                if best[paged] is None or rec[0] < best[paged][0]:
                    best[paged] = rec
                    hits[paged] = e.kv_prefix_hit_tokens() - h0
            ratios.append(walls[False] / walls[True])
        ratios.sort()
        return eng, best, hits, ratios[len(ratios) // 2], ratios

    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))

    def leg(best, hits, gen_tokens):
        wall, ttfts, itls, _ = best
        out = {
            "tokens_per_sec": round(gen_tokens / wall, 1),
            "wall_s": round(wall, 3),
            "ttft_ms_p50": round(1e3 * _percentile(ttfts, 0.5), 2),
            "inter_token_ms_mean": round(
                1e3 * sum(itls) / len(itls), 3) if itls else 0.0,
            "prefix_hit_tokens": hits,
        }
        out.update(decode_mbu_fields(cfg, n_params, slots, cache_len,
                                     out["tokens_per_sec"]))
        return out

    gen_tokens = n_requests * new
    _, s_best, s_hits, s_ratio, s_ratios = ab(shared_pass)
    _, n_best, n_hits, n_ratio, n_ratios = ab(disjoint_pass)
    on = leg(s_best[True], s_hits[True], gen_tokens)
    off = leg(s_best[False], s_hits[False], gen_tokens)
    pn = leg(n_best[True], n_hits[True], gen_tokens)
    ln = leg(n_best[False], n_hits[False], gen_tokens)
    prompt_tokens = n_requests * (prefix_len + tail)
    dev = jax.devices()[0]
    rec = {
        "metric": f"{preset}_serving_paged_kv_shared_prefix_"
                  f"ttft_improvement",
        "value": (round(off["ttft_ms_p50"] / on["ttft_ms_p50"], 3)
                  if on["ttft_ms_p50"] else 0.0),
        "unit": "x TTFT p50, shared-prefix paged vs linear "
                "(leg-order-alternating pairs, best-of-reps)",
        "slots": slots,
        "chunk": chunk,
        "n_requests": n_requests,
        "prefix_len": prefix_len,
        "tail_len": tail,
        "max_new": new,
        "kv_block_size": kv_block_size,
        "prompt_tokens_per_pass": prompt_tokens,
        "shared": {"paged": on, "linear": off},
        "nonshared": {"paged": pn, "linear": ln},
        "backend": dev.platform,
        "device_kind": dev.device_kind,
    }
    # The "no decode regression" guard: paged vs linear on DISJOINT
    # prompts (no sharing to win, pure layout overhead), as the MEDIAN
    # of per-pair wall ratios — > 1.0 means paged is faster.  The
    # shared-pair median quantifies the headline the same way.
    rec["shared_wall_ratio_median"] = round(s_ratio, 3)
    rec["shared_pair_wall_ratios"] = [round(r, 4) for r in s_ratios]
    rec["nonshared_tokens_per_sec_ratio"] = round(n_ratio, 3)
    rec["nonshared_pair_wall_ratios"] = [round(r, 4) for r in n_ratios]
    return rec


def bench_fused_attn_ab(preset, slots, chunk, n_requests, prompt_range,
                        new_range, cache_len, seed, kv_block_size,
                        sweep_slots, reps=3):
    """The --fused-ab run: the three stacked decode-speed stages of the
    fused paged-attention push, each as its own A/B, one committed
    record (``profiles/bench/fused_attn_ab.jsonl``).

    1. **fused vs gather** — one engine compiled with the fused
       paged-attention kernel (the default), one under the
       ``TTD_NO_FUSED_ATTN=1`` kill switch (the XLA block-gather leg);
       the env choice burns into the compiled programs, so each leg is
       its own warmed engine and the switch flips around CONSTRUCTION,
       not the timed passes.  On CPU both legs compile the gather
       program — the committed ratio ~1.0 IS the no-regression bar
       (≤2%), and the same harness run on TPU measures the real
       kernel.
    2. **int8 pool vs fp** — ``kv_cache_int8`` engine vs the
       full-precision pool at the same shape (half the cache bytes on
       the bandwidth-bound path; CPU pays the quantize/dequant compute
       honestly).
    3. **capacity growth** — the freed HBM spent: slots grown along
       ``sweep_slots`` with the pool sized to match
       (slots × ceil(cache_len / block_size) int8 blocks), tok/s +
       ``mbu_pct`` + ``kv_pool_bytes`` per point — the raw decode-MBU
       curve ROADMAP item 2 asks for.

    All timed pairs follow the trace-ab noise discipline:
    leg-order-alternating back-to-back pairs, median of per-pair wall
    ratios.
    """
    import dataclasses

    import jax
    import jax.numpy as jnp

    from tensorflow_train_distributed_tpu.models.llama import (
        LLAMA_PRESETS, LlamaModel,
    )
    from tensorflow_train_distributed_tpu.serving import ServingEngine

    cfg = LLAMA_PRESETS[preset]
    icfg = dataclasses.replace(cfg, kv_cache_int8=True)
    params = LlamaModel(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    vocab = min(cfg.vocab_size, 30_000)
    reqs = _requests(n_requests, *prompt_range, *new_range, vocab, seed)
    gen_tokens = sum(m for _, m in reqs)
    rows = cache_len or cfg.max_positions
    nblk_lane = -(-rows // kv_block_size)

    def build(config, fused_killed, s=slots, pool=None):
        """Construct + warm an engine under the requested kill-switch
        state (the fused/gather choice compiles in at first trace;
        after warmup the jit cache pins it, so the timed passes below
        need no env management)."""
        had = os.environ.get("TTD_NO_FUSED_ATTN")
        if fused_killed:
            os.environ["TTD_NO_FUSED_ATTN"] = "1"
        else:
            os.environ.pop("TTD_NO_FUSED_ATTN", None)
        try:
            e = ServingEngine(config, params, slots=s, chunk=chunk,
                              cache_len=cache_len,
                              kv_block_size=kv_block_size,
                              kv_pool_blocks=pool)
            for p, m in reqs:                      # warmup: compiles
                e.submit(p, m)
            e.run()
        finally:
            if had is None:
                os.environ.pop("TTD_NO_FUSED_ATTN", None)
            else:
                os.environ["TTD_NO_FUSED_ATTN"] = had
        return e

    def ab(eng_a, eng_b, kv8_a=False, kv8_b=False):
        """Leg-order-alternating pairs → (leg_a, leg_b, median of
        per-pair wall ratios b/a, ratios).  >1 means leg a faster."""
        best = {"a": None, "b": None}
        ratios = []
        for i in range(max(1, reps)):
            walls = {}
            for tag in (("a", "b") if i % 2 == 0 else ("b", "a")):
                e = eng_a if tag == "a" else eng_b
                r = _run_engine_timed(e, reqs)
                walls[tag] = r[0]
                if best[tag] is None or r[0] < best[tag][0]:
                    best[tag] = r
            ratios.append(walls["b"] / walls["a"])
        ratios.sort()

        def leg(b, s, kv8):
            wall, ttfts, itls, _ = b
            out = {
                "tokens_per_sec": round(gen_tokens / wall, 1),
                "wall_s": round(wall, 3),
                "ttft_ms_p50": round(1e3 * _percentile(ttfts, 0.5), 2),
            }
            out.update(decode_mbu_fields(
                cfg, n_params, s, rows, out["tokens_per_sec"], kv8))
            return out

        return (leg(best["a"], slots, kv8_a), leg(best["b"], slots,
                                                  kv8_b),
                ratios[len(ratios) // 2],
                [round(r, 4) for r in ratios])

    # Stage 1: fused vs the TTD_NO_FUSED_ATTN gather leg.
    eng_fused = build(cfg, fused_killed=False)
    eng_gather = build(cfg, fused_killed=True)
    fused_leg, gather_leg, fused_ratio, fused_ratios = ab(
        eng_fused, eng_gather)

    # Stage 2: int8 pool vs fp (both on the default fused/gather
    # choice — the fp leg reuses stage 1's engine).
    eng_int8 = build(icfg, fused_killed=False)
    int8_leg, fp_leg, int8_ratio, int8_ratios = ab(
        eng_int8, eng_fused, kv8_a=True)
    int8_leg["kv_pool_bytes"] = eng_int8.kv_pool_bytes()
    fp_leg["kv_pool_bytes"] = eng_fused.kv_pool_bytes()

    # Stage 3: spend the freed HBM — slots (and the pool with them)
    # grown along the sweep, int8 pools, mbu per point.  The stage-1/2
    # engines are fully consumed: drop them BEFORE the sweep, or their
    # three pinned pools (+ cast param copies) shrink the very HBM
    # headroom the largest sweep points exist to probe.
    fused_engaged = eng_fused.fused_attn()
    del eng_fused, eng_gather, eng_int8
    growth = []
    for s in sweep_slots:
        e = build(icfg, fused_killed=False, s=s, pool=s * nblk_lane)
        best = None
        for _ in range(max(1, reps)):
            r = _run_engine_timed(e, reqs)
            if best is None or r[0] < best[0]:
                best = r
        tps = round(gen_tokens / best[0], 1)
        point = {"slots": s, "kv_pool_blocks": s * nblk_lane,
                 "kv_pool_bytes": e.kv_pool_bytes(),
                 "tokens_per_sec": tps,
                 "wall_s": round(best[0], 3)}
        point.update(decode_mbu_fields(icfg, n_params, s, rows, tps,
                                       True))
        growth.append(point)

    dev = jax.devices()[0]
    return {
        "metric": f"{preset}_serving_fused_attn_wall_ratio",
        "value": round(fused_ratio, 3),
        "unit": "x wall, XLA block-gather leg vs fused paged-attention"
                " leg (median of per-pair wall ratios; ~1.0 on CPU "
                "where both legs compile the gather program — the "
                "no-regression bar; >1 on TPU = fused faster)",
        "fused_engaged": fused_engaged,
        "slots": slots,
        "chunk": chunk,
        "n_requests": n_requests,
        "gen_tokens": gen_tokens,
        "cache_len": rows,
        "kv_block_size": kv_block_size,
        "reps": reps,
        "fused": fused_leg,
        "gather": gather_leg,
        "pair_wall_ratios": fused_ratios,
        "int8_pool": {
            "unit": "x wall, fp pool vs int8 pool (median of per-pair "
                    "wall ratios; >1 = int8 faster)",
            "wall_ratio_median": round(int8_ratio, 3),
            "pair_wall_ratios": int8_ratios,
            "int8": int8_leg,
            "fp": fp_leg,
        },
        "pool_growth": growth,
        "backend": dev.platform,
        "device_kind": dev.device_kind,
    }


def bench_serving(preset, slots, chunk, n_requests, prompt_range,
                  new_range, cache_len, baseline, seed,
                  draft_preset="", speculative_k=0, overlap_ab=True,
                  kv_int8=False, reps=3):
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tensorflow_train_distributed_tpu.models.generate import generate
    from tensorflow_train_distributed_tpu.models.llama import (
        LLAMA_PRESETS, LlamaModel,
    )
    from tensorflow_train_distributed_tpu.serving import ServingEngine

    cfg = LLAMA_PRESETS[preset]
    if kv_int8:
        # int8 paged/per-slot KV cache: half the cache bytes per decode
        # step — params are layout-independent, so the same tree serves.
        cfg = dataclasses.replace(cfg, kv_cache_int8=True)
    params = LlamaModel(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    reqs = _requests(n_requests, *prompt_range, *new_range,
                     min(cfg.vocab_size, 30_000), seed)
    gen_tokens = sum(m for _, m in reqs)

    draft_cfg = draft_params = None
    if draft_preset == "self":
        # Acceptance CEILING: the target drafts for itself (p == q, all
        # drafts accepted) — measures the speculative machinery's best
        # case and its mechanical overhead; pair with a random-init
        # draft (the floor) to bracket real trained drafts.
        draft_cfg, draft_params = cfg, params
    elif draft_preset:
        draft_cfg = LLAMA_PRESETS[draft_preset]
        if kv_int8:
            # The draft's caches quantize in lockstep with the
            # target's (the tools/serve.py --kv-int8 rule) — a
            # '_kv8'-named record must not secretly serve an fp-KV
            # draft.  The 'self' branch above shares cfg, already
            # replaced.
            draft_cfg = dataclasses.replace(draft_cfg,
                                            kv_cache_int8=True)
        draft_params = LlamaModel(draft_cfg).init(
            jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]

    def make_engine(overlap):
        return ServingEngine(
            cfg, params, slots=slots, chunk=chunk, cache_len=cache_len,
            draft_config=draft_cfg, draft_params=draft_params,
            speculative_k=speculative_k if draft_cfg else 0,
            overlap=overlap)

    def warm(overlap):
        # ONE engine for warmup + timed runs: the jitted programs are
        # keyed on the engine instance (static self), so a fresh engine
        # would pay every compile again inside the timed region.
        # run()/serve_step are reentrant (tests/test_serving.py) —
        # stale slot caches cannot contaminate.
        e = make_engine(overlap)
        for p, m in reqs:                          # warmup: compiles
            e.submit(p, m)
        e.run()
        return e

    def one_pass(e):
        # Zero the accounting per pass so the committed ratio
        # describes the best pass's window only.
        for k in e.overlap_stats:
            e.overlap_stats[k] = 0 if isinstance(
                e.overlap_stats[k], int) else 0.0
        rec = _run_engine_timed(e, reqs)
        return rec + (dict(e.overlap_stats), e.overlap_ratio())

    def summarize(best):
        wall, ttfts, itls, total, stats, ratio = best
        return {
            "tokens_per_sec": round(gen_tokens / wall, 1),
            "wall_s": round(wall, 3),
            "ttft_ms_p50": round(1e3 * _percentile(ttfts, 0.5), 2),
            "inter_token_ms_mean": round(
                1e3 * sum(itls) / len(itls), 3) if itls else 0.0,
            "overlap_ratio": round(ratio, 3),
            "overlapped_harvests": stats["overlapped_harvests"],
        }, total

    # Best-of-``reps``, with the A/B legs INTERLEAVED (on, off, on,
    # off, ...): single-pass walls on a shared/loaded host are noisy at
    # these scales, min-wall reads through scheduler noise, and
    # alternating the legs keeps slow drift in background load from
    # biasing whichever leg runs later.
    eng = warm(overlap=True)
    eng_off = warm(overlap=False) if overlap_ab else None
    best_on = best_off = None
    for _ in range(max(1, reps)):
        rec = one_pass(eng)
        if best_on is None or rec[0] < best_on[0]:
            best_on = rec
        if eng_off is not None:
            rec = one_pass(eng_off)
            if best_off is None or rec[0] < best_off[0]:
                best_off = rec
    on_rec, total_len = summarize(best_on)
    dt = on_rec["wall_s"]
    dev = jax.devices()[0]
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    rows = cache_len or cfg.max_positions
    mbu_of = lambda tps: decode_mbu_fields(  # noqa: E731 (leg helper)
        cfg, n_params, slots, rows, tps, kv_int8)
    # Ceiling ('self') and floor (random-init) runs must be
    # distinguishable by metric name alone, not just the draft_preset
    # field — and int8-KV runs by the _kv8 suffix (the bench_lm
    # convention).
    name = (f"{preset}_serving_engine_spec_{draft_preset}"
            if draft_preset else f"{preset}_serving_engine")
    if kv_int8:
        name += "_kv8"
    rec = {
        "metric": f"{name}_tokens_per_sec",
        "value": on_rec["tokens_per_sec"],
        "unit": "generated tokens/sec",
        "wall_s": dt,
        "ttft_ms_p50": on_rec["ttft_ms_p50"],
        "inter_token_ms_mean": on_rec["inter_token_ms_mean"],
        "overlap_ratio": on_rec["overlap_ratio"],
        "overlapped_harvests": on_rec["overlapped_harvests"],
        "slots": slots,
        "chunk": chunk,
        "n_requests": n_requests,
        "gen_tokens": gen_tokens,
        "total_tokens_out": total_len,
        "fused_attn": eng.fused_attn(),
        "backend": dev.platform,
        "device_kind": dev.device_kind,
    }
    rec.update(mbu_of(on_rec["tokens_per_sec"]))
    if kv_int8:
        rec["kv_cache"] = "int8"
        rec["kv_pool_bytes"] = eng.kv_pool_bytes()
    if overlap_ab:
        # The OFF leg: the synchronous path the TTD_NO_OVERLAP kill
        # switch restores — the host-stall A/B the headline claims.
        off_rec, _ = summarize(best_off)
        off_rec.update(mbu_of(off_rec["tokens_per_sec"]))
        rec["no_overlap"] = off_rec
        if off_rec["wall_s"]:
            rec["overlap_speedup"] = round(
                off_rec["wall_s"] / dt, 3) if dt else 0.0
    if draft_preset:
        rec["draft_preset"] = draft_preset
        rec["speculative_k"] = speculative_k
        s = eng.spec_stats
        if s["slot_rounds"]:
            # Fraction of drafted tokens accepted: each ACTIVE slot in a
            # round drafts k tokens (slot_rounds, not engine rounds).
            rec["acceptance_rate"] = round(
                s["drafted_accepted"] / (s["slot_rounds"]
                                         * speculative_k), 3)
    if baseline:
        def run_static():
            done = 0
            for i in range(0, len(reqs), slots):
                grp = reqs[i:i + slots]
                plen = max(len(p) for p, _ in grp)
                mnew = max(m for _, m in grp)
                if mnew == 0:
                    continue
                batch = np.zeros((len(grp), plen), np.int32)
                for j, (p, _) in enumerate(grp):
                    batch[j, plen - len(p):] = p  # left-pad: keeps the
                    # last prompt token at the shared final position so
                    # one batched generate covers the group.  The pad
                    # zeros are treated as real context (positions start
                    # at 0), so baseline OUTPUTS are not valid
                    # generations — the baseline is FLOP/timing-
                    # equivalent only, which is all the A/B compares.
                out = generate(cfg, params, jnp.asarray(batch), mnew)
                done += int(np.asarray(out).shape[1]) * len(grp)
            return done

        run_static()                               # warmup
        t0 = time.perf_counter()
        run_static()
        dt_static = time.perf_counter() - t0
        rec["static_batch_wall_s"] = round(dt_static, 3)
        rec["static_batch_tokens_per_sec"] = round(gen_tokens / dt_static, 1)
        rec["engine_speedup"] = round(dt_static / dt, 3)
    return rec


def bench_spec_adaptive_ab(preset, draft_preset, slots, chunk,
                           n_requests, prompt_range, new_range,
                           cache_len, seed, depths=(0, 2, 4, 8),
                           reps=3, wide_d_model=0):
    """The acceptance-adaptive speculation A/B: adaptive depth
    (``spec_depths`` buckets + DepthController) vs every FIXED depth
    in the bucket set, on a MIXED workload no single fixed depth can
    win — an easy phase (high-acceptance cheap draft: deep k
    amortizes target steps) plus a hard phase (random-init draft:
    acceptance ~0, every drafted token is wasted work and k=0 is
    optimal).  A fixed depth is tuned for one phase and pays on the
    other; the controller should ride each phase at its optimum, so
    the bar is adaptive ~= best fixed (<= 2% behind) AND >= 1.15x the
    worst fixed.

    Speculation only pays when the draft step is much cheaper than
    the target step, so the TARGET here is the preset deepened 4x
    with the upper residual blocks' output projections ZEROED — every
    upper block is x + 0 (an exact identity), so the deep model
    computes the preset's function at 4x the preset's per-step cost.
    The easy draft is the target's first quarter SHARING its weights:
    same logits, ~unit acceptance, ~1/4 the step cost — a synthetic
    stand-in for a well-trained draft (the 'self'/random bracket
    bench_serving documents, collapsed to its interesting corner).
    The hard draft is the same small config randomly initialized.

    Each policy gets TWO engines (one per phase — the phase is a
    property of the draft model, not the requests) warmed on its own
    phase's requests, so the adaptive engines compile their depth
    buckets outside the timed region (the hard engine walks
    deepest->0 during warmup; the easy engine never leaves the
    deepest bucket).  The fixed-0 comparator is a draft-free engine —
    plain decode, the honest 'no speculation' leg.

    Noise discipline: per ROUND, every policy runs its full mixed
    pass back-to-back, with policy order alternating between rounds;
    the headline is the MEDIAN over rounds of the per-round wall
    ratio adaptive/best-fixed (best fixed = the depth with the lowest
    median wall)."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from tensorflow_train_distributed_tpu.models.llama import (
        LLAMA_PRESETS, LlamaModel,
    )
    from tensorflow_train_distributed_tpu.serving import ServingEngine

    draft_cfg = LLAMA_PRESETS[draft_preset or preset]
    if wide_d_model:
        # CPU-leg sizing: widen the preset until the target's weights
        # spill the last-level cache — decode goes weight-streaming
        # (bandwidth) bound, which is the regime where a multi-position
        # verify costs ~one step and speculation pays at all.  TPU
        # presets are already there; the tiny CPU preset is not.
        draft_cfg = dataclasses.replace(
            draft_cfg, d_model=wide_d_model,
            ffn_size=wide_d_model * 11 // 4,
            num_heads=8, num_kv_heads=4)
    cfg = dataclasses.replace(draft_cfg,
                              num_layers=4 * draft_cfg.num_layers)
    params = LlamaModel(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]

    def _zero_upper(path, leaf):
        # Upper blocks become exact identities: zero the residual
        # output projections (attention/out, mlp/wo), so the block
        # adds exact 0.0 to the stream.
        keys = [str(getattr(k, "key", k)) for k in path]
        if (keys and keys[0].startswith("layer_")
                and int(keys[0][len("layer_"):]) >= draft_cfg.num_layers
                and ("out" in keys or "wo" in keys)):
            return jnp.zeros_like(leaf)
        return leaf

    params = jax.tree_util.tree_map_with_path(_zero_upper, params)
    # Easy draft = the target's first quarter, sharing its weights —
    # the identity upper blocks make its logits the target's logits.
    easy_draft_params = {
        k: params[k] for k in
        ["token_embed", "final_norm", "lm_head"]
        + [f"layer_{i}" for i in range(draft_cfg.num_layers)]}
    bad_draft_params = LlamaModel(draft_cfg).init(
        jax.random.PRNGKey(7), jnp.zeros((1, 8), jnp.int32))["params"]
    vocab = min(cfg.vocab_size, 30_000)
    easy_reqs = _requests(n_requests, *prompt_range, *new_range,
                          vocab, seed)
    hard_reqs = _requests(n_requests, *prompt_range, *new_range,
                          vocab, seed + 1)
    gen_tokens = sum(m for _, m in easy_reqs + hard_reqs)
    deepest = max(depths)

    def make(policy, regime):
        d_cfg, d_params = ((draft_cfg, easy_draft_params)
                           if regime == "easy"
                           else (draft_cfg, bad_draft_params))
        if policy == "adaptive":
            kw = dict(speculative_k=deepest, spec_depths=depths)
        elif policy == 0:
            d_cfg = d_params = None               # plain decode
            kw = dict(speculative_k=0)
        else:
            kw = dict(speculative_k=policy)
        eng = ServingEngine(cfg, params, slots=slots, chunk=chunk,
                            cache_len=cache_len, draft_config=d_cfg,
                            draft_params=d_params, **kw)
        reqs = easy_reqs if regime == "easy" else hard_reqs
        for pr, m in reqs:                        # warmup: compiles
            eng.submit(pr, m)
        eng.run()
        return eng

    policies = ["adaptive"] + [int(k) for k in depths]
    engines = {p: {r: make(p, r) for r in ("easy", "hard")}
               for p in policies}
    walls = {p: [] for p in policies}
    for i in range(max(1, reps)):
        order = policies if i % 2 == 0 else list(reversed(policies))
        for pol in order:
            w = (_run_engine_timed(engines[pol]["easy"], easy_reqs)[0]
                 + _run_engine_timed(engines[pol]["hard"], hard_reqs)[0])
            walls[pol].append(w)

    def median(xs):
        xs = sorted(xs)
        return xs[len(xs) // 2]

    fixed = {k: median(walls[k]) for k in depths}
    best_k = min(fixed, key=fixed.get)
    worst_k = max(fixed, key=fixed.get)
    vs_best = sorted(a / b for a, b in
                     zip(walls["adaptive"], walls[best_k]))
    vs_worst = sorted(b / a for a, b in
                      zip(walls["adaptive"], walls[worst_k]))
    tele = {r: engines["adaptive"][r].spec_telemetry()
            for r in ("easy", "hard")}
    dev = jax.devices()[0]
    return {
        "metric": f"{preset}_serving_spec_adaptive_wall_ratio",
        "value": round(median(vs_best), 4),
        "unit": "x wall, adaptive depth vs best fixed depth on the "
                "mixed easy/hard workload (median of per-round wall "
                "ratios; <= 1.02 = within 2% of best fixed)",
        "vs_worst_fixed_speedup": round(median(vs_worst), 4),
        "best_fixed_k": best_k,
        "worst_fixed_k": worst_k,
        "depths": list(depths),
        "pair_wall_ratios_vs_best": [round(r, 4) for r in vs_best],
        "pair_wall_ratios_vs_worst": [round(r, 4) for r in vs_worst],
        "per_policy": {
            str(p): {
                "wall_s_median": round(median(walls[p]), 3),
                "tokens_per_sec": round(
                    gen_tokens / median(walls[p]), 1),
            } for p in policies},
        "adaptive_depth_rounds": {
            r: {str(d): v["rounds"]
                for d, v in tele[r].get("per_depth", {}).items()}
            for r in tele},
        "adaptive_switches": {
            r: tele[r].get("switches", 0) for r in tele},
        "slots": slots,
        "chunk": chunk,
        "n_requests_per_phase": n_requests,
        "gen_tokens": gen_tokens,
        "reps": reps,
        "wide_d_model": wide_d_model,
        "target_layers": cfg.num_layers,
        "draft_layers": draft_cfg.num_layers,
        "backend": dev.platform,
        "device_kind": dev.device_kind,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--preset", default="llama_125m")
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--chunk", type=int, default=8)
    p.add_argument("--requests", type=int, default=32)
    p.add_argument("--prompt-range", default="16,120",
                   help="lo,hi inclusive prompt lengths")
    p.add_argument("--new-range", default="16,128",
                   help="lo,hi inclusive max_new_tokens")
    p.add_argument("--cache-len", type=int, default=0,
                   help="0 -> config.max_positions")
    p.add_argument("--baseline", action="store_true",
                   help="also time the static-batch generate path")
    p.add_argument("--speculative-draft", default="",
                   help="llama preset for a draft model: speculative "
                        "serving A/B (random-init draft = the "
                        "acceptance FLOOR; 'self' = the target drafts "
                        "for itself, the acceptance CEILING — the pair "
                        "brackets real trained drafts)")
    p.add_argument("--speculative-k", type=int, default=4)
    p.add_argument("--no-ab", action="store_true",
                   help="skip the overlap-OFF leg of the async-decode "
                        "pipelining A/B (halves the timed work)")
    p.add_argument("--mixed", action="store_true",
                   help="mixed long/short workload instead of the "
                        "throughput run: fill the lanes with short "
                        "decoders, inject one LONG prompt mid-stream, "
                        "and A/B interleaved prefill ON vs the atomic-"
                        "admission kill switch — reports active lanes' "
                        "p99 inter-token latency during the admission "
                        "plus the injected requests' TTFTs")
    p.add_argument("--shared-prefix", action="store_true",
                   help="paged-KV prefix-sharing A/B instead of the "
                        "throughput run: every request shares one "
                        "long system prompt (--prefix-len) + a "
                        "distinct tail, paged radix sharing vs the "
                        "linear cache, leg-order-alternating pairs; "
                        "plus a disjoint-prompt pair pinning the "
                        "no-regression guard (committed record: "
                        "profiles/bench/paged_kv_ab.jsonl)")
    p.add_argument("--prefix-len", type=int, default=96,
                   help="--shared-prefix only: shared system prompt "
                        "length in tokens")
    p.add_argument("--kv-block-size", type=int, default=16,
                   help="--shared-prefix / --fused-ab: paged-KV block "
                        "size")
    p.add_argument("--fused-ab", action="store_true",
                   help="fused paged-attention A/B instead of the "
                        "throughput run: fused kernel vs the "
                        "TTD_NO_FUSED_ATTN XLA block-gather leg, int8 "
                        "KV pool vs fp, and the --sweep-slots capacity "
                        "growth curve — tok/s + mbu_pct per leg "
                        "(committed record: "
                        "profiles/bench/fused_attn_ab.jsonl)")
    p.add_argument("--sweep-slots", default="",
                   help="--fused-ab only: comma-separated slot counts "
                        "for the capacity-growth sweep (each point "
                        "sizes the int8 pool to slots * "
                        "ceil(cache_len/block_size)); default: "
                        "slots,2*slots")
    p.add_argument("--kv-int8", action="store_true",
                   help="throughput run with the int8 KV cache "
                        "(kv_cache_int8 config): half the cache bytes "
                        "per decode step; metric name gains the _kv8 "
                        "suffix")
    p.add_argument("--trace-ab", action="store_true",
                   help="flight-recorder overhead A/B instead of the "
                        "throughput run: identical passes with the "
                        "recorder on (the always-on default) vs "
                        "TTD_NO_TRACE=1, reporting the tok/s overhead "
                        "percentage (committed record: "
                        "profiles/bench/trace_overhead_ab.jsonl)")
    p.add_argument("--trace-fleet-ab", action="store_true",
                   help="FLEET observability overhead A/B: a parent + "
                        "subprocess-worker pool serving with clock "
                        "sync, event relay, and the crash-durable "
                        "trace spool armed everywhere vs "
                        "TTD_NO_TRACE=1 + TTD_NO_CLOCK_SYNC=1 and no "
                        "spool (committed record: "
                        "profiles/bench/trace_fleet_ab.jsonl)")
    p.add_argument("--fleet-replicas", type=int, default=2,
                   help="--trace-fleet-ab only: subprocess workers "
                        "per pool leg")
    p.add_argument("--spec-adaptive-ab", action="store_true",
                   help="acceptance-adaptive speculation A/B instead "
                        "of the throughput run: adaptive depth vs "
                        "every fixed depth in --spec-depths, on a "
                        "mixed easy (self-draft) / hard (random-init "
                        "draft) workload no single fixed depth wins "
                        "(committed record: "
                        "profiles/bench/spec_adaptive_ab.jsonl)")
    p.add_argument("--spec-depths", default="0,2,4,8",
                   help="--spec-adaptive-ab only: comma-separated "
                        "depth buckets (also the fixed comparator "
                        "set)")
    p.add_argument("--spec-d-model", type=int, default=0,
                   help="--spec-adaptive-ab only: widen the preset to "
                        "this d_model so decode is weight-streaming "
                        "bound (the CPU leg's sizing; 0 = preset "
                        "unchanged, the TPU leg)")
    p.add_argument("--prefill-chunk", type=int, default=16,
                   help="--mixed only: prefill piece size (one budget "
                        "installment)")
    p.add_argument("--long-pieces", type=int, default=6,
                   help="--mixed only: budget installments the long "
                        "prompt spans (its length = pieces * "
                        "prefill_chunk)")
    p.add_argument("--reps", type=int, default=3,
                   help="timed passes per leg; min wall is reported "
                        "(reads through host scheduler noise)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--platform", default="",
                   help="force a jax platform ('cpu' for smoke runs)")
    args = p.parse_args(argv)
    if args.platform:
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            force_platform,
        )

        force_platform(args.platform)
    if args.platform and args.platform != "tpu":
        cm = contextlib.nullcontext()
    else:
        from tensorflow_train_distributed_tpu.runtime.chip_lock import (
            chip_lock,
        )

        cm = chip_lock()
    prompt_range = tuple(int(x) for x in args.prompt_range.split(","))
    new_range = tuple(int(x) for x in args.new_range.split(","))
    try:
        with cm:
            if args.mixed:
                rec = bench_serving_mixed(
                    args.preset, args.slots, args.chunk,
                    args.cache_len or None, args.seed,
                    args.prefill_chunk, args.long_pieces,
                    reps=args.reps)
            elif args.shared_prefix:
                rec = bench_paged_kv_ab(
                    args.preset, args.slots, args.chunk, args.requests,
                    args.prefix_len, args.cache_len or None, args.seed,
                    args.kv_block_size, reps=args.reps)
            elif args.trace_ab:
                rec = bench_trace_ab(args.preset, args.slots, args.chunk,
                                     args.requests, prompt_range,
                                     new_range, args.cache_len or None,
                                     args.seed, reps=args.reps)
            elif args.trace_fleet_ab:
                rec = bench_trace_fleet_ab(
                    args.preset, args.slots, args.chunk,
                    args.requests, prompt_range, new_range,
                    args.cache_len or None, args.seed,
                    reps=args.reps, replicas=args.fleet_replicas)
            elif args.spec_adaptive_ab:
                depths = tuple(int(x)
                               for x in args.spec_depths.split(","))
                draft = (args.speculative_draft
                         if args.speculative_draft != "self" else "")
                rec = bench_spec_adaptive_ab(
                    args.preset, draft, args.slots, args.chunk,
                    args.requests, prompt_range, new_range,
                    args.cache_len or None, args.seed, depths,
                    reps=args.reps, wide_d_model=args.spec_d_model)
            elif args.fused_ab:
                sweep = ([int(s) for s in args.sweep_slots.split(",")]
                         if args.sweep_slots
                         else [args.slots, 2 * args.slots])
                rec = bench_fused_attn_ab(
                    args.preset, args.slots, args.chunk, args.requests,
                    prompt_range, new_range, args.cache_len or None,
                    args.seed, args.kv_block_size, sweep,
                    reps=args.reps)
            else:
                rec = bench_serving(args.preset, args.slots, args.chunk,
                                    args.requests, prompt_range,
                                    new_range,
                                    args.cache_len or None,
                                    args.baseline,
                                    args.seed,
                                    draft_preset=args.speculative_draft,
                                    speculative_k=args.speculative_k,
                                    overlap_ab=not args.no_ab,
                                    kv_int8=args.kv_int8,
                                    reps=args.reps)
    except Exception as e:
        if args.mixed:
            metric = f"{args.preset}_serving_mixed_p99_inter_token_ms"
            unit = "ms p99 active-lane inter-token during long admission"
        elif args.shared_prefix:
            metric = (f"{args.preset}_serving_paged_kv_shared_prefix_"
                      f"ttft_improvement")
            unit = "x TTFT p50, shared-prefix paged vs linear"
        elif args.trace_ab:
            metric = f"{args.preset}_serving_trace_overhead_pct"
            unit = "% tok/s lost, flight recorder on vs TTD_NO_TRACE=1"
        elif args.trace_fleet_ab:
            metric = f"{args.preset}_serving_trace_fleet_overhead_pct"
            unit = ("% tok/s lost, clock sync + relay + spool armed "
                    "fleet-wide vs all kill switches")
        elif args.spec_adaptive_ab:
            metric = f"{args.preset}_serving_spec_adaptive_wall_ratio"
            unit = "x wall, adaptive depth vs best fixed depth"
        elif args.fused_ab:
            metric = f"{args.preset}_serving_fused_attn_wall_ratio"
            unit = ("x wall, XLA block-gather leg vs fused "
                    "paged-attention leg")
        else:
            name = (f"{args.preset}_serving_engine_spec"
                    if args.speculative_draft
                    else f"{args.preset}_serving_engine")
            metric, unit = f"{name}_tokens_per_sec", "generated tokens/sec"
        print(json.dumps({
            "metric": metric, "value": 0.0, "unit": unit,
            "error": f"{type(e).__name__}: {e}"}), flush=True)
        return 1
    print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
