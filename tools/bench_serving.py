"""Continuous-batching engine throughput vs static-batch generate.

Serves a mixed-length synthetic request stream through
``serving.ServingEngine`` (slot-refill decode) and reports GENERATED
tokens/sec.  ``--baseline`` also times the static-batch path the engine
replaces — same requests grouped into arrival-order batches of
``--slots``, each batch padded to its longest prompt and decoded for its
largest max_new (what ``generate()`` forces) — so the engine's win IS
the padding/straggler waste it removes.

Prints one JSON line per run (bench_lm.py conventions).
"""

import argparse
import contextlib
import json
import os
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))


def _requests(n, plo, phi, glo, ghi, vocab, seed):
    import numpy as np

    rng = np.random.default_rng(seed)
    return [(list(rng.integers(1, vocab, int(rng.integers(plo, phi + 1)))),
             int(rng.integers(glo, ghi + 1))) for _ in range(n)]


def bench_serving(preset, slots, chunk, n_requests, prompt_range,
                  new_range, cache_len, baseline, seed,
                  draft_preset="", speculative_k=0):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tensorflow_train_distributed_tpu.models.generate import generate
    from tensorflow_train_distributed_tpu.models.llama import (
        LLAMA_PRESETS, LlamaModel,
    )
    from tensorflow_train_distributed_tpu.serving import ServingEngine

    cfg = LLAMA_PRESETS[preset]
    params = LlamaModel(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    reqs = _requests(n_requests, *prompt_range, *new_range,
                     min(cfg.vocab_size, 30_000), seed)
    gen_tokens = sum(m for _, m in reqs)

    draft_cfg = draft_params = None
    if draft_preset == "self":
        # Acceptance CEILING: the target drafts for itself (p == q, all
        # drafts accepted) — measures the speculative machinery's best
        # case and its mechanical overhead; pair with a random-init
        # draft (the floor) to bracket real trained drafts.
        draft_cfg, draft_params = cfg, params
    elif draft_preset:
        draft_cfg = LLAMA_PRESETS[draft_preset]
        draft_params = LlamaModel(draft_cfg).init(
            jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]
    # ONE engine for warmup + timed runs: the jitted programs are keyed
    # on the engine instance (static self), so a fresh engine would pay
    # every compile again inside the timed region.  run() is reentrant
    # (tests/test_serving.py) — stale slot caches cannot contaminate.
    eng = ServingEngine(cfg, params, slots=slots, chunk=chunk,
                        cache_len=cache_len, draft_config=draft_cfg,
                        draft_params=draft_params,
                        speculative_k=speculative_k if draft_cfg else 0)

    def run_engine():
        for p, m in reqs:
            eng.submit(p, m)
        out = eng.run()
        # Materialize (run() already fetched host-side token lists).
        return sum(len(v) for v in out.values())

    run_engine()                                   # warmup: compiles
    t0 = time.perf_counter()
    total_len = run_engine()
    dt = time.perf_counter() - t0
    dev = jax.devices()[0]
    # Ceiling ('self') and floor (random-init) runs must be
    # distinguishable by metric name alone, not just the draft_preset
    # field.
    name = (f"{preset}_serving_engine_spec_{draft_preset}"
            if draft_preset else f"{preset}_serving_engine")
    rec = {
        "metric": f"{name}_tokens_per_sec",
        "value": round(gen_tokens / dt, 1),
        "unit": "generated tokens/sec",
        "wall_s": round(dt, 3),
        "slots": slots,
        "chunk": chunk,
        "n_requests": n_requests,
        "gen_tokens": gen_tokens,
        "total_tokens_out": total_len,
        "backend": dev.platform,
        "device_kind": dev.device_kind,
    }
    if draft_preset:
        rec["draft_preset"] = draft_preset
        rec["speculative_k"] = speculative_k
        s = eng.spec_stats
        if s["slot_rounds"]:
            # Fraction of drafted tokens accepted: each ACTIVE slot in a
            # round drafts k tokens (slot_rounds, not engine rounds).
            rec["acceptance_rate"] = round(
                s["drafted_accepted"] / (s["slot_rounds"]
                                         * speculative_k), 3)
    if baseline:
        def run_static():
            done = 0
            for i in range(0, len(reqs), slots):
                grp = reqs[i:i + slots]
                plen = max(len(p) for p, _ in grp)
                mnew = max(m for _, m in grp)
                if mnew == 0:
                    continue
                batch = np.zeros((len(grp), plen), np.int32)
                for j, (p, _) in enumerate(grp):
                    batch[j, plen - len(p):] = p  # left-pad: keeps the
                    # last prompt token at the shared final position so
                    # one batched generate covers the group.  The pad
                    # zeros are treated as real context (positions start
                    # at 0), so baseline OUTPUTS are not valid
                    # generations — the baseline is FLOP/timing-
                    # equivalent only, which is all the A/B compares.
                out = generate(cfg, params, jnp.asarray(batch), mnew)
                done += int(np.asarray(out).shape[1]) * len(grp)
            return done

        run_static()                               # warmup
        t0 = time.perf_counter()
        run_static()
        dt_static = time.perf_counter() - t0
        rec["static_batch_wall_s"] = round(dt_static, 3)
        rec["static_batch_tokens_per_sec"] = round(gen_tokens / dt_static, 1)
        rec["engine_speedup"] = round(dt_static / dt, 3)
    return rec


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--preset", default="llama_125m")
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--chunk", type=int, default=8)
    p.add_argument("--requests", type=int, default=32)
    p.add_argument("--prompt-range", default="16,120",
                   help="lo,hi inclusive prompt lengths")
    p.add_argument("--new-range", default="16,128",
                   help="lo,hi inclusive max_new_tokens")
    p.add_argument("--cache-len", type=int, default=0,
                   help="0 -> config.max_positions")
    p.add_argument("--baseline", action="store_true",
                   help="also time the static-batch generate path")
    p.add_argument("--speculative-draft", default="",
                   help="llama preset for a draft model: speculative "
                        "serving A/B (random-init draft = the "
                        "acceptance FLOOR; 'self' = the target drafts "
                        "for itself, the acceptance CEILING — the pair "
                        "brackets real trained drafts)")
    p.add_argument("--speculative-k", type=int, default=4)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--platform", default="",
                   help="force a jax platform ('cpu' for smoke runs)")
    args = p.parse_args(argv)
    if args.platform:
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            force_platform,
        )

        force_platform(args.platform)
    if args.platform and args.platform != "tpu":
        cm = contextlib.nullcontext()
    else:
        from tensorflow_train_distributed_tpu.runtime.chip_lock import (
            chip_lock,
        )

        cm = chip_lock()
    prompt_range = tuple(int(x) for x in args.prompt_range.split(","))
    new_range = tuple(int(x) for x in args.new_range.split(","))
    try:
        with cm:
            rec = bench_serving(args.preset, args.slots, args.chunk,
                                args.requests, prompt_range, new_range,
                                args.cache_len or None, args.baseline,
                                args.seed,
                                draft_preset=args.speculative_draft,
                                speculative_k=args.speculative_k)
    except Exception as e:
        name = (f"{args.preset}_serving_engine_spec"
                if args.speculative_draft
                else f"{args.preset}_serving_engine")
        print(json.dumps({
            "metric": f"{name}_tokens_per_sec",
            "value": 0.0, "unit": "generated tokens/sec",
            "error": f"{type(e).__name__}: {e}"}), flush=True)
        return 1
    print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
