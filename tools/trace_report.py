"""Render a flight-recorder dump: stage latencies + request waterfalls.

The offline face of ``runtime.events`` (the always-on span/instant ring
buffer), in the same spirit as ``tools/profile_summary.py`` for XPlane
captures: given a Chrome-trace JSON — fetched from a live gateway's
``GET /debug/trace?last_s=N`` or written by ``Recorder.save()`` — it
answers "where did the time go" (a per-stage latency table over span
names: count, mean, p50, p99, max) and "what happened to request X"
(``--request N``: that request's admission→prefill→decode→retire
waterfall, the offline twin of ``GET /v1/requests/<id>``).
``--requests`` lists every request id in the window with its terminal
status, and ``--journal supervisor.jsonl`` appends the supervisor's
attempt timeline so relaunches are part of the same report.

Usage:
  curl -s 'localhost:8000/debug/trace?last_s=300' > /tmp/trace.json
  python tools/trace_report.py /tmp/trace.json
  python tools/trace_report.py /tmp/trace.json --request 17
  python tools/trace_report.py /tmp/trace.json --requests \
      --journal /ckpt/supervisor.jsonl

Fleet observability (PR 20) adds two more faces:

- ``--fleet`` renders the CROSS-WORKER view of the same trace: the
  parent's ring already holds every proc/TCP worker's relayed events,
  offset-corrected by the PING/PONG clock sync and tagged
  ``replica=``/``clock_conf_s=`` — this groups them into per-replica
  lanes, prints each replica's clock-sync quality (from the export's
  ``otherData.fleet``), and measures every prefill→decode KV-handoff
  hop (``handoff/export`` span end → ``handoff/install`` span start)
  plus migration/failover hops.  With ``--request N`` the waterfall
  gains a lane column, so a disaggregated request reads top-to-bottom
  across the fleet.
- ``--post-mortem DIR`` reconstructs the last seconds before a death
  from a ``TTD_TRACE_SPOOL`` directory: each process's rotating JSONL
  segments (wall-anchored per segment header) joined with the parent's
  ``corpse-*.json`` snapshots (exit reason, clock offset, the last
  relayed events) and optionally ``--journal`` — the waterfall a
  SIGKILLed worker can no longer serve from ``/debug/trace``.

(The JSON itself also loads directly in Perfetto / chrome://tracing —
this tool is for terminals and incident notes.)
"""

from __future__ import annotations

import argparse
import collections
import glob
import json
import os
import sys


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[i]


def load_events(path: str) -> list:
    with open(path) as f:
        obj = json.load(f)
    evs = obj["traceEvents"] if isinstance(obj, dict) else obj
    if not isinstance(evs, list):
        raise SystemExit(f"{path}: not a Chrome trace (no traceEvents)")
    return evs


def load_other(path: str) -> dict:
    """The export's ``otherData`` (fleet states, roofline snapshot,
    spool status) — empty for bare event-array dumps."""
    with open(path) as f:
        obj = json.load(f)
    return dict(obj.get("otherData") or {}) if isinstance(obj, dict) \
        else {}


def stage_table(evs: list) -> list:
    """(name, count, total_ms, mean_ms, p50_ms, p99_ms, max_ms) per
    span name, busiest first."""
    durs = collections.defaultdict(list)
    for e in evs:
        if e.get("ph") == "X":
            durs[e["name"]].append(e.get("dur", 0.0) / 1e3)
    rows = []
    for name, ds in durs.items():
        ds.sort()
        total = sum(ds)
        rows.append((name, len(ds), total, total / len(ds),
                     _percentile(ds, 0.5), _percentile(ds, 0.99),
                     ds[-1]))
    rows.sort(key=lambda r: -r[2])
    return rows


def instant_counts(evs: list) -> list:
    counts = collections.Counter(
        e["name"] for e in evs if e.get("ph") == "i")
    return counts.most_common()


def kv_cache_summary(evs: list) -> dict:
    """Paged-KV cache economics from the engine's flight-recorder
    events: ``kv/alloc`` spans land in the stage table like any other
    stage; this folds the instants' args into totals — prefix-hit
    count + tokens saved (prefill compute skipped), blocks evicted
    under pressure, and admissions refused for want of blocks — plus
    how many decode dispatches ran the FUSED paged-attention kernel
    (the ``decode/dispatch`` span's ``fused`` tag: the engine records
    at each dispatch whether its programs were compiled with
    ``ops.pallas_kernels.paged_attention`` or the XLA block-gather
    A/B leg).  Empty dict when the window has no paged-KV events
    (linear cache)."""
    out = {"prefix_hits": 0, "prefix_hit_tokens": 0,
           "evicted_blocks": 0, "refused_admissions": 0,
           "fused_attn_dispatches": 0}
    seen = False
    for e in evs:
        name = e.get("name", "")
        args = e.get("args") or {}
        if name == "decode/dispatch" and args.get("fused"):
            out["fused_attn_dispatches"] += 1
            seen = True
            continue
        if not name.startswith("kv/"):
            continue
        seen = True
        if name == "kv/prefix_hit":
            out["prefix_hits"] += 1
            out["prefix_hit_tokens"] += args.get("tokens", 0)
        elif name == "kv/evict":
            out["evicted_blocks"] += args.get("blocks", 0)
        elif name == "kv/refused":
            out["refused_admissions"] += 1
    return out if seen else {}


def spec_depth_summary(evs: list) -> dict:
    """Speculative-depth timeline from the ``decode/dispatch`` spans'
    ``spec_k`` arg (the depth the engine chose for that round — the
    adaptive controller's decisions, or the constant ``--speculative-k``
    on a fixed engine).  Returns ``{}`` when no dispatch span carries
    ``spec_k`` (pre-adaptive trace).  ``segments`` collapses consecutive
    same-depth rounds into ``(start_ms_rel, depth, rounds)`` rows, so an
    oscillating controller is visible as a long segment list even when
    the per-depth totals look calm."""
    rounds = {}
    segments = []
    t0 = None
    for e in evs:
        if e.get("ph") != "X" or e.get("name") != "decode/dispatch":
            continue
        args = e.get("args") or {}
        if "spec_k" not in args:
            continue
        k = args["spec_k"]
        if t0 is None:
            t0 = e["ts"]
        rounds[k] = rounds.get(k, 0) + 1
        if segments and segments[-1][1] == k:
            segments[-1][2] += 1
        else:
            segments.append([(e["ts"] - t0) / 1e3, k, 1])
    if not rounds:
        return {}
    return {"rounds": rounds, "segments": segments,
            "switches": len(segments) - 1}


def migration_summary(evs: list) -> dict:
    """Live-migration economics from the pool's flight-recorder
    instants: every ``request/migrate`` hop (who moved where, at which
    token, how many KV bytes rode the MIGRATE frame), plus drain-time
    ``replica/evacuate`` and ``pool/defragment`` roll-ups — the
    "did the drain actually move my streams" answer next to the KV
    table's "did prefix caching engage".  Empty when the window has no
    migration events (single replica, or TTD_NO_MIGRATION=1)."""
    out = {"migrations": 0, "kv_bytes": 0, "warm_tokens": 0,
           "ms": [], "evacuations": 0, "evacuated_lanes": 0,
           "defrag_moves": 0, "hops": []}
    seen = False
    for e in evs:
        name = e.get("name", "")
        args = e.get("args") or {}
        if name == "request/migrate":
            seen = True
            out["migrations"] += 1
            out["kv_bytes"] += args.get("bytes", 0)
            out["warm_tokens"] += args.get("tokens", 0)
            out["ms"].append(args.get("ms", 0.0))
            out["hops"].append((args.get("request_id"),
                                args.get("from_replica"),
                                args.get("to_replica"),
                                args.get("resumed_at"),
                                args.get("bytes", 0)))
        elif name == "replica/evacuate":
            seen = True
            out["evacuations"] += 1
            out["evacuated_lanes"] += args.get("moved", 0)
        elif name == "pool/defragment":
            seen = True
            out["defrag_moves"] += args.get("moved", 0)
    return out if seen else {}


#: The trainer's step sub-spans (grad-quant split step) plus the parent
#: dispatch span — the denominator of the comm fraction.  The bucketed
#: overlap step adds ``train/step_barrier`` (the single host-blocking
#: point replacing the sequential pipeline's per-phase blocking).
_TRAIN_STEP_SPANS = ("train/step_dispatch", "train/grad_fwdbwd",
                     "train/grad_comm", "train/optimizer_apply",
                     "train/step_barrier")


def train_step_summary(evs: list) -> list:
    """Trainer step anatomy with a comm-fraction column.

    Under quantized gradient collectives the trainer's
    ``train/step_dispatch`` span splits into ``train/grad_fwdbwd`` /
    ``train/grad_comm`` / ``train/optimizer_apply`` sub-spans (each a
    blocking dispatch, so durations are device time).  This folds them
    into ``(span, count, total_ms, frac_of_step)`` rows where
    ``frac_of_step`` is the span's share of the step-dispatch total —
    the comm-fraction number the grad-quant A/B
    (``tools/bench_grad_quant.py``) is judged on, visible in any
    ``/debug/trace`` window.  Empty when the window has no grad-comm
    spans (unquantized trainer, or no training).

    Under the bucketed overlap step (``grad_overlap>1``) the comm/apply
    spans carry ``bucket=<i>, buckets=<K>`` attrs and meter DISPATCH
    time only — the blocking device wait collapses into the single
    ``train/step_barrier`` span, so the grad-comm fraction IS the
    realized-overlap number.  Bucket-tagged spans additionally break
    out as ``<span>[bucket=<i>]`` sub-rows under their total."""
    totals: dict = {}
    per_bucket: dict = {}
    for e in evs:
        name = e.get("name", "")
        if e.get("ph") != "X" or name not in _TRAIN_STEP_SPANS:
            continue
        row = totals.setdefault(name, [0, 0.0])
        row[0] += 1
        row[1] += e.get("dur", 0.0) / 1e3
        b = (e.get("args") or {}).get("bucket")
        if b is not None:
            brow = per_bucket.setdefault(name, {}).setdefault(
                int(b), [0, 0.0])
            brow[0] += 1
            brow[1] += e.get("dur", 0.0) / 1e3
    if "train/grad_comm" not in totals:
        return []
    step_ms = totals.get("train/step_dispatch", [0, 0.0])[1]
    if step_ms <= 0:        # engine-level runs without the fit loop
        step_ms = sum(ms for name, (_, ms) in totals.items()
                      if name != "train/step_barrier")
    rows = []
    for name in _TRAIN_STEP_SPANS:
        if name not in totals:
            continue
        n, ms = totals[name]
        rows.append((name, n, ms, (ms / step_ms if step_ms > 0 else 0.0)))
        for b in sorted(per_bucket.get(name, ())):
            bn, bms = per_bucket[name][b]
            rows.append((f"{name}[bucket={b}]", bn, bms,
                         (bms / step_ms if step_ms > 0 else 0.0)))
    return rows


def memory_summary(evs: list) -> dict:
    """Per-pool memory table from the memcheck sanitizer's
    ``memory/<pool>`` spans/instants (``TTD_MEMCHECK=1``): allocations
    charged, peak and last-seen live bytes, the declared budget with
    headroom %, and pre-raise near-misses (``memory/near_miss``
    instants past 90% of budget) — the "where is my HBM" answer the
    paged-KV and compile tables give for blocks and compiles.  Keyed
    by pool name; empty when the window has no memory events
    (sanitizer unarmed)."""
    pools: dict = {}
    for e in evs:
        name = e.get("name", "")
        args = e.get("args") or {}
        if name == "memory/near_miss":
            row = pools.setdefault(args.get("pool", "?"), {
                "allocs": 0, "peak_live": 0, "live": 0, "budget": 0,
                "near_misses": 0})
            row["near_misses"] += 1
            row["budget"] = max(row["budget"], args.get("budget", 0))
            continue
        if not name.startswith("memory/"):
            continue
        pool = args.get("pool") or name[len("memory/"):]
        row = pools.setdefault(pool, {
            "allocs": 0, "peak_live": 0, "live": 0, "budget": 0,
            "near_misses": 0})
        row["allocs"] += 1
        live = args.get("live", args.get("bytes", 0)) or 0
        row["peak_live"] = max(row["peak_live"], live)
        row["live"] = live                 # events are time-ordered
        row["budget"] = max(row["budget"], args.get("budget", 0))
    return pools


def compile_summary(evs: list) -> list:
    """Per-jit-site compilation table from the compilecheck sanitizer's
    ``compile/<site>`` spans (``TTD_COMPILECHECK=1``): how many
    signatures each site compiled in the window and what they cost —
    the "where did my decode step go" answer when the stall WAS a
    recompile.  Empty when the window has no compile spans (sanitizer
    unarmed, or a healthy steady state past warmup)."""
    per: dict = {}
    for e in evs:
        name = e.get("name", "")
        if e.get("ph") != "X" or not name.startswith("compile/"):
            continue
        site = name[len("compile/"):]
        row = per.setdefault(site, [0, 0.0])
        row[0] += 1
        row[1] += e.get("dur", 0.0) / 1e3
    return sorted(((site, n, ms) for site, (n, ms) in per.items()),
                  key=lambda r: -r[2])


def request_ids(evs: list) -> list:
    """(request_id, status) for every gateway request in the window
    (status from its retire instant; 'in-window' when none recorded)."""
    status: dict = {}
    for e in evs:
        args = e.get("args") or {}
        rid = args.get("request_id")
        if rid is None:
            continue
        if e["name"] == "request/retire":
            status[rid] = args.get("status", "?")
        else:
            status.setdefault(rid, "in-window")
    return sorted(status.items())


def request_waterfall(evs: list, request_id: int) -> list:
    """The request's events, driver + engine joined — the same
    latest-admission / rid-window rule as
    ``Recorder.request_timeline`` applied to exported JSON."""
    admit_t = None
    for e in evs:
        if (e["name"] == "request/admitted"
                and (e.get("args") or {}).get("request_id") == request_id):
            admit_t = e["ts"]
    rid = None
    grant_t = retire_t = None
    out = []
    for e in evs:
        args = e.get("args") or {}
        if (args.get("request_id") != request_id
                or (admit_t is not None and e["ts"] < admit_t)):
            continue
        out.append(e)
        if e["name"] == "request/engine_submit" and "rid" in args:
            rid, grant_t = args["rid"], e["ts"]
        if e["name"] == "request/retire":
            retire_t = e["ts"]
    if rid is not None:
        lo = grant_t - 1e3          # ts in microseconds; hi exact (the
        hi = retire_t if retire_t is not None else float("inf")
        #   retire follows every engine event of the request)
        for e in evs:
            args = e.get("args") or {}
            if ("request_id" not in args and args.get("rid") == rid
                    and lo <= e["ts"] <= hi):
                out.append(e)
    out.sort(key=lambda e: e["ts"])
    return out


def print_waterfall(evs: list, request_id: int) -> None:
    wf = request_waterfall(evs, request_id)
    if not wf:
        print(f"request {request_id}: no events in this window")
        return
    t0 = wf[0]["ts"]
    print(f"\n== request {request_id} waterfall "
          f"({len(wf)} events, t=0 at first event)")
    print(f"{'t_ms':>10}  {'dur_ms':>8}  event")
    for e in wf:
        args = dict(e.get("args") or {})
        args.pop("request_id", None)
        dur = f"{e['dur'] / 1e3:8.3f}" if "dur" in e else " " * 8
        extra = ("  " + " ".join(f"{k}={v}" for k, v in args.items())
                 if args else "")
        print(f"{(e['ts'] - t0) / 1e3:10.3f}  {dur}  {e['name']}{extra}")


def fleet_lanes(evs: list) -> dict:
    """Group events into per-replica lanes: ``replica`` from attrs
    (the relay stamps every worker event; pool pump threads stamp the
    parent's per-replica driver events), ``gateway`` for everything
    unstamped.  Each lane reports its event count, span of activity,
    and the worst clock-sync confidence seen (``clock_conf_s`` rides
    every relayed event — None means the lane never crossed a process
    boundary)."""
    lanes: dict = {}
    for e in evs:
        args = e.get("args") or {}
        lane = args.get("replica", "gateway")
        row = lanes.setdefault(str(lane), {
            "events": 0, "t_min": None, "t_max": None,
            "clock_conf_s": None, "relayed": 0})
        row["events"] += 1
        ts = e.get("ts", 0.0)
        row["t_min"] = ts if row["t_min"] is None else min(
            row["t_min"], ts)
        row["t_max"] = ts if row["t_max"] is None else max(
            row["t_max"], ts)
        conf = args.get("clock_conf_s")
        if conf is not None:
            row["relayed"] += 1
            if row["clock_conf_s"] is None or conf > row["clock_conf_s"]:
                row["clock_conf_s"] = conf
    return lanes


def fleet_hops(evs: list) -> list:
    """Every cross-worker hop in the window, measured:

    - ``kv_handoff``: the prefill→decode KV handoff — wire+install
      latency is the gap from the ``handoff/export`` span's END to the
      ``handoff/install`` span's START (both parent-recorded, one
      clock domain, positive by construction) for the same request;
    - ``migrate``: a live lane move (the instant's ``ms`` arg is the
      measured move time);
    - ``failover``: a re-admission on a survivor (no wire latency —
      the dead replica shipped nothing).

    Rows: (kind, request_id, from, to, hop_ms, detail)."""
    exports: dict = {}      # request_id -> (end_ts, prefill_replica)
    hops: list = []
    for e in evs:
        args = e.get("args") or {}
        rid = args.get("request_id")
        name = e.get("name", "")
        if name == "handoff/export" and e.get("ph") == "X":
            exports[rid] = (e["ts"] + e.get("dur", 0.0),
                            args.get("prefill_replica"))
        elif name == "handoff/install" and e.get("ph") == "X":
            exp = exports.get(rid)
            if exp is not None:
                hop_ms = (e["ts"] - exp[0]) / 1e3
                hops.append(("kv_handoff", rid, exp[1],
                             args.get("decode_replica"), hop_ms,
                             f"{args.get('bytes', 0)} bytes"))
        elif name == "request/kv_handoff":
            # Pre-span traces (or local installs): keep the terminal
            # instant visible even without a measured hop.
            if not any(h[0] == "kv_handoff" and h[1] == rid
                       for h in hops):
                hops.append(("kv_handoff", rid,
                             args.get("prefill_replica"),
                             args.get("decode_replica"), None,
                             f"{args.get('bytes', 0)} bytes"))
        elif name == "request/migrate":
            hops.append(("migrate", rid, args.get("from_replica"),
                         args.get("to_replica"), args.get("ms"),
                         f"{args.get('bytes', 0)} KV bytes, resumed at "
                         f"token {args.get('resumed_at')}"))
        elif name == "request/failover":
            hops.append(("failover", rid, args.get("from_replica"),
                         args.get("to_replica"), None,
                         f"resumed at token {args.get('resume_from')}"))
    return hops


def print_fleet(evs: list, other: dict,
                request_id: "int | None" = None) -> None:
    lanes = fleet_lanes(evs)
    print(f"\n== fleet view: {len(lanes)} lanes")
    states = {str(d.get("replica")): d for d in other.get("fleet", [])}
    print(f"  {'lane':>8}  {'events':>7}  {'relayed':>7}  "
          f"{'span_ms':>9}  {'clock_conf':>10}  state")
    for lane in sorted(lanes, key=lambda x: (x == "gateway", x)):
        row = lanes[lane]
        span_ms = ((row["t_max"] - row["t_min"]) / 1e3
                   if row["events"] else 0.0)
        conf = (f"±{row['clock_conf_s'] * 1e3:.2f}ms"
                if row["clock_conf_s"] is not None else "local")
        st = states.get(lane, {})
        extra = st.get("state", "")
        clock = st.get("clock") or {}
        if clock.get("synced"):
            extra += (f"  offset={clock.get('offset_s', 0) * 1e3:+.3f}ms"
                      f" rtt={clock.get('rtt_s', 0) * 1e3:.3f}ms")
        print(f"  {lane:>8}  {row['events']:7d}  {row['relayed']:7d}  "
              f"{span_ms:9.2f}  {conf:>10}  {extra}")
    hops = fleet_hops(evs)
    if hops:
        print(f"\n== fleet hops: {len(hops)}")
        print(f"  {'kind':>11}  {'request':>8}  {'from':>4}  {'to':>4}  "
              f"{'hop_ms':>8}  detail")
        for kind, rid, src, dst, ms, detail in hops:
            ms_s = f"{ms:8.3f}" if ms is not None else "      --"
            print(f"  {kind:>11}  {rid!s:>8}  {src!s:>4}  {dst!s:>4}  "
                  f"{ms_s}  {detail}")
    if request_id is not None:
        wf = request_waterfall(evs, request_id)
        if not wf:
            print(f"\nrequest {request_id}: no events in this window")
            return
        t0 = wf[0]["ts"]
        print(f"\n== request {request_id} fleet waterfall "
              f"({len(wf)} events, lane column = replica)")
        print(f"{'t_ms':>10}  {'dur_ms':>8}  {'lane':>8}  event")
        for e in wf:
            args = dict(e.get("args") or {})
            args.pop("request_id", None)
            lane = str(args.pop("replica", "gateway"))
            conf = args.pop("clock_conf_s", None)
            dur = f"{e['dur'] / 1e3:8.3f}" if "dur" in e else " " * 8
            extra = " ".join(f"{k}={v}" for k, v in args.items())
            if conf is not None:
                extra += f" (±{conf * 1e3:.2f}ms)"
            print(f"{(e['ts'] - t0) / 1e3:10.3f}  {dur}  {lane:>8}  "
                  f"{e['name']}{'  ' + extra if extra else ''}")


def roofline_table(other: dict) -> list:
    """(program, dispatches, gflops_per_s, gbytes_per_s, mfu_pct,
    mbu_pct) rows from the export's live roofline snapshot — empty
    when the trace predates PR 20 or TTD_COMPILECHECK was unarmed."""
    rows = []
    for prog, s in sorted((other.get("roofline") or {}).items()):
        rows.append((prog, s.get("dispatches", 0),
                     s.get("flops_per_s", 0.0) / 1e9,
                     s.get("bytes_per_s", 0.0) / 1e9,
                     s.get("mfu_pct"), s.get("mbu_pct")))
    return rows


# -- post-mortem (TTD_TRACE_SPOOL + corpse snapshots) ----------------------


def load_spool_dir(directory: str) -> dict:
    """Parse a spool directory: per-pid event streams (wall-anchored
    via each segment's header line) + the parent's corpse snapshots.

    Returns ``{"procs": {pid: {"events": [...], "dropped": n,
    "segments": n}}, "corpses": [...]}`` where each event is
    ``{"name", "ph", "wall_s", "mono_s", "dur", "attrs"}``."""
    procs: dict = {}
    for path in sorted(glob.glob(os.path.join(directory,
                                              "spool-*.jsonl"))):
        anchor = None       # (wall_anchor_s, mono_anchor_s) of segment
        pid = None
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue    # torn tail line: the crash wrote it
                if isinstance(rec, dict) and rec.get("spool"):
                    pid = rec.get("pid")
                    anchor = (float(rec.get("wall_anchor_s", 0.0)),
                              float(rec.get("mono_anchor_s", 0.0)))
                    row = procs.setdefault(pid, {
                        "events": [], "dropped": 0, "segments": 0})
                    row["segments"] += 1
                elif isinstance(rec, dict) and "dropped" in rec:
                    if pid in procs:
                        procs[pid]["dropped"] += int(rec["dropped"])
                    continue
                # One {"b": [...]} line per flush batch; bare event
                # arrays accepted too (hand-written fixtures).
                if anchor is None:
                    continue
                if isinstance(rec, dict):
                    batch = rec.get("b") or []
                elif isinstance(rec, list) and len(rec) >= 6:
                    batch = [rec]
                else:
                    batch = []
                for ev in batch:
                    if not isinstance(ev, list) or len(ev) < 6:
                        continue
                    name, ph, t0, dur, _tid, attrs = ev[:6]
                    procs[pid]["events"].append({
                        "name": name, "ph": ph,
                        "mono_s": t0,
                        "wall_s": t0 - anchor[1] + anchor[0],
                        "dur": dur, "attrs": attrs or {}})
    corpses = []
    for path in sorted(glob.glob(os.path.join(directory,
                                              "corpse-*.json"))):
        try:
            with open(path) as f:
                corpses.append(json.load(f))
        except (OSError, ValueError):
            continue
    for row in procs.values():
        row["events"].sort(key=lambda e: e["wall_s"])
    return {"procs": procs, "corpses": corpses}


def post_mortem_report(directory: str, last_s: float = 10.0) -> dict:
    """The reconstruction the dead process can no longer serve: for
    each corpse snapshot, the worker's own final ``last_s`` seconds of
    spooled events joined with the parent's view (exit reason, clock
    offset at death, the last relayed events).  ``timeline`` holds
    every process's tail merged on wall clock (spool segment anchors),
    tagged by pid."""
    spool = load_spool_dir(directory)
    deaths = []
    for c in spool["corpses"]:
        pid = c.get("pid")
        proc = spool["procs"].get(pid, {})
        evs = proc.get("events", [])
        cutoff = (evs[-1]["wall_s"] - last_s) if evs else 0.0
        deaths.append({
            "replica": c.get("replica"),
            "pid": pid,
            "reason": c.get("reason"),
            "returncode": c.get("returncode"),
            "drained": c.get("drained"),
            "clock": c.get("clock") or {},
            "wall_s": c.get("wall_s"),
            "events_relayed": c.get("events_relayed"),
            "last_relayed": c.get("last_events") or [],
            "final_events": [e for e in evs if e["wall_s"] >= cutoff],
            "spool_segments": proc.get("segments", 0),
            "spool_dropped": proc.get("dropped", 0),
        })
    timeline = []
    for pid, proc in spool["procs"].items():
        for e in proc["events"]:
            timeline.append(dict(e, pid=pid))
    timeline.sort(key=lambda e: e["wall_s"])
    return {"deaths": deaths, "timeline": timeline,
            "procs": sorted(spool["procs"]),
            "corpses": len(spool["corpses"])}


def print_post_mortem(directory: str, journal: "str | None" = None,
                      last_s: float = 10.0) -> None:
    rep = post_mortem_report(directory, last_s=last_s)
    print(f"# post-mortem: {directory} — "
          f"{len(rep['procs'])} spooled processes, "
          f"{rep['corpses']} corpse snapshots")
    if not rep["deaths"]:
        print("  no corpse snapshots: nothing died while the parent "
              "watched (or TTD_TRACE_SPOOL was unset in the parent)")
    for d in rep["deaths"]:
        clock = d["clock"] or {}
        sync = (f"offset={clock.get('offset_s', 0) * 1e3:+.3f}ms "
                f"±{clock.get('conf_s', 0) * 1e3:.2f}ms"
                if clock.get("synced") else "unsynced (HELLO estimate)")
        print(f"\n== death: replica={d['replica']} pid={d['pid']} "
              f"reason={d['reason']} rc={d['returncode']} "
              f"drained={d['drained']}")
        print(f"   clock at death: {sync}; "
              f"{d['events_relayed']} events relayed; spool: "
              f"{d['spool_segments']} segments, "
              f"{d['spool_dropped']} dropped")
        if d["final_events"]:
            t_end = d["final_events"][-1]["wall_s"]
            print(f"   final {last_s:.0f}s from its own spool "
                  f"({len(d['final_events'])} events, t=0 at death):")
            for e in d["final_events"][-40:]:
                attrs = e.get("attrs") or {}
                extra = " ".join(f"{k}={v}" for k, v in attrs.items())
                dur = (f" dur={e['dur'] * 1e3:.3f}ms"
                       if e.get("dur") else "")
                print(f"   {e['wall_s'] - t_end:9.3f}s  {e['name']}"
                      f"{dur}{'  ' + extra if extra else ''}")
        elif d["last_relayed"]:
            print(f"   no spool from the worker (its TTD_TRACE_SPOOL "
                  f"was unset?); last {len(d['last_relayed'])} events "
                  f"the parent relayed:")
            for name, ph, t0, dur, attrs in d["last_relayed"][-20:]:
                extra = " ".join(f"{k}={v}"
                                 for k, v in (attrs or {}).items())
                print(f"     {name}  {extra}")
    if journal:
        print_journal(journal)


def print_journal(path: str) -> None:
    print(f"\n== supervisor journal: {path}")
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            ev = rec.pop("event", "?")
            print("  " + ev.ljust(10)
                  + " ".join(f"{k}={v}" for k, v in rec.items()))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("trace", nargs="?", default=None,
                   help="Chrome-trace JSON (GET /debug/trace "
                        "output or Recorder.save()); optional with "
                        "--post-mortem")
    p.add_argument("--request", type=int, default=None,
                   help="render one request's waterfall")
    p.add_argument("--requests", action="store_true",
                   help="list request ids in the window with status")
    p.add_argument("--fleet", action="store_true",
                   help="cross-worker view: per-replica lanes, clock "
                        "quality, measured handoff/migration hops")
    p.add_argument("--post-mortem", default=None, metavar="DIR",
                   help="reconstruct the last seconds before a death "
                        "from a TTD_TRACE_SPOOL directory (spool "
                        "segments + corpse snapshots)")
    p.add_argument("--last-s", type=float, default=10.0,
                   help="post-mortem tail length per death "
                        "(default 10s)")
    p.add_argument("--journal", default=None,
                   help="supervisor JSONL to append as an attempt "
                        "timeline")
    args = p.parse_args(argv)
    if args.post_mortem is not None:
        print_post_mortem(args.post_mortem, journal=args.journal,
                          last_s=args.last_s)
        if args.trace is None:
            return 0
    if args.trace is None:
        p.error("a trace file is required unless --post-mortem is "
                "given")
    evs = load_events(args.trace)
    other = load_other(args.trace)
    print(f"# {args.trace}: {len(evs)} events")
    if args.fleet:
        print_fleet(evs, other, request_id=args.request)
        roof = roofline_table(other)
        if roof:
            print("\n== live roofline (per compiled program)")
            print(f"  {'dispatches':>10}  {'gflop/s':>9}  {'gbyte/s':>9}"
                  f"  {'mfu%':>6}  {'mbu%':>6}  program")
            for prog, n, gf, gb, mfu, mbu in roof:
                mfu_s = f"{mfu:6.2f}" if mfu is not None else "    --"
                mbu_s = f"{mbu:6.2f}" if mbu is not None else "    --"
                print(f"  {n:10d}  {gf:9.3f}  {gb:9.3f}  {mfu_s}  "
                      f"{mbu_s}  {prog}")
        if args.journal:
            print_journal(args.journal)
        return 0

    rows = stage_table(evs)
    if rows:
        print(f"\n{'count':>7}  {'total_ms':>10}  {'mean_ms':>9}  "
              f"{'p50_ms':>8}  {'p99_ms':>8}  {'max_ms':>8}  span")
        for name, n, total, mean, p50, p99, mx in rows:
            print(f"{n:7d}  {total:10.2f}  {mean:9.3f}  {p50:8.3f}  "
                  f"{p99:8.3f}  {mx:8.3f}  {name}")
    inst = instant_counts(evs)
    if inst:
        print(f"\n{'count':>7}  instant")
        for name, n in inst:
            print(f"{n:7d}  {name}")

    kv = kv_cache_summary(evs)
    if kv:
        print("\n== paged KV cache")
        print(f"  prefix hits        {kv['prefix_hits']}"
              f"  ({kv['prefix_hit_tokens']} prompt tokens skipped)")
        print(f"  evicted blocks     {kv['evicted_blocks']}")
        print(f"  refused admissions {kv['refused_admissions']}")
        print(f"  fused-attn dispatches {kv['fused_attn_dispatches']}"
              f"  (decode chunks through ops.pallas_kernels."
              f"paged_attention)")

    spec = spec_depth_summary(evs)
    if spec:
        print("\n== speculative depth (spec_k on decode/dispatch)")
        by_depth = " ".join(f"k={k}:{n}" for k, n
                            in sorted(spec["rounds"].items()))
        print(f"  rounds by depth    {by_depth}")
        print(f"  depth switches     {spec['switches']}")
        print(f"  {'start_ms':>10}  {'depth':>5}  {'rounds':>6}")
        for start, k, n in spec["segments"]:
            print(f"  {start:10.3f}  {k:5d}  {n:6d}")

    mig = migration_summary(evs)
    if mig:
        ms = sorted(mig["ms"])
        print("\n== live migration")
        print(f"  migrations         {mig['migrations']}"
              f"  ({mig['kv_bytes']} KV bytes shipped, "
              f"{mig['warm_tokens']} warm tokens installed)")
        if ms:
            print(f"  move time ms       p50={_percentile(ms, 0.5):.3f}"
                  f" p99={_percentile(ms, 0.99):.3f} max={ms[-1]:.3f}")
        print(f"  drain evacuations  {mig['evacuations']}"
              f"  ({mig['evacuated_lanes']} lanes moved)")
        print(f"  defrag moves       {mig['defrag_moves']}")
        if mig["hops"]:
            print(f"  {'request':>9}  {'from':>4}  {'to':>4}  "
                  f"{'at_tok':>6}  {'kv_bytes':>9}")
            for rid, src, dst, at, nbytes in mig["hops"]:
                print(f"  {rid!s:>9}  {src!s:>4}  {dst!s:>4}  "
                      f"{at!s:>6}  {nbytes:9d}")

    anatomy = train_step_summary(evs)
    if anatomy:
        print("\n== train step anatomy (grad-quant split step)")
        print(f"{'count':>7}  {'total_ms':>10}  {'comm-frac':>9}  span")
        for name, n, ms, frac in anatomy:
            frac_s = (f"{frac:9.3f}" if name != "train/step_dispatch"
                      else " " * 9)
            print(f"{n:7d}  {ms:10.2f}  {frac_s}  {name}")

    memory = memory_summary(evs)
    if memory:
        print("\n== memory pools (memcheck spans)")
        print(f"{'allocs':>7}  {'peak_MiB':>9}  {'live_MiB':>9}  "
              f"{'budget_MiB':>10}  {'headroom':>8}  {'near-miss':>9}"
              f"  pool")
        for pool in sorted(memory):
            row = memory[pool]
            mib = 1024.0 * 1024.0
            budget = row["budget"]
            headroom = (f"{100.0 * (1 - row['peak_live'] / budget):7.1f}%"
                        if budget else "      --")
            print(f"{row['allocs']:7d}  {row['peak_live'] / mib:9.2f}  "
                  f"{row['live'] / mib:9.2f}  "
                  f"{(budget / mib) if budget else 0:10.2f}  "
                  f"{headroom}  {row['near_misses']:9d}  {pool}")

    compiles = compile_summary(evs)
    if compiles:
        print("\n== compilations (compilecheck spans)")
        print(f"{'count':>7}  {'total_ms':>10}  site")
        for site, n, ms in compiles:
            print(f"{n:7d}  {ms:10.2f}  {site}")

    if args.requests:
        ids = request_ids(evs)
        print(f"\n== requests in window: {len(ids)}")
        for rid, status in ids:
            print(f"  {rid:>8}  {status}")
    if args.request is not None:
        print_waterfall(evs, args.request)
    if args.journal:
        print_journal(args.journal)
    return 0


if __name__ == "__main__":
    sys.exit(main())
