"""Regenerate the checked-in mini-corpora under tests/data/.

The repo carries two small real on-disk corpora (mmap shard layout,
``data.filesource``) so file-based ingestion — FILE autoshard, mmap
random access, native staging — is exercised against actual files, not
procedural sources:

- ``tests/data/mnist_mini``: 256 MNIST-style records, images stored
  uint8 (decode with the ``u8_image_to_f32`` transform), 8 shards.
- ``tests/data/mlm_mini``: 256 BERT-MLM records (vocab 256, seq 64),
  8 shards.

Deterministic: re-running reproduces byte-identical corpora.
"""

import pathlib
import sys

import numpy as np

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tensorflow_train_distributed_tpu.data.datasets import (  # noqa: E402
    SyntheticMLM, SyntheticMNIST,
)
from tensorflow_train_distributed_tpu.data.filesource import (  # noqa: E402
    write_shards,
)


class _U8Mnist:
    """MNIST records with images quantized to uint8 for storage."""

    def __init__(self, n):
        self.src = SyntheticMNIST(num_examples=n)

    def __len__(self):
        return len(self.src)

    def __getitem__(self, idx):
        rec = self.src[idx]
        return {"image": np.round(rec["image"] * 255).astype(np.uint8),
                "label": rec["label"]}


def main():
    out = REPO / "tests" / "data"
    write_shards(out / "mnist_mini", _U8Mnist(256), num_shards=8)
    write_shards(out / "mlm_mini",
                 SyntheticMLM(num_examples=256, seq_len=64, vocab_size=256),
                 num_shards=8)
    for name in ("mnist_mini", "mlm_mini"):
        total = sum(f.stat().st_size
                    for f in (out / name).rglob("*") if f.is_file())
        print(f"{name}: {total / 1024:.0f} KiB")


if __name__ == "__main__":
    main()
