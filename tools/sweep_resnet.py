"""Sweep ResNet bench configs × XLA flags on the live chip.

Each point runs ``bench.py`` in a fresh subprocess (XLA/libtpu flags only
apply at backend init) and records images/sec/chip.  Used to pick the
batch size and libtpu flags for the headline benchmark — results land in
PROFILE.md.

Usage: python tools/sweep_resnet.py [--quick]
"""

import argparse
import itertools
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BATCHES = [192, 256, 320, 384, 512]
FLAG_SETS = {
    "default": "",
    # Bigger scoped-vmem budget lets the fusion engine keep deeper
    # (BN-stat + elementwise) fusions resident; MaxText ships 81920.
    "vmem80m": "--xla_tpu_scoped_vmem_limit_kib=81920",
    "vmem112m": "--xla_tpu_scoped_vmem_limit_kib=114688",
}


def run_point(batch: int, flags: str, iters: int, config: str):
    env = dict(os.environ)
    if flags:
        env["LIBTPU_INIT_ARGS"] = flags
    cmd = [sys.executable, os.path.join(REPO, "bench.py"),
           "--configs", config, "--batch-per-chip", str(batch),
           "--iters", str(iters), "--acquire-timeout", "120",
           "--families", "resnet",
           "--no-cpu-fallback", "--no-persist", "--profile-dir", ""]
    out = subprocess.run(cmd, capture_output=True, text=True, env=env,
                         timeout=900, cwd=REPO)
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        return rec.get("value", 0.0), rec.get("error")
    return 0.0, f"no JSON (rc={out.returncode}): {out.stderr[-200:]!r}"


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--quick", action="store_true",
                   help="batch 256 only, default+vmem80m flags")
    p.add_argument("--iters", type=int, default=30)
    p.add_argument("--config", default="resnet50_s2d")
    args = p.parse_args()

    batches = [256] if args.quick else BATCHES
    flag_sets = ({k: FLAG_SETS[k] for k in ("default", "vmem80m")}
                 if args.quick else FLAG_SETS)
    results = {}
    for batch, (fname, flags) in itertools.product(batches,
                                                   flag_sets.items()):
        value, err = run_point(batch, flags, args.iters, args.config)
        key = f"b{batch}/{fname}"
        results[key] = value
        print(f"{key}: {value} img/s/chip"
              + (f"  ERROR: {err}" if err else ""), flush=True)
    best = max(results, key=results.get)
    print(json.dumps({"best": best, "value": results[best],
                      "sweep": results}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
