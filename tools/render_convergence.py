"""Render mini-convergence JSONL curves as a text report.

``profiles/convergence/*.jsonl`` (written by the CLI's ``--jsonl-log``
during the multi-epoch mini-convergence runs, VERDICT r3 items 5/8) →
a compact human-readable report: per-curve sparkline + loss statistics,
plus a numerics-agreement section for A/B pairs like
``resnet50_imagenet_s2d`` vs ``..._s2d_bnsub`` (the strided-BN-statistics
pre-certification: subset statistics must not change the training
trajectory materially before the variant can claim the headline bench).

Usage:
    python tools/render_convergence.py [--dir profiles/convergence]
        [--write]   # also write <dir>/README.md
"""

from __future__ import annotations

import argparse
import json
import math
from pathlib import Path

BLOCKS = "▁▂▃▄▅▆▇█"


def load_curve(path: Path) -> tuple[list[int], list[float]]:
    steps, losses = [], []
    for line in path.read_text().splitlines():
        if not line.strip():
            continue
        rec = json.loads(line)
        if "loss" in rec:
            steps.append(int(rec["step"]))
            losses.append(float(rec["loss"]))
    return steps, losses


def smooth(xs: list[float], window: int) -> list[float]:
    """Trailing moving average (window clipped at the start)."""
    out = []
    for i in range(len(xs)):
        lo = max(0, i - window + 1)
        out.append(sum(xs[lo:i + 1]) / (i + 1 - lo))
    return out


def sparkline(xs: list[float], width: int = 60) -> str:
    if not xs:
        return ""
    # Resample to n <= width points spanning the WHOLE curve.
    n = min(width, len(xs))
    pts = [xs[round(i * (len(xs) - 1) / max(1, n - 1))] for i in range(n)]
    lo, hi = min(pts), max(pts)
    span = (hi - lo) or 1.0
    return "".join(
        BLOCKS[min(len(BLOCKS) - 1,
                   int((p - lo) / span * (len(BLOCKS) - 1) + 0.5))]
        for p in pts)


def curve_summary(name: str, steps: list[int], losses: list[float],
                  window: int = 10) -> dict:
    s = smooth(losses, window)
    q = max(1, len(s) // 4)
    return {
        "name": name,
        "points": len(s),
        "first": s[0],
        "first_quarter_mean": sum(s[:q]) / q,
        "last_quarter_mean": sum(s[-q:]) / q,
        "final": s[-1],
        "min": min(s),
        "spark": sparkline(s),
        "smoothed": s,
        "steps": steps,
    }


def render(curves: list[dict]) -> str:
    lines = [
        "# Mini-convergence curves",
        "",
        "Multi-epoch CPU-mesh training curves (300 steps via the real CLI,",
        "`--jsonl-log`): the first sustained-training artifacts and the",
        "regression baseline for numerics-affecting changes (bnsub BN",
        "statistics, pallas kernel swaps).  Regenerate the captures with",
        "`tools/capture_convergence.sh` (the exact 300-step recipes), then",
        "re-render with `tools/render_convergence.py --write`;",
        "tests/test_convergence.py pins shorter (80-step) versions in CI.",
        "",
    ]
    for c in curves:
        drop = c["first_quarter_mean"] - c["last_quarter_mean"]
        lines += [
            f"## {c['name']}",
            "",
            "```",
            c["spark"],
            "```",
            "",
            f"- points: {c['points']}  loss first→final: "
            f"{c['first']:.4f} → {c['final']:.4f} (min {c['min']:.4f})",
            f"- first-quarter mean {c['first_quarter_mean']:.4f} → "
            f"last-quarter mean {c['last_quarter_mean']:.4f} "
            f"(drop {drop:.4f})",
            "",
        ]
    # A/B numerics agreement for the bnsub certification pair.
    by_name = {c["name"]: c for c in curves}
    base = by_name.get("resnet50_imagenet_s2d_32px")
    sub = by_name.get("resnet50_imagenet_s2d_bnsub_32px")
    if base and sub:
        n = min(len(base["smoothed"]), len(sub["smoothed"]))
        diffs = [abs(a - b) for a, b in
                 zip(base["smoothed"][:n], sub["smoothed"][:n])]
        final_gap = abs(base["last_quarter_mean"] - sub["last_quarter_mean"])
        drop = (base["first_quarter_mean"] - base["last_quarter_mean"])
        rel = final_gap / abs(drop) if drop else math.inf
        lines += [
            "## bnsub numerics certification (exact vs 2-strided BN stats)",
            "",
            f"- final-quarter loss gap: {final_gap:.4f} "
            f"({100 * rel:.1f}% of the baseline's total loss drop)",
            f"- max |Δ| over smoothed curves: {max(diffs):.4f}",
            "- criterion (tests/test_convergence.py): final-quarter gap "
            "< 15% of the baseline loss drop.  (This 32px/batch-8 setting "
            "is the CONSERVATIVE case: stride-2 stats over 8x8-and-under "
            "feature maps; at the headline 224px/batch-256 the subsampled "
            "pool still exceeds 200k samples/channel per stage-1 map.)",
            "",
        ]
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--dir", default="profiles/convergence")
    p.add_argument("--write", action="store_true",
                   help="also write <dir>/README.md")
    args = p.parse_args(argv)
    root = Path(args.dir)
    paths = sorted(root.glob("*.jsonl"))
    if not paths:
        raise SystemExit(f"no *.jsonl curves under {root}")
    curves = []
    for path in paths:
        steps, losses = load_curve(path)
        if losses:
            curves.append(curve_summary(path.stem, steps, losses))
    report = render(curves)
    print(report)
    if args.write:
        (root / "README.md").write_text(report)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
