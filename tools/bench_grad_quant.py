"""Quantized gradient collectives A/B harness (ROADMAP item 4a).

One run, four legs over the SAME fixed-seed batch stream on one mesh:

- ``none``  — today's single-program GSPMD step (implicit f32 gradient
  allreduce), the baseline.
- ``f32``   — the explicit three-program pipeline (per-shard grads →
  sync → apply) with the exact f32 psum sync: isolates the pipeline
  restructuring from the quantization.
- ``int8``  — the EQuARX pipeline: int8+scales on the wire with the
  error-feedback residual carried in the train state.
- ``int8`` + ``TTD_NO_GRAD_QUANT=1`` — the kill switch, which must be
  BITWISE-equal to ``none`` (same params after N steps).

Reported per quant leg: fixed-seed loss curve (parity vs the baseline),
median wall/step, analytic gradient wire bytes
(``collectives.grad_sync_wire_bytes``), and the comm fraction measured
from the flight recorder's ``train/grad_comm`` / ``train/grad_fwdbwd``
/ ``train/optimizer_apply`` sub-spans (each a blocking dispatch — real
device time).  A restore-compat check round-trips a pre-quant
checkpoint into the residual-carrying train state.

``--overlap`` switches to the bucketed-overlap A/B (ROADMAP item 3):
three int8 legs over the same fixed-seed stream — ``seq`` (the
sequential three-program pipeline, ``grad_overlap=0``), ``ovl`` (the
bucketed overlap step, K buckets dispatched in-flight), and ``ovl`` +
``TTD_NO_GRAD_OVERLAP=1`` (the kill switch, which must be BITWISE-equal
to ``seq``).  Reported: median of per-step wall-ratio pairs, per-leg
blocking comm-fraction (the overlap step's ``train/grad_comm`` spans
meter dispatch only; its device wait is the ``train/step_barrier``
span), and loss parity ovl-vs-seq.  Record goes to
``profiles/bench/grad_overlap_ab.jsonl``.

Appends one JSON record to ``profiles/bench/grad_quant_ab.jsonl`` (or
the overlap sink above) and prints a compact headline as the last
stdout line (driver emit contract).

Usage::

    python tools/bench_grad_quant.py --platform cpu --cpu-devices 8
    python tools/bench_grad_quant.py --steps 50 --batch 64   # on TPU
    python tools/bench_grad_quant.py --overlap --platform cpu \
        --cpu-devices 8
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT_DEFAULT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "profiles", "bench", "grad_quant_ab.jsonl")
OUT_OVERLAP = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "profiles", "bench", "grad_overlap_ab.jsonl")

LOSS_PARITY_TOL = 0.1       # |loss_int8 - loss_none| bound, per step
#: ovl-vs-seq: both legs are int8 with error feedback; they differ only
#: in Q8 block placement (leaf-aligned vs concat-spanning), so parity
#: is held an order of magnitude tighter than int8-vs-exact.
OVERLAP_PARITY_TOL = 1e-3


def _make_task(vocab: int, d_model: int, layers: int, seq: int):
    import jax.numpy as jnp

    from tensorflow_train_distributed_tpu.models.llama import (
        CausalLmTask, LlamaConfig,
    )

    return CausalLmTask(LlamaConfig(
        vocab_size=vocab, d_model=d_model, num_layers=layers,
        num_heads=4, num_kv_heads=None, ffn_size=2 * d_model,
        max_positions=seq, dtype=jnp.float32, scan_layers=False))


def _batches(steps: int, batch: int, seq: int, vocab: int, seed: int):
    import numpy as np

    rng = np.random.default_rng(seed)
    out = []
    for _ in range(steps):
        toks = rng.integers(0, vocab, (batch, seq)).astype(np.int32)
        out.append({"tokens": toks,
                    "targets": np.roll(toks, -1, axis=1)})
    return out


def _span_totals(evs) -> dict:
    totals: dict = {}
    for name, ph, _t0, dur, _tid, _attrs in evs:
        if ph == "X" and name.startswith("train/"):
            totals[name] = totals.get(name, 0.0) + dur
    return totals


def run_leg(grad_quant: str, task, mesh, batches, seed: int,
            kill_switch: bool = False, grad_overlap=None,
            kill_env: str = "TTD_NO_GRAD_QUANT") -> dict:
    import jax
    import numpy as np
    import optax

    from tensorflow_train_distributed_tpu.parallel import collectives
    from tensorflow_train_distributed_tpu.parallel.sharding import (
        shard_batch,
    )
    from tensorflow_train_distributed_tpu.runtime import events
    from tensorflow_train_distributed_tpu.training import (
        Trainer, TrainerConfig,
    )

    cfg_kw = {} if grad_overlap is None else {"grad_overlap": grad_overlap}
    prior = os.environ.get(kill_env)
    if kill_switch:
        os.environ[kill_env] = "1"
    try:
        trainer = Trainer(
            task, optax.adamw(3e-3), mesh,
            config=TrainerConfig(seed=seed, log_every=10 ** 9,
                                 grad_quant=grad_quant, **cfg_kw))
    finally:
        if kill_switch:
            if prior is None:
                os.environ.pop(kill_env, None)
            else:
                os.environ[kill_env] = prior
    state = trainer.create_state(batches[0])
    step = trainer._compiled_train_step()
    rec = events.get_recorder()
    losses, walls = [], []
    for i, b in enumerate(batches):
        dev = shard_batch(mesh, b)
        t0 = time.perf_counter()
        state, m = step(state, dev)
        losses.append(float(m["loss"]))      # device fetch = step barrier
        walls.append(time.perf_counter() - t0)
        if i == 0:
            # Step 0 compiles all three programs INSIDE their spans;
            # drop it from the span totals, consistent with walls[1:].
            rec.clear()
    totals = _span_totals(rec.events())
    leg = {
        "grad_quant": trainer.grad_quant,
        "grad_overlap": trainer.grad_overlap,
        "kill_switch": kill_switch,
        "loss_first": round(losses[0], 6),
        "loss_last": round(losses[-1], 6),
        "losses": [round(x, 6) for x in losses],
        "wall_per_step_ms": round(
            statistics.median(walls[1:] or walls) * 1e3, 3),
        "walls_ms": [round(w * 1e3, 3) for w in walls],
        "wire_bytes_per_step": collectives.grad_sync_wire_bytes(
            state.params, mesh.shape["data"],
            "f32" if trainer.grad_quant == "none" else trainer.grad_quant),
    }
    if "grad_buckets" in m:
        leg["grad_buckets"] = int(m["grad_buckets"])
        leg["bucket_wire_mb"] = round(float(m["grad_comm_mb"]), 6)
    comm = totals.get("train/grad_comm")
    if comm is not None:
        # The barrier term is zero on the sequential pipeline (every
        # dispatch blocks inline) and the realized device wait on the
        # overlap step — so comm_fraction is BLOCKING comm share on
        # both: full device sync time sequentially, dispatch-only time
        # under overlap.
        span_sum = sum(totals.get(k, 0.0) for k in (
            "train/grad_fwdbwd", "train/grad_comm",
            "train/optimizer_apply", "train/step_barrier"))
        leg["grad_comm_ms_total"] = round(comm * 1e3, 3)
        leg["comm_fraction"] = round(comm / span_sum, 4) if span_sum else 0.0
    final_params = jax.tree.map(np.asarray, jax.device_get(state.params))
    return leg, final_params, trainer


def _bitwise_equal(a, b) -> bool:
    import jax
    import numpy as np

    leaves_a = jax.tree.leaves(a)
    leaves_b = jax.tree.leaves(b)
    return (len(leaves_a) == len(leaves_b)
            and all(np.array_equal(x, y)
                    for x, y in zip(leaves_a, leaves_b)))


def _restore_compat_check(task, mesh, batch) -> bool:
    """A checkpoint saved WITHOUT residual leaves (pre-quant trainer)
    must restore into the residual-carrying template with residuals
    zero-initialized."""
    import jax
    import numpy as np
    import optax

    from tensorflow_train_distributed_tpu.training import (
        Trainer, TrainerConfig,
    )
    from tensorflow_train_distributed_tpu.training.checkpoint import (
        CheckpointManager,
    )

    with tempfile.TemporaryDirectory() as d:
        old = Trainer(task, optax.adamw(3e-3), mesh,
                      config=TrainerConfig(log_every=10 ** 9))
        state = old.create_state(batch)
        mgr = CheckpointManager(os.path.join(d, "ckpt"))
        mgr.save(0, state, force=True)
        mgr.wait_until_finished()
        new = Trainer(task, optax.adamw(3e-3), mesh,
                      config=TrainerConfig(log_every=10 ** 9,
                                           grad_quant="int8"))
        template = new.create_state(batch)
        restored = mgr.restore(template)
        mgr.close()
        if restored is None or restored.grad_residual is None:
            return False
        zeros = all(not np.asarray(r).any()
                    for r in jax.tree.leaves(restored.grad_residual))
        params_eq = _bitwise_equal(
            jax.device_get(restored.params), jax.device_get(state.params))
        return zeros and params_eq


def run_overlap_ab(args, mesh, task, batches) -> int:
    """The bucketed-overlap A/B: sequential int8 vs K-bucket overlap
    vs the ``TTD_NO_GRAD_OVERLAP`` kill switch, same fixed-seed
    stream.  Headline value is the median of per-step wall-ratio PAIRS
    (seq_i / ovl_i — pairing before the median cancels the stream's
    per-step size/content variance)."""
    import jax

    legs = {}
    params = {}
    legs["seq"], params["seq"], _ = run_leg(
        "int8", task, mesh, batches, args.seed, grad_overlap=0)
    legs["ovl"], params["ovl"], ovl_trainer = run_leg(
        "int8", task, mesh, batches, args.seed,
        grad_overlap=args.grad_overlap)
    leg_ks, params["ks"], ks_trainer = run_leg(
        "int8", task, mesh, batches, args.seed, kill_switch=True,
        grad_overlap=args.grad_overlap, kill_env="TTD_NO_GRAD_OVERLAP")

    # Warmup step 0 (compiles) excluded from pairing, like wall medians.
    pairs = [(a, b) for a, b in zip(legs["seq"]["walls_ms"][1:],
                                    legs["ovl"]["walls_ms"][1:]) if b > 0]
    ratios = [a / b for a, b in pairs]
    diffs = [abs(a - b) for a, b in zip(legs["ovl"]["losses"],
                                        legs["seq"]["losses"])]
    cf_seq = legs["seq"].get("comm_fraction")
    cf_ovl = legs["ovl"].get("comm_fraction")
    record = {
        "metric": "grad_overlap_ab",
        "value": round(statistics.median(ratios), 4) if ratios else 0.0,
        "unit": "x wall-clock, sequential/overlap int8 "
                "(median of per-step pairs)",
        "backend": jax.default_backend(),
        "devices": int(mesh.devices.size),
        "config": {"steps": args.steps, "batch": args.batch,
                   "seq": args.seq, "vocab": args.vocab,
                   "d_model": args.d_model, "layers": args.layers,
                   "seed": args.seed, "optimizer": "adamw(3e-3)",
                   "grad_overlap": args.grad_overlap},
        "legs": legs,
        "blocking_comm_fraction": {
            "seq": cf_seq, "ovl": cf_ovl,
            "reduced": (cf_seq is not None and cf_ovl is not None
                        and cf_ovl < cf_seq),
        },
        "loss_parity": {
            "max_abs_diff_ovl_vs_seq": round(max(diffs), 6),
            "tol": OVERLAP_PARITY_TOL,
            "within_tol": max(diffs) <= OVERLAP_PARITY_TOL,
            "ovl_loss_decreased":
                legs["ovl"]["loss_last"] < legs["ovl"]["loss_first"],
        },
        "killswitch": {
            "resolved_grad_overlap": ks_trainer.grad_overlap,
            "bitwise_equal_to_seq": _bitwise_equal(params["ks"],
                                                   params["seq"]),
            "wall_per_step_ms": leg_ks["wall_per_step_ms"],
        },
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if jax.default_backend() == "cpu":
        record["cpu_note"] = (
            "virtual CPU mesh: all devices share one host's cores, so "
            "overlapping comm with compute cannot create wall-clock "
            "headroom (there is no independent fabric to hide work on) "
            "— the blocking comm-fraction drop is the acceptance "
            "metric here; the wall ratio realizes on TPU "
            "(chip_playbook grad-overlap stanza is the hardware leg)")
    if args.out:
        os.makedirs(os.path.dirname(args.out), exist_ok=True)
        with open(args.out, "a") as f:
            f.write(json.dumps(record) + "\n")
    full = json.dumps(record)
    if len(full) <= 4096:
        print(full, flush=True)
    headline = {k: record[k] for k in
                ("metric", "value", "unit", "backend", "devices",
                 "blocking_comm_fraction", "measured_at")}
    headline["grad_buckets"] = legs["ovl"].get("grad_buckets")
    headline["loss_parity_ok"] = record["loss_parity"]["within_tol"]
    headline["killswitch_bitwise"] = (
        record["killswitch"]["bitwise_equal_to_seq"])
    print(json.dumps(headline), flush=True)
    ok = (record["loss_parity"]["within_tol"]
          and record["killswitch"]["bitwise_equal_to_seq"]
          and record["blocking_comm_fraction"]["reduced"])
    return 0 if ok else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--seq", type=int, default=64)
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--d-model", type=int, default=128)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default=None,
                   help="JSONL record sink ('' disables; default "
                        "grad_quant_ab.jsonl, or grad_overlap_ab.jsonl "
                        "with --overlap)")
    p.add_argument("--platform", default=None, choices=["cpu", "tpu"])
    p.add_argument("--cpu-devices", type=int, default=None)
    p.add_argument("--overlap", action="store_true",
                   help="run the bucketed-overlap A/B (seq int8 vs "
                        "overlap int8 vs kill switch) instead of the "
                        "quant A/B")
    p.add_argument("--grad-overlap", type=int, default=4,
                   help="bucket count K for the overlap leg")
    args = p.parse_args(argv)
    if args.out is None:
        args.out = OUT_OVERLAP if args.overlap else OUT_DEFAULT

    if args.platform or args.cpu_devices:
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            force_platform,
        )

        force_platform(args.platform, args.cpu_devices)

    import jax

    from tensorflow_train_distributed_tpu.runtime.mesh import (
        MeshConfig, build_mesh,
    )

    if len(jax.devices()) < 2:
        print(json.dumps({
            "metric": "grad_quant_ab", "value": 0.0, "error":
            "needs >= 2 devices (pass --platform cpu --cpu-devices 8 "
            "for the virtual mesh)"}))
        return 1
    mesh = build_mesh(MeshConfig(data=-1))
    task = _make_task(args.vocab, args.d_model, args.layers, args.seq)
    batches = _batches(args.steps, args.batch, args.seq, args.vocab,
                       args.seed)

    if args.overlap:
        return run_overlap_ab(args, mesh, task, batches)

    legs = {}
    params = {}
    # grad_overlap=0 pins the quant A/B to the sequential pipeline the
    # record has always measured; the overlap step has its own A/B.
    leg_none, params["none"], _ = run_leg("none", task, mesh, batches,
                                          args.seed)
    legs["none"] = leg_none
    for gq in ("f32", "int8"):
        legs[gq], params[gq], _ = run_leg(gq, task, mesh, batches,
                                          args.seed, grad_overlap=0)
    leg_ks, params["ks"], ks_trainer = run_leg(
        "int8", task, mesh, batches, args.seed, kill_switch=True,
        grad_overlap=0)

    diffs = [abs(a - b) for a, b in zip(legs["int8"]["losses"],
                                        legs["none"]["losses"])]
    wire_f32 = legs["none"]["wire_bytes_per_step"]
    wire_int8 = legs["int8"]["wire_bytes_per_step"]
    record = {
        "metric": "grad_quant_ab",
        "value": round(wire_f32 / max(wire_int8, 1), 3),
        "unit": "x less gradient wire bytes (int8 vs f32)",
        "backend": jax.default_backend(),
        "devices": int(mesh.devices.size),
        "config": {"steps": args.steps, "batch": args.batch,
                   "seq": args.seq, "vocab": args.vocab,
                   "d_model": args.d_model, "layers": args.layers,
                   "seed": args.seed, "optimizer": "adamw(3e-3)"},
        "legs": legs,
        "killswitch": {
            "resolved_grad_quant": ks_trainer.grad_quant,
            "bitwise_equal_to_none": _bitwise_equal(params["ks"],
                                                    params["none"]),
            "wall_per_step_ms": leg_ks["wall_per_step_ms"],
        },
        "loss_parity": {
            "max_abs_diff_int8_vs_none": round(max(diffs), 6),
            "tol": LOSS_PARITY_TOL,
            "within_tol": max(diffs) <= LOSS_PARITY_TOL,
            "int8_loss_decreased":
                legs["int8"]["loss_last"] < legs["int8"]["loss_first"],
        },
        "comm_fraction": {
            gq: legs[gq].get("comm_fraction") for gq in ("f32", "int8")},
        # The invariant lever: gradient bytes on the wire per step,
        # int8 leg as a fraction of the f32 leg's.
        "comm_bytes_fraction": round(wire_int8 / max(wire_f32, 1), 4),
        "restore_compat_ok": _restore_compat_check(task, mesh,
                                                   batches[0]),
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if jax.default_backend() == "cpu":
        record["cpu_note"] = (
            "virtual CPU mesh: all devices share one host's cores, so "
            "the quantize ALU work is Nx serialized and the span-time "
            "comm fraction is compute-bound — the same verdict "
            "bench_allreduce documents for the host ring's q8 leg; the "
            "wire-bytes fraction above is the invariant lever, "
            "realized where per-rank fabric bandwidth is below quant "
            "throughput (DCN/ICI-bound regimes; chip_playbook step 9 "
            "is the TPU leg)")
    if args.out:
        os.makedirs(os.path.dirname(args.out), exist_ok=True)
        with open(args.out, "a") as f:
            f.write(json.dumps(record) + "\n")
    full = json.dumps(record)
    if len(full) <= 4096:
        print(full, flush=True)
    headline = {k: record[k] for k in
                ("metric", "value", "unit", "backend", "devices",
                 "comm_fraction", "measured_at")}
    headline["loss_parity_ok"] = record["loss_parity"]["within_tol"]
    headline["killswitch_bitwise"] = (
        record["killswitch"]["bitwise_equal_to_none"])
    headline["restore_compat_ok"] = record["restore_compat_ok"]
    print(json.dumps(headline), flush=True)
    ok = (record["loss_parity"]["within_tol"]
          and record["killswitch"]["bitwise_equal_to_none"]
          and record["restore_compat_ok"])
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
