"""Allreduce bus-bandwidth microbenchmark (a driver headline metric).

Measures the two collective paths the framework owns:

- **device**: XLA allreduce over the mesh's data axis (ICI on TPU) via
  ``parallel.collectives.allreduce_bus_bandwidth`` — the TPU-native
  equivalent of the reference's NCCL allreduce benchmark (NCCL busBW
  convention: ``2(k-1)/k · bytes/time``), directly comparable to
  ``nccl-tests`` numbers.
- **host** (``--host``): the native C++ TCP ring (``native/ringcoll``) over
  N localhost processes — the DCN/host-side fallback path.

Prints one JSON line per measurement, driver-style.

Usage::

    python tools/bench_allreduce.py                  # device path, real mesh
    python tools/bench_allreduce.py --size-mb 256
    python tools/bench_allreduce.py --host --world 4
    python tools/bench_allreduce.py --platform cpu --cpu-devices 8
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench_device(size_mb: float, iters: int, quant: str = "none") -> dict:
    import jax

    from tensorflow_train_distributed_tpu.parallel import collectives
    from tensorflow_train_distributed_tpu.runtime.mesh import (
        MeshConfig, build_mesh,
    )

    mesh = build_mesh(MeshConfig(data=-1))
    r = collectives.allreduce_bus_bandwidth(mesh, "data", size_mb=size_mb,
                                            iters=iters, quant=quant)
    out = {
        "metric": ("allreduce_bus_bandwidth_device" if quant == "none"
                   else "allreduce_bus_bandwidth_device_q8"),
        "value": round(r["bus_bandwidth_gbps"], 3),
        "unit": "GB/s",
        "devices": r["devices"],
        "message_bytes": r["message_bytes"],
        "backend": jax.default_backend(),
        "wire": r["wire"],
    }
    if "wire_bytes" in r:
        # Effective-f32 convention: the figure counts payload reduced,
        # wire_bytes the int8+scales actually moved (~4x less).
        out["wire_bytes"] = r["wire_bytes"]
    return out


def _host_worker(rank: int, world: int, peers: list[str], size_mb: float,
                 iters: int, algo: str, q) -> None:
    import time

    import numpy as np

    from tensorflow_train_distributed_tpu.native.ringcoll import (
        HostMesh, HostRing,
    )

    n = int(size_mb * 1e6 / 4)
    if algo == "ring":
        group = HostRing(rank, peers, timeout_ms=20_000)
        reduce_fn = group.allreduce
    elif algo == "ring_q8":
        # EQuARX-style quantized ring: int8+scales on the wire (~4x less
        # traffic).  bus_gbps reports EFFECTIVE f32 bandwidth (payload
        # reduced per second), so the win shows as a higher number — ON A
        # REAL NETWORK.  Measured on this 1-core box (loopback wire at
        # memory speed, all ranks sharing one core): 0.27 vs 0.42 GB/s —
        # the quantize/dequant CPU work is the bottleneck, not the wire.
        # The crossover: q8 wins when per-rank wire bandwidth is below
        # the per-core quant throughput (~1-2 GB/s) — cross-datacenter /
        # oversubscribed DCN, exactly the path this ring serves.
        group = HostRing(rank, peers, timeout_ms=20_000)
        reduce_fn = group.allreduce_q8
    else:
        group = HostMesh(rank, peers, timeout_ms=20_000)
        reduce_fn = lambda x: group.allreduce(x, algorithm=algo)  # noqa: E731
    x = np.ones(n, np.float32)
    reduce_fn(x)  # warmup
    t0 = time.perf_counter()
    for _ in range(iters):
        reduce_fn(x)
    dt = (time.perf_counter() - t0) / iters
    group.close()
    if rank == 0:
        bus = 2 * (world - 1) / world * n * 4 / dt
        q.put({"time_s": dt, "bus_gbps": bus / 1e9})


def bench_host(world: int, size_mb: float, iters: int,
               algo: str = "ring") -> dict:
    import multiprocessing as mp
    import queue as queue_mod

    from tensorflow_train_distributed_tpu.testing.multiprocess import (
        free_ports,
    )

    peers = [f"127.0.0.1:{p}" for p in free_ports(world)]
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [
        ctx.Process(target=_host_worker,
                    args=(r, world, peers, size_mb, iters, algo, q))
        for r in range(world)
    ]
    for p in procs:
        p.start()
    try:
        import time

        deadline = time.monotonic() + 120
        result = None
        while result is None:
            try:
                result = q.get(timeout=2)
            except queue_mod.Empty:
                failed = {p.name: p.exitcode for p in procs if p.exitcode}
                if failed:
                    raise RuntimeError(
                        f"{algo} workers exited nonzero before producing a "
                        f"result (e.g. a port race on setup): {failed}"
                    ) from None
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"host {algo} benchmark timed out after 120 s with "
                        "no result and no worker failure") from None
        for p in procs:
            p.join(timeout=30)
        failed = {p.name: p.exitcode for p in procs if p.exitcode}
        if failed:
            raise RuntimeError(f"{algo} workers exited nonzero: {failed}")
    finally:
        for p in procs:
            if p.is_alive():
                p.terminate()
            p.join(timeout=5)
    return {
        "metric": f"allreduce_bus_bandwidth_host_{algo}",
        "value": round(result["bus_gbps"], 3),
        "unit": "GB/s",
        "devices": world,
        "message_bytes": int(size_mb * 1e6),
        "backend": f"tcp_{algo}",
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--size-mb", type=float, default=64.0)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--host", action="store_true",
                   help="benchmark the native TCP ring instead of the "
                        "device mesh")
    p.add_argument("--world", type=int, default=4,
                   help="with --host: number of ring processes")
    p.add_argument("--algo", default="ring",
                   choices=["ring", "ring_q8", "hd", "shuffle"],
                   help="with --host: allreduce algorithm (ring is "
                        "bandwidth-optimal, hd latency-optimal, shuffle "
                        "single-hop; hd/shuffle need power-of-2 world)")
    p.add_argument("--quant", default="none", choices=["none", "int8"],
                   help="device path: benchmark the int8-wire quantized "
                        "allreduce (the trainer's grad-quant comm "
                        "program) instead of the exact f32 psum; the "
                        "figure stays EFFECTIVE f32 bandwidth, so the "
                        "~4x wire saving shows wherever the fabric is "
                        "the bottleneck (the host analog is --algo "
                        "ring_q8)")
    p.add_argument("--platform", default=None, choices=["cpu", "tpu"])
    p.add_argument("--cpu-devices", type=int, default=None)
    args = p.parse_args(argv)

    if args.platform or args.cpu_devices:
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            force_platform,
        )

        force_platform(args.platform, args.cpu_devices)

    if args.host:
        if args.algo != "ring" and args.world & (args.world - 1):
            p.error(f"--algo {args.algo} requires a power-of-2 --world, "
                    f"got {args.world} (use --algo ring)")
        out = bench_host(args.world, args.size_mb, args.iters, args.algo)
    else:
        out = bench_device(args.size_mb, args.iters, args.quant)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
