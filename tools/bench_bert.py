"""BERT-base MLM pretrain throughput: samples/sec/chip + MFU.

One of the driver-designated metrics (BASELINE.md: "BERT-base MLM
samples/sec") with no published reference number — this tool establishes
the rebuild's own baseline on the live backend, end-to-end through the
jitted Trainer step (mixed bf16, adamw, masked-token-weighted loss).

MFU uses the standard encoder FLOP estimate:
  flops/token ≈ 6·N_params + 12·L·d_model·seq
(6·N covers fwd+bwd matmuls; the attention term is un-halved — BERT
attention is bidirectional, not causal).

Prints one JSON line per run (bench_lm.py conventions).
"""

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))  # repo root (the package)
sys.path.insert(0, _HERE)                   # tools/ (bench_lm helpers)

from bench_lm import (  # noqa: E402
    check_hbm_budget,
    param_count,
    peak_tflops,
    timed_step_seconds,
)


def bench_bert(preset: str, batch: int, seq: int, warmup: int, iters: int,
               force_hbm: bool = False, remat: bool = False):
    import jax
    import numpy as np
    import optax

    from tensorflow_train_distributed_tpu.models import bert
    from tensorflow_train_distributed_tpu.parallel.sharding import shard_batch
    from tensorflow_train_distributed_tpu.runtime.mesh import (
        MeshConfig, build_mesh,
    )
    from tensorflow_train_distributed_tpu.training import (
        Policy, Trainer, TrainerConfig,
    )

    import dataclasses

    cfg = bert.BERT_PRESETS[preset]
    if remat:
        cfg = dataclasses.replace(cfg, remat=True)
    if seq > cfg.max_positions:
        raise SystemExit(f"--seq {seq} > max_positions {cfg.max_positions}")
    task = bert.make_task(cfg)
    import jax.numpy as jnp

    mesh = build_mesh(MeshConfig(data=-1))
    n_chips = mesh.devices.size
    abstract = jax.eval_shape(lambda: task.init_variables(
        jax.random.key(0),
        {"input_ids": jnp.zeros((1, seq), jnp.int32)}))
    # Bidirectional attention; BERT runs the reference einsum attention,
    # which saves per-head [B,H,S,S] for backward when remat is off —
    # score_heads makes the estimate account for that.
    check_hbm_budget(
        param_count(abstract["params"]), cfg.num_layers, cfg.hidden_size,
        batch, seq, remat=cfg.remat, causal=False, force=force_hbm,
        device=mesh.devices.flat[0], score_heads=cfg.num_heads)
    trainer = Trainer(
        task, optax.adamw(1e-4, weight_decay=0.01), mesh,
        policy=Policy.from_name("mixed_bfloat16"),
        config=TrainerConfig(log_every=1_000_000),
    )
    rng = np.random.default_rng(0)
    global_batch = batch * n_chips
    # 15% masked positions, the BERT pretrain convention.
    weights = np.zeros((global_batch, seq), np.float32)
    for row in weights:
        row[rng.choice(seq, max(1, int(0.15 * seq)), replace=False)] = 1.0
    data = {
        "input_ids": rng.integers(0, cfg.vocab_size,
                                  (global_batch, seq)).astype(np.int32),
        "labels": rng.integers(0, cfg.vocab_size,
                               (global_batch, seq)).astype(np.int32),
        "mask_weights": weights,
    }
    state = trainer.create_state(data)
    n_params = param_count(state.params)
    step = trainer._compiled_train_step()
    dev_batch = shard_batch(mesh, data)
    dt = timed_step_seconds(step, state, dev_batch, warmup, iters)
    samples_per_sec_chip = global_batch / dt / n_chips
    dev0 = mesh.devices.flat[0]
    flops_per_token = (6 * n_params
                       + 12 * cfg.num_layers * cfg.hidden_size * seq)
    rec = {
        "metric": f"{preset}_mlm_samples_per_sec_per_chip",
        "value": round(samples_per_sec_chip, 1),
        "unit": "samples/sec/chip",
        "step_time_ms": round(dt * 1e3, 2),
        "batch_per_chip": batch,
        "seq_len": seq,
        "n_chips": n_chips,
        "n_params": n_params,
        "backend": dev0.platform,
    }
    peak = peak_tflops(dev0)
    if peak is not None:
        mfu = samples_per_sec_chip * seq * flops_per_token / (peak * 1e12)
        rec["mfu_pct"] = round(100 * mfu, 2)
        rec["device_kind"] = dev0.device_kind
    return rec


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--preset", default="bert_base")
    p.add_argument("--batch-per-chip", type=int, default=32)
    p.add_argument("--seq", type=int, default=128,
                   help="pretrain phase-1 convention: seq 128")
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--platform", default="",
                   help="force a jax platform (e.g. 'cpu' for a smoke run "
                        "that must not touch the TPU tunnel)")
    p.add_argument("--force-hbm", action="store_true",
                   help="skip the pre-flight HBM estimate (an OOM compile "
                        "can kill the chip tunnel)")
    p.add_argument("--remat", action="store_true",
                   help="per-layer activation checkpointing (bigger "
                        "batch/seq at recompute cost)")
    args = p.parse_args(argv)
    if args.platform:
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            force_platform,
        )

        force_platform(args.platform)
    import contextlib

    if args.platform and args.platform != "tpu":
        cm = contextlib.nullcontext()
    else:
        # May touch the single-chip tunnel: serialize with every other
        # framework TPU process (concurrent use corrupts timings).
        from tensorflow_train_distributed_tpu.runtime.chip_lock import (
            chip_lock,
        )

        cm = chip_lock()
    try:
        with cm:
            rec = bench_bert(args.preset, args.batch_per_chip, args.seq,
                             args.warmup, args.iters,
                             force_hbm=args.force_hbm, remat=args.remat)
    except Exception as e:  # machine-readable failure, bench.py lesson
        print(json.dumps({
            "metric": f"{args.preset}_mlm_samples_per_sec_per_chip",
            "value": 0.0, "unit": "samples/sec/chip",
            "error": f"{type(e).__name__}: {e}"}), flush=True)
        return 1
    print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
