"""Export a trained config as a TF SavedModel (serving interop).

Usage:
  python tools/export_savedmodel.py --config mnist \
      --checkpoint-dir /ckpt --out /tmp/mnist_saved
  (omit --checkpoint-dir to export a fresh init — signature smoke test)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--config", required=True)
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--out", required=True)
    p.add_argument("--platform", default="cpu",
                   help="jax platform for the export trace ('' = default)")
    args = p.parse_args(argv)
    from tensorflow_train_distributed_tpu.export_tf import (
        export_from_registry,
    )

    export_from_registry(args.config, args.checkpoint_dir, args.out,
                         platform=args.platform)
    print(f"SavedModel written to {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
