"""ViT image-classification train throughput: images/sec/chip + MFU.

Beyond the reference's model list (SURVEY.md §2.1 has LeNet/ResNet-50 for
vision) — the ViT family rides the shared encoder stack, so this bench
gives the transformer-vision silicon number next to ResNet's.  Runs the
jitted Trainer step end-to-end (mixed bf16, adamw, label smoothing).

MFU uses the encoder FLOP estimate over the patch sequence:
  flops/image ≈ S·(6·N_params + 12·L·hidden·S)
(S = patches (+1 for cls pooling); bidirectional attention, un-halved —
the BERT convention in tools/bench_bert.py).

Prints one JSON line per run (bench_lm.py conventions).
"""

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))  # repo root (the package)
sys.path.insert(0, _HERE)                   # tools/ (bench_lm helpers)

from bench_lm import (  # noqa: E402
    check_hbm_budget,
    param_count,
    peak_tflops,
    timed_step_seconds,
)


def bench_vit(preset: str, batch: int, warmup: int, iters: int,
              force_hbm: bool = False, remat: bool = False):
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from tensorflow_train_distributed_tpu.models import vit
    from tensorflow_train_distributed_tpu.parallel.sharding import shard_batch
    from tensorflow_train_distributed_tpu.runtime.mesh import (
        MeshConfig, build_mesh,
    )
    from tensorflow_train_distributed_tpu.training import (
        Policy, Trainer, TrainerConfig,
    )

    cfg = vit.VIT_PRESETS[preset]
    if remat:
        cfg = dataclasses.replace(cfg, remat=True)
    task = vit.make_task(cfg)
    mesh = build_mesh(MeshConfig(data=-1))
    n_chips = mesh.devices.size
    seq = cfg.num_patches + (1 if cfg.pooling == "cls" else 0)
    abstract = jax.eval_shape(lambda: task.init_variables(
        jax.random.key(0),
        {"image": jnp.zeros((1, cfg.image_size, cfg.image_size, 3)),
         "label": jnp.zeros((1,), jnp.int32)}))
    # Encoder shapes: bidirectional einsum attention saves per-head
    # [B,H,S,S] for backward when remat is off (the BERT guard setup).
    check_hbm_budget(
        param_count(abstract["params"]), cfg.num_layers, cfg.hidden_size,
        batch, seq, remat=cfg.remat, causal=False, force=force_hbm,
        device=mesh.devices.flat[0], score_heads=cfg.num_heads)
    trainer = Trainer(
        task, optax.adamw(1e-3, weight_decay=0.05), mesh,
        policy=Policy.from_name("mixed_bfloat16"),
        config=TrainerConfig(log_every=1_000_000),
    )
    rng = np.random.default_rng(0)
    global_batch = batch * n_chips
    data = {
        "image": rng.normal(0, 1, (global_batch, cfg.image_size,
                                   cfg.image_size, 3)).astype(np.float32),
        "label": rng.integers(0, cfg.num_classes,
                              (global_batch,)).astype(np.int32),
    }
    state = trainer.create_state(data)
    n_params = param_count(state.params)
    step = trainer._compiled_train_step()
    dev_batch = shard_batch(mesh, data)
    dt = timed_step_seconds(step, state, dev_batch, warmup, iters)
    images_per_sec_chip = global_batch / dt / n_chips
    dev0 = mesh.devices.flat[0]
    flops_per_image = seq * (
        6 * n_params + 12 * cfg.num_layers * cfg.hidden_size * seq)
    rec = {
        "metric": f"{preset}_train_images_per_sec_per_chip",
        "value": round(images_per_sec_chip, 1),
        "unit": "images/sec/chip",
        "step_time_ms": round(dt * 1e3, 2),
        "batch_per_chip": batch,
        "patch_seq": seq,
        "n_chips": n_chips,
        "n_params": n_params,
        "backend": dev0.platform,
    }
    peak = peak_tflops(dev0)
    if peak is not None:
        mfu = images_per_sec_chip * flops_per_image / (peak * 1e12)
        rec["mfu_pct"] = round(100 * mfu, 2)
        rec["device_kind"] = dev0.device_kind
        if mfu > 0.75:
            # No real training step sustains >75% MFU; a tunnel timing
            # artifact does (hunter requeues, merge skips these).
            rec["implausible"] = True
    return rec


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--preset", default="vit_b16")
    p.add_argument("--batch-per-chip", type=int, default=64)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--platform", default="",
                   help="force a jax platform (e.g. 'cpu' for a smoke run "
                        "that must not touch the TPU tunnel)")
    p.add_argument("--force-hbm", action="store_true",
                   help="skip the pre-flight HBM estimate (an OOM compile "
                        "can kill the chip tunnel)")
    p.add_argument("--remat", action="store_true",
                   help="per-layer activation checkpointing (bigger batch "
                        "at recompute cost)")
    args = p.parse_args(argv)
    if args.platform:
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            force_platform,
        )

        force_platform(args.platform)
    import contextlib

    if args.platform and args.platform != "tpu":
        cm = contextlib.nullcontext()
    else:
        from tensorflow_train_distributed_tpu.runtime.chip_lock import (
            chip_lock,
        )

        cm = chip_lock()
    try:
        with cm:
            rec = bench_vit(args.preset, args.batch_per_chip,
                            args.warmup, args.iters,
                            force_hbm=args.force_hbm, remat=args.remat)
    except Exception as e:  # machine-readable failure, bench.py lesson
        print(json.dumps({
            "metric": f"{args.preset}_train_images_per_sec_per_chip",
            "value": 0.0, "unit": "images/sec/chip",
            "error": f"{type(e).__name__}: {e}"}), flush=True)
        return 1
    print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
