#!/bin/bash
# Mini-convergence capture: the EXACT recipes behind the committed
# profiles/convergence/*.jsonl artifacts (300 or 1000 steps each through the
# real CLI on the host CPU; ~25 min on a 1-core box).  Re-render the
# report afterwards: python tools/render_convergence.py --write
# CI pins 80-step versions of the same runs (tests/test_convergence.py).
set -e
cd "$(dirname "$0")/.."
OUT=profiles/convergence
# bnsub certification pair: identical data/seed/LR; only BN statistics differ.
for cfg in resnet50_imagenet_s2d resnet50_imagenet_s2d_bnsub; do
  rm -f $OUT/${cfg}_32px.jsonl
  timeout 3000 python -m tensorflow_train_distributed_tpu \
      --config $cfg --steps 300 --global-batch-size 8 --platform cpu \
      --log-every 1 --lr-schedule constant --learning-rate 0.01 \
      --dataset-kwarg image_size=32 --dataset-kwarg num_examples=512 \
      --dataset-kwarg num_classes=100 \
      --jsonl-log $OUT/${cfg}_32px.jsonl >/dev/null 2>&1
  echo "done: $cfg"
done
# Multi-epoch mini-convergence: 1024 examples / batch 16 = 64 steps/epoch,
# 300 steps = ~4.7 epochs.
rm -f $OUT/bert_tiny_mlm.jsonl $OUT/llama_tiny_sft.jsonl
timeout 3000 python -m tensorflow_train_distributed_tpu \
    --config bert_tiny_mlm --steps 300 --global-batch-size 16 \
    --platform cpu --log-every 1 --dataset-kwarg num_examples=1024 \
    --jsonl-log $OUT/bert_tiny_mlm.jsonl >/dev/null 2>&1
echo "done: bert_tiny_mlm"
timeout 3000 python -m tensorflow_train_distributed_tpu \
    --config llama_tiny_sft --steps 300 --global-batch-size 16 \
    --platform cpu --log-every 1 --dataset-kwarg num_examples=1024 \
    --jsonl-log $OUT/llama_tiny_sft.jsonl >/dev/null 2>&1
echo "done: llama_tiny_sft"
# Long-horizon artifacts: 1000 steps (~15.6 epochs at 1024/16) for the
# bert/decoder families — the strongest sustained-training baselines.
for cfg in bert_tiny_mlm llama_tiny_sft; do
  rm -f $OUT/${cfg}_1k.jsonl
  timeout 5000 python -m tensorflow_train_distributed_tpu \
      --config $cfg --steps 1000 --global-batch-size 16 --platform cpu \
      --log-every 1 --dataset-kwarg num_examples=1024 \
      --jsonl-log $OUT/${cfg}_1k.jsonl >/dev/null 2>&1
  echo "done: ${cfg}_1k"
done
# gmm certification pair: dense vs dropless expert dispatch, same data/LR
# — plus the shared-expert variant (same data/LR; the always-on SwiGLU
# should match-or-beat the plain router curve).
for cfg in moe_tiny_lm moe_tiny_lm_gmm moe_tiny_shared_lm; do
  rm -f $OUT/${cfg}.jsonl
  timeout 2500 python -m tensorflow_train_distributed_tpu \
      --config $cfg --steps 300 --global-batch-size 16 --platform cpu \
      --log-every 1 --dataset-kwarg num_examples=1024 \
      --jsonl-log $OUT/${cfg}.jsonl >/dev/null 2>&1
  echo "done: $cfg"
done
echo ALL_DONE
