"""Decoder DECODE throughput: generated tokens/sec/chip (serving side).

Beyond the reference (a training harness with no serving loop): measures
the KV-cache autoregressive path — one jitted prefill + ``lax.scan``
decode — end-to-end through ``models.generate``.  The decode regime is
memory-bandwidth-bound (each step reads all params + the cache for one
token), so the companion number is model-bandwidth utilization (MBU):
bytes-touched/step ≈ param_bytes + cache_bytes vs the chip's HBM
bandwidth — the serving analog of training MFU.

Prints one JSON line (bench_lm.py conventions; chip lock held on TPU).
"""

import argparse
import contextlib
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tensorflow_train_distributed_tpu.training.memory import (  # noqa: E402
    hbm_bandwidth_bytes_per_sec,
    hbm_budget_bytes,
)


def bench_generate(preset: str, batch: int, prompt_len: int,
                   max_new: int, warmup: int, iters: int,
                   temperature: float = 0.0,
                   force_hbm: bool = False,
                   sliding_window: int = 0,
                   quant: str = "",
                   kv_cache_int8: bool = False):
    import dataclasses
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tensorflow_train_distributed_tpu.models import generate, llama

    if max_new < 2:
        # The decode-step rate is (full - one-step) / (max_new - 1); a
        # single new token IS the prefill call. Guarded here too so
        # library callers get the clean error, not ZeroDivisionError.
        raise ValueError(f"max_new must be >= 2, got {max_new}")
    if preset in llama.LLAMA_PRESETS:
        cfg = llama.LLAMA_PRESETS[preset]
        model_cls = llama.LlamaModel
    else:
        # MoE presets decode through the same generate() dispatch.
        from tensorflow_train_distributed_tpu.models import moe

        if preset not in moe.MOE_PRESETS:
            # ValueError, not SystemExit: main()'s except-Exception turns
            # it into the one-JSON-line error record consumers parse.
            raise ValueError(
                f"unknown preset {preset!r}: not in LLAMA_PRESETS or "
                f"MOE_PRESETS")
        cfg = moe.MOE_PRESETS[preset]
        model_cls = moe.MoeLmModel
        if kv_cache_int8 or sliding_window:
            raise ValueError(
                "--kv-cache/--sliding-window apply to llama-family "
                "presets only")
    if kv_cache_int8:
        cfg = dataclasses.replace(cfg, kv_cache_int8=True)
    if sliding_window:
        # A/B the rolling window-sized KV cache against the preset's full
        # attention (cache rows = window instead of prompt+new).
        cfg = dataclasses.replace(cfg, sliding_window=sliding_window)
    total_len = prompt_len + max_new
    if total_len > cfg.max_positions:
        raise SystemExit(
            f"prompt+new = {total_len} > max_positions "
            f"{cfg.max_positions}")
    dev = jax.devices()[0]
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(
        1, cfg.vocab_size, (batch, prompt_len)).astype(np.int32))
    model = model_cls(cfg)
    abstract = jax.eval_shape(
        lambda: model.init(jax.random.key(0), prompt[:, :8]))
    n_params = sum(x.size for x in
                   jax.tree_util.tree_leaves(abstract["params"]))
    # Decode working set in the config's COMPUTE dtype (generate casts
    # params to cfg.dtype; tiny presets are f32, big ones bf16): cast
    # params + the KV cache (2 tensors × L × B × total_len × kv_heads ×
    # head_dim).
    itemsize = jnp.dtype(cfg.dtype).itemsize
    kv_heads = cfg.num_kv_heads or cfg.num_heads
    # Normalize the llama-only knobs ONCE (MoeConfig lacks the fields and
    # its branch above rejected the flags) — scattered getattrs would
    # mask attribute typos (the lora.spec_of lesson).
    cfg_window = getattr(cfg, "sliding_window", None)
    cfg_kv8 = bool(getattr(cfg, "kv_cache_int8", False))
    cache_rows = total_len
    if cfg_window and cfg_window < total_len:
        cache_rows = cfg_window  # rolling ring buffer
    kv_itemsize = 1 if cfg_kv8 else itemsize
    cache_bytes = (2 * cfg.num_layers * batch * cache_rows
                   * kv_heads * (cfg.d_model // cfg.num_heads)
                   * kv_itemsize)
    if cfg_kv8:
        # Plus the f32 per-(position, kv_head) scale buffers (2 per
        # layer: k and v) — ~6% of the bf16 cache at head_dim 64, and
        # they stream on every step just like the cache rows.
        cache_bytes += 2 * cfg.num_layers * batch * cache_rows             * kv_heads * 4
    need = n_params * (itemsize + 4) + cache_bytes  # cast copy + f32 init
    budget = (hbm_budget_bytes(dev.device_kind)
              if dev.platform == "tpu" else None)
    if budget is not None and need > budget and not force_hbm:
        print(json.dumps({
            "error": "decode working set exceeds HBM budget; an OOM "
                     "compile can kill the chip tunnel — rerun with "
                     "--force-hbm to gamble",
            "estimated_gib": round(need / 2**30, 2),
            "budget_gib": round(budget / 2**30, 2)}), flush=True)
        raise SystemExit(2)
    params = model.init(jax.random.key(0), prompt[:, :8])["params"]
    quant_scales = None
    weight_bytes = n_params * itemsize
    if quant:
        if quant != "int8":
            raise SystemExit(f"--quant supports 'int8', got {quant!r}")
        from tensorflow_train_distributed_tpu.models.quant import (
            quantize_params,
            quantized_bytes,
        )

        params, quant_scales = quantize_params(params)
        # Exact per-step weight traffic: int8 kernels at 1 B, their f32
        # scales, and everything unquantized (embeds/norms — ~20% of a
        # 125M-class decoder, NOT negligible) at the compute dtype the
        # decode loop streams them in.
        weight_bytes = quantized_bytes(quant_scales) + sum(
            x.size * (1 if x.dtype == jnp.int8 else itemsize)
            for x in jax.tree_util.tree_leaves(params)
            if hasattr(x, "dtype"))

    def run(n):
        return generate.generate(cfg, params, prompt, n,
                                 temperature=temperature,
                                 rng=jax.random.key(1),
                                 quant_scales=quant_scales)

    def timed(n):
        # Warmup MUST fetch (np.asarray), not just block: on the axon
        # tunnel, block_until_ready on a never-fetched computation can
        # return at RPC-ack time (measured: a 256-token generate
        # "completing" in 0.92 ms — 100x the HBM roofline).  After one
        # real fetch the block path reflects device time (597 ms for the
        # same call), so the timed loop can keep the cheap block (a
        # per-iteration fetch would add ~85 ms of tunnel D2H latency to
        # every sample).
        np.asarray(run(n))  # compile + materialize
        for _ in range(warmup):
            np.asarray(run(n))
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = run(n)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters

    # Two timed variants separate prefill from the decode loop: the
    # max_new=1 call is prefill + one step, so the per-step decode time
    # is the difference divided by the extra steps — MBU then measures
    # the DECODE loop, not a prefill-diluted blend.
    dt_full = timed(max_new)
    dt_one = timed(1)
    step_s = max(dt_full - dt_one, 1e-9) / (max_new - 1)
    decode_tok_per_sec = batch / step_s
    rec = {
        "metric": f"{preset}_decode_tokens_per_sec_per_chip",
        "value": round(decode_tok_per_sec, 1),
        "unit": "tokens/sec/chip",
        "batch": batch,
        "prompt_len": prompt_len,
        "max_new_tokens": max_new,
        "time_per_call_ms": round(dt_full * 1e3, 2),
        "prefill_ms": round(dt_one * 1e3, 2),
        "ms_per_token_step": round(step_s * 1e3, 3),
        "call_tokens_per_sec": round(batch * max_new / dt_full, 1),
        "n_params": n_params,
        "backend": dev.platform,
    }
    if cfg_window:
        rec["sliding_window"] = cfg_window
        rec["kv_cache_rows"] = cache_rows
    if quant:
        rec["quant"] = quant
    if cfg_kv8:
        rec["kv_cache"] = "int8"
    bw = (hbm_bandwidth_bytes_per_sec(dev.device_kind)
          if dev.platform == "tpu" else None)
    if bw is not None:
        # Each decode step streams the cast params + the filled cache
        # once, whatever the batch (that's why batching decode is nearly
        # free until compute-bound).
        bytes_per_step = weight_bytes + cache_bytes
        rec["mbu_pct"] = round(100 * bytes_per_step / step_s / bw, 2)
        rec["device_kind"] = dev.device_kind
        if step_s < 0.5 * bytes_per_step / bw:
            # Faster than 2x the weight-streaming roofline: a timing
            # artifact (tunnel ack instead of device time), not physics.
            rec["implausible"] = True
    return rec


def _at_least_two(s: str) -> int:
    v = int(s)
    if v < 2:
        raise argparse.ArgumentTypeError(
            f"--max-new must be >= 2 (decode rate is measured against a "
            f"max_new=1 prefill call), got {v}")
    return v


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--preset", default="llama_125m")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--prompt-len", type=int, default=128)
    # >= 2: the decode-step rate comes from (full - one-step) / (n - 1).
    p.add_argument("--max-new", type=_at_least_two, default=128)
    p.add_argument("--warmup", type=int, default=1)
    p.add_argument("--iters", type=int, default=5)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--platform", default="",
                   help="force a jax platform ('cpu' for smoke runs)")
    p.add_argument("--force-hbm", action="store_true")
    p.add_argument("--sliding-window", type=int, default=0,
                   help="override the preset with sliding-window "
                        "attention: decode keeps a rolling WINDOW-row "
                        "KV cache (A/B vs full attention; 0 = preset "
                        "default)")
    p.add_argument("--kv-cache", default="", choices=["", "int8"],
                   help="'int8': quantized KV cache (linear cache only) "
                        "— halves cache HBM traffic, the large-batch "
                        "decode lever")
    p.add_argument("--quant", default="", choices=["", "int8"],
                   help="'int8': weight-only int8 serving "
                        "(models.quant) — kernels stream from HBM at "
                        "1 byte/param in the decode loop")
    args = p.parse_args(argv)
    if args.platform:
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            force_platform,
        )

        force_platform(args.platform)
    if args.platform and args.platform != "tpu":
        cm = contextlib.nullcontext()
    else:
        from tensorflow_train_distributed_tpu.runtime.chip_lock import (
            chip_lock,
        )

        cm = chip_lock()
    try:
        with cm:
            rec = bench_generate(args.preset, args.batch, args.prompt_len,
                                 args.max_new, args.warmup, args.iters,
                                 temperature=args.temperature,
                                 force_hbm=args.force_hbm,
                                 sliding_window=args.sliding_window,
                                 quant=args.quant,
                                 kv_cache_int8=args.kv_cache == "int8")
    except Exception as e:
        print(json.dumps({
            "metric": f"{args.preset}_decode_tokens_per_sec_per_chip",
            "value": 0.0, "unit": "tokens/sec/chip",
            "error": f"{type(e).__name__}: {e}"}), flush=True)
        return 1
    print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
