"""Sample from a trained (or HF-imported) decoder checkpoint via the CLI.

The inference face of the Llama family: load weights from an orbax
checkpoint dir (params-only partial restore — no optimizer state
materialized) or a local HuggingFace checkpoint, then run the KV-cache
``generate`` path (greedy / temperature / top-k / top-p).

Prompts are token ids: ``--prompt 1,15043,29892`` (comma-separated),
repeatable for a batch.  This CLI does no text tokenization itself —
transformers+tokenizers ARE installed in this image, so turn text into
ids with the checkpoint's own tokenizer (e.g.
``AutoTokenizer.from_pretrained(hf_dir).encode(text)``).

Examples:
  python tools/sample.py --config llama_tiny_sft --checkpoint-dir /ck \\
      --prompt 1,2,3 --max-new 32
  python tools/sample.py --config llama2_7b_sft --init-from-hf /hf \\
      --prompt 1,15043 --max-new 64 --temperature 0.8 --top-p 0.95
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _restore_params(checkpoint_dir: str):
    """Params-only orbax restore, with the isdir guard FIRST: orbax
    would create a typo'd directory as a side effect of opening it."""
    import os as _os

    from tensorflow_train_distributed_tpu.training.checkpoint import (
        CheckpointManager,
    )

    if not _os.path.isdir(checkpoint_dir):
        raise SystemExit(f"no checkpoint dir at {checkpoint_dir}")
    mgr = CheckpointManager(checkpoint_dir, async_save=False)
    params = mgr.restore_params()
    mgr.close()
    if params is None:
        raise SystemExit(f"no checkpoint under {checkpoint_dir}")
    return params


def load_decoder_params(args, cfg, is_moe):
    """Weights from --checkpoint-dir (orbax) or --init-from-hf (HF
    import with the registry config's layout) — shared by sample.py
    and serve.py.  Import validators exit with the clean CLI
    convention, not a traceback."""
    if getattr(args, "init_from_hf", None):
        from tensorflow_train_distributed_tpu.models import import_hf

        importer = (import_hf.import_moe if is_moe
                    else import_hf.import_llama)
        try:
            return importer(args.init_from_hf, cfg)
        except ValueError as e:
            raise SystemExit(str(e))
    return cfg, _restore_params(args.checkpoint_dir)


def resolve_decoder_task(config_name: str, verb: str):
    """Registry lookup + decoder-family guard (shared with serve.py).

    Returns ``(task, config, is_moe)`` or SystemExits with the CLI
    convention."""
    from tensorflow_train_distributed_tpu.models import registry
    from tensorflow_train_distributed_tpu.models.llama import CausalLmTask
    from tensorflow_train_distributed_tpu.models.moe import MoeLmTask

    task = registry.get_entry(config_name)["task_factory"]()
    if not isinstance(task, (CausalLmTask, MoeLmTask)):
        raise SystemExit(
            f"--config {config_name} is not a decoder LM; {verb} needs "
            "a llama- or moe-family config")
    return task, task.config, isinstance(task, MoeLmTask)


def parse_prompt_spec(spec: str, flag: str = "--prompt"):
    """One token-id list flag value -> list of ints (shared with
    serve.py, which also parses --prefix through it)."""
    try:
        return [int(t) for t in spec.split(",") if t]
    except ValueError:
        raise SystemExit(f"{flag} must be comma-separated ints, got "
                         f"{spec!r}")


def check_vocab_ids(rows, vocab_size: int) -> None:
    """Reject out-of-vocab prompt ids (shared with serve.py)."""
    bad = [t for r in rows for t in r if not 0 <= t < vocab_size]
    if bad:
        raise SystemExit(f"prompt ids outside vocab [0, {vocab_size}): "
                         f"{sorted(set(bad))[:8]}")


def apply_dispatch_arg(args, cfg, is_moe):
    """--dispatch override, applied to the config BEFORE weights load
    (dense and gmm share one parameter tree, so the override never
    invalidates a checkpoint) — shared with serve.py/serve_http.py."""
    if not getattr(args, "dispatch", ""):
        return cfg
    if not is_moe:
        raise SystemExit("--dispatch selects the MoE expert-dispatch "
                         "formulation; it does not apply to dense "
                         "decoder configs")
    import dataclasses

    return dataclasses.replace(cfg, dispatch=args.dispatch)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--config", required=True,
                   help="registry config name (a llama-family preset)")
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--checkpoint-dir",
                     help="orbax checkpoint dir (params-only restore)")
    src.add_argument("--init-from-hf",
                     help="local HuggingFace LlamaForCausalLM checkpoint")
    p.add_argument("--prompt", action="append", required=True,
                   metavar="IDS", help="comma-separated token ids; repeat "
                   "for a batch. Rows must be the SAME length (static "
                   "shapes, and the decode path has no pad masking — run "
                   "unequal prompts as separate batches)")
    p.add_argument("--max-new", type=int, default=32)
    p.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy")
    p.add_argument("--top-k", type=int, default=None)
    p.add_argument("--top-p", type=float, default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--quant", default="", choices=["", "int8"],
                   help="'int8': post-training weight-only quantization "
                        "(models.quant) before sampling — halves decode "
                        "weight HBM traffic vs bf16")
    p.add_argument("--lora-rank", type=int, default=0,
                   help="serve a LoRA checkpoint: the rank/alpha/targets "
                        "it was TRAINED with (adapters applied unmerged; "
                        "generate refuses adapter-bearing trees without "
                        "this). Composing with --quant requires merging "
                        "via tools/export_hf_checkpoint.py instead")
    p.add_argument("--lora-alpha", type=float, default=None,
                   help="default: the sidecar's, else 16.0")
    p.add_argument("--lora-targets", default=None,
                   help="default: the sidecar's, else query,value")
    p.add_argument("--speculative-draft-config", default=None,
                   help="enable speculative decoding: registry config of "
                        "the DRAFT model (same vocab; batch-1). Greedy "
                        "output is provably identical to the target's "
                        "own greedy decode; with --temperature the "
                        "rejection rule keeps the plain sampled law")
    p.add_argument("--speculative-draft-checkpoint", default=None,
                   help="orbax checkpoint dir for the draft's weights")
    p.add_argument("--speculative-k", type=int, default=4,
                   help="draft block length per round")
    p.add_argument("--dispatch", default="", choices=["", "dense", "gmm"],
                   help="MoE expert-dispatch override (MoE configs "
                        "only). 'gmm' is DROPLESS — routing, and "
                        "therefore outputs, legitimately differ from "
                        "capacity-dropped 'dense'. Default: the "
                        "config's own setting")
    p.add_argument("--platform", default="",
                   help="force a jax platform (e.g. 'cpu')")
    args = p.parse_args(argv)

    if args.platform:
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            force_platform,
        )

        force_platform(args.platform)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tensorflow_train_distributed_tpu.models.generate import generate

    task, cfg, is_moe = resolve_decoder_task(args.config, "sampling")
    cfg = apply_dispatch_arg(args, cfg, is_moe)

    rows = [parse_prompt_spec(spec) for spec in args.prompt]
    if not rows or any(not r for r in rows):
        raise SystemExit("--prompt rows must be non-empty")
    if len({len(r) for r in rows}) != 1:
        raise SystemExit(
            "all --prompt rows must have equal length (static shapes, and "
            "the decode path has no pad masking — padding would condition "
            "on pad tokens as real context; run unequal prompts as "
            "separate invocations)")
    if args.temperature == 0 and (args.top_k is not None
                                  or args.top_p is not None):
        raise SystemExit(
            "--top-k/--top-p filter a sampling distribution; add "
            "--temperature > 0 (they have no effect on greedy argmax)")
    check_vocab_ids(rows, cfg.vocab_size)
    if args.max_new < 1:
        raise SystemExit(f"--max-new must be >= 1, got {args.max_new}")
    if len(rows[0]) + args.max_new > cfg.max_positions:
        raise SystemExit(
            f"prompt {len(rows[0])} + --max-new {args.max_new} exceeds the "
            f"config's max_positions={cfg.max_positions} (the KV cache)")
    prompt = np.asarray(rows, np.int32)

    cfg, params = load_decoder_params(args, cfg, is_moe)

    import dataclasses as _dc

    from tensorflow_train_distributed_tpu.models.lora import (
        LoraSpec, load_spec, validate_targets,
    )

    sidecar = (load_spec(args.checkpoint_dir)
               if args.checkpoint_dir else None)
    spec = None
    flags_given = (args.lora_alpha is not None
                   or args.lora_targets is not None)
    if is_moe and (flags_given or args.lora_rank or sidecar is not None):
        raise SystemExit(
            "--lora-* applies to llama-family configs only (and this "
            "checkpoint dir carries a lora_spec.json sidecar, which a "
            "MoE config cannot serve)" if sidecar is not None else
            "--lora-* applies to llama-family configs only")
    if flags_given and not args.lora_rank:
        raise SystemExit(
            "--lora-alpha/--lora-targets need --lora-rank too (a lone "
            "flag would be silently dropped in favor of the checkpoint's "
            "lora_spec.json)")
    if args.lora_rank:
        try:
            spec = LoraSpec(
                rank=args.lora_rank,
                alpha=(16.0 if args.lora_alpha is None
                       else args.lora_alpha),
                targets=validate_targets(
                    ("query,value" if args.lora_targets is None
                     else args.lora_targets).split(",")))
        except ValueError as e:
            raise SystemExit(str(e))
        if sidecar is not None and spec != sidecar:
            raise SystemExit(
                f"--lora-* flags {spec} disagree with the checkpoint's "
                f"persisted lora_spec.json {sidecar} — drop the flags "
                "(the sidecar is authoritative) or fix them")
    elif sidecar is not None:
        spec = sidecar  # self-describing checkpoint
    if spec is not None:
        cfg = _dc.replace(cfg, lora=spec)

    # Speculative flag validation BEFORE any quantization work: these
    # checks only read args, and a doomed invocation must not pay a
    # full-tree quantize first.
    draft_task = None
    if args.speculative_draft_config:
        if args.quant or spec is not None:
            raise SystemExit(
                "--speculative-draft-config does not compose with "
                "--quant or LoRA serving (merge first).  Sampling DOES "
                "compose: with --temperature the draft samples its "
                "proposals and acceptance uses the rejection rule, so "
                "outputs follow the same law as plain sampled decoding")
        if is_moe:
            raise SystemExit("speculative decoding needs a llama-family "
                             "TARGET --config")
        if prompt.shape[0] != 1:
            raise SystemExit("speculative decoding is batch-1: pass ONE "
                             "--prompt")
        if not args.speculative_draft_checkpoint:
            raise SystemExit("--speculative-draft-checkpoint is required "
                             "with --speculative-draft-config")
        from tensorflow_train_distributed_tpu.models import registry
        from tensorflow_train_distributed_tpu.models.llama import (
            CausalLmTask,
        )

        draft_task = registry.get_entry(
            args.speculative_draft_config)["task_factory"]()
        if not isinstance(draft_task, CausalLmTask):
            # One accurate message (moe drafts are NOT accepted, so the
            # generic llama-or-moe wording would mislead).
            raise SystemExit("the draft config must be a llama-family "
                             "decoder")

    quant_scales = None
    if args.quant:
        from tensorflow_train_distributed_tpu.models.quant import (
            quantize_params,
        )

        params, quant_scales = quantize_params(params)

    if draft_task is not None:
        from tensorflow_train_distributed_tpu.models.speculative import (
            generate_speculative,
        )

        draft_params = _restore_params(args.speculative_draft_checkpoint)
        try:
            toks, stats = generate_speculative(
                cfg, params, draft_task.config, draft_params,
                jnp.asarray(prompt), args.max_new,
                k=args.speculative_k, temperature=args.temperature,
                top_k=args.top_k, top_p=args.top_p, seed=args.seed)
        except ValueError as e:
            # The library's guards (vocab match, k >= 1, the
            # prompt+max_new+k+1 cache budget on BOTH models, LoRA
            # leaves) — surface them as the clean CLI error every other
            # bad input gets.
            raise SystemExit(str(e))
        out = np.asarray(toks)
        print(json.dumps({"speculative_stats": stats}), flush=True)
    else:
        rng = (jax.random.key(args.seed)
               if args.temperature > 0 else None)
        out = np.asarray(generate(
            cfg, params, prompt, args.max_new,
            temperature=args.temperature, top_k=args.top_k,
            top_p=args.top_p, rng=rng, quant_scales=quant_scales))
    for row_in, row_out in zip(rows, out):
        print(json.dumps({
            "prompt": row_in,
            "completion": [int(t) for t in row_out[len(row_in):]],
        }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
