"""Export a trained Llama-family config as a HuggingFace checkpoint.

The inverse of ``--init-from-hf``: fine-tune on TPU meshes here, then
hand the directory to any HF consumer (``AutoModelForCausalLM.
from_pretrained`` loads it; sliding-window configs export as Mistral).

Usage:
  python tools/export_hf_checkpoint.py --config llama_tiny_sft \
      --checkpoint-dir /ckpt --out /tmp/hf_export
  (omit --checkpoint-dir to export a fresh init — interop smoke test)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--config", required=True)
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--out", required=True)
    p.add_argument("--platform", default="cpu",
                   help="jax platform for the restore ('' = default)")
    p.add_argument("--lora-alpha", type=float, default=16.0,
                   help="the --lora-alpha the checkpoint was TRAINED "
                        "with (checkpoints carrying adapters are merged "
                        "before export; alpha is not recoverable from "
                        "the weights)")
    args = p.parse_args(argv)
    from tensorflow_train_distributed_tpu.models.export_hf import (
        export_hf_from_registry,
    )

    out = export_hf_from_registry(args.config, args.checkpoint_dir,
                                  args.out, platform=args.platform,
                                  lora_alpha=args.lora_alpha)
    print(f"HF checkpoint written to {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
