"""MoE decoder training throughput: tokens/sec/chip + active-param MFU.

Beyond the reference (no MoE anywhere in it — SURVEY.md §2.4): the EP
family's silicon number, end-to-end through the jitted Trainer step
(GShard dense-dispatch routing, aux losses folded in, mixed bf16,
adamw).

MFU counts ACTIVE FLOPs (the MoE convention): each token runs the dense
trunk plus ``top_k`` of ``num_experts`` expert FFNs, so
  flops/token ≈ 6·(N_dense + (top_k/E)·N_expert)
               + 12·L·d_model·(seq/2)   (causal attention)
Counting total params instead would flatter a sparse model ~E/k×.

HBM pre-flight: the calibrated decoder activation model does not cover
MoE dispatch buffers, so the guard here is state-based with an explicit
dispatch-tensor term ([G,S,E,C] dispatch+combine in f32, the dominant
routing buffer) — deliberately conservative; --force-hbm overrides.

Prints one JSON line per run (bench_lm.py conventions).
"""

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))  # repo root (the package)
sys.path.insert(0, _HERE)                   # tools/ (bench_lm helpers)

from bench_lm import (  # noqa: E402
    hbm_budget_bytes,
    param_count,
    peak_tflops,
    timed_step_seconds,
)
from tensorflow_train_distributed_tpu.training.memory import (  # noqa: E402
    STATE_BYTES_PER_PARAM,
)


def _split_params(abstract_params):
    """(dense_params, expert_params) — expert leaves live under an
    'experts' module (the nn.vmap stack)."""
    import jax

    dense = expert = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(
            abstract_params)[0]:
        keys = [getattr(p, "key", "") for p in path]
        if "experts" in keys:
            expert += leaf.size
        else:
            dense += leaf.size
    return dense, expert


def bench_moe(preset: str, batch: int, seq: int, warmup: int, iters: int,
              force_hbm: bool = False, dispatch: str = "dense"):
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from tensorflow_train_distributed_tpu.models import moe
    from tensorflow_train_distributed_tpu.parallel.sharding import shard_batch
    from tensorflow_train_distributed_tpu.runtime.mesh import (
        MeshConfig, build_mesh,
    )
    from tensorflow_train_distributed_tpu.training import (
        Policy, Trainer, TrainerConfig,
    )

    cfg = dataclasses.replace(moe.MOE_PRESETS[preset], dispatch=dispatch)
    if seq > cfg.max_positions:
        raise SystemExit(f"--seq {seq} > max_positions {cfg.max_positions}")
    task = moe.MoeLmTask(cfg)
    mesh = build_mesh(MeshConfig(data=-1))
    n_chips = mesh.devices.size
    abstract = jax.eval_shape(lambda: task.init_variables(
        jax.random.key(0),
        {"tokens": jnp.zeros((1, seq), jnp.int32),
         "targets": jnp.zeros((1, seq), jnp.int32)}))
    n_params = param_count(abstract["params"])
    n_dense, n_expert = _split_params(abstract["params"])
    dev0 = mesh.devices.flat[0]
    budget = hbm_budget_bytes(dev0)
    if budget is not None and not force_hbm:
        # State + the routing/dispatch buffers; remat keeps per-layer
        # activations transient.  Conservative on purpose (an OOM compile
        # can kill the chip tunnel).
        n_moe_layers = -(-cfg.num_layers // max(cfg.moe_every, 1))
        if dispatch == "gmm":
            # Dropless path: expert-sorted row copies + f32 gate/up
            # activations instead of [G,S,E,C] dispatch one-hots.
            m = batch * seq * cfg.top_k
            dispatch_bytes = (m * (4 * cfg.d_model + 8 * cfg.ffn_size)
                              * n_moe_layers)
        else:
            capacity = max(1, int(cfg.capacity_factor * cfg.top_k * seq
                                  / cfg.num_experts))
            dispatch_bytes = (2 * batch * seq * cfg.num_experts * capacity
                              * 4 * n_moe_layers)
        act = 30 * cfg.num_layers * batch * seq * cfg.d_model * 2
        need = n_params * STATE_BYTES_PER_PARAM + dispatch_bytes + act
        if need > budget:
            print(json.dumps({
                "error": "pre-flight HBM estimate exceeds budget — rerun "
                         "with --force-hbm to gamble",
                "estimated_gib": round(need / 2**30, 2),
                "budget_gib": round(budget / 2**30, 2)}), flush=True)
            raise SystemExit(2)
    trainer = Trainer(
        task, optax.adamw(1e-4, b1=0.9, b2=0.95, weight_decay=0.1), mesh,
        policy=Policy.from_name("mixed_bfloat16"),
        config=TrainerConfig(log_every=1_000_000),
    )
    rng = np.random.default_rng(0)
    global_batch = batch * n_chips
    data = {
        "tokens": rng.integers(0, cfg.vocab_size,
                               (global_batch, seq)).astype(np.int32),
        "targets": rng.integers(0, cfg.vocab_size,
                                (global_batch, seq)).astype(np.int32),
    }
    state = trainer.create_state(data)
    step = trainer._compiled_train_step()
    dev_batch = shard_batch(mesh, data)
    dt = timed_step_seconds(step, state, dev_batch, warmup, iters)
    tok_per_sec_chip = global_batch * seq / dt / n_chips
    active = n_dense + n_expert * cfg.top_k / cfg.num_experts
    flops_per_token = (6 * active
                       + 12 * cfg.num_layers * cfg.d_model * seq * 0.5)
    name = preset if dispatch == "dense" else f"{preset}_{dispatch}"
    rec = {
        "metric": f"{name}_train_tokens_per_sec_per_chip",
        "value": round(tok_per_sec_chip, 1),
        "unit": "tokens/sec/chip",
        "step_time_ms": round(dt * 1e3, 2),
        "batch_per_chip": batch,
        "seq_len": seq,
        "n_chips": n_chips,
        "n_params": n_params,
        "n_active_params": int(active),
        "num_experts": cfg.num_experts,
        "top_k": cfg.top_k,
        "dispatch": dispatch,
        "backend": dev0.platform,
    }
    peak = peak_tflops(dev0)
    if peak is not None:
        mfu = tok_per_sec_chip * flops_per_token / (peak * 1e12)
        rec["mfu_pct"] = round(100 * mfu, 2)
        rec["device_kind"] = dev0.device_kind
        if mfu > 0.75:
            rec["implausible"] = True
    return rec


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--preset", default="moe_370m")
    p.add_argument("--batch-per-chip", type=int, default=8)
    p.add_argument("--seq", type=int, default=1024)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--platform", default="",
                   help="force a jax platform ('cpu' for smoke runs)")
    p.add_argument("--dispatch", default="dense", choices=["dense", "gmm"],
                   help="expert compute: GShard dense-dispatch einsums or "
                        "megablox grouped-matmul dropless routing")
    p.add_argument("--force-hbm", action="store_true")
    args = p.parse_args(argv)
    if args.platform:
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            force_platform,
        )

        force_platform(args.platform)
    import contextlib

    if args.platform and args.platform != "tpu":
        cm = contextlib.nullcontext()
    else:
        from tensorflow_train_distributed_tpu.runtime.chip_lock import (
            chip_lock,
        )

        cm = chip_lock()
    try:
        with cm:
            rec = bench_moe(args.preset, args.batch_per_chip, args.seq,
                            args.warmup, args.iters,
                            force_hbm=args.force_hbm,
                            dispatch=args.dispatch)
    except Exception as e:  # machine-readable failure, bench.py lesson
        name = (args.preset if args.dispatch == "dense"
                else f"{args.preset}_{args.dispatch}")
        print(json.dumps({
            "metric": f"{name}_train_tokens_per_sec_per_chip",
            "value": 0.0, "unit": "tokens/sec/chip",
            "dispatch": args.dispatch,
            "error": f"{type(e).__name__}: {e}"}), flush=True)
        return 1
    print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
