"""Summarize a jax.profiler trace: per-op-category device time per step.

The judge-facing evidence pipeline behind PROFILE.md: bench.py (and
``--profile-dir`` on the CLI) capture XPlane traces; this tool aggregates
the device plane's ``XLA Ops`` line into op-kind buckets (conv/matmul
fusions, BN statistics, converts, elementwise, copies, ...) so "where does
the step time go" is one command, not a notebook session.

Parses the raw ``xplane.pb`` with TensorFlow's bundled proto (same XPlane
stack the reference's profiler writes — SURVEY.md §5.1); no
tensorboard-plugin needed (its converter is binary-incompatible with the
installed TF in this env).

Usage:
  python tools/profile_summary.py profiles/bench/resnet50_s2d [--top 12]
  (positional arg: a trace dir containing plugins/profile/*/...xplane.pb,
   or a direct path to one .pb file)
"""

from __future__ import annotations

import argparse
import collections
import glob
import os
import re
import sys


def find_xplane(path: str) -> str:
    if path.endswith(".pb"):
        return path
    hits = sorted(glob.glob(
        os.path.join(path, "**", "*.xplane.pb"), recursive=True))
    if not hits:
        raise SystemExit(f"no *.xplane.pb under {path}")
    return hits[-1]  # newest capture


def classify(name: str) -> str:
    """HLO op name → coarse category."""
    m = re.match(r"%([a-z-]+)", name)
    kind = m.group(1) if m else "other"
    if kind == "fusion":
        if "convolution" in name or re.search(r"\bconv", name):
            return "fusion:conv"
        if re.search(r"= \(f32\[\d+\]", name):
            return "fusion:reduce-stats"   # BN-style per-channel stats
        if re.search(r"= (bf16|f32|f16)\[[\d,]+\]", name):
            return "fusion:elementwise"
        return "fusion:other"
    if kind == "convert":
        return "convert(+fused reduce)"
    if kind in ("copy-start", "copy-done", "copy"):
        return "copy"
    if kind in ("all-reduce", "all-gather", "reduce-scatter",
                "collective-permute", "all-to-all"):
        return "collective"
    if kind == "custom-call":
        return "custom-call (pallas/libtpu)"
    return kind


def summarize(pb_path: str, top: int = 12):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    xs = xplane_pb2.XSpace()
    with open(pb_path, "rb") as f:
        xs.ParseFromString(f.read())
    devices = [p for p in xs.planes
               if p.name.startswith("/device:") and p.lines]
    if not devices:
        raise SystemExit(f"{pb_path}: no device plane with events")
    out = []
    for plane in devices:
        md = plane.event_metadata
        steps_line = next((ln for ln in plane.lines if ln.name == "Steps"),
                          None)
        n_steps = max(len(steps_line.events), 1) if steps_line else 1
        ops_line = next((ln for ln in plane.lines if ln.name == "XLA Ops"),
                        None)
        if ops_line is None:
            continue
        agg = collections.Counter()
        cnt = collections.Counter()
        for ev in ops_line.events:
            cat = classify(md[ev.metadata_id].name)
            agg[cat] += ev.duration_ps
            cnt[cat] += 1
        total = sum(agg.values())
        rows = [(ps / 1e9 / n_steps, 100 * ps / total, cnt[c] // n_steps, c)
                for c, ps in agg.most_common(top)]
        out.append((plane.name, n_steps, total / 1e9 / n_steps, rows))
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("trace", help="trace dir or .xplane.pb file")
    p.add_argument("--top", type=int, default=12)
    args = p.parse_args(argv)
    pb = find_xplane(args.trace)
    print(f"# {pb}")
    for name, n_steps, ms_per_step, rows in summarize(pb, args.top):
        print(f"\n== {name}: {n_steps} steps, {ms_per_step:.2f} ms/step "
              "(XLA Ops line)")
        print(f"{'ms/step':>9}  {'share':>6}  {'ops/step':>8}  category")
        for ms, pct, n, cat in rows:
            print(f"{ms:9.2f}  {pct:5.1f}%  {n:8d}  {cat}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
