"""Decoder (Llama-family) training throughput: tokens/sec/chip + MFU.

Secondary benchmark (the driver's headline is bench.py / ResNet-50): the
flagship causal-LM path — RoPE/RMSNorm/SwiGLU, scan+remat, pallas flash
attention on TPU — measured end-to-end through the jitted Trainer step.

MFU uses the standard decoder FLOP estimate (PaLM-appendix style):
  flops/token ≈ 6·N_params + 12·L·d_model·seq·0.5   (causal attention)
fwd+bwd included in the 6·N factor; remat recompute is NOT counted (MFU is
model FLOPs, not hardware FLOPs — remat makes true utilization higher).

Prints one JSON line per benched config.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# bf16 peak TFLOP/s by TPU generation (device_kind substrings); MFU is
# omitted for kinds not listed rather than reported against a wrong peak.
PEAK_TFLOPS_BY_KIND = {
    "v5 lite": 197.0,
    "v5e": 197.0,
    "v5p": 459.0,
    "v4": 275.0,
    "v6": 918.0,
}


def peak_tflops(device) -> float | None:
    if device.platform != "tpu":
        return None
    kind = device.device_kind.lower()
    for sub, peak in PEAK_TFLOPS_BY_KIND.items():
        if sub in kind:
            return peak
    return None


def param_count(tree):
    import jax

    return sum(x.size for x in jax.tree_util.tree_leaves(tree))


def bench_lm(preset: str, batch: int, seq: int, warmup: int, iters: int,
             remat=None, remat_policy=None):
    import jax
    import numpy as np
    import optax

    from tensorflow_train_distributed_tpu.models import llama
    from tensorflow_train_distributed_tpu.parallel.sharding import shard_batch
    from tensorflow_train_distributed_tpu.runtime.mesh import (
        MeshConfig, build_mesh,
    )
    from tensorflow_train_distributed_tpu.training import (
        Policy, Trainer, TrainerConfig,
    )

    import dataclasses

    cfg = llama.LLAMA_PRESETS[preset]
    if remat is not None:
        # remat trades recompute for memory; when the model fits without
        # it (small presets, single chip) turning it off is pure speed.
        cfg = dataclasses.replace(cfg, remat=remat)
    if remat_policy is not None:
        cfg = dataclasses.replace(cfg, remat_policy=remat_policy)
    if seq > cfg.max_positions:
        raise SystemExit(f"--seq {seq} > max_positions {cfg.max_positions}")
    mesh = build_mesh(MeshConfig(data=-1))
    n_chips = mesh.devices.size
    task = llama.CausalLmTask(cfg)
    trainer = Trainer(
        task, optax.adamw(1e-4, b1=0.9, b2=0.95, weight_decay=0.1), mesh,
        policy=Policy.from_name("mixed_bfloat16"),
        config=TrainerConfig(log_every=1_000_000),
    )
    rng = np.random.default_rng(0)
    global_batch = batch * n_chips
    data = {
        "tokens": rng.integers(0, cfg.vocab_size,
                               (global_batch, seq)).astype(np.int32),
        "targets": rng.integers(0, cfg.vocab_size,
                                (global_batch, seq)).astype(np.int32),
    }
    state = trainer.create_state(data)
    n_params = param_count(state.params)
    step = trainer._compiled_train_step()
    dev_batch = shard_batch(mesh, data)
    for _ in range(warmup):
        state, m = step(state, dev_batch)
    jax.block_until_ready(state)
    t0 = time.perf_counter()
    for _ in range(iters):
        state, m = step(state, dev_batch)
    jax.block_until_ready(m)
    dt = (time.perf_counter() - t0) / iters
    tok_per_sec_chip = global_batch * seq / dt / n_chips
    dev0 = mesh.devices.flat[0]
    flops_per_token = 6 * n_params + 12 * cfg.num_layers * cfg.d_model * \
        seq * 0.5
    rec = {
        "metric": f"{preset}_train_tokens_per_sec_per_chip",
        "value": round(tok_per_sec_chip, 1),
        "unit": "tokens/sec/chip",
        "step_time_ms": round(dt * 1e3, 2),
        "batch_per_chip": batch,
        "seq_len": seq,
        "n_chips": n_chips,
        "n_params": n_params,
        "backend": dev0.platform,
    }
    peak = peak_tflops(dev0)
    if peak is not None:
        mfu = tok_per_sec_chip * flops_per_token / (peak * 1e12)
        rec["mfu_pct"] = round(100 * mfu, 2)
        rec["device_kind"] = dev0.device_kind
    return rec


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--preset", default="llama_125m")
    p.add_argument("--batch-per-chip", type=int, default=8)
    p.add_argument("--seq", type=int, default=2048)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--iters", type=int, default=10)
    rm = p.add_mutually_exclusive_group()
    rm.add_argument("--remat", dest="remat", action="store_true",
                    default=None, help="force activation remat on")
    rm.add_argument("--no-remat", dest="remat", action="store_false",
                    help="disable remat (faster when memory allows)")
    p.add_argument("--remat-policy", default=None,
                   choices=("full", "dots"),
                   help="what remat saves (see LlamaConfig.remat_policy)")
    args = p.parse_args(argv)
    try:
        rec = bench_lm(args.preset, args.batch_per_chip, args.seq,
                       args.warmup, args.iters, remat=args.remat,
                       remat_policy=args.remat_policy)
    except Exception as e:  # machine-readable failure, bench.py lesson
        print(json.dumps({"metric": f"{args.preset}_train_tokens_per_sec"
                          "_per_chip", "value": 0.0,
                          "unit": "tokens/sec/chip",
                          "error": f"{type(e).__name__}: {e}"}))
        return 1
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
