"""Decoder (Llama-family) training throughput: tokens/sec/chip + MFU.

Secondary benchmark (the driver's headline is bench.py / ResNet-50): the
flagship causal-LM path — RoPE/RMSNorm/SwiGLU, scan+remat, pallas flash
attention on TPU — measured end-to-end through the jitted Trainer step.

MFU uses the standard decoder FLOP estimate (PaLM-appendix style):
  flops/token ≈ 6·N_params + 12·L·d_model·seq·0.5   (causal attention)
fwd+bwd included in the 6·N factor; remat recompute is NOT counted (MFU is
model FLOPs, not hardware FLOPs — remat makes true utilization higher).

Prints one JSON line per benched config.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Device tables + the calibrated activation model live in the package
# (training.memory) — one source for bench tools and the planner.
from tensorflow_train_distributed_tpu.training.memory import (  # noqa: E402
    STATE_BYTES_PER_PARAM,
    decoder_activation_bytes,
)
from tensorflow_train_distributed_tpu.training.memory import (  # noqa: E402
    hbm_budget_bytes as _hbm_budget_for_kind,
)
from tensorflow_train_distributed_tpu.training.memory import (  # noqa: E402
    peak_tflops as _peak_for_kind,
)


def peak_tflops(device) -> float | None:
    if device.platform != "tpu":
        return None
    return _peak_for_kind(device.device_kind)


def param_count(tree):
    import jax

    return sum(x.size for x in jax.tree_util.tree_leaves(tree))


def hbm_budget_bytes(device) -> float | None:
    """Per-chip HBM budget, or None when the guard doesn't apply (non-TPU
    backend, or a TPU generation the table doesn't know)."""
    if device.platform != "tpu":
        return None
    return _hbm_budget_for_kind(device.device_kind)


def check_hbm_budget(n_params: int, n_layers: int, d_model: int,
                     batch: int, seq: int, remat: bool, *,
                     causal: bool, force: bool, device,
                     score_heads: int = 1,
                     ffn_size: int | None = None,
                     save_ffn_hiddens: bool = True) -> None:
    """Pre-flight HBM estimate — refuse configs that would OOM on-chip.

    An HBM-OOM *compile request* has twice killed this environment's
    single-chip tunnel for the rest of the session (see PROFILE.md), so a
    bench must not gamble.  Skipped entirely off-TPU (CPU smoke runs risk
    nothing).  The activation model (``training.memory``) is empirical,
    calibrated against observed XLA allocations on v5e; state is
    ``params × 14 B`` (bf16 compute copy + f32 master + 2×f32 adam
    moments + grads in flight).

    Raises SystemExit with a machine-readable JSON line unless ``force``.
    """
    budget = hbm_budget_bytes(device)
    if budget is None:
        return
    state = n_params * STATE_BYTES_PER_PARAM
    act = decoder_activation_bytes(n_layers, d_model, batch, seq,
                                   remat=remat, causal=causal,
                                   score_heads=score_heads,
                                   ffn_size=ffn_size,
                                   save_ffn_hiddens=save_ffn_hiddens)
    need = state + act
    # The estimate intentionally errs a little high (b16 no-remat: est 28
    # vs 26.4 GiB observed), so compare against the full budget: known-good
    # llama_125m b8 no-remat (est 14.9) passes, the two tunnel-killers
    # (b16 no-remat est 28, llama_1b no-remat state alone > 17) refuse.
    if need <= budget or force:
        return
    import json as _json

    print(_json.dumps({
        "error": "pre-flight HBM estimate exceeds budget; an OOM compile "
                 "can kill the chip tunnel — rerun with --force-hbm to "
                 "gamble anyway",
        "estimated_gib": round(need / 2**30, 2),
        "budget_gib": round(budget / 2**30, 2),
        "device_kind": device.device_kind,
        "state_gib": round(state / 2**30, 2),
        "activations_gib": round(act / 2**30, 2),
    }), flush=True)
    raise SystemExit(2)


def timed_step_seconds(step, state, dev_batch, warmup: int,
                       iters: int, trace_dir: str = "") -> float:
    """Shared measure loop: warmup, then a timed window; mean step s.

    The warmup FETCHES the step metrics (host transfer), not just
    block_until_ready: on the axon tunnel a block on a never-fetched
    computation can return at RPC-ack time (bench_generate measured a
    100x-roofline artifact exactly this way).  After one real fetch the
    block path reflects device time, so the timed loop keeps the cheap
    block — the chained state dependency forces each step anyway.

    ``trace_dir``: capture an XPlane trace of the TIMED window (post-
    warmup steady state) — one measure loop serves bench and profiling
    (the step donates its state buffers, so a second loop on the same
    state would hit deleted buffers).
    """
    import jax
    import numpy as np
    import time as _time

    for _ in range(max(warmup, 1)):  # >=1: the fetch must happen
        state, m = step(state, dev_batch)
        jax.tree.map(np.asarray, m)
    jax.block_until_ready(state)
    import contextlib

    if trace_dir:
        from tensorflow_train_distributed_tpu.runtime.profiling import (
            trace,
        )

        cm = trace(trace_dir)
    else:
        cm = contextlib.nullcontext()
    with cm:
        # Timestamps INSIDE the trace window: start_trace runs before t0
        # and stop_trace (XPlane serialization, 100s of ms) after t1, so
        # profiling never inflates the reported step time.
        t0 = _time.perf_counter()
        for _ in range(iters):
            state, m = step(state, dev_batch)
        jax.block_until_ready(m)
        t1 = _time.perf_counter()
    return (t1 - t0) / iters


def bench_lm(preset: str, batch: int, seq: int, warmup: int, iters: int,
             remat=None, remat_policy=None, force_hbm: bool = False,
             sliding_window: int = 0, fused_qkv: bool = False,
             scan_layers=None, profile_dir: str = ""):
    import jax
    import numpy as np
    import optax

    from tensorflow_train_distributed_tpu.models import llama
    from tensorflow_train_distributed_tpu.parallel.sharding import shard_batch
    from tensorflow_train_distributed_tpu.runtime.mesh import (
        MeshConfig, build_mesh,
    )
    from tensorflow_train_distributed_tpu.training import (
        Policy, Trainer, TrainerConfig,
    )

    import dataclasses

    cfg = llama.LLAMA_PRESETS[preset]
    if sliding_window:
        cfg = dataclasses.replace(cfg, sliding_window=sliding_window)
    if remat is not None:
        # remat trades recompute for memory; when the model fits without
        # it (small presets, single chip) turning it off is pure speed.
        cfg = dataclasses.replace(cfg, remat=remat)
    if remat_policy is not None:
        cfg = dataclasses.replace(cfg, remat_policy=remat_policy)
    if fused_qkv:
        # MFU lever A/B (fresh init both arms -- loss values differ from
        # split-projection runs, throughput is the comparison).
        cfg = dataclasses.replace(cfg, fused_qkv=True)
    if scan_layers is not None:
        # Unrolled-vs-scanned A/B: nn.scan keeps ONE compiled layer body
        # (fast compiles, the multi-chip default), but blocks XLA fusion
        # across layer boundaries -- a plausible MFU thief at 125m scale
        # where per-layer work is small.  Unrolling trades compile time
        # for whatever cross-layer fusion buys.
        cfg = dataclasses.replace(cfg, scan_layers=scan_layers)
    if seq > cfg.max_positions:
        raise SystemExit(f"--seq {seq} > max_positions {cfg.max_positions}")
    task = llama.CausalLmTask(cfg)
    import jax.numpy as jnp

    mesh = build_mesh(MeshConfig(data=-1))
    n_chips = mesh.devices.size
    abstract = jax.eval_shape(lambda: task.init_variables(
        jax.random.key(0),
        {"tokens": jnp.zeros((1, seq), jnp.int32),
         "targets": jnp.zeros((1, seq), jnp.int32)}))
    # remat_policy="dots" saves every matmul output — including the SwiGLU
    # hiddens that dominate the no-remat footprint — so for budgeting it
    # is the no-remat estimate, not the full-remat one.  "no_ffn" is the
    # no-remat estimate MINUS those hiddens (that's its whole point).
    effective_remat = cfg.remat and cfg.remat_policy not in ("dots",
                                                             "no_ffn")
    check_hbm_budget(
        param_count(abstract["params"]), cfg.num_layers, cfg.d_model,
        batch, seq, effective_remat, causal=True, force=force_hbm,
        device=mesh.devices.flat[0], ffn_size=cfg.ffn_size,
        save_ffn_hiddens=not (cfg.remat and cfg.remat_policy == "no_ffn"))
    trainer = Trainer(
        task, optax.adamw(1e-4, b1=0.9, b2=0.95, weight_decay=0.1), mesh,
        policy=Policy.from_name("mixed_bfloat16"),
        config=TrainerConfig(log_every=1_000_000),
    )
    rng = np.random.default_rng(0)
    global_batch = batch * n_chips
    data = {
        "tokens": rng.integers(0, cfg.vocab_size,
                               (global_batch, seq)).astype(np.int32),
        "targets": rng.integers(0, cfg.vocab_size,
                                (global_batch, seq)).astype(np.int32),
    }
    state = trainer.create_state(data)
    n_params = param_count(state.params)
    step = trainer._compiled_train_step()
    dev_batch = shard_batch(mesh, data)
    # profile_dir: XPlane trace of the timed window — the decoder analog
    # of bench.py's ResNet traces (render: tools/profile_summary.py).
    dt = timed_step_seconds(step, state, dev_batch, warmup, iters,
                            trace_dir=profile_dir)
    tok_per_sec_chip = global_batch * seq / dt / n_chips
    dev0 = mesh.devices.flat[0]
    # Average attended context per token: seq/2 causal; a binding
    # sliding window caps it (honest MFU — full-attention FLOPs would
    # overstate the windowed model's utilization).
    ctx = seq * 0.5
    if cfg.sliding_window and cfg.sliding_window < seq:
        w = cfg.sliding_window
        ctx = (w * (w + 1) / 2 + (seq - w) * w) / seq
    flops_per_token = 6 * n_params + 12 * cfg.num_layers * cfg.d_model * ctx
    rec = {
        "metric": f"{preset}_train_tokens_per_sec_per_chip",
        "value": round(tok_per_sec_chip, 1),
        "unit": "tokens/sec/chip",
        "step_time_ms": round(dt * 1e3, 2),
        "batch_per_chip": batch,
        "seq_len": seq,
        "n_chips": n_chips,
        "n_params": n_params,
        "backend": dev0.platform,
    }
    if cfg.sliding_window:
        rec["sliding_window"] = cfg.sliding_window
    if cfg.fused_qkv:
        rec["fused_qkv"] = True
    rec["scan_layers"] = cfg.scan_layers
    peak = peak_tflops(dev0)
    if peak is not None:
        mfu = tok_per_sec_chip * flops_per_token / (peak * 1e12)
        rec["mfu_pct"] = round(100 * mfu, 2)
        rec["device_kind"] = dev0.device_kind
        if mfu > 0.75:
            # No real training step sustains >75% MFU; a tunnel timing
            # artifact does (hunter requeues, merge skips these).
            rec["implausible"] = True
    return rec


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--preset", default="llama_125m")
    p.add_argument("--sliding-window", type=int, default=0,
                   help="override the preset with sliding-window "
                        "attention (O(seq*window) chunked path) — A/B "
                        "vs full attention; 0 = preset default")
    p.add_argument("--batch-per-chip", type=int, default=8)
    p.add_argument("--profile-dir", default="",
                   help="capture an XPlane trace of the timed steps into "
                        "this dir (render: tools/profile_summary.py)")
    p.add_argument("--seq", type=int, default=2048)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--iters", type=int, default=10)
    rm = p.add_mutually_exclusive_group()
    rm.add_argument("--remat", dest="remat", action="store_true",
                    default=None, help="force activation remat on")
    rm.add_argument("--no-remat", dest="remat", action="store_false",
                    help="disable remat (faster when memory allows)")
    sc = p.add_mutually_exclusive_group()
    sc.add_argument("--scan-layers", dest="scan_layers",
                    action="store_true", default=None)
    sc.add_argument("--no-scan-layers", dest="scan_layers",
                    action="store_false", default=None,
                    help="unroll the depth loop (A/B vs nn.scan: trades "
                         "compile time for cross-layer fusion)")
    p.add_argument("--fused-qkv", action="store_true",
                   help="fuse q/k/v into one gemm (MFU lever A/B; "
                        "param layout differs from split projections)")
    p.add_argument("--remat-policy", default=None,
                   choices=("full", "dots", "no_ffn"),
                   help="what remat saves (see LlamaConfig.remat_policy)")
    p.add_argument("--platform", default="",
                   help="force a jax platform (e.g. 'cpu' for a smoke run "
                        "that must not touch the TPU tunnel)")
    p.add_argument("--force-hbm", action="store_true",
                   help="skip the pre-flight HBM estimate (an OOM compile "
                        "can kill the chip tunnel)")
    args = p.parse_args(argv)
    if args.platform:
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            force_platform,
        )

        force_platform(args.platform)
    import contextlib

    if args.platform and args.platform != "tpu":
        cm = contextlib.nullcontext()
    else:
        # May touch the single-chip tunnel: serialize with every other
        # framework TPU process (concurrent use corrupts timings).
        from tensorflow_train_distributed_tpu.runtime.chip_lock import (
            chip_lock,
        )

        cm = chip_lock()
    try:
        with cm:
            rec = bench_lm(args.preset, args.batch_per_chip, args.seq,
                           args.warmup, args.iters, remat=args.remat,
                           remat_policy=args.remat_policy,
                           force_hbm=args.force_hbm,
                           sliding_window=args.sliding_window,
                           fused_qkv=args.fused_qkv,
                           scan_layers=args.scan_layers,
                           profile_dir=args.profile_dir)
    except Exception as e:  # machine-readable failure, bench.py lesson
        print(json.dumps({"metric": f"{args.preset}_train_tokens_per_sec"
                          "_per_chip", "value": 0.0,
                          "unit": "tokens/sec/chip",
                          "error": f"{type(e).__name__}: {e}"}))
        return 1
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
