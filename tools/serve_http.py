"""Online HTTP serving gateway over the continuous-batching engine.

The ONLINE face of ``serving.ServingEngine`` — where ``tools/serve.py``
collects every request up front and exits when the batch finishes, this
launcher keeps the engine decoding while a threaded HTTP frontend
(``tensorflow_train_distributed_tpu.server``) accepts, sheds, streams,
and times out requests concurrently:

- ``POST /v1/generate``  {"prompt": [ids], "max_new": N, "seed": S?,
  "stream": bool?, "timeout_s": F?} → {"id", "prompt", "tokens"}
  (tokens = prompt + continuation, byte-identical to serve.py on the
  same requests); ``stream`` chunks tokens as they commit (NDJSON).
- ``GET /healthz``  liveness + occupancy (503 while draining).
- ``GET /metrics``  Prometheus text: request/token counters, queue
  depth, slot occupancy (decoding + prefilling lanes), TTFT /
  inter-token / latency histograms, the engine's overlap ratio,
  ``ttd_engine_prefill_stall_seconds`` (decode time lost to atomic
  admission — ~0 with the default interleaved prefill scheduler), and
  the paged-KV cache economics: ``ttd_engine_kv_blocks_in_use`` /
  ``ttd_engine_kv_blocks_total`` (admission is block-keyed by
  default), ``ttd_engine_prefix_hit_tokens_total`` (prefill skipped
  via cross-request prefix sharing) and
  ``ttd_engine_kv_evictions_total``.

Robustness: admission queue bounded at ``--max-queue`` (beyond it: 429
with Retry-After), per-request deadlines (``--default-timeout`` /
per-request ``timeout_s`` → 504, slot freed), request-size and vocab
validation (``check_vocab_ids`` — same screens as serve.py), graceful
drain on SIGTERM/SIGINT (stop admitting, finish in-flight, flush
metrics).  With ``--replicas N`` the gateway fronts N independent
engine replicas (load + KV-affinity routing, per-replica health and
``--watchdog-timeout`` hung-dispatch detection in ``/healthz``,
deterministic failover that resumes a dead replica's requests on
survivors from their last streamed token, staged ``--drain-timeout``
drain); 503 only when NO replica can accept work.  Model/engine flags
are shared with serve.py (``add_engine_args``), so both CLIs configure
every replica identically.

Examples:
  python tools/serve_http.py --config llama_tiny_sft \\
      --checkpoint-dir /ck --port 8000 --slots 8
  curl -s localhost:8000/v1/generate -d '{"prompt": [1,2,3], "max_new": 16}'
  curl -s localhost:8000/metrics | grep ttd_gateway
"""

import argparse
import logging
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))  # repo root (the package)
sys.path.insert(0, _HERE)                   # tools/ siblings

from sample import (  # noqa: E402 (tools/ sibling)
    check_vocab_ids,
    resolve_decoder_task,
)
from serve import (  # noqa: E402 (tools/ sibling)
    add_engine_args,
    build_engine,
    maybe_dense_moe_hint,
    parse_prefix_arg,
)


def make_vocab_validator(vocab_size: int):
    """check_vocab_ids wears SystemExit (the CLI convention); the
    gateway needs a 400, so rewrap — one shared screen either way."""
    from tensorflow_train_distributed_tpu.server import RequestError

    def _validate(prompt, max_new, seed):
        try:
            check_vocab_ids([[int(t) for t in prompt]], vocab_size)
        except SystemExit as e:
            raise RequestError(str(e))

    return _validate


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    add_engine_args(p)
    p.add_argument("--host", default="0.0.0.0",
                   help="bind address (default: all interfaces)")
    p.add_argument("--port", type=int, default=8000,
                   help="0 = ephemeral (printed at startup)")
    p.add_argument("--max-queue", type=int, default=64,
                   help="admission bound: requests WAITING for a slot "
                        "beyond this are shed with 429 + Retry-After")
    p.add_argument("--default-timeout", type=float, default=0.0,
                   help="per-request deadline in seconds when the body "
                        "carries no timeout_s (0 = none); an expired "
                        "request answers 504 and frees its slot")
    p.add_argument("--retry-after", type=float, default=1.0,
                   help="Retry-After seconds on shed (429) responses")
    p.add_argument("--replicas", type=int, default=1,
                   help="engine replicas behind the gateway: with N>1 "
                        "admissions route by load + KV-prefix affinity, "
                        "each replica has its own health/watchdog, and "
                        "a request whose replica dies resumes on a "
                        "survivor from its last streamed token "
                        "(TTD_NO_FAILOVER=1 forces the single-engine "
                        "path)")
    p.add_argument("--watchdog-timeout", type=float, default=30.0,
                   help="seconds a decode dispatch may run before the "
                        "replica is declared dead (hung-device "
                        "detection; 0 disables — size it above "
                        "worst-case XLA compile time or warm up first)")
    p.add_argument("--drain-timeout", type=float, default=0.0,
                   help="bound on the SIGTERM drain (replicas drain "
                        "one at a time; 0 = wait indefinitely)")
    args = p.parse_args(argv)
    if args.replicas < 1:
        raise SystemExit(f"--replicas must be >= 1, got {args.replicas}")

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    if args.platform:
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            force_platform,
        )

        force_platform(args.platform)

    from tensorflow_train_distributed_tpu.server import ServingGateway

    _, cfg, is_moe = resolve_decoder_task(args.config, "serving")
    prefix_ids = parse_prefix_arg(args, cfg)
    # One engine per replica, configured identically (each builds its
    # own caches and preloads the prefix into its own pool — replica
    # state stays fully independent so any one can die alone).
    engines = [build_engine(args, cfg, is_moe, prefix_ids)
               for _ in range(args.replicas)]
    # Online: request lengths are unknowable at startup, so a dense-
    # dispatch MoE always gets the compile-storm warning.
    maybe_dense_moe_hint(engines[0])
    if args.replicas > 1:
        # Warm every replica before taking traffic: the decode program
        # (and one prefill shape) compiles now, so the first user
        # request is fast on every replica and the pool's
        # hung-dispatch watchdog never has to stare down a cold
        # compile (it additionally only arms after a replica's first
        # completed step).
        for i, eng in enumerate(engines):
            print(f"warming replica {i}...", flush=True)
            eng.submit([1], 1)
            eng.run()

    gw = ServingGateway(
        engines if args.replicas > 1 else engines[0],
        host=args.host, port=args.port, max_queue=args.max_queue,
        default_timeout_s=args.default_timeout or None,
        default_max_new=args.max_new,
        validate=make_vocab_validator(cfg.vocab_size),
        retry_after_s=args.retry_after,
        watchdog_timeout_s=args.watchdog_timeout or None)
    gw.install_signal_handlers(
        drain_timeout=args.drain_timeout or None)
    gw.start()
    print(f"gateway listening on {args.host}:{gw.port} "
          f"(config={args.config}, replicas={args.replicas}, "
          f"slots={args.slots}, max_queue={args.max_queue})", flush=True)
    gw.wait()           # until SIGTERM/SIGINT drains
    return 0


if __name__ == "__main__":
    sys.exit(main())
