"""Online HTTP serving gateway over the continuous-batching engine.

The ONLINE face of ``serving.ServingEngine`` — where ``tools/serve.py``
collects every request up front and exits when the batch finishes, this
launcher keeps the engine decoding while a threaded HTTP frontend
(``tensorflow_train_distributed_tpu.server``) accepts, sheds, streams,
and times out requests concurrently:

- ``POST /v1/generate``  {"prompt": [ids], "max_new": N, "seed": S?,
  "stream": bool?, "timeout_s": F?} → {"id", "prompt", "tokens"}
  (tokens = prompt + continuation, byte-identical to serve.py on the
  same requests); ``stream`` chunks tokens as they commit (NDJSON).
- ``GET /healthz``  liveness + occupancy (503 while draining).
- ``GET /metrics``  Prometheus text: request/token counters, queue
  depth, slot occupancy (decoding + prefilling lanes), TTFT /
  inter-token / latency histograms, the engine's overlap ratio,
  ``ttd_engine_prefill_stall_seconds`` (decode time lost to atomic
  admission — ~0 with the default interleaved prefill scheduler), and
  the paged-KV cache economics: ``ttd_engine_kv_blocks_in_use`` /
  ``ttd_engine_kv_blocks_total`` (admission is block-keyed by
  default), ``ttd_engine_prefix_hit_tokens_total`` (prefill skipped
  via cross-request prefix sharing) and
  ``ttd_engine_kv_evictions_total``.

Robustness: admission queue bounded at ``--max-queue`` (beyond it: 429
with Retry-After), per-request deadlines (``--default-timeout`` /
per-request ``timeout_s`` → 504, slot freed), request-size and vocab
validation (``check_vocab_ids`` — same screens as serve.py), graceful
drain on SIGTERM/SIGINT (stop admitting, finish in-flight, flush
metrics).  With ``--replicas N`` the gateway fronts N independent
engine replicas (load + KV-affinity routing, per-replica health and
``--watchdog-timeout`` hung-dispatch detection in ``/healthz``,
deterministic failover that resumes a dead replica's requests on
survivors from their last streamed token, staged ``--drain-timeout``
drain); 503 only when NO replica can accept work.  With ≥2 replicas
the staged drain MIGRATES each draining replica's live lanes to
survivors first (KV blocks + decode state over ``MIGRATE`` frames —
no re-prefill, no stream interruption; ``TTD_NO_MIGRATION=1``
restores wait-then-drain), and ``/healthz`` reports each draining
replica's ``lanes_remaining``.  Model/engine flags
are shared with serve.py (``add_engine_args``), so both CLIs configure
every replica identically.

Examples:
  python tools/serve_http.py --config llama_tiny_sft \\
      --checkpoint-dir /ck --port 8000 --slots 8
  curl -s localhost:8000/v1/generate -d '{"prompt": [1,2,3], "max_new": 16}'
  curl -s localhost:8000/metrics | grep ttd_gateway
"""

import argparse
import logging
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))  # repo root (the package)
sys.path.insert(0, _HERE)                   # tools/ siblings

from sample import (  # noqa: E402 (tools/ sibling)
    check_vocab_ids,
    resolve_decoder_task,
)
from serve import (  # noqa: E402 (tools/ sibling)
    add_engine_args,
    build_engine,
    maybe_dense_moe_hint,
    parse_prefix_arg,
)


def make_vocab_validator(vocab_size: int):
    """check_vocab_ids wears SystemExit (the CLI convention); the
    gateway needs a 400, so rewrap — one shared screen either way."""
    from tensorflow_train_distributed_tpu.server import RequestError

    def _validate(prompt, max_new, seed):
        try:
            check_vocab_ids([[int(t) for t in prompt]], vocab_size)
        except SystemExit as e:
            raise RequestError(str(e))

    return _validate


def _serve_procs(args, cfg) -> int:
    """The out-of-process gateway: N subprocess workers behind a
    ``ProcPool``, each rebuilding the engine from THIS CLI's serialized
    flags (``serve.worker_engine_factory``), so parent screening and
    worker engines agree.  A worker SIGKILL/OOM/native crash fails one
    replica (classified in /healthz) and its requests resume on a
    survivor; the pool scales between --scale-min/--scale-max and
    respawns dead workers under --restart-budget."""
    from tensorflow_train_distributed_tpu.server import (
        ProcPool,
        ServingGateway,
        WorkerSpec,
    )

    spec = WorkerSpec(
        factory="serve:worker_engine_factory",
        factory_json=dict(vars(args)),
        pythonpath=(_HERE,),
    )
    scale_min = args.scale_min or args.replicas
    scale_max = max(args.scale_max or args.replicas, scale_min)
    pool = ProcPool(
        spec, replicas=args.replicas, scale_min=scale_min,
        scale_max=scale_max, max_queue=args.max_queue,
        validate=make_vocab_validator(cfg.vocab_size),
        default_timeout_s=args.default_timeout or None,
        retry_after_s=args.retry_after,
        watchdog_timeout_s=args.watchdog_timeout or None,
        idle_grace_s=args.idle_grace,
        max_restarts=args.restart_budget)
    gw = ServingGateway(pool, host=args.host, port=args.port,
                        default_max_new=args.max_new)
    gw.install_signal_handlers(drain_timeout=args.drain_timeout or None)
    gw.start()
    # Advertise the port only once every worker finished its handshake
    # (engine built + warm in the child) — the warm-up analog.
    print(f"waiting for {args.replicas} subprocess workers...",
          flush=True)
    if not pool.wait_ready(timeout=600.0):
        print("workers failed to come up inside 600s; draining",
              flush=True)
        gw.drain(timeout=30)
        return 1
    print(f"gateway listening on {args.host}:{gw.port} "
          f"(config={args.config}, replica-procs={args.replicas}, "
          f"scale=[{scale_min},{scale_max}], slots={args.slots}, "
          f"max_queue={args.max_queue})", flush=True)
    gw.wait()           # until SIGTERM/SIGINT drains
    return 0


def _serve_net(args, cfg) -> int:
    """The multi-host gateway: a ``NetPool`` listens on ``--listen``
    and standalone worker daemons (``tools/serve_worker.py``, any
    machine) dial in, HELLO their ``--role``, and become replicas.
    Dedicated prefill workers stage prompts and hand finished KV to
    decode workers over binary KV_HANDOFF frames (disaggregated
    serving; ``TTD_NO_DISAGG=1`` collapses the role split, workers
    stay connected).  Engine flags on THIS CLI only drive gateway-side
    screening — each worker builds its engine from its OWN flags."""
    from tensorflow_train_distributed_tpu.server import (
        NetPool,
        ServingGateway,
    )

    lhost, sep, lport = args.listen.rpartition(":")
    if not sep or not lport.isdigit():
        raise SystemExit(f"--listen wants HOST:PORT, got {args.listen!r}")
    scale_min = args.scale_min or args.replicas
    max_workers = max(args.scale_max or args.replicas, scale_min)
    pool = NetPool(
        host=lhost or "0.0.0.0", port=int(lport),
        scale_min=scale_min, max_workers=max_workers,
        max_queue=args.max_queue,
        validate=make_vocab_validator(cfg.vocab_size),
        default_timeout_s=args.default_timeout or None,
        retry_after_s=args.retry_after,
        watchdog_timeout_s=args.watchdog_timeout or None,
        max_restarts=args.restart_budget)
    gw = ServingGateway(pool, host=args.host, port=args.port,
                        default_max_new=args.max_new)
    gw.install_signal_handlers(drain_timeout=args.drain_timeout or None)
    gw.start()
    print(f"worker listener on {lhost or '0.0.0.0'}:{pool.port}; "
          f"waiting for {scale_min} dial-in workers...", flush=True)
    if not pool.wait_ready(timeout=600.0):
        print("workers failed to dial in inside 600s; draining",
              flush=True)
        gw.drain(timeout=30)
        return 1
    print(f"gateway listening on {args.host}:{gw.port} "
          f"(config={args.config}, dial-in workers, "
          f"scale_min={scale_min}, max_workers={max_workers}, "
          f"max_queue={args.max_queue})", flush=True)
    gw.wait()           # until SIGTERM/SIGINT drains
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    add_engine_args(p)
    p.add_argument("--host", default="0.0.0.0",
                   help="bind address (default: all interfaces)")
    p.add_argument("--port", type=int, default=8000,
                   help="0 = ephemeral (printed at startup)")
    p.add_argument("--max-queue", type=int, default=64,
                   help="admission bound: requests WAITING for a slot "
                        "beyond this are shed with 429 + Retry-After")
    p.add_argument("--default-timeout", type=float, default=0.0,
                   help="per-request deadline in seconds when the body "
                        "carries no timeout_s (0 = none); an expired "
                        "request answers 504 and frees its slot")
    p.add_argument("--retry-after", type=float, default=1.0,
                   help="Retry-After seconds on shed (429) responses")
    p.add_argument("--replicas", type=int, default=1,
                   help="engine replicas behind the gateway: with N>1 "
                        "admissions route by load + KV-prefix affinity, "
                        "each replica has its own health/watchdog, and "
                        "a request whose replica dies resumes on a "
                        "survivor from its last streamed token "
                        "(TTD_NO_FAILOVER=1 forces the single-engine "
                        "path)")
    p.add_argument("--listen", default="", metavar="HOST:PORT",
                   help="multi-host serving: listen here for "
                        "tools/serve_worker.py daemons to DIAL IN as "
                        "replicas (same frame protocol as "
                        "--replica-procs, across machines; workers "
                        "declare --role prefill|decode|both for "
                        "disaggregated prefill→decode KV handoff; "
                        "--replicas/--scale-min is the dial-in floor "
                        "wait_ready blocks on, --scale-max the fleet "
                        "cap; TTD_NO_DISAGG=1 collapses the role "
                        "split)")
    p.add_argument("--replica-procs", action="store_true",
                   help="run each replica as a SUBPROCESS worker "
                        "(server.procpool) speaking the length-prefixed "
                        "driver protocol: a replica OOM/native crash/"
                        "SIGKILL fails one worker, never the gateway, "
                        "and the pool scales elastically between "
                        "--scale-min/--scale-max "
                        "(TTD_NO_PROC_REPLICAS=1 falls back to "
                        "in-process replicas)")
    p.add_argument("--scale-min", type=int, default=0,
                   help="--replica-procs: never drain below this many "
                        "workers (0 = --replicas); dead workers are "
                        "respawned toward it under --restart-budget")
    p.add_argument("--scale-max", type=int, default=0,
                   help="--replica-procs: spawn up to this many workers "
                        "under queue pressure (0 = --replicas — no "
                        "scale-up)")
    p.add_argument("--restart-budget", type=int, default=8,
                   help="--replica-procs: total dead-worker respawns "
                        "before the pool stops resurrecting (a crash-"
                        "looping engine must not fork-bomb); respawns "
                        "back off exponentially")
    p.add_argument("--idle-grace", type=float, default=30.0,
                   help="--replica-procs: seconds of whole-pool idle "
                        "before ONE scale-up worker is drained back "
                        "(staged, never below --scale-min)")
    p.add_argument("--watchdog-timeout", type=float, default=30.0,
                   help="seconds a decode dispatch may run before the "
                        "replica is declared dead (hung-device "
                        "detection; 0 disables — size it above "
                        "worst-case XLA compile time or warm up first)")
    p.add_argument("--drain-timeout", type=float, default=0.0,
                   help="bound on the SIGTERM drain (replicas drain "
                        "one at a time; 0 = wait indefinitely)")
    args = p.parse_args(argv)
    if args.replicas < 1:
        raise SystemExit(f"--replicas must be >= 1, got {args.replicas}")

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    if args.platform:
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            force_platform,
        )

        force_platform(args.platform)

    from tensorflow_train_distributed_tpu.server import ServingGateway

    _, cfg, is_moe = resolve_decoder_task(args.config, "serving")
    prefix_ids = parse_prefix_arg(args, cfg)

    if args.listen:
        return _serve_net(args, cfg)
    if args.replica_procs:
        from tensorflow_train_distributed_tpu.server.procpool import (
            proc_replicas_killed,
        )

        if proc_replicas_killed():
            print("TTD_NO_PROC_REPLICAS=1: subprocess replicas "
                  "disabled, falling back to in-process replicas",
                  flush=True)
            args.replica_procs = False
    if args.replica_procs:
        return _serve_procs(args, cfg)
    # One engine per replica, configured identically (each builds its
    # own caches and preloads the prefix into its own pool — replica
    # state stays fully independent so any one can die alone).
    engines = [build_engine(args, cfg, is_moe, prefix_ids)
               for _ in range(args.replicas)]
    # Online: request lengths are unknowable at startup, so a dense-
    # dispatch MoE always gets the compile-storm warning.
    maybe_dense_moe_hint(engines[0])
    if args.replicas > 1:
        # Warm every replica before taking traffic: the decode program
        # (and one prefill shape) compiles now, so the first user
        # request is fast on every replica and the pool's
        # hung-dispatch watchdog never has to stare down a cold
        # compile (it additionally only arms after a replica's first
        # completed step).
        for i, eng in enumerate(engines):
            print(f"warming replica {i}...", flush=True)
            eng.submit([1], 1)
            eng.run()

    gw = ServingGateway(
        engines if args.replicas > 1 else engines[0],
        host=args.host, port=args.port, max_queue=args.max_queue,
        default_timeout_s=args.default_timeout or None,
        default_max_new=args.max_new,
        validate=make_vocab_validator(cfg.vocab_size),
        retry_after_s=args.retry_after,
        watchdog_timeout_s=args.watchdog_timeout or None)
    gw.install_signal_handlers(
        drain_timeout=args.drain_timeout or None)
    gw.start()
    print(f"gateway listening on {args.host}:{gw.port} "
          f"(config={args.config}, replicas={args.replicas}, "
          f"slots={args.slots}, max_queue={args.max_queue})", flush=True)
    gw.wait()           # until SIGTERM/SIGINT drains
    return 0


if __name__ == "__main__":
    sys.exit(main())
