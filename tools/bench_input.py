"""Host input-pipeline throughput: records/sec through decode+augment.

The accelerator step is only half the ResNet story — the reference feeds
it from tf.data's parallel C++ decode. This tool measures what THIS
framework's host path sustains (pure CPU; safe to run with a dead chip
tunnel), so "input-bound vs compute-bound" is a measured fact:
chip consumes ~2430 img/s (PROFILE.md); the host must match it with
in-process decode, the out-of-process worker fleet (--data-workers), or
pre-decoded storage (the mmap path / native stager warm start).

Modes benched over one generated JPEG TFRecord corpus:
- inprocess: HostDataLoader + imagenet_train transform on the trainer
  thread;
- workersN: DataServiceDispatcher with N worker processes;
- mmap: the same images pre-decoded into the mmap shard layout
  (u8_image_to_f32 transform) — the storage-side answer.

Prints one JSON line: records/sec per mode.
"""

import argparse
import io
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _make_corpus(root: str, n: int, hw: int, shards: int = 4) -> None:
    import numpy as np
    from PIL import Image

    from tensorflow_train_distributed_tpu.data.tfrecord import (
        TFRecordWriter, encode_example, write_features_sidecar,
    )

    rng = np.random.default_rng(0)
    per = n // shards
    for s in range(shards):
        with TFRecordWriter(os.path.join(root,
                                         f"imgs-{s}.tfrecord")) as w:
            for i in range(per):
                arr = rng.integers(0, 255, (hw, hw, 3)).astype(np.uint8)
                buf = io.BytesIO()
                Image.fromarray(arr).save(buf, "JPEG")
                w.write(encode_example({
                    "image/encoded": buf.getvalue(),
                    "image/class/label": np.int64(i % 1000)}))
    write_features_sidecar(root, None)


def _drain(batches, max_records: int, batch_size: int) -> float:
    t0 = time.perf_counter()
    seen = 0
    for b in batches:
        seen += batch_size
        if seen >= max_records:
            break
    return seen / (time.perf_counter() - t0)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--records", type=int, default=512,
                   help="records per timed drain")
    p.add_argument("--image-hw", type=int, default=256,
                   help="stored JPEG side length (decode cost driver)")
    p.add_argument("--size", type=int, default=224,
                   help="output crop size (imagenet_train_{size})")
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--workers", default="2,4",
                   help="comma list of worker-fleet sizes to bench")
    args = p.parse_args(argv)

    from tensorflow_train_distributed_tpu.runtime.mesh import force_platform

    force_platform("cpu")  # pure host benchmark; never touch the tunnel

    import numpy as np

    from tensorflow_train_distributed_tpu.data import (
        DataConfig, HostDataLoader,
    )
    from tensorflow_train_distributed_tpu.data.service import (
        DataServiceDispatcher, SourceSpec,
    )
    from tensorflow_train_distributed_tpu.data.tfrecord import (
        open_tfrecord_dir,
    )

    transform = f"imagenet_train_{args.size}"
    cfg = DataConfig(global_batch_size=args.batch, shuffle=True,
                     seed=0, num_epochs=None)
    results = {}
    decode = {}
    with tempfile.TemporaryDirectory() as root:
        _make_corpus(root, args.records, args.image_hw)

        # Raw DECODE throughput (no crop/augment): PIL vs the native
        # libjpeg thread pool (GIL-free; scales with cores in-process,
        # where the PIL path needs a process per core) vs DCT-domain
        # half-resolution decode (the cheap first step when the model
        # only needs a small crop).
        import io as io_lib

        from PIL import Image as PILImage

        from tensorflow_train_distributed_tpu.data.image import (
            _encoded_bytes,
        )
        from tensorflow_train_distributed_tpu.data.tfrecord import (
            TFRecordSource,
        )
        from tensorflow_train_distributed_tpu.native import jpeg as njpeg

        paths = sorted(
            os.path.join(root, f) for f in os.listdir(root)
            if f.endswith(".tfrecord"))
        raw_src = TFRecordSource(paths, None)
        raws = [_encoded_bytes(raw_src[i]) for i in range(len(raw_src))]

        t0 = time.perf_counter()
        for data in raws:
            with PILImage.open(io_lib.BytesIO(data)) as im:
                np.asarray(im.convert("RGB"), np.uint8)
        decode["pil"] = round(len(raws) / (time.perf_counter() - t0), 1)
        if njpeg.available():
            for threads in (1, 2, 4):
                t0 = time.perf_counter()
                njpeg.decode_batch(raws, num_threads=threads)
                decode[f"native_t{threads}"] = round(
                    len(raws) / (time.perf_counter() - t0), 1)
            t0 = time.perf_counter()
            njpeg.decode_batch(raws, scale_denom=2, num_threads=1)
            decode["native_halfres_t1"] = round(
                len(raws) / (time.perf_counter() - t0), 1)

        src = open_tfrecord_dir(root, transform=transform)
        results["inprocess"] = round(_drain(
            iter(HostDataLoader(src, cfg)), args.records, args.batch), 1)

        # uint8 ship-raw-normalize-on-device variant: no host f32 math,
        # 4x smaller batches over PCIe (models.resnet normalizes uint8
        # inputs; bit-exact parity tested).
        u8_src = open_tfrecord_dir(
            root, transform=f"imagenet_train_u8_{args.size}")
        results["inprocess_u8"] = round(_drain(
            iter(HostDataLoader(u8_src, cfg)), args.records, args.batch), 1)

        for n in (int(x) for x in args.workers.split(",") if x):
            spec = SourceSpec("tfrecord_dir",
                              {"root": root, "transform": transform})
            with DataServiceDispatcher(spec, cfg, num_workers=n) as disp:
                results[f"workers{n}"] = round(_drain(
                    iter(disp.client()), args.records, args.batch), 1)

        # Storage-side answer: pre-decoded uint8 mmap shards (decode paid
        # once at dataset build; steady-state is memory-bandwidth reads).
        from tensorflow_train_distributed_tpu.data.filesource import (
            open_sharded, write_shards,
        )

        decoded = [src[i] for i in range(min(len(src), args.records))]

        class _Dec:
            def __len__(self):
                return len(decoded)

            def __getitem__(self, i):
                r = decoded[i]
                return {"image": (np.clip((r["image"] * 0.25 + 0.5), 0, 1)
                                  * 255).astype(np.uint8),
                        "label": np.int32(r["label"])}

        mmap_root = os.path.join(root, "mmap")
        write_shards(mmap_root, _Dec(), num_shards=4)
        mm = open_sharded(mmap_root, transform="u8_image_to_f32")
        results["mmap_predecoded"] = round(_drain(
            iter(HostDataLoader(mm, cfg)), args.records, args.batch), 1)

    print(json.dumps({
        "metric": "input_pipeline_records_per_sec",
        "unit": "records/sec",
        "image_hw": args.image_hw,
        "crop": args.size,
        "modes": results,
        "decode_modes": decode,
        "value": max(results.values()),
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
