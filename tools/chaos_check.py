#!/usr/bin/env python
"""One-command chaos smoke: kill -9 + torn checkpoint → full recovery.

Runs a tiny LeNet/MNIST job twice on the CPU backend:

1. **reference** — uninterrupted ``--steps N``;
2. **chaos** — the same config under ``--supervise`` with a canned
   fault plan: the step-4 checkpoint save is made PARTIAL (commit
   marker dropped, arrays truncated — a crashed writer) and the
   process is SIGKILLed at step 5, both only on supervisor attempt 0.

The supervisor must classify the kill as a crash, relaunch, and the
relaunch must quarantine the torn step-4 save, fall back to step 2,
resume the data stream mid-epoch, and finish — with final params
**bitwise identical** to the reference run.  Verdict is a JSON line on
stdout; exit 0 iff every check passed.  Usable locally and as a CI
gate; the tier-1 chaos parity test drives this same entry point.

``--serving`` runs the SERVING-side chaos parity instead — the same
discipline applied to the multi-replica gateway: a two-replica
gateway under concurrent streaming load has replica 0 killed
(``serve:dispatch:N:kill9:replica=0`` — abrupt vanish, no
notification) mid-stream, and the gate asserts every accepted request
completes on the survivor with a token stream **equal to an
uninterrupted single-replica run** (greedy and seeded-sampling legs),
exactly one replica dead, at least one failover, and /healthz
degraded-but-routable.  The tier-1 serving chaos smoke drives this
same entry point in-process.

``--serving --procs`` runs the same gate over SUBPROCESS replicas
(``server.procpool``): the kill is a **real** ``os.kill(pid,
SIGKILL)`` delivered inside one worker by the ``killpid`` fault armed
in that worker's own environment — the gateway process survives, the
dead worker is classified "killed by signal 9" in /healthz, streams
stay token-equal, and the elastic pool respawns the corpse.

``--serving --disagg`` runs the DISAGGREGATED leg over TCP dial-in
workers (``server.netpool`` + ``tools/serve_worker.py``): a 1-prefill
+ 2-decode fleet under mixed load loses the prefill worker the moment
the first KV handoff is observed AND one decode worker to a real
in-worker SIGKILL mid-stream — survivors must complete every request
token-equal to an uninterrupted co-located run, with later long
prompts degrading to local prefill and dead-decode streams failing
over via resume-from-token.

``--serving --migrate`` runs the LIVE-MIGRATION leg: every active
stream on a three-replica gateway is migrated TWICE mid-generation
under concurrent load — lane KV exported from its replica, installed
on another, decode resumed without re-prefill — and one stream's
replica is additionally killed mid-migration.  Every token stream
must stay EQUAL to an uninterrupted single-engine run (greedy and
seeded legs): migration is a placement lever, never a correctness
knob, and the parity bar doubles as the no-token-duplicated/dropped
detector.

``--train-elastic`` runs the ELASTIC-MESH chaos gate: a supervised
8-device training run loses half its devices mid-run (the
``mesh:device_lost`` fault point), the supervisor classifies the exit
as device loss (crash budget untouched), relaunches onto the 4
survivors, and the relaunch restores the pre-loss checkpoint
RESHARDED onto the half-size mesh and finishes — with the final loss
matching an uninterrupted 8-device run within the harness parity bar
(reduction reassociation across mesh sizes makes bitwise impossible).
The tier-1 elastic chaos smoke drives this same entry point.

Usage::

    python tools/chaos_check.py [--workdir DIR] [--steps 8]
    python tools/chaos_check.py --serving
    python tools/chaos_check.py --serving --disagg
    python tools/chaos_check.py --serving --migrate
    python tools/chaos_check.py --train-elastic
"""

import argparse
import json
import logging
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:    # runnable as `python tools/chaos_check.py`
    sys.path.insert(0, REPO_ROOT)

KILL_STEP = 5
CORRUPT_STEP = 4
CKPT_EVERY = 2
# --train-elastic: lose half an 8-device mesh at this step; the
# relaunch restores the step-4 checkpoint RESHARDED onto the 4
# survivors and must converge loss-parity with an uninterrupted run.
ELASTIC_DEVICES = 8
ELASTIC_SURVIVORS = 4
ELASTIC_LOSS_STEP = 5
# Harness parity bar: the resharded continuation reassociates the
# per-device reductions (8-way vs 4-way batch splits), so parity is a
# tolerance, not bitwise — same bar family as the grad-quant A/B.
ELASTIC_LOSS_BAR = 0.1


def _cli(steps, ckpt_dir, *extra, cpu_devices=2):
    return [
        sys.executable, "-m", "tensorflow_train_distributed_tpu",
        "--config", "mnist", "--steps", str(steps),
        "--platform", "cpu", "--cpu-devices", str(cpu_devices),
        "--strategy", "dp", "--global-batch-size", "16",
        "--log-every", "1", "--seed", "0",
        "--checkpoint-dir", ckpt_dir,
        "--checkpoint-every", str(CKPT_EVERY),
        *extra,
    ]


def run_chaos_check(workdir: str, *, steps: int = 8,
                    timeout_s: float = 600.0) -> dict:
    """Run the scenario; return ``{"ok", "checks", ...}``."""
    import numpy as np

    ref_dir = os.path.join(workdir, "ref")
    chaos_dir = os.path.join(workdir, "chaos")
    journal = os.path.join(workdir, "supervisor.jsonl")
    checks = {}

    ref = subprocess.run(_cli(steps, ref_dir), capture_output=True,
                         text=True, timeout=timeout_s, cwd=REPO_ROOT)
    checks["reference_rc0"] = ref.returncode == 0
    if not checks["reference_rc0"]:
        return {"ok": False, "checks": checks,
                "stderr": ref.stderr[-2000:]}

    plan = (f"ckpt:save:partial:step={CORRUPT_STEP}:attempt=0;"
            f"step:{KILL_STEP}:kill9:attempt=0")
    chaos = subprocess.run(
        _cli(steps, chaos_dir,
             "--supervise", "--max-restarts", "2",
             "--restart-backoff", "0.05",
             "--supervisor-journal", journal,
             "--fault-plan", plan),
        capture_output=True, text=True, timeout=timeout_s,
        cwd=REPO_ROOT)
    checks["chaos_rc0"] = chaos.returncode == 0

    # Supervisor journal: exactly one crash (the SIGKILL, rc=-9), then
    # a clean exit — the preemption/crash classification surface.
    events = []
    if os.path.exists(journal):
        with open(journal) as f:
            events = [json.loads(line) for line in f if line.strip()]
    exits = [e for e in events if e.get("event") == "exit"]
    checks["killed_then_clean"] = (
        len(exits) == 2
        and exits[0]["class"] == "crash" and exits[0]["rc"] == -9
        and exits[1]["class"] == "clean")

    # The torn step-4 save was quarantined, not deleted and not served.
    quarantined = os.path.join(chaos_dir, "corrupt", str(CORRUPT_STEP))
    checks["bad_step_quarantined"] = os.path.isdir(quarantined)
    checks["fell_back_to_previous"] = (
        f"restored checkpoint step {CORRUPT_STEP - CKPT_EVERY}"
        in chaos.stderr + chaos.stdout)

    # Headline: final params bitwise-equal to the uninterrupted run.
    bitwise = False
    if checks["chaos_rc0"]:
        # The parity check reads checkpoints in-process: force the same
        # CPU topology the child CLIs trained on (orbax rebuilds each
        # array's sharding from the checkpoint's sharding file, which
        # names those devices; env vars are too late under launchers
        # whose sitecustomize imports jax — see tests/conftest.py).
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            force_platform,
        )

        force_platform("cpu", 2)
        from tensorflow_train_distributed_tpu.training.checkpoint import (
            CheckpointManager,
        )

        mr = CheckpointManager(ref_dir, async_save=False)
        mc = CheckpointManager(chaos_dir, async_save=False)
        try:
            pr = mr.restore_params(steps)
            pc = mc.restore_params(steps)
            import jax

            leaves_r = jax.tree.leaves(pr)
            leaves_c = jax.tree.leaves(pc)
            bitwise = len(leaves_r) == len(leaves_c) and all(
                np.array_equal(np.asarray(a), np.asarray(b))
                for a, b in zip(leaves_r, leaves_c))
        finally:
            mr.close()
            mc.close()
    checks["params_bitwise_equal"] = bitwise

    return {"ok": all(checks.values()), "checks": checks,
            "journal": exits,
            "chaos_tail": (chaos.stderr[-1500:]
                           if not all(checks.values()) else "")}


def run_train_elastic(workdir: str, *, steps: int = 8,
                      devices: int = ELASTIC_DEVICES,
                      survivors: int = ELASTIC_SURVIVORS,
                      timeout_s: float = 600.0) -> dict:
    """Elastic mesh chaos: kill half the devices mid-training, relaunch
    on the survivors, demand loss parity with an uninterrupted run.

    Two runs of the same mini config (LeNet/MNIST, fixed seed):

    1. **reference** — uninterrupted ``--steps N`` on ``devices``
       virtual CPU devices;
    2. **chaos** — the same config under ``--supervise`` with
       ``mesh:device_lost:<survivors>:step=<K>:attempt=0`` armed: the
       step-K boundary raises ``DeviceLost``, the child records the
       survivor count in the elastic sidecar and exits with the
       device-loss code, and the supervisor relaunches it with
       ``TTD_ELASTIC_DEVICES=<survivors>`` — the relaunch builds a
       half-size mesh, restores the latest checkpoint RESHARDED onto
       it, repositions the data stream, and finishes.

    The gate: device_loss classified (not a crash — budget untouched),
    the resize journaled, the relaunch restored the pre-loss
    checkpoint onto the smaller mesh, and the final loss matches the
    uninterrupted run within ``ELASTIC_LOSS_BAR`` (the 8-way → 4-way
    reduction reassociation makes bitwise impossible; the bar is the
    harness's loss-parity convention).
    """
    ref_dir = os.path.join(workdir, "ref")
    chaos_dir = os.path.join(workdir, "chaos")
    ref_jsonl = os.path.join(workdir, "ref.jsonl")
    chaos_jsonl = os.path.join(workdir, "chaos.jsonl")
    journal = os.path.join(workdir, "supervisor.jsonl")
    checks = {}

    ref = subprocess.run(
        _cli(steps, ref_dir, "--jsonl-log", ref_jsonl,
             cpu_devices=devices),
        capture_output=True, text=True, timeout=timeout_s,
        cwd=REPO_ROOT)
    checks["reference_rc0"] = ref.returncode == 0
    if not checks["reference_rc0"]:
        return {"ok": False, "mode": "train-elastic", "checks": checks,
                "stderr": ref.stderr[-2000:]}

    plan = (f"mesh:device_lost:{survivors}:step={ELASTIC_LOSS_STEP}"
            ":attempt=0")
    chaos = subprocess.run(
        _cli(steps, chaos_dir, "--jsonl-log", chaos_jsonl,
             "--supervise", "--max-restarts", "2",
             "--restart-backoff", "0.05",
             "--supervisor-journal", journal,
             "--fault-plan", plan,
             cpu_devices=devices),
        capture_output=True, text=True, timeout=timeout_s,
        cwd=REPO_ROOT)
    checks["chaos_rc0"] = chaos.returncode == 0
    log = chaos.stderr + chaos.stdout

    # Journal: one device_loss exit (classified, NOT a crash), a
    # resize record carrying the survivor count, then a clean exit.
    events = []
    if os.path.exists(journal):
        with open(journal) as f:
            events = [json.loads(line) for line in f if line.strip()]
    exits = [e for e in events if e.get("event") == "exit"]
    resizes = [e for e in events if e.get("event") == "resize"]
    from tensorflow_train_distributed_tpu.runtime.supervisor import (
        DEVICE_LOSS_EXIT_CODE,
    )

    checks["device_loss_then_clean"] = (
        len(exits) == 2
        and exits[0]["class"] == "device_loss"
        and exits[0]["rc"] == DEVICE_LOSS_EXIT_CODE
        and exits[1]["class"] == "clean")
    checks["crash_budget_untouched"] = not any(
        e["class"] == "crash" for e in exits)
    checks["resize_journaled"] = (
        len(resizes) == 1 and resizes[0].get("survivors") == survivors)

    # The relaunch restored the PRE-LOSS checkpoint onto the smaller
    # mesh (reshard-on-resize restore), not a fresh init.
    pre_loss_step = (ELASTIC_LOSS_STEP // CKPT_EVERY) * CKPT_EVERY
    checks["restored_pre_loss_step"] = (
        f"restored checkpoint step {pre_loss_step}" in log)
    checks["relaunched_on_survivors"] = (
        f"'data': {survivors}" in log)

    # Headline: loss parity with the uninterrupted run at the final
    # step (jsonl streams; the chaos file carries both attempts —
    # the LAST record is the relaunched run's final step).
    def last_loss(path):
        if not os.path.exists(path):
            return None
        rec = None
        with open(path) as f:
            for line in f:
                if line.strip():
                    rec = json.loads(line)
        return rec

    ref_last = last_loss(ref_jsonl)
    chaos_last = last_loss(chaos_jsonl)
    checks["reached_final_step"] = bool(
        ref_last and chaos_last
        and ref_last["step"] == steps and chaos_last["step"] == steps)
    delta = (abs(ref_last["loss"] - chaos_last["loss"])
             if checks["reached_final_step"] else None)
    checks["loss_parity"] = (delta is not None
                             and delta <= ELASTIC_LOSS_BAR)

    return {"ok": all(checks.values()), "mode": "train-elastic",
            "checks": checks, "journal": exits + resizes,
            "final_loss_delta": delta,
            "loss_bar": ELASTIC_LOSS_BAR,
            "chaos_tail": (log[-1500:]
                           if not all(checks.values()) else "")}


def run_serving_chaos(*, sampling: bool = True, n_requests: int = 8,
                      kill_dispatch: int = 3,
                      watchdog_timeout_s: float = 10.0,
                      timeout_s: float = 120.0) -> dict:
    """Kill one of two gateway replicas mid-stream under load; every
    accepted request must complete on the survivor with tokens EQUAL
    to an uninterrupted single-replica run.  In-process (the kill9
    serve fault is an abrupt replica-thread vanish — a true SIGKILL
    would take both replicas).  Returns ``{"ok", "checks", ...}``.

    ``kill_dispatch`` must stay within replica 0's GUARANTEED dispatch
    count under the worst placement skew: with a small ``n_requests``
    its share can be one short request (~3 serve_steps: staged
    prefill + two decode chunks), so an ordinal past 3 can simply
    never fire and the run reports no-death/no-failover instead of
    chaos parity."""
    import json as _json
    import threading
    import urllib.request

    import numpy as np

    import jax

    if jax.default_backend() != "cpu":
        # CLI path only: in-process callers (the tier-1 smoke) already
        # run on the CPU backend, and force_platform's clear_backends
        # would invalidate their live arrays.
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            force_platform,
        )

        force_platform("cpu")
    import jax.numpy as jnp

    from tensorflow_train_distributed_tpu.models.llama import (
        LLAMA_PRESETS,
        LlamaModel,
    )
    from tensorflow_train_distributed_tpu.runtime import faults
    from tensorflow_train_distributed_tpu.server import ServingGateway
    from tensorflow_train_distributed_tpu.serving import ServingEngine

    checks = {}
    cfg = LLAMA_PRESETS["llama_tiny"]
    params = LlamaModel(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    kw = dict(slots=2, cache_len=64, chunk=4, prompt_buckets=(8, 16, 32))
    if sampling:
        kw.update(temperature=0.8, top_k=40)
    rng = np.random.default_rng(0)
    reqs = [([int(t) for t in rng.integers(1, 200,
                                           int(rng.integers(2, 8)))],
             int(rng.integers(6, 14)), 1000 + i)
            for i in range(n_requests)]

    # Reference: the same requests on ONE uninterrupted engine.
    ref_eng = ServingEngine(cfg, params, **kw)
    rids = [ref_eng.submit(p, m, seed=s if sampling else None)
            for p, m, s in reqs]
    ref_out = ref_eng.run()
    refs = [ref_out[r] for r in rids]

    # Two replicas, prewarmed (a first dispatch compiles — the
    # watchdog must see hung devices, not XLA).
    engines = [ServingEngine(cfg, params, **kw) for _ in range(2)]
    for e in engines:
        e.submit([1, 2, 3], 5, seed=0 if sampling else None)
        e.run()
    faults.arm(f"serve:dispatch:{kill_dispatch}:kill9:replica=0")
    gw = ServingGateway(engines, host="127.0.0.1", port=0,
                        max_queue=4 * n_requests,
                        watchdog_timeout_s=watchdog_timeout_s).start()
    try:
        results: list = [None] * len(reqs)

        def client(i):
            prompt, max_new, seed = reqs[i]
            body = {"prompt": prompt, "max_new": max_new,
                    "stream": True}
            if sampling:
                body["seed"] = seed
            req = urllib.request.Request(
                f"http://127.0.0.1:{gw.port}/v1/generate",
                data=_json.dumps(body).encode(),
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req,
                                            timeout=timeout_s) as r:
                    toks, err = [], None
                    for raw in r:
                        obj = _json.loads(raw)
                        if "tokens" in obj:
                            toks.extend(obj["tokens"])
                        elif "error" in obj:
                            err = obj["error"]
                    results[i] = (err, list(prompt) + toks)
            except OSError as e:
                results[i] = (f"{type(e).__name__}: {e}", None)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(reqs))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        checks["all_completed"] = all(
            r is not None and r[0] is None for r in results)
        checks["streams_match_reference"] = checks[
            "all_completed"] and all(
            r[1] == ref for r, ref in zip(results, refs))
        states = gw.pool.replica_states()
        checks["one_replica_dead"] = (
            sum(s["state"] == "dead" for s in states) == 1)
        checks["failover_happened"] = (
            gw.metrics.failovers.value() >= 1)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{gw.port}/healthz", timeout=10) as r:
            checks["healthz_degraded_not_503"] = (
                r.status == 200
                and _json.loads(r.read())["status"] == "degraded")
    finally:
        faults.disarm()
        gw.drain(timeout=30)
    return {"ok": all(checks.values()), "checks": checks,
            "mode": "serving",
            "leg": "sampled" if sampling else "greedy",
            "failovers": gw.metrics.failovers.value(),
            "results": [] if all(checks.values()) else
            [(r[0] if r else "no result") for r in results]}


def run_serving_chaos_procs(*, sampling: bool = True,
                            n_requests: int = 8,
                            kill_dispatch: int = 3,
                            workers: int = 2,
                            watchdog_timeout_s: float = 30.0,
                            timeout_s: float = 300.0) -> dict:
    """The SUBPROCESS leg of the serving chaos gate: the same
    discipline as ``run_serving_chaos``, but each replica is a real
    subprocess worker (``server.procpool``) and the kill is a REAL
    ``os.kill(pid, SIGKILL)`` — ``serve:dispatch:N:killpid:replica=0``
    armed in the workers' own environment fires inside worker 0 at its
    Nth dispatch, mid-stream under load.  The gate asserts:

    - the GATEWAY process never feels it: every accepted request
      completes, failed-over streams token-equal to an uninterrupted
      in-process single-engine run (greedy and seeded legs — the
      resume-from-token contract crosses the process boundary);
    - exactly one worker dead, classified "killed by signal 9" in the
      per-replica health state;
    - the elastic pool RESPAWNS the dead worker (restart budget) and
      capacity returns without operator action.

    Workers warm their engines in the child before the HELLO, so the
    watchdog never stares down a cold XLA compile."""
    import json as _json
    import threading
    import time
    import urllib.request

    import numpy as np

    import jax

    if jax.default_backend() != "cpu":
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            force_platform,
        )

        force_platform("cpu")
    import jax.numpy as jnp

    from tensorflow_train_distributed_tpu.models.llama import (
        LLAMA_PRESETS,
        LlamaModel,
    )
    from tensorflow_train_distributed_tpu.server import (
        ProcPool,
        ServingGateway,
        WorkerSpec,
    )
    from tensorflow_train_distributed_tpu.serving import ServingEngine

    checks = {}
    kw = dict(slots=2, cache_len=64, chunk=4)
    if sampling:
        kw.update(temperature=0.8, top_k=40)
    rng = np.random.default_rng(0)
    reqs = [([int(t) for t in rng.integers(1, 200,
                                           int(rng.integers(2, 8)))],
             int(rng.integers(6, 14)), 1000 + i)
            for i in range(n_requests)]

    # Reference: the same requests on ONE uninterrupted in-process
    # engine, built exactly as the workers build theirs (same preset,
    # same init seed -> bitwise-identical params).
    cfg = LLAMA_PRESETS["llama_tiny"]
    params = LlamaModel(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    ref_eng = ServingEngine(cfg, params,
                            prompt_buckets=(8, 16, 32), **kw)
    rids = [ref_eng.submit(p, m, seed=s if sampling else None)
            for p, m, s in reqs]
    ref_out = ref_eng.run()
    refs = [ref_out[r] for r in rids]

    # The worker fleet: the killpid plan rides the workers' OWN
    # environment, scoped to replica 0 — a REAL SIGKILL of exactly one
    # subprocess, delivered at its kill_dispatch'th driver dispatch.
    spec = WorkerSpec(
        factory="llama",
        factory_json=dict(preset="llama_tiny", init_seed=0,
                          prompt_buckets=[8, 16, 32], **kw),
        env={"TTD_FAULT_PLAN":
             f"serve:dispatch:{kill_dispatch}:killpid:replica=0"})
    pool = ProcPool(spec, replicas=workers,
                    max_queue=4 * n_requests,
                    watchdog_timeout_s=watchdog_timeout_s,
                    monitor_poll_s=0.02,
                    spawn_cooldown_s=0.0,
                    restart_backoff_s=0.05)
    gw = ServingGateway(pool, host="127.0.0.1", port=0).start()
    try:
        checks["workers_ready"] = pool.wait_ready(timeout=timeout_s)
        killed_pid = pool.replicas[0].driver.pid
        results: list = [None] * len(reqs)

        def client(i):
            prompt, max_new, seed = reqs[i]
            body = {"prompt": prompt, "max_new": max_new,
                    "stream": True}
            if sampling:
                body["seed"] = seed
            req = urllib.request.Request(
                f"http://127.0.0.1:{gw.port}/v1/generate",
                data=_json.dumps(body).encode(),
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req,
                                            timeout=timeout_s) as r:
                    toks, err = [], None
                    for raw in r:
                        obj = _json.loads(raw)
                        if "tokens" in obj:
                            toks.extend(obj["tokens"])
                        elif "error" in obj:
                            err = obj["error"]
                    results[i] = (err, list(prompt) + toks)
            except OSError as e:
                results[i] = (f"{type(e).__name__}: {e}", None)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(reqs))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        checks["all_completed"] = all(
            r is not None and r[0] is None for r in results)
        checks["streams_match_reference"] = checks[
            "all_completed"] and all(
            r[1] == ref for r, ref in zip(results, refs))
        states = pool.replica_states()
        dead = [s for s in states if s["state"] == "dead"]
        checks["one_worker_dead"] = len(dead) == 1
        checks["killed_by_signal_9"] = (
            len(dead) == 1
            and "signal 9" in dead[0].get("reason", "")
            and dead[0].get("failure_class") == "killed"
            and dead[0].get("pid") == killed_pid)
        checks["failover_happened"] = (
            gw.metrics.failovers.value() >= 1)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{gw.port}/healthz", timeout=10) as r:
            checks["healthz_routable"] = (
                r.status == 200
                and _json.loads(r.read())["status"]
                in ("ok", "degraded"))
        # The elastic pool respawns the corpse (restart budget):
        # capacity returns without operator action.
        deadline = time.monotonic() + 30.0
        while (pool.alive_count() < workers
               and time.monotonic() < deadline):
            time.sleep(0.05)
        checks["worker_respawned"] = (
            pool.alive_count() >= workers
            and pool.restarts_total() >= 1)
    finally:
        gw.drain(timeout=60)
    return {"ok": all(checks.values()), "checks": checks,
            "mode": "serving-procs",
            "leg": "sampled" if sampling else "greedy",
            "failovers": gw.metrics.failovers.value(),
            "restarts": pool.restarts_total(),
            "results": [] if all(checks.values()) else
            [(r[0] if r else "no result") for r in results]}


def _post_mortem(spool_dir):
    """tools/trace_report's post-mortem loader (imported by path so
    the gate works both as a script and under pytest)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "trace_report", os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "trace_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.post_mortem_report(spool_dir, last_s=120.0)


def run_serving_chaos_disagg(*, sampling: bool = True,
                             n_requests: int = 6,
                             kill_dispatch: int = 2,
                             watchdog_timeout_s: float = 30.0,
                             timeout_s: float = 600.0,
                             spool_dir=None) -> dict:
    """The DISAGGREGATED leg of the serving chaos gate: a 1-prefill +
    2-decode TCP dial-in fleet (``server.netpool`` +
    ``tools/serve_worker.py``) under mixed long-prompt/short-prompt
    streaming load loses BOTH halves of the split:

    - the prefill worker is SIGKILLed the moment the first KV handoff
      is observed (mid-handoff under load — every later long prompt
      must degrade to LOCAL prefill on a decode worker);
    - decode worker 1 takes a REAL ``os.kill(pid, SIGKILL)`` at its
      ``kill_dispatch``'th dispatch (the killpid fault armed in ITS
      environment, scoped by its ``--replica-id``) — mid-stream, so
      in-flight streams fail over via resume-from-token.

    The gate asserts every accepted request completes on the
    survivors with tokens EQUAL to an uninterrupted co-located
    in-process run (greedy and seeded legs — disaggregation plus a
    double kill is still not a correctness knob), both corpses are
    classified "vanished without BYE"/disconnected against their real
    pids, at least one handoff and one failover actually happened,
    and /healthz stays routable.

    ``kill_dispatch`` must stay within decode worker 1's GUARANTEED
    dispatch count under the worst placement skew (same rule as the
    in-process leg): any one placed request yields at least two
    dispatches (bucketed prefill + a decode chunk), and with
    ``n_requests`` concurrent streams across two decode workers the
    load-ranked placement hands every decode worker at least one —
    so 2 always fires, while a larger ordinal can silently never
    trigger and the run reports no-death instead of chaos parity."""
    import json as _json
    import threading
    import time
    import urllib.request

    import numpy as np

    import jax

    if jax.default_backend() != "cpu":
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            force_platform,
        )

        force_platform("cpu")
    import jax.numpy as jnp

    from tensorflow_train_distributed_tpu.models.llama import (
        LLAMA_PRESETS,
        LlamaModel,
    )
    from tensorflow_train_distributed_tpu.runtime import events
    from tensorflow_train_distributed_tpu.server import (
        NetPool,
        ServingGateway,
    )
    from tensorflow_train_distributed_tpu.serving import ServingEngine

    checks = {}
    # Crash durability rides this leg: every process (parent + the
    # three workers, two of which get SIGKILLed) spools its ring to
    # the same directory, and the gate asserts the spool + corpse
    # snapshots reconstruct the dead decode worker's final dispatches
    # after the fact — the PR-20 post-mortem acceptance.
    if spool_dir is None:
        spool_dir = tempfile.mkdtemp(prefix="ttd-chaos-spool-")
    spool_env_prev = os.environ.get("TTD_TRACE_SPOOL")
    os.environ["TTD_TRACE_SPOOL"] = spool_dir
    events.get_recorder().start_spool(spool_dir)
    kw = dict(slots=2, cache_len=64, chunk=4)
    if sampling:
        kw.update(temperature=0.8, top_k=40)
    rng = np.random.default_rng(0)
    # Mixed load: even requests span >1 KV block (16 tokens) so their
    # placement triggers a prefill→decode handoff; odd ones are short
    # decode-heavy streams that keep the decode workers dispatching.
    reqs = []
    for i in range(n_requests):
        plen = int(rng.integers(18, 30)) if i % 2 == 0 else int(
            rng.integers(2, 8))
        reqs.append(([int(t) for t in rng.integers(1, 200, plen)],
                     int(rng.integers(6, 12)), 1000 + i))

    # Reference: the same requests on ONE uninterrupted co-located
    # engine, built exactly as the workers build theirs.
    cfg = LLAMA_PRESETS["llama_tiny"]
    params = LlamaModel(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    ref_eng = ServingEngine(cfg, params,
                            prompt_buckets=(8, 16, 32), **kw)
    rids = [ref_eng.submit(p, m, seed=s if sampling else None)
            for p, m, s in reqs]
    ref_out = ref_eng.run()
    refs = [ref_out[r] for r in rids]

    pool = NetPool(host="127.0.0.1", port=0,
                   scale_min=3, max_workers=4,
                   max_queue=4 * n_requests,
                   watchdog_timeout_s=watchdog_timeout_s,
                   monitor_poll_s=0.02)
    # The gateway's start() starts the pool (and with it the TCP
    # listener) — the workers can only learn the port after it.
    gw = ServingGateway(pool, host="127.0.0.1", port=0).start()
    spec_json = _json.dumps(dict(preset="llama_tiny", init_seed=0,
                                 prompt_buckets=[8, 16, 32], **kw))

    def worker(rid, role, extra_env=None):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.update(extra_env or {})
        return subprocess.Popen(
            [sys.executable,
             os.path.join(REPO_ROOT, "tools", "serve_worker.py"),
             "--dial", f"127.0.0.1:{pool.port}",
             "--factory", "llama", "--json", spec_json,
             "--replica-id", str(rid), "--role", role],
            cwd=REPO_ROOT, env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)

    procs = [
        worker(0, "prefill"),
        worker(1, "decode",
               {"TTD_FAULT_PLAN":
                f"serve:dispatch:{kill_dispatch}:killpid:replica=1"}),
        worker(2, "decode"),
    ]
    handoffs = 0
    try:
        checks["workers_ready"] = pool.wait_ready(timeout=timeout_s)
        rec = events.get_recorder()
        cursor, _ = rec.events_after(0)
        results: list = [None] * len(reqs)

        def client(i):
            prompt, max_new, seed = reqs[i]
            body = {"prompt": prompt, "max_new": max_new,
                    "stream": True}
            if sampling:
                body["seed"] = seed
            req = urllib.request.Request(
                f"http://127.0.0.1:{gw.port}/v1/generate",
                data=_json.dumps(body).encode(),
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req,
                                            timeout=timeout_s) as r:
                    toks, err = [], None
                    for raw in r:
                        obj = _json.loads(raw)
                        if "tokens" in obj:
                            toks.extend(obj["tokens"])
                        elif "error" in obj:
                            err = obj["error"]
                    results[i] = (err, list(prompt) + toks)
            except OSError as e:
                results[i] = (f"{type(e).__name__}: {e}", None)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(reqs))]
        for t in threads:
            t.start()
        # Kill the prefill worker the instant the first handoff lands
        # (mid-handoff under load: more exchanges are imminent and
        # must degrade to local prefill on the decode side).
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            cursor, evs = rec.events_after(cursor)
            handoffs += sum(1 for e in evs
                            if e[0] == "request/kv_handoff")
            if handoffs:
                procs[0].kill()
                break
            if all(t0 is not None for t0 in results):
                break
            time.sleep(0.005)
        for t in threads:
            t.join()

        checks["all_completed"] = all(
            r is not None and r[0] is None for r in results)
        checks["streams_match_reference"] = checks[
            "all_completed"] and all(
            r[1] == ref for r, ref in zip(results, refs))
        checks["handoff_happened"] = handoffs >= 1
        states = pool.replica_states()

        def dead_as_disconnect(pid):
            dead = [s for s in states
                    if s["state"] == "dead" and s.get("pid") == pid]
            return (len(dead) == 1
                    and dead[0].get("failure_class") == "disconnected")

        checks["prefill_worker_dead"] = dead_as_disconnect(
            procs[0].pid)
        checks["decode_worker_dead"] = dead_as_disconnect(
            procs[1].pid)
        checks["failover_happened"] = (
            gw.metrics.failovers.value() >= 1)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{gw.port}/healthz", timeout=10) as r:
            checks["healthz_routable"] = (
                r.status == 200
                and _json.loads(r.read())["status"]
                in ("ok", "degraded"))
        # Post-mortem reconstruction: the decode worker died to a
        # REAL SIGKILL — no flush, no BYE — yet its fsynced spool
        # segments plus the parent's corpse snapshot must still show
        # what it was doing.  The parent's own spool carries the
        # fleet view (handoff + failover) of the same death.
        events.get_recorder().flush_spool()
        pm = _post_mortem(spool_dir)
        dead_pid = procs[1].pid
        death = next((d for d in pm["deaths"]
                      if d.get("pid") == dead_pid), None)
        # Over TCP the parent can't waitpid a remote process: a
        # SIGKILL renders as EOF-without-BYE ("disconnected"), the
        # subprocess transport would say "killed" (rc -9).
        checks["post_mortem_corpse_for_decode"] = (
            death is not None and not death.get("drained")
            and (death.get("reason") == "killed"
                 or "disconnected" in str(death.get("reason"))))
        names = []
        if death is not None:
            names = ([e["name"] for e in death["final_events"]]
                     + [e[0] for e in death["last_relayed"]])
        checks["post_mortem_final_dispatch"] = any(
            n.startswith(("decode/", "prefill/", "engine/"))
            for n in names)
        parent_names = {e["name"] for e in pm["timeline"]
                        if e["pid"] == os.getpid()}
        checks["post_mortem_fleet_waterfall"] = (
            "request/kv_handoff" in parent_names
            and "request/failover" in parent_names)
    finally:
        gw.drain(timeout=60)
        for proc in procs:
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=15)
        events.get_recorder().stop_spool()
        if spool_env_prev is None:
            os.environ.pop("TTD_TRACE_SPOOL", None)
        else:
            os.environ["TTD_TRACE_SPOOL"] = spool_env_prev
    return {"ok": all(checks.values()), "checks": checks,
            "mode": "serving-disagg", "spool_dir": spool_dir,
            "leg": "sampled" if sampling else "greedy",
            "failovers": gw.metrics.failovers.value(),
            "handoffs": handoffs,
            "results": [] if all(checks.values()) else
            [(r[0] if r else "no result") for r in results]}


def run_serving_chaos_migrate(*, sampling: bool = True,
                              speculative: bool = False,
                              n_requests: int = 6,
                              replicas: int = 3,
                              watchdog_timeout_s: float = 10.0,
                              timeout_s: float = 300.0) -> dict:
    """The LIVE-MIGRATION leg of the serving chaos gate: every active
    stream on a three-replica gateway is migrated TWICE mid-generation
    under concurrent streaming load — lane KV exported from its
    replica, installed on another, decode resumed WITHOUT re-prefill —
    and every token stream must stay EQUAL to an uninterrupted
    single-engine run (greedy and seeded legs: migration is a
    placement lever, never a correctness knob).  Once a stream has
    both hops, it starts murdering: its CURRENT replica takes a kill9
    vanish (the in-process stand-in for SIGKILL, same as
    ``run_serving_chaos``) armed mid-migration — the interrupted
    stream must still complete via the failover/migration
    re-placement with no token duplicated or dropped (the parity
    check IS the dup/drop detector).

    The gate asserts: every request completes token-equal to the
    reference, every stream actually migrated twice (the client
    triggers each hop only after a committed chunk proves the stream
    mid-generation), KV bytes moved on at least one hop (long prompts
    cross the block threshold), at least one replica died to an armed
    mid-migration kill (never the whole fleet), and /healthz stays
    routable."""
    import json as _json
    import threading
    import urllib.request

    import numpy as np

    import jax

    if jax.default_backend() != "cpu":
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            force_platform,
        )

        force_platform("cpu")
    import jax.numpy as jnp

    from tensorflow_train_distributed_tpu.models.llama import (
        LLAMA_PRESETS,
        LlamaModel,
    )
    from tensorflow_train_distributed_tpu.runtime import events, faults
    from tensorflow_train_distributed_tpu.server import ServingGateway
    from tensorflow_train_distributed_tpu.serving import ServingEngine

    checks = {}
    cfg = LLAMA_PRESETS["llama_tiny"]
    params = LlamaModel(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    # slots=3 leaves the fleet UNDER-subscribed (9 slots, 6 streams):
    # a migration needs a free lane on a non-source replica, and at
    # slots=2 the 6 concurrent streams saturate all 6 slots — every
    # mid-run hop would fail on capacity until a stream finished.
    kw = dict(slots=3, cache_len=64, chunk=4,
              prompt_buckets=(8, 16, 32))
    if sampling:
        kw.update(temperature=0.8, top_k=40)
    if speculative:
        # The speculative leg: every lane carries a DRAFT KV cache
        # alongside the target's — its export/install must round-trip
        # both (the meta's kv["draft"] flag) and the migrated stream
        # must still equal the uninterrupted speculative reference.
        import dataclasses

        if sampling:
            raise ValueError("speculative leg runs greedy")
        draft_cfg = dataclasses.replace(cfg, num_layers=1,
                                        num_heads=2, num_kv_heads=1)
        draft_params = LlamaModel(draft_cfg).init(
            jax.random.PRNGKey(123),
            jnp.zeros((1, 8), jnp.int32))["params"]
        kw.update(draft_config=draft_cfg, draft_params=draft_params,
                  speculative_k=3)
    rng = np.random.default_rng(0)
    # Long-ish prompts (even requests span >1 KV block, so their lane
    # export ships real rows) and max_new >= 28 (7+ chunks at chunk=4:
    # the engine cannot finish a stream before its client — which may
    # lag a couple of chunks behind under GIL contention — has seen
    # enough committed chunks to land both migrations).
    reqs = []
    for i in range(n_requests):
        plen = int(rng.integers(18, 28)) if i % 2 == 0 else int(
            rng.integers(2, 8))
        reqs.append(([int(t) for t in rng.integers(1, 200, plen)],
                     int(rng.integers(28, 36)), 1000 + i))

    # Reference: the same requests on ONE uninterrupted engine.
    ref_eng = ServingEngine(cfg, params, **kw)
    rids = [ref_eng.submit(p, m, seed=s if sampling else None)
            for p, m, s in reqs]
    ref_out = ref_eng.run()
    refs = [ref_out[r] for r in rids]

    engines = [ServingEngine(cfg, params, **kw)
               for _ in range(replicas)]
    for e in engines:                  # warm: compile before the clock
        e.submit([1, 2, 3], 5, seed=0 if sampling else None)
        e.run()
    gw = ServingGateway(engines, host="127.0.0.1", port=0,
                        max_queue=4 * n_requests,
                        watchdog_timeout_s=watchdog_timeout_s).start()
    rec = events.get_recorder()
    cursor, _ = rec.events_after(0)
    migrations = [0] * n_requests
    kill_lock = threading.Lock()
    try:
        results: list = [None] * len(reqs)

        def client(i):
            prompt, max_new, seed = reqs[i]
            body = {"prompt": prompt, "max_new": max_new,
                    "stream": True}
            if sampling:
                body["seed"] = seed
            req = urllib.request.Request(
                f"http://127.0.0.1:{gw.port}/v1/generate",
                data=_json.dumps(body).encode(),
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req,
                                            timeout=timeout_s) as r:
                    toks, err, rid, chunks = [], None, None, 0
                    for raw in r:
                        obj = _json.loads(raw)
                        if "id" in obj:
                            rid = obj["id"]
                        if "tokens" in obj:
                            toks.extend(obj["tokens"])
                            chunks += 1
                            if rid is None:
                                continue
                            # Migrate on every committed chunk until
                            # two hops landed — each attempt is
                            # provably mid-generation (a committed,
                            # non-final chunk just arrived); a False
                            # (stream raced ahead, transient queue
                            # state) simply retries next chunk.
                            if migrations[i] < 2:
                                if gw.pool.migrate(rid):
                                    migrations[i] += 1
                            else:
                                # Later hops, any stream: murder the
                                # CURRENT replica the instant another
                                # migration begins — the export races
                                # the death and the stream must finish
                                # either way.  Re-armed on committed
                                # chunks until a replica actually
                                # dies (the export can win the race
                                # AND leave the source laneless, in
                                # which case the dispatch fault never
                                # fires); the lock serializes the
                                # no-death check against concurrent
                                # armers.  A FIRED plan also stops
                                # re-arming: the kill has landed but
                                # the death DECLARATION lags it, and
                                # arming a fresh kill on a different
                                # replica in that window cascades
                                # until the whole fleet is dead.
                                with kill_lock:
                                    if any(s["state"] == "dead"
                                           for s in
                                           gw.pool.replica_states()):
                                        continue
                                    cur = faults.plan()
                                    if cur is not None and any(
                                            e.fired
                                            for e in cur.entries):
                                        continue
                                    preq = gw.pool._requests.get(rid)
                                    src = (preq.replica
                                           if preq is not None
                                           else None)
                                    if src is None:
                                        continue
                                    faults.arm(
                                        "serve:dispatch:1:kill9:"
                                        f"replica={src.idx}")
                                if gw.pool.migrate(rid):
                                    migrations[i] += 1
                        elif "error" in obj:
                            err = obj["error"]
                    results[i] = (err, list(prompt) + toks)
            except OSError as e:
                results[i] = (f"{type(e).__name__}: {e}", None)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(reqs))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        checks["all_completed"] = all(
            r is not None and r[0] is None for r in results)
        checks["streams_match_reference"] = checks[
            "all_completed"] and all(
            r[1] == ref for r, ref in zip(results, refs))
        checks["every_stream_migrated_twice"] = all(
            m >= 2 for m in migrations)
        _, evs = rec.events_after(cursor)
        moved_bytes = sum(e[5].get("bytes", 0) for e in evs
                          if e[0] == "request/migrate")
        checks["kv_bytes_moved"] = moved_bytes > 0
        # The death DECLARATION can lag the last client completion
        # (a laneless vanished replica is only noticed by the
        # watchdog's liveness scan) — poll briefly before judging.
        deadline = time.monotonic() + max(15.0, watchdog_timeout_s + 5)
        while time.monotonic() < deadline:
            states = gw.pool.replica_states()
            if any(s["state"] == "dead" for s in states):
                break
            time.sleep(0.25)
        n_dead = sum(s["state"] == "dead" for s in states)
        checks["replica_died"] = 1 <= n_dead <= replicas - 1
        with urllib.request.urlopen(
                f"http://127.0.0.1:{gw.port}/healthz", timeout=10) as r:
            checks["healthz_routable"] = (
                r.status == 200
                and _json.loads(r.read())["status"]
                in ("ok", "degraded"))
    finally:
        faults.disarm()
        gw.drain(timeout=30)
    return {"ok": all(checks.values()), "checks": checks,
            "mode": "serving-migrate",
            "leg": ("speculative" if speculative
                    else "sampled" if sampling else "greedy"),
            "migrations": migrations,
            "migrated_kv_bytes": moved_bytes,
            "results": [] if all(checks.values()) else
            [(r[0] if r else "no result") for r in results]}


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser(
        prog="chaos_check",
        description="kill-9 + torn-checkpoint recovery smoke test")
    p.add_argument("--workdir", default=None,
                   help="scratch dir (default: a fresh temp dir)")
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--keep", action="store_true",
                   help="keep the scratch dir for inspection")
    p.add_argument("--serving", action="store_true",
                   help="serving-side chaos instead: kill one of two "
                        "gateway replicas mid-stream under load; "
                        "accepted requests must complete on the "
                        "survivor token-equal to an uninterrupted "
                        "single-replica run (greedy + sampled legs)")
    p.add_argument("--procs", action="store_true",
                   help="with --serving: run the replicas as real "
                        "SUBPROCESS workers and deliver a REAL "
                        "os.kill(pid, SIGKILL) to one of them "
                        "mid-stream (the killpid fault in the "
                        "worker's own environment); survivors must "
                        "complete everything token-equal and the "
                        "elastic pool must respawn the corpse")
    p.add_argument("--disagg", action="store_true",
                   help="with --serving: run the DISAGGREGATED leg — "
                        "a 1-prefill + 2-decode TCP dial-in fleet "
                        "loses the prefill worker mid-handoff AND a "
                        "decode worker mid-stream (real SIGKILLs); "
                        "survivors must complete everything "
                        "token-equal with later long prompts "
                        "degrading to local prefill")
    p.add_argument("--migrate", action="store_true",
                   help="with --serving: run the LIVE-MIGRATION leg — "
                        "every active stream on a 3-replica gateway "
                        "is migrated twice mid-generation under load "
                        "(KV exported/installed, decode resumed "
                        "without re-prefill), one stream's replica is "
                        "additionally killed mid-migration, and every "
                        "token stream must equal an uninterrupted "
                        "single-engine run")
    p.add_argument("--train-elastic", action="store_true",
                   help="elastic mesh chaos instead: a supervised "
                        "8-device training run loses half its devices "
                        "mid-run (mesh:device_lost fault), relaunches "
                        "on the 4 survivors with the checkpoint "
                        "resharded, and must converge loss-parity "
                        "with an uninterrupted 8-device run")
    args = p.parse_args(argv)
    if args.serving and args.train_elastic:
        p.error("--serving and --train-elastic are separate gates; "
                "pick one")
    if args.train_elastic:
        workdir = args.workdir or tempfile.mkdtemp(
            prefix="chaos_elastic_")
        os.makedirs(workdir, exist_ok=True)
        try:
            verdict = run_train_elastic(workdir, steps=args.steps)
        finally:
            if not args.keep and args.workdir is None:
                shutil.rmtree(workdir, ignore_errors=True)
        print(json.dumps(verdict))
        return 0 if verdict["ok"] else 1
    if args.serving:
        if sum((args.procs, args.disagg, args.migrate)) > 1:
            p.error("--procs, --disagg and --migrate are separate "
                    "serving legs; pick one")
        run = (run_serving_chaos_migrate if args.migrate
               else run_serving_chaos_disagg if args.disagg
               else run_serving_chaos_procs if args.procs
               else run_serving_chaos)
        greedy = run(sampling=False)
        sampled = run(sampling=True)
        verdict = {"ok": greedy["ok"] and sampled["ok"],
                   "mode": ("serving-migrate" if args.migrate
                            else "serving-disagg" if args.disagg
                            else "serving-procs" if args.procs
                            else "serving"),
                   "greedy": greedy, "sampled": sampled}
        if args.migrate:
            spec = run_serving_chaos_migrate(sampling=False,
                                             speculative=True)
            verdict["speculative"] = spec
            verdict["ok"] = verdict["ok"] and spec["ok"]
        print(json.dumps(verdict))
        return 0 if verdict["ok"] else 1
    if args.procs:
        p.error("--procs modifies --serving; pass both")
    if args.disagg:
        p.error("--disagg modifies --serving; pass both")
    if args.migrate:
        p.error("--migrate modifies --serving; pass both")
    workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_check_")
    os.makedirs(workdir, exist_ok=True)
    try:
        verdict = run_chaos_check(workdir, steps=args.steps)
    finally:
        if not args.keep and args.workdir is None:
            shutil.rmtree(workdir, ignore_errors=True)
    print(json.dumps(verdict))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
