#!/usr/bin/env python
"""One-command chaos smoke: kill -9 + torn checkpoint → full recovery.

Runs a tiny LeNet/MNIST job twice on the CPU backend:

1. **reference** — uninterrupted ``--steps N``;
2. **chaos** — the same config under ``--supervise`` with a canned
   fault plan: the step-4 checkpoint save is made PARTIAL (commit
   marker dropped, arrays truncated — a crashed writer) and the
   process is SIGKILLed at step 5, both only on supervisor attempt 0.

The supervisor must classify the kill as a crash, relaunch, and the
relaunch must quarantine the torn step-4 save, fall back to step 2,
resume the data stream mid-epoch, and finish — with final params
**bitwise identical** to the reference run.  Verdict is a JSON line on
stdout; exit 0 iff every check passed.  Usable locally and as a CI
gate; the tier-1 chaos parity test drives this same entry point.

Usage::

    python tools/chaos_check.py [--workdir DIR] [--steps 8]
"""

import argparse
import json
import logging
import os
import shutil
import subprocess
import sys
import tempfile

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:    # runnable as `python tools/chaos_check.py`
    sys.path.insert(0, REPO_ROOT)

KILL_STEP = 5
CORRUPT_STEP = 4
CKPT_EVERY = 2


def _cli(steps, ckpt_dir, *extra):
    return [
        sys.executable, "-m", "tensorflow_train_distributed_tpu",
        "--config", "mnist", "--steps", str(steps),
        "--platform", "cpu", "--cpu-devices", "2",
        "--strategy", "dp", "--global-batch-size", "16",
        "--log-every", "1", "--seed", "0",
        "--checkpoint-dir", ckpt_dir,
        "--checkpoint-every", str(CKPT_EVERY),
        *extra,
    ]


def run_chaos_check(workdir: str, *, steps: int = 8,
                    timeout_s: float = 600.0) -> dict:
    """Run the scenario; return ``{"ok", "checks", ...}``."""
    import numpy as np

    ref_dir = os.path.join(workdir, "ref")
    chaos_dir = os.path.join(workdir, "chaos")
    journal = os.path.join(workdir, "supervisor.jsonl")
    checks = {}

    ref = subprocess.run(_cli(steps, ref_dir), capture_output=True,
                         text=True, timeout=timeout_s, cwd=REPO_ROOT)
    checks["reference_rc0"] = ref.returncode == 0
    if not checks["reference_rc0"]:
        return {"ok": False, "checks": checks,
                "stderr": ref.stderr[-2000:]}

    plan = (f"ckpt:save:partial:step={CORRUPT_STEP}:attempt=0;"
            f"step:{KILL_STEP}:kill9:attempt=0")
    chaos = subprocess.run(
        _cli(steps, chaos_dir,
             "--supervise", "--max-restarts", "2",
             "--restart-backoff", "0.05",
             "--supervisor-journal", journal,
             "--fault-plan", plan),
        capture_output=True, text=True, timeout=timeout_s,
        cwd=REPO_ROOT)
    checks["chaos_rc0"] = chaos.returncode == 0

    # Supervisor journal: exactly one crash (the SIGKILL, rc=-9), then
    # a clean exit — the preemption/crash classification surface.
    events = []
    if os.path.exists(journal):
        with open(journal) as f:
            events = [json.loads(line) for line in f if line.strip()]
    exits = [e for e in events if e.get("event") == "exit"]
    checks["killed_then_clean"] = (
        len(exits) == 2
        and exits[0]["class"] == "crash" and exits[0]["rc"] == -9
        and exits[1]["class"] == "clean")

    # The torn step-4 save was quarantined, not deleted and not served.
    quarantined = os.path.join(chaos_dir, "corrupt", str(CORRUPT_STEP))
    checks["bad_step_quarantined"] = os.path.isdir(quarantined)
    checks["fell_back_to_previous"] = (
        f"restored checkpoint step {CORRUPT_STEP - CKPT_EVERY}"
        in chaos.stderr + chaos.stdout)

    # Headline: final params bitwise-equal to the uninterrupted run.
    bitwise = False
    if checks["chaos_rc0"]:
        # The parity check reads checkpoints in-process: force the same
        # CPU topology the child CLIs trained on (orbax rebuilds each
        # array's sharding from the checkpoint's sharding file, which
        # names those devices; env vars are too late under launchers
        # whose sitecustomize imports jax — see tests/conftest.py).
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            force_platform,
        )

        force_platform("cpu", 2)
        from tensorflow_train_distributed_tpu.training.checkpoint import (
            CheckpointManager,
        )

        mr = CheckpointManager(ref_dir, async_save=False)
        mc = CheckpointManager(chaos_dir, async_save=False)
        try:
            pr = mr.restore_params(steps)
            pc = mc.restore_params(steps)
            import jax

            leaves_r = jax.tree.leaves(pr)
            leaves_c = jax.tree.leaves(pc)
            bitwise = len(leaves_r) == len(leaves_c) and all(
                np.array_equal(np.asarray(a), np.asarray(b))
                for a, b in zip(leaves_r, leaves_c))
        finally:
            mr.close()
            mc.close()
    checks["params_bitwise_equal"] = bitwise

    return {"ok": all(checks.values()), "checks": checks,
            "journal": exits,
            "chaos_tail": (chaos.stderr[-1500:]
                           if not all(checks.values()) else "")}


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser(
        prog="chaos_check",
        description="kill-9 + torn-checkpoint recovery smoke test")
    p.add_argument("--workdir", default=None,
                   help="scratch dir (default: a fresh temp dir)")
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--keep", action="store_true",
                   help="keep the scratch dir for inspection")
    args = p.parse_args(argv)
    workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_check_")
    os.makedirs(workdir, exist_ok=True)
    try:
        verdict = run_chaos_check(workdir, steps=args.steps)
    finally:
        if not args.keep and args.workdir is None:
            shutil.rmtree(workdir, ignore_errors=True)
    print(json.dumps(verdict))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
