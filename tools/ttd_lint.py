#!/usr/bin/env python
"""ttd-lint CLI: static concurrency/purity/conventions analysis.

Usage::

    python -m tools.ttd_lint                  # whole package + tools
    python -m tools.ttd_lint --checker concurrency path/to/file.py
    python -m tools.ttd_lint --list

Exit status: 0 clean, 1 findings, 2 usage error.  The tier-1 test
(tests/test_ttd_lint.py) runs the same entry over the whole tree and
asserts zero findings — run this locally before pushing anything that
touches locks, thread roles, ``TTD_*`` flags, or metric names.

Suppress a deliberate exception with ``# ttd-lint:
disable=<checker>`` on the offending line (one shared format across
all checkers); the suppression is greppable documentation.
"""

from __future__ import annotations

import argparse
import os
import sys


def main(argv=None) -> int:
    # Keep the analyzers importable from a bare checkout.
    repo = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from tensorflow_train_distributed_tpu.runtime.lint import core

    core._load_checkers()
    parser = argparse.ArgumentParser(
        prog="ttd_lint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("paths", nargs="*",
                        help="files/dirs to lint (default: the package "
                             "and tools/)")
    parser.add_argument("--checker", action="append", default=None,
                        metavar="NAME",
                        help="run only this checker (repeatable); "
                             "default: all")
    parser.add_argument("--list", action="store_true",
                        help="list known checkers and exit")
    args = parser.parse_args(argv)

    if args.list:
        for name in sorted(core.CHECKERS):
            print(name)
        return 0
    try:
        findings = core.run_lint(paths=args.paths or None,
                                 checkers=args.checker, root=repo)
    except ValueError as e:
        print(f"ttd_lint: {e}", file=sys.stderr)
        return 2
    for f in findings:
        print(f.format(root=repo))
    if findings:
        print(f"ttd_lint: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
