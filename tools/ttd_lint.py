#!/usr/bin/env python
"""ttd-lint CLI: static concurrency/purity/compile/conventions analysis.

Usage::

    python -m tools.ttd_lint                  # whole package + tools
    python -m tools.ttd_lint --checker concurrency path/to/file.py
    python -m tools.ttd_lint --json           # machine-readable findings
    python -m tools.ttd_lint --list

Exit status: 0 clean, 2 usage error; findings exit with the OR of each
failing checker's stable bit (concurrency=4, dispatch=8,
kill-switch=16, prometheus=32, compilecheck=64, suppression=128,
memcheck=256 — folded into the generic bit 1 in the 8-bit process
status, exact in ``--json`` — io/syntax=1; ``core.CHECKER_EXIT_BITS``),
so a machine caller can tell WHICH disciplines failed from the status
alone.  ``--json`` prints ``{"findings": [...], "counts": {...},
"exit_code": N}`` on stdout for callers that want structure instead of
text (the tier-1 gate asserts on it).  The tier-1 test (tests/test_ttd_lint.py) runs
the same entry over the whole tree and asserts zero findings — run
this locally before pushing anything that touches locks, thread
roles, jit boundaries, ``TTD_*`` flags, or metric names.

Suppress a deliberate exception with ``# ttd-lint:
disable=<checker> -- <why>`` on the offending line (one shared format
across all checkers; the reason is mandatory and unused suppressions
are reported) — the suppression is greppable documentation.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    # Keep the analyzers importable from a bare checkout.
    repo = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from tensorflow_train_distributed_tpu.runtime.lint import core

    core._load_checkers()
    parser = argparse.ArgumentParser(
        prog="ttd_lint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("paths", nargs="*",
                        help="files/dirs to lint (default: the package "
                             "and tools/)")
    parser.add_argument("--checker", action="append", default=None,
                        metavar="NAME",
                        help="run only this checker (repeatable); "
                             "default: all")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output: findings, "
                             "per-checker counts, and the exit code "
                             "as one JSON object")
    parser.add_argument("--list", action="store_true",
                        help="list known checkers and exit")
    args = parser.parse_args(argv)

    if args.list:
        for name in sorted(core.CHECKERS):
            print(name)
        return 0
    try:
        findings = core.run_lint(paths=args.paths or None,
                                 checkers=args.checker, root=repo)
    except ValueError as e:
        print(f"ttd_lint: {e}", file=sys.stderr)
        return 2
    code = core.exit_code(findings)
    if args.json:
        counts: dict = {}
        for f in findings:
            counts[f.checker] = counts.get(f.checker, 0) + 1
        print(json.dumps({
            "findings": [{"checker": f.checker,
                          "path": os.path.relpath(f.path, repo),
                          "line": f.line,
                          "message": f.message} for f in findings],
            "counts": counts,
            "exit_bits": core.CHECKER_EXIT_BITS,
            "exit_code": code,
        }, indent=2))
        return code
    for f in findings:
        print(f.format(root=repo))
    if findings:
        print(f"ttd_lint: {len(findings)} finding(s)", file=sys.stderr)
    return code


if __name__ == "__main__":
    sys.exit(main())
