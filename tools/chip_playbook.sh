#!/bin/bash
# SUPERSEDED by tools/chip_hunter.py for the intermittent-tunnel regime
# (PROFILE.md round-4: the tunnel returns in alive-windows of minutes —
# a monolithic playbook wastes the window on whichever step is next;
# the hunter probes continuously and fires short atomic steps).  This
# script remains as the ONE-SHOT form for a KNOWN-stable chip session:
# run top to bottom, then tools/merge_tpu_results.py is unnecessary
# (bench.py persists directly).
#
# Every tool takes the host-wide chip lock itself (runtime/chip_lock.py)
# — but never run two of these concurrently anyway: concurrent tunnel
# use corrupts timings (PROFILE.md).
set -u
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/chip_results_$(date +%H%M).log}
say() { echo "$@" | tee -a "$LOG"; }
say "=== chip playbook start $(date -u) ==="

say "--- 1. BERT-base MLM samples/sec (BASELINE.md driver metric) ---"
timeout 1200 python tools/bench_bert.py --preset bert_base \
    --batch-per-chip 32 --seq 128 --warmup 3 --iters 20 \
    2>>"$LOG" | tee -a "$LOG"

say "--- 2. bench.py live multi-family capture (persists the record) ---"
timeout 3600 python bench.py --acquire-timeout 300 2>>"$LOG" | tee -a "$LOG"

say "--- 3. decoder remat_policy=no_ffn at b8 then b12 ---"
for B in 8 12; do
  timeout 1200 python tools/bench_lm.py --preset llama_125m \
      --batch-per-chip $B --seq 2048 --remat --remat-policy no_ffn \
      2>>"$LOG" | tee -a "$LOG"
done

say "--- 4. pallas kernel A/B (rms_norm + fused CE vs pure-jax/XLA) ---"
say "  4a. pallas ON (default on tpu):"
timeout 1200 python tools/bench_lm.py --preset llama_125m \
    --batch-per-chip 8 --seq 2048 --no-remat 2>>"$LOG" | tee -a "$LOG"
say "  4b. pallas OFF (TTD_NO_PALLAS=1):"
TTD_NO_PALLAS=1 timeout 1200 python tools/bench_lm.py --preset llama_125m \
    --batch-per-chip 8 --seq 2048 --no-remat 2>>"$LOG" | tee -a "$LOG"

say "--- 5. decode throughput (serving) ---"
timeout 1200 python tools/bench_generate.py --preset llama_125m \
    --batch 8 --prompt-len 128 --max-new 256 2>>"$LOG" | tee -a "$LOG"

say "--- 6. sliding-window A/B (train + serve; chunked path vs full) ---"
timeout 1200 python tools/bench_lm.py --preset llama_125m \
    --batch-per-chip 8 --seq 2048 --no-remat --sliding-window 512 \
    2>>"$LOG" | tee -a "$LOG"
# serve leg: window must be < prompt+max_new (384) or the rolling cache
# never engages and this measures full attention twice.
timeout 1200 python tools/bench_generate.py --preset llama_125m \
    --batch 8 --prompt-len 128 --max-new 256 --sliding-window 256 \
    2>>"$LOG" | tee -a "$LOG"

say "--- 7. fused paged-attention decode push (3 stacked A/Bs in one run:"
say "    fused kernel vs TTD_NO_FUSED_ATTN block-gather, int8 KV pool vs"
say "    fp, --sweep-slots capacity growth; every leg carries mbu_pct) ---"
timeout 2400 python tools/bench_serving.py --preset llama_125m \
    --slots 32 --chunk 16 --requests 64 --prompt-range 16,120 \
    --new-range 32,128 --cache-len 512 --kv-block-size 16 \
    --fused-ab --sweep-slots 32,48,64 2>>"$LOG" | tee -a "$LOG"

say "--- 8. kv-int8 engine throughput (paged pool; vs the fp leg the"
say "    same flags produce without --kv-int8) ---"
timeout 1200 python tools/bench_serving.py --preset llama_125m \
    --slots 32 --chunk 16 --requests 64 --cache-len 512 --kv-int8 \
    --no-ab 2>>"$LOG" | tee -a "$LOG"
timeout 1200 python tools/bench_serving.py --preset llama_125m \
    --slots 32 --chunk 16 --requests 64 --cache-len 512 \
    --no-ab 2>>"$LOG" | tee -a "$LOG"

say "--- 9. quantized gradient collectives A/B (train-side analogue of"
say "    7-8: int8-wire EQuARX pipeline + error feedback vs the f32"
say "    explicit pipeline vs today's implicit GSPMD allreduce; needs a"
say "    multi-chip slice — on data=1 the trainer falls back to the"
say "    exact path and the record says so) ---"
timeout 1200 python tools/bench_grad_quant.py --steps 30 \
    2>>"$LOG" | tee -a "$LOG"
# device allreduce busBW, f32 vs int8 wire (NCCL convention; int8 leg
# reports EFFECTIVE f32 bandwidth — the ICI-bound regime is where the
# 4x wire saving becomes throughput):
timeout 600 python tools/bench_allreduce.py --size-mb 64 2>>"$LOG" | tee -a "$LOG"
timeout 600 python tools/bench_allreduce.py --size-mb 64 --quant int8 \
    2>>"$LOG" | tee -a "$LOG"

say "--- 10. bucketed comm/compute overlap A/B (sequential int8 pipeline"
say "    vs --grad-overlap K in-flight bucketed sync vs the"
say "    TTD_NO_GRAD_OVERLAP kill switch; on real chips the fabric runs"
say "    during backward so use the FULL model/batch — the CPU-sized"
say "    --batch/--seq shrink in the committed record exists only"
say "    because the virtual mesh shares one host core) ---"
timeout 1200 python tools/bench_grad_quant.py --overlap --steps 30 \
    2>>"$LOG" | tee -a "$LOG"
# bucket-count sweep: K is a pure perf knob (results bitwise-invariant
# to the partition) — keep the K with the lowest blocking comm-fraction:
for K in 2 4 8; do
    timeout 1200 python tools/bench_grad_quant.py --overlap \
        --grad-overlap "$K" --steps 30 2>>"$LOG" | tee -a "$LOG"
done

say "--- 11. acceptance-adaptive speculative depth A/B (adaptive"
say "    controller vs every fixed depth in the bucket set, mixed"
say "    easy/hard workload; on real chips the per-round dispatch"
say "    overhead the controller amortizes is HBM-bound verify work,"
say "    so the adaptive margin should widen vs the CPU record) ---"
timeout 2400 python tools/bench_serving.py --preset llama_125m \
    --spec-adaptive-ab --slots 16 --chunk 8 --requests 24 \
    --prompt-range 16,120 --new-range 32,128 --cache-len 512 \
    --reps 5 2>>"$LOG" | tee -a "$LOG"

say "=== playbook done $(date -u); results in $LOG ==="
say "NEXT: update PROFILE.md (bnsub vs s2d from step 2; no_ffn from 3;"
say "pallas verdict from 4 — keep whichever wins as the default;"
say "fused/int8/growth verdicts from 7-8 -> append the TPU legs to"
say "profiles/bench/fused_attn_ab.jsonl and keep the faster default;"
say "grad-quant + busBW verdicts from 9 -> append the TPU legs to"
say "profiles/bench/grad_quant_ab.jsonl; overlap verdict + best K from"
say "10 -> append the TPU legs to profiles/bench/grad_overlap_ab.jsonl"
say "and pin the winning --grad-overlap default; adaptive-depth verdict"
say "from 11 -> append the TPU leg to"
say "profiles/bench/spec_adaptive_ab.jsonl)."
