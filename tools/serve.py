"""Batch-serve mixed-length requests through the continuous-batching engine.

The OFFLINE CLI face of ``serving.ServingEngine`` (slot-refill decode):
every request is collected up front, the engine runs to completion, the
process exits.  For ONLINE serving — accepting HTTP requests while the
engine decodes, with admission control, deadlines, streaming, and a
/metrics surface — use ``tools/serve_http.py`` (the
``tensorflow_train_distributed_tpu.server`` gateway); token output is
identical for the same requests.

Unlike ``tools/sample.py`` (one static batch, equal-length prompts),
requests here may have DIFFERENT prompt lengths and budgets — the
engine keeps ``--slots`` of them in flight and refills as they finish,
emitting each result as one JSONL line ``{"id", "prompt", "tokens"}``
(tokens = prompt + continuation, exactly generate()'s convention).

Requests come from repeated ``--prompt`` flags or ``--requests FILE``
(JSONL: ``{"prompt": [ids...], "max_new": N, "seed": S?}``).  Prompts
are token ids; this CLI does no text tokenization itself (transformers
+ tokenizers ARE installed in this image — load the checkpoint's
``tokenizer.json`` with ``tokenizers``/``transformers`` to turn text
into ids, e.g. ``AutoTokenizer.from_pretrained(hf_dir).encode(text)``).

Examples:
  python tools/serve.py --config llama_tiny_sft --checkpoint-dir /ck \\
      --prompt 1,2,3 --prompt 4,5,6,7,8 --max-new 32
  python tools/serve.py --config llama_tiny_sft --checkpoint-dir /ck \\
      --requests reqs.jsonl --slots 8 --temperature 0.8 --top-k 20
"""

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))  # repo root (the package)
sys.path.insert(0, _HERE)                   # tools/ (sample.py helper)

from sample import (  # noqa: E402 (tools/ sibling)
    _restore_params,
    apply_dispatch_arg,
    check_vocab_ids,
    load_decoder_params,
    parse_prompt_spec,
    resolve_decoder_task,
)


def _int_or_auto(v: str):
    """``--kv-pool-blocks`` values: an int, or the literal 'auto'
    (device-HBM autosizing — the engine solves the pool size and
    budget from the chip's reported memory)."""
    return v if v == "auto" else int(v)


def parse_spec_depth_arg(arg: str, fixed_k: int):
    """``--spec-depth`` → (speculative_k, spec_depths-or-None).

    '' keeps today's fixed ``--speculative-k``; 'fixed:K' pins K
    (bitwise the same engine); 'adaptive' uses the default bucket set
    (0, 2, 4, 8); 'adaptive:K1,K2,...' sets the buckets.  Shared by
    serve/serve_http/bench so every launcher parses the policy
    identically."""
    if not arg:
        return fixed_k, None
    if arg.startswith("fixed:"):
        return int(arg.split(":", 1)[1]), None
    if arg == "adaptive":
        return fixed_k, (0, 2, 4, 8)
    if arg.startswith("adaptive:"):
        depths = tuple(int(x) for x in arg.split(":", 1)[1].split(","))
        return fixed_k, depths
    raise SystemExit(
        f"--spec-depth must be 'fixed:K', 'adaptive', or "
        f"'adaptive:K1,K2,...', got {arg!r}")


def add_engine_args(p) -> None:
    """Engine/model flag surface SHARED with tools/serve_http.py: one
    definition, so the offline CLI and the online gateway always load
    and configure the engine identically (the parity contract)."""
    p.add_argument("--config", required=True,
                   help="registry config name (a decoder-family preset)")
    src_grp = p.add_mutually_exclusive_group(required=True)
    src_grp.add_argument("--checkpoint-dir",
                         help="orbax checkpoint dir (params-only restore)")
    src_grp.add_argument("--init-from-hf",
                         help="local HuggingFace checkpoint (llama-family "
                              "or sparse-MoE) to serve directly")
    p.add_argument("--max-new", type=int, default=32,
                   help="default generation budget (per-request values "
                        "in JSONL / HTTP bodies override it)")
    p.add_argument("--prefix", default="",
                   metavar="IDS", help="comma-separated token ids of a "
                   "shared prompt prefix (system prompt): prefilled "
                   "ONCE, reused by every request whose prompt extends "
                   "it (engine.preload_prefix)")
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--chunk", type=int, default=8)
    p.add_argument("--cache-len", type=int, default=0,
                   help="0 -> config.max_positions")
    p.add_argument("--eos-id", type=int, default=None)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--top-k", type=int, default=None)
    p.add_argument("--top-p", type=float, default=None)
    p.add_argument("--quant", default="", choices=["", "int8"])
    p.add_argument("--kv-int8", action="store_true",
                   help="int8 KV cache (llama-family configs): cache "
                        "rows store 1 byte + a per-row f32 scale — "
                        "halves KV HBM in both the paged pool and the "
                        "linear cache, the large-batch decode "
                        "bandwidth lever. The freed memory is worth "
                        "spending: grow --kv-pool-blocks (and --slots) "
                        "into it. Composes with --quant and "
                        "speculative serving (the draft's caches "
                        "quantize too)")
    p.add_argument("--speculative-draft-config", default=None,
                   help="enable speculative serving: registry config of "
                        "the DRAFT model (same vocab). Every slot keeps "
                        "its own acceptance length; greedy outputs stay "
                        "token-identical to plain serving, sampled ones "
                        "follow the same distribution (rejection rule)")
    p.add_argument("--speculative-draft-checkpoint", default=None,
                   help="orbax checkpoint dir for the draft's weights")
    p.add_argument("--speculative-k", type=int, default=4,
                   help="draft block length per round")
    p.add_argument("--spec-depth", default="",
                   help="draft-depth policy (needs the draft flags): "
                        "'fixed:K' pins depth K bitwise (same as "
                        "--speculative-k K); 'adaptive' precompiles "
                        "depth buckets {0,2,4,8} and a controller "
                        "picks per round from measured acceptance "
                        "(deepen when high, back off to plain decode "
                        "on collapse, hysteresis against thrash); "
                        "'adaptive:K1,K2,...' sets the bucket list. "
                        "TTD_NO_ADAPTIVE_SPEC=1 is the no-redeploy "
                        "kill switch back to the fixed depth")
    p.add_argument("--dispatch", default="", choices=["", "dense", "gmm"],
                   help="MoE expert-dispatch override (MoE configs "
                        "only). 'gmm' is DROPLESS: routing — and "
                        "therefore outputs — legitimately differs from "
                        "capacity-dropped 'dense', but serving regains "
                        "bucketed/chunked prefill and prefix caching "
                        "(dense compiles one prefill program per "
                        "distinct prompt length and refuses "
                        "--prefix). Default: the config's own setting")
    p.add_argument("--no-overlap", action="store_true",
                   help="disable async decode pipelining (the engine's "
                        "one-chunk-lookahead host/device overlap); "
                        "TTD_NO_OVERLAP=1 is the no-redeploy "
                        "equivalent. Outputs are bitwise-identical "
                        "either way — this is a perf kill switch")
    p.add_argument("--prefill-chunk", type=int, default=None,
                   help="prefill prompts in fixed-size pieces of this "
                        "many tokens (ONE compiled program at any "
                        "prompt length) instead of the padded prompt "
                        "buckets; also the natural installment size "
                        "for --prefill-budget. Rejected for "
                        "dense-dispatch MoE (exact-length prefill)")
    p.add_argument("--prefill-budget", type=int, default=None,
                   help="tokens of staged prefill advanced per engine "
                        "step (decode-priority admission: a new "
                        "prompt's prefill interleaves with active "
                        "lanes' decode chunks instead of blocking "
                        "them). Default: one prefill piece per step; "
                        "0 restores atomic admission")
    p.add_argument("--no-interleave", action="store_true",
                   help="disable the interleaved prefill scheduler "
                        "(same as --prefill-budget 0: a request's "
                        "whole prefill runs inline at admission, "
                        "stalling active decode lanes for its "
                        "length); TTD_NO_INTERLEAVE=1 is the "
                        "no-redeploy equivalent. Outputs are "
                        "bitwise-identical either way — this is a "
                        "scheduling kill switch")
    p.add_argument("--kv-block-size", type=int, default=16,
                   help="paged KV cache: rows per physical block. "
                        "Smaller blocks = finer prefix sharing and "
                        "less tail waste per lane; larger blocks = "
                        "shorter block tables and coarser gathers. "
                        "Prefix sharing is block-granular, so shared "
                        "system prompts win most when their length is "
                        "a multiple of this")
    p.add_argument("--kv-pool-blocks", type=_int_or_auto, default=None,
                   help="paged KV cache: total physical blocks in the "
                        "pool (default: slots * ceil(cache_len / "
                        "block_size) — the linear cache's exact "
                        "memory). Admission is keyed on free blocks: "
                        "shrink to trade memory for queueing, grow to "
                        "serve more/longer shared prefixes warm. "
                        "'auto' solves the pool size AND "
                        "--hbm-budget-bytes exactly from the device's "
                        "reported memory (pool rows + prefill "
                        "transients + draft pools + --hbm-headroom), "
                        "so one binary lands correctly sized on any "
                        "chip; TTD_NO_HBM_AUTOSIZE=1 restores the "
                        "default heuristic")
    p.add_argument("--hbm-headroom", type=float, default=0.1,
                   help="fraction of device HBM the autosize solve "
                        "leaves free (weights, activations, XLA "
                        "scratch live outside the solved pools); only "
                        "meaningful with --kv-pool-blocks auto")
    p.add_argument("--no-paged-kv", action="store_true",
                   help="serve on the per-slot LINEAR KV cache instead "
                        "of the paged block pool (no cross-request "
                        "prefix sharing beyond --prefix); "
                        "TTD_NO_PAGED_KV=1 is the no-redeploy "
                        "equivalent. Outputs are bitwise-identical "
                        "either way — this is a memory-layout kill "
                        "switch")
    p.add_argument("--hbm-budget-bytes", type=int, default=None,
                   help="declared HBM budget for the engine's memory "
                        "pools (memcheck): with TTD_MEMCHECK=1, the "
                        "allocation that would exceed it raises "
                        "MemoryBudgetError with the live set diffed "
                        "(instead of an opaque XLA OOM later), and "
                        "admission refuses requests whose projected "
                        "bytes cannot fit. Default: track-only — "
                        "ttd_engine_hbm_bytes gauges, no enforcement")
    p.add_argument("--platform", default="",
                   help="force a jax platform (e.g. 'cpu')")


def parse_prefix_arg(args, cfg):
    """--prefix ids, vocab-screened BEFORE any checkpoint load: the
    prefix becomes real context for every matching request — an
    out-of-vocab id would silently clamp in the embedding gather and
    corrupt every continuation; same screens as --prompt."""
    prefix_ids = (parse_prompt_spec(args.prefix, flag="--prefix")
                  if args.prefix else [])
    if prefix_ids:
        check_vocab_ids([prefix_ids], cfg.vocab_size)
    return prefix_ids


def maybe_dense_moe_hint(eng, lengths=None) -> None:
    """Startup hint for the dense-dispatch MoE compile storm: exact-
    length prefill compiles one XLA program per DISTINCT prompt length
    and disables prefix caching.  ``lengths``: the request lengths when
    known up front (serve.py) — the hint only fires when they vary;
    None (the online gateway: lengths unknowable at startup) always
    hints."""
    if not getattr(eng, "_exact_prefill", False):
        return
    if lengths is not None and len(set(lengths)) <= 1:
        return
    print("hint: serving a dense-dispatch MoE with varied prompt "
          "lengths compiles one prefill program PER DISTINCT length "
          "and cannot reuse prompt prefixes; pass --dispatch gmm "
          "(dropless — no capacity competition, so outputs "
          "legitimately differ from dense) to regain bucketed prefill "
          "and prefix caching, or pad prompts to a few fixed lengths "
          "host-side (MIGRATION.md §8)", file=sys.stderr)


def build_engine(args, cfg, is_moe, prefix_ids):
    """Load weights (+ optional draft), quantize, construct the engine,
    preload the prefix — shared by serve.py and serve_http.py.
    ValueErrors surface as the clean SystemExit CLI convention."""
    from tensorflow_train_distributed_tpu.serving import ServingEngine

    cfg = apply_dispatch_arg(args, cfg, is_moe)
    if getattr(args, "kv_int8", False):
        import dataclasses

        if is_moe:
            raise SystemExit("--kv-int8 applies to llama-family "
                             "configs only (MoeConfig has no "
                             "kv_cache_int8 knob)")
        cfg = dataclasses.replace(cfg, kv_cache_int8=True)
    draft_cfg = draft_params = None
    if (args.speculative_draft_checkpoint
            and not args.speculative_draft_config):
        raise SystemExit("--speculative-draft-checkpoint needs "
                         "--speculative-draft-config")
    if args.speculative_draft_config:
        if not args.speculative_draft_checkpoint:
            raise SystemExit("--speculative-draft-checkpoint is required "
                             "with --speculative-draft-config")
        _, draft_cfg, draft_moe = resolve_decoder_task(
            args.speculative_draft_config, "speculative serving")
        if draft_moe:
            raise SystemExit("the draft config must be a llama-family "
                             "decoder")
        if getattr(args, "kv_int8", False):
            import dataclasses

            # Both caches ride the same bandwidth: --kv-int8 quantizes
            # the draft's KV alongside the target's (the --quant rule).
            draft_cfg = dataclasses.replace(draft_cfg,
                                            kv_cache_int8=True)
        draft_params = _restore_params(args.speculative_draft_checkpoint)

    cfg, params = load_decoder_params(args, cfg, is_moe)
    quant_scales = draft_quant_scales = None
    if args.quant == "int8":
        from tensorflow_train_distributed_tpu.models.quant import (
            quantize_params,
        )

        params, quant_scales = quantize_params(params)
        if draft_params is not None:
            # --quant quantizes BOTH models (decode is weight-HBM-bound
            # on both); each tree carries its own scales.
            draft_params, draft_quant_scales = quantize_params(
                draft_params)

    spec_k, spec_depths = parse_spec_depth_arg(
        getattr(args, "spec_depth", "") or "",
        getattr(args, "speculative_k", 4))
    if spec_depths is not None and draft_cfg is None:
        raise SystemExit("--spec-depth adaptive needs "
                         "--speculative-draft-config")
    try:
        eng = ServingEngine(
            cfg, params, slots=args.slots, chunk=args.chunk,
            cache_len=args.cache_len or None, eos_id=args.eos_id,
            temperature=args.temperature, top_k=args.top_k,
            top_p=args.top_p, quant_scales=quant_scales,
            draft_config=draft_cfg, draft_params=draft_params,
            draft_quant_scales=draft_quant_scales,
            speculative_k=(spec_k if draft_cfg is not None else 0),
            spec_depths=(spec_depths if draft_cfg is not None
                         else None),
            overlap=not getattr(args, "no_overlap", False),
            prefill_chunk=getattr(args, "prefill_chunk", None),
            prefill_budget=(0 if getattr(args, "no_interleave", False)
                            else getattr(args, "prefill_budget", None)),
            paged=not getattr(args, "no_paged_kv", False),
            kv_block_size=getattr(args, "kv_block_size", 16),
            kv_pool_blocks=getattr(args, "kv_pool_blocks", None),
            hbm_budget_bytes=getattr(args, "hbm_budget_bytes", None),
            hbm_headroom=getattr(args, "hbm_headroom", 0.1))
        if prefix_ids:
            eng.preload_prefix(prefix_ids)
    except ValueError as e:
        raise SystemExit(str(e))
    return eng


def worker_engine_factory(spec: dict):
    """Subprocess-replica engine factory — the PRODUCTION one
    ``server.worker`` resolves as ``serve:worker_engine_factory``.
    ``spec`` is the launcher CLI's parsed flag namespace, serialized
    (``vars(args)`` — everything argparse produced is JSON-clean), so
    the worker replays the exact flag set the parent screened with:
    parent-side facades and worker-side engines are built from ONE
    flag surface and cannot drift."""
    args = argparse.Namespace(**spec)
    if getattr(args, "platform", ""):
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            force_platform,
        )

        force_platform(args.platform)
    _, cfg, is_moe = resolve_decoder_task(args.config, "serving")
    prefix_ids = parse_prefix_arg(args, cfg)
    eng = build_engine(args, cfg, is_moe, prefix_ids)
    # Warm before the HELLO: the decode program (and one prefill
    # shape) compiles now, inside the child, so the parent's
    # wait_ready covers the compile and the pool's hung-dispatch
    # watchdog never stares down a cold XLA compile.  Requests are
    # seeded independently — a warm pass changes no later output.
    eng.submit([1], 1)
    eng.run()
    return eng


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    add_engine_args(p)
    p.add_argument("--prompt", action="append", default=[],
                   metavar="IDS", help="comma-separated token ids; repeat "
                   "per request (lengths may differ — that is the point)")
    p.add_argument("--requests", default="",
                   help="JSONL file: {'prompt': [ids], 'max_new': N, "
                        "'seed': S?} per line")
    p.add_argument("--output", default="-",
                   help="output JSONL path ('-' = stdout)")
    args = p.parse_args(argv)

    if args.platform:
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            force_platform,
        )

        force_platform(args.platform)

    _, cfg, is_moe = resolve_decoder_task(args.config, "serving")

    reqs = [{"prompt": parse_prompt_spec(spec), "max_new": args.max_new}
            for spec in args.prompt]
    if args.requests:
        if not os.path.isfile(args.requests):
            raise SystemExit(f"no requests file at {args.requests}")
        with open(args.requests) as f:
            for i, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                    if not isinstance(rec.get("prompt"), list):
                        # A string would silently iterate characters.
                        raise ValueError("'prompt' must be a list of ids")
                    if not rec["prompt"]:
                        raise ValueError("empty prompt")
                    def _int(v, what):
                        # int() would silently truncate 1.9 -> 1 (and
                        # accept bools); demand real integers.
                        if not isinstance(v, int) or isinstance(v, bool):
                            raise ValueError(f"{what} must be an integer")
                        return v

                    rec = {"prompt": [_int(t, "token ids")
                                      for t in rec["prompt"]],
                           "max_new": _int(rec.get("max_new",
                                                   args.max_new),
                                           "max_new"),
                           **({"seed": _int(rec["seed"], "seed")}
                              if "seed" in rec else {})}
                except (json.JSONDecodeError, KeyError, TypeError,
                        ValueError, AttributeError) as e:
                    raise SystemExit(
                        f"{args.requests}:{i + 1}: bad request line "
                        f"({e})")
                reqs.append(rec)
    if not reqs:
        raise SystemExit("no requests (--prompt or --requests)")
    check_vocab_ids([r["prompt"] for r in reqs], cfg.vocab_size)
    prefix_ids = parse_prefix_arg(args, cfg)

    # Probe --output writability BEFORE serving (an unwritable path
    # must fail in milliseconds, not after minutes of decode) — append
    # mode, so an early failure later (bad checkpoint, OOM) does NOT
    # truncate a pre-existing results file.
    if args.output != "-":
        try:
            open(args.output, "a").close()
        except OSError as e:
            raise SystemExit(f"cannot write --output {args.output}: {e}")

    eng = build_engine(args, cfg, is_moe, prefix_ids)
    maybe_dense_moe_hint(eng, [len(r["prompt"]) for r in reqs])
    # Submit validation errors (oversized prompts, budget vs cache)
    # exit with the same clean SystemExit convention as every other
    # serve.py input error — and they happen BEFORE the truncating
    # open below, so a failed rerun never destroys a previous results
    # file.
    try:
        ids = [eng.submit(r["prompt"], r["max_new"],
                          seed=r.get("seed")) for r in reqs]
    except ValueError as e:
        raise SystemExit(str(e))
    out = eng.run()
    if args.speculative_draft_config:
        # Observable proof the speculative path actually engaged (and
        # the acceptance rate the draft is buying).  The rate divides
        # by SLOT-rounds × k (each active slot drafts k per round) —
        # engine rounds alone would inflate it by the slot count.
        s = eng.spec_stats
        rate = (s["drafted_accepted"] / (s["slot_rounds"]
                                         * args.speculative_k)
                if s["slot_rounds"] else 0.0)
        print(f"speculative: rounds={s['rounds']} "
              f"slot_rounds={s['slot_rounds']} "
              f"accepted={s['drafted_accepted']} "
              f"emitted={s['emitted']} "
              f"acceptance={rate:.3f}", file=sys.stderr)
    lines = [json.dumps({"id": rid, "prompt": r["prompt"],
                         "tokens": out[rid]}) + "\n"
             for rid, r in zip(ids, reqs)]
    if args.output == "-":
        sys.stdout.writelines(lines)
    else:
        # Results in hand before the sink is touched: a failure during
        # serving (OOM, interrupt) must never destroy a pre-existing
        # results file.  Write-temp-then-rename keeps the replacement
        # atomic too.
        tmp = args.output + ".tmp"
        with open(tmp, "w") as sink:
            sink.writelines(lines)
        os.replace(tmp, args.output)
    return 0


if __name__ == "__main__":
    sys.exit(main())
