#!/usr/bin/env python
"""Chip hunter: exploit short, intermittent TPU-tunnel alive-windows.

The round-3/4 chip outages (PROFILE.md) showed the tunnel comes back in
windows of a few minutes, not hours — a monolithic playbook wastes the
window on whichever step happens to be next.  This driver:

- probes the tunnel cheaply (subprocess ``jax.devices()`` with a hard
  timeout) in a loop;
- the moment a probe answers, runs the highest-priority *pending* step
  (each an atomic, short, self-reporting bench command);
- keeps going through the queue while the window stays open;
- on a step timeout (window closed mid-run), pushes that step to the
  back of the queue and returns to probing — no step can poison the
  queue; after ``--max-attempts`` failures a step is abandoned (logged
  to ``abandoned.jsonl``) so a deterministic failure cannot eat alive-
  windows forever;
- enables the persistent XLA compile cache for every step so a retry
  after a mid-compile death does not pay the compile twice.

State lives under ``--state-dir`` (default /tmp/chip_hunter): per-step
attempt counts (``attempts.json``), abandoned steps
(``abandoned.jsonl``), and ``results.jsonl`` (one line per successful
step: {"step": name, "secs": wall, "at": utc-iso, "json": <parsed
emit>}).  Exit codes: 0 = every configured step has a result; 3 =
deadline hit with steps still pending; 4 = queue drained but one or
more steps were abandoned (this run or a previous one).  To retry
abandoned steps delete ``abandoned.jsonl`` (their attempt counts reset
on abandonment, so they get a full ``--max-attempts`` again).

Usage:  python tools/chip_hunter.py [--deadline-hours 9] [--only s1,s2]
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The probe honors the host-wide chip lock (chip_lock.py contract: every
# TPU-backend init takes it) with a short budget: a held lock means some
# framework process is mid-measurement — report that distinctly so the
# hunter waits without calling the tunnel dead.  It must EXECUTE a real
# (tiny) program, not just enumerate devices: at 01:05 on 07-31 the
# tunnel answered jax.devices() instantly and then hung every compile —
# an enumerate-only probe green-lights a window that cannot run work.
PROBE = [sys.executable, "-c", f"""
import sys
sys.path.insert(0, {REPO!r})
from tensorflow_train_distributed_tpu.runtime.chip_lock import chip_lock
try:
    with chip_lock(timeout=8.0, poll=2.0):
        import jax, jax.numpy as jnp
        ds = jax.devices()
        x = jnp.ones((128, 128), jnp.bfloat16)
        y = (x @ x).block_until_ready()
        print('PROBE-OK', len(ds), ds[0].platform, y.dtype)
except TimeoutError as e:
    print('PROBE-HELD', e)
"""]

# (name, timeout_s, argv) — priority order.  Every command must print a
# JSON line on success (the bench tools' contract); rc==0 AND a parseable
# JSON line with backend tpu counts as done.
STEPS = [
    # ── Round-5 priority block: the families with ZERO silicon numbers
    # (VERDICT r4 "What's missing" 1-3, 5).  These run before any
    # re-confirmation step so a short window lands new evidence first.
    # EP family silicon number: MoE train throughput, active-param MFU.
    ("moe", 700,
     [sys.executable, "tools/bench_moe.py", "--preset", "moe_370m",
      "--batch-per-chip", "8", "--seq", "1024", "--iters", "10"]),
    # Dropless megablox grouped-matmul dispatch A/B against the dense
    # GShard einsums (same params, same router — only data movement
    # differs; models/moe.py MoeConfig.dispatch).
    ("moe_gmm", 700,
     [sys.executable, "tools/bench_moe.py", "--preset", "moe_370m",
      "--batch-per-chip", "8", "--seq", "1024", "--iters", "10",
      "--dispatch", "gmm"]),
    # Continuous-batching engine vs static-batch generate: mixed-length
    # request stream; the speedup IS the padding/straggler waste removed
    # (serving.py).
    ("serve_engine", 900,
     [sys.executable, "tools/bench_serving.py", "--preset", "llama_125m",
      "--slots", "8", "--chunk", "8", "--requests", "32",
      "--prompt-range", "16,120", "--new-range", "16,128",
      "--baseline"]),
    # Decoder step-time breakdown: the committed trace feeding the next
    # MFU push (where do the 502 ms go at 125m/no_ffn?).
    ("lm_profile", 700,
     [sys.executable, "tools/bench_lm.py", "--preset", "llama_125m",
      "--batch-per-chip", "8", "--seq", "2048",
      "--remat", "--remat-policy", "no_ffn", "--iters", "8",
      "--profile-dir", "profiles/bench/llama_125m_noffn"]),
    # Crossover hunt: does splash win at longer sequence?  Same window,
    # s=4096 (b4 keeps the chunked f32 score stacks inside HBM with
    # margin; the bench pre-flight still guards).
    ("lm_window_s4096", 700,
     [sys.executable, "tools/bench_lm.py", "--preset", "llama_125m",
      "--batch-per-chip", "4", "--seq", "4096", "--remat",
      "--sliding-window", "512"],
     {"TTD_NO_SPLASH": "1"}),
    ("lm_window_splash_s4096", 700,
     [sys.executable, "tools/bench_lm.py", "--preset", "llama_125m",
      "--batch-per-chip", "4", "--seq", "4096", "--remat",
      "--sliding-window", "512"],
     {"TTD_SPLASH": "1"}),
    # Fused-QKV MFU lever (VERDICT r4 item 4): one qkv gemm vs three —
    # A/B against lm_noffn_b8's 32.6k tok/s record, same shape/remat.
    ("lm_fused_qkv", 700,
     [sys.executable, "tools/bench_lm.py", "--preset", "llama_125m",
      "--batch-per-chip", "8", "--seq", "2048",
      "--remat", "--remat-policy", "no_ffn", "--fused-qkv"]),
    # Unrolled-vs-scanned depth loop: nn.scan compiles one layer body
    # but blocks cross-layer fusion; at 125m the per-layer work is
    # small enough that unrolling may buy real MFU.  Longer timeout:
    # unrolled compiles 12 layer bodies.
    ("lm_noscan", 900,
     [sys.executable, "tools/bench_lm.py", "--preset", "llama_125m",
      "--batch-per-chip", "8", "--seq", "2048",
      "--remat", "--remat-policy", "no_ffn", "--no-scan-layers"]),
    # Speculative serving bracket: 'self' draft = acceptance CEILING
    # (target drafts for itself — best case + mechanical overhead),
    # random tiny draft = FLOOR; real trained drafts land between.
    ("serve_spec_self", 900,
     [sys.executable, "tools/bench_serving.py", "--preset", "llama_125m",
      "--slots", "8", "--chunk", "8", "--requests", "32",
      "--prompt-range", "16,120", "--new-range", "16,128",
      "--speculative-draft", "self", "--speculative-k", "4"]),
    # Floor draft = a DIFFERENTLY-SEEDED llama_125m (same vocab — the
    # engine rejects vocab mismatches — and full draft cost at ~zero
    # acceptance: the worst possible case for the machinery).
    ("serve_spec_floor", 900,
     [sys.executable, "tools/bench_serving.py", "--preset", "llama_125m",
      "--slots", "8", "--chunk", "8", "--requests", "32",
      "--prompt-range", "16,120", "--new-range", "16,128",
      "--speculative-draft", "llama_125m", "--speculative-k", "4"]),
    # ── Re-confirmation block: already measured this week; refresh for
    # the round-5 record when the priority block has drained.
    ("resnet_s2d", 560,
     [sys.executable, "bench.py", "--configs", "resnet50_s2d",
      "--families", "resnet", "--warmup", "3", "--iters", "10",
      "--acquire-timeout", "60", "--probe-timeout", "45",
      "--bench-timeout", "400", "--no-cpu-fallback", "--no-persist"]),
    # (resnet50_s2d_bnsub was a queued step here until it was MEASURED
    # and rejected on silicon: 2134 img/s vs s2d's 2436 — PROFILE.md.)
    # Decoder remat lever (VERDICT r3 item 2).
    ("lm_noffn_b8", 600,
     [sys.executable, "tools/bench_lm.py", "--preset", "llama_125m",
      "--batch-per-chip", "8", "--seq", "2048",
      "--remat", "--remat-policy", "no_ffn"]),
    # Pallas kernel A/B (VERDICT r3 item 3) — ON then OFF.
    ("lm_pallas_on", 600,
     [sys.executable, "tools/bench_lm.py", "--preset", "llama_125m",
      "--batch-per-chip", "8", "--seq", "2048", "--no-remat"]),
    ("lm_pallas_off", 600,
     [sys.executable, "tools/bench_lm.py", "--preset", "llama_125m",
      "--batch-per-chip", "8", "--seq", "2048", "--no-remat"],
     {"TTD_NO_PALLAS": "1"}),
    ("lm_noffn_b12", 600,
     [sys.executable, "tools/bench_lm.py", "--preset", "llama_125m",
      "--batch-per-chip", "12", "--seq", "2048",
      "--remat", "--remat-policy", "no_ffn"]),
    # Serving path (+ int8 weight-only A/B: decode is weight-HBM-bound,
    # so int8 kernels should approach 2x the bf16 step rate).
    ("gen", 600,
     [sys.executable, "tools/bench_generate.py", "--preset", "llama_125m",
      "--batch", "8", "--prompt-len", "128", "--max-new", "256"]),
    ("gen_int8", 600,
     [sys.executable, "tools/bench_generate.py", "--preset", "llama_125m",
      "--batch", "8", "--prompt-len", "128", "--max-new", "256",
      "--quant", "int8"]),
    # int8 KV cache at the batch where cache reads bound the step
    # (measured: b32 bf16 cache = 6.79 ms/step) — A/B against gen_b32.
    ("gen_kv8_b32", 700,
     [sys.executable, "tools/bench_generate.py", "--preset", "llama_125m",
      "--batch", "32", "--prompt-len", "128", "--max-new", "256",
      "--kv-cache", "int8"]),
    # Long-context levers (round-4 additions).  Window training pairs
    # with FULL remat: the chunked path's per-layer f32 score stacks
    # ([L,B,H,chunks,c,c+w]) OOM the chip if saved (measured 25 GB under
    # no-remat AND under no_ffn, whose outer scan saves attention
    # internals) — full remat keeps them per-layer transients.
    # Pinned to the CHUNKED path (TTD_NO_SPLASH): explicit so the step
    # stays comparable to its historical record (58.1k tok/s) no matter
    # what the library default is.
    ("lm_window", 600,
     [sys.executable, "tools/bench_lm.py", "--preset", "llama_125m",
      "--batch-per-chip", "8", "--seq", "2048", "--remat",
      "--sliding-window", "512"],
     {"TTD_NO_SPLASH": "1"}),
    # Splash-kernel window A/B.  MEASURED 2026-07-31: splash 43.8k
    # (full remat) / 53.7k (+no_ffn) vs chunked 58.1k → splash LOST at
    # this shape and became opt-in (TTD_SPLASH=1, ops/attention.py);
    # these steps pin the flag so re-runs still measure splash.
    ("lm_window_splash", 600,
     [sys.executable, "tools/bench_lm.py", "--preset", "llama_125m",
      "--batch-per-chip", "8", "--seq", "2048", "--remat",
      "--sliding-window", "512"],
     {"TTD_SPLASH": "1"}),
    ("lm_window_noffn_splash", 600,
     [sys.executable, "tools/bench_lm.py", "--preset", "llama_125m",
      "--batch-per-chip", "8", "--seq", "2048", "--remat",
      "--remat-policy", "no_ffn", "--sliding-window", "512"],
     {"TTD_SPLASH": "1"}),
    # Serve leg: window MUST be < prompt+max_new (384) or the rolling
    # cache never engages and the A/B measures full attention twice.
    ("gen_window", 600,
     [sys.executable, "tools/bench_generate.py", "--preset", "llama_125m",
      "--batch", "8", "--prompt-len", "128", "--max-new", "256",
      "--sliding-window", "256"]),
    # ViT family: the transformer-vision number beside ResNet's.
    ("vit", 700,
     [sys.executable, "tools/bench_vit.py", "--preset", "vit_b16",
      "--batch-per-chip", "64", "--warmup", "3", "--iters", "10"]),
    # Mid-size decoder MFU point: 350M is where matmuls should outgrow
    # the per-op overheads that cap 125m at ~15%.
    ("lm_350m", 700,
     [sys.executable, "tools/bench_lm.py", "--preset", "llama_350m",
      "--batch-per-chip", "4", "--seq", "2048",
      "--remat", "--remat-policy", "no_ffn", "--iters", "10"]),
    # BERT re-capture only if the early-session number needs refreshing;
    # cheap with a warm compile cache, lowest priority.
    ("bert", 480,
     [sys.executable, "tools/bench_bert.py", "--preset", "bert_base",
      "--batch-per-chip", "32", "--seq", "128",
      "--warmup", "3", "--iters", "20"]),
    # Full persisted multi-family capture: long, run last when the cache
    # is warm from the atomic steps (so a ~5-min window can carry it).
    # Outer timeout exceeds the worst-case inner budget (acquire 60 +
    # probe 45 + resnet bench 1200 default + 3 family subprocesses at
    # 420 each ≈ 2565) so a healthy near-complete run is never killed.
    ("full_bench", 2700,
     [sys.executable, "bench.py", "--acquire-timeout", "60",
      "--probe-timeout", "45", "--family-timeout", "420",
      "--no-cpu-fallback"]),
]


def log(state_dir: str, msg: str) -> None:
    line = f"[{time.strftime('%H:%M:%S')}] {msg}"
    print(line, flush=True)
    with open(os.path.join(state_dir, "hunter.log"), "a") as f:
        f.write(line + "\n")


def probe(timeout_s: float) -> str:
    """'alive' | 'held' (another framework process on the chip) | 'dead'.

    The probe runs in its OWN process group and is group-killed on
    timeout: a probe hung in backend init holds the chip flock, and an
    orphaned one (observed when a hunter was SIGKILLed mid-probe) makes
    every later probe read 'held' forever.
    """
    try:
        proc = subprocess.Popen(PROBE, stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True,
                                cwd=REPO, start_new_session=True)
    except OSError:
        return "dead"  # fork/pid pressure: sleep and re-probe, not die
    try:
        stdout, _ = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except OSError:
            pass
        proc.wait()
        return "dead"
    except OSError:
        return "dead"
    if "PROBE-OK" in stdout and "tpu" in stdout.lower():
        return "alive"
    if "PROBE-HELD" in stdout:
        return "held"
    return "dead"


def last_json_line(text: str):
    """Richest JSON line from a step's stdout.

    bench.py prints the full record first and a compact headline LAST
    (the driver-tail contract); the hunter merges per-config detail into
    the persisted record, so prefer the last line that carries a
    ``configs`` tree, falling back to the last parseable line."""
    fallback = None
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and isinstance(
                    rec.get("configs"), dict):
                return rec
            if fallback is None:
                fallback = rec
    return fallback


def _bench_full_emit_path() -> str:
    """bench.py's FULL_EMIT_PATH, imported (not re-derived) so a move
    of the persisted-record location cannot silently strand the merge
    on a stale literal.  bench.py's module level is side-effect-free
    (stdlib imports and constants only)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_for_emit_path", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.FULL_EMIT_PATH


FULL_EMIT = _bench_full_emit_path()


def _prefer_full_emit(rec, t0: float):
    """bench.py diverts oversized full records (the per-config tree can
    top 4 KiB) to ``last_emit.json`` and prints only the bounded
    headline; the merge wants the tree, so pick up the file whenever
    this step wrote it (mtime >= step start, same headline value)."""
    if rec is None or isinstance(rec.get("configs"), dict):
        return rec
    try:
        if os.path.getmtime(FULL_EMIT) < t0:
            return rec
        with open(FULL_EMIT) as f:
            full = json.load(f)
    except (OSError, ValueError):
        return rec
    if (isinstance(full, dict) and isinstance(full.get("configs"), dict)
            and full.get("value") == rec.get("value")):
        return full
    return rec


def run_step(name, timeout_s, argv, extra_env, state_dir):
    env = dict(os.environ)
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_tpu_cache")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "4")
    env.update(extra_env or {})
    t0 = time.time()
    # New session + killpg on timeout: bench.py spawns per-family
    # grandchildren that deliberately keep the chip flock alive past
    # their parent's death (pass_fds) — killing only the direct child
    # would leave an orphan holding the lock and poison every later step.
    proc = subprocess.Popen(argv, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True, cwd=REPO,
                            env=env, start_new_session=True)
    try:
        stdout, stderr = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except OSError:
            pass
        proc.wait()
        return None, f"timeout after {timeout_s}s (process group killed)"
    dt = time.time() - t0
    rec = _prefer_full_emit(last_json_line(stdout), t0)
    if proc.returncode != 0:
        tail = (stderr or stdout).strip().splitlines()[-3:]
        return None, (f"rc={proc.returncode} after {dt:.0f}s: "
                      f"{' | '.join(tail)}")
    if rec is None:
        return None, f"rc=0 but no JSON line after {dt:.0f}s"
    if rec.get("backend", "tpu") != "tpu":
        return None, f"emitted backend={rec.get('backend')!r} (not tpu)"
    if "error" in rec:
        return None, f"emitted error: {rec['error']!r}"
    if rec.get("implausible"):
        return None, ("emitted implausible=true (timing artifact faster "
                      "than the hardware roofline)")
    with open(os.path.join(state_dir, "results.jsonl"), "a") as f:
        f.write(json.dumps({"step": name, "secs": round(dt, 1),
                            "at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                                time.gmtime()),
                            "json": rec}) + "\n")
    return rec, None


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--state-dir", default="/tmp/chip_hunter")
    p.add_argument("--deadline-hours", type=float, default=9.0)
    p.add_argument("--probe-timeout", type=float, default=50.0)
    p.add_argument("--sleep", type=float, default=180.0,
                   help="seconds between probes while the tunnel is dead")
    p.add_argument("--only", default="",
                   help="comma-separated step names to restrict the queue")
    p.add_argument("--max-attempts", type=int, default=4,
                   help="abandon a step after this many failed attempts")
    args = p.parse_args(argv)

    os.makedirs(args.state_dir, exist_ok=True)
    steps = {s[0]: (s[1], s[2], s[3] if len(s) > 3 else None)
             for s in STEPS}
    queue = [s[0] for s in STEPS]
    if args.only:
        keep = [n.strip() for n in args.only.split(",") if n.strip()]
        unknown = [n for n in keep if n not in steps]
        if unknown:
            p.error(f"unknown step(s) in --only: {unknown}; "
                    f"valid: {sorted(steps)}")
        queue = [n for n in queue if n in keep]
    # Resume: drop steps already recorded in results.jsonl, steps already
    # abandoned in a previous run, and reload persisted attempt counts so
    # the abandon backstop survives restarts.
    res_path = os.path.join(args.state_dir, "results.jsonl")
    if os.path.exists(res_path):
        with open(res_path) as f:
            done = {json.loads(ln)["step"] for ln in f if ln.strip()}
        queue = [n for n in queue if n not in done]
    aband_path = os.path.join(args.state_dir, "abandoned.jsonl")
    if os.path.exists(aband_path):
        with open(aband_path) as f:
            gone = {json.loads(ln)["step"] for ln in f if ln.strip()}
        if gone:
            log(args.state_dir,
                f"skipping previously abandoned steps: {sorted(gone)} "
                f"(delete {aband_path} to retry them)")
        queue = [n for n in queue if n not in gone]
    att_path = os.path.join(args.state_dir, "attempts.json")
    attempts: dict[str, int] = {}
    if os.path.exists(att_path):
        try:
            with open(att_path) as f:
                attempts = {k: int(v) for k, v in json.load(f).items()}
        except (OSError, ValueError):
            attempts = {}

    deadline = time.time() + args.deadline_hours * 3600
    log(args.state_dir, f"hunter start: queue={queue}")
    while queue and time.time() < deadline:
        state = probe(args.probe_timeout)
        if state != "alive":
            log(args.state_dir, f"probe: tunnel {state}; sleeping "
                f"{args.sleep:.0f}s ({len(queue)} steps pending)")
            time.sleep(args.sleep)
            continue
        log(args.state_dir, f"probe: TUNNEL ALIVE — running {queue[0]}")
        name = queue.pop(0)
        timeout_s, cmd, extra_env = steps[name]
        rec, err = run_step(name, timeout_s, cmd, extra_env,
                            args.state_dir)
        if err:
            attempts[name] = attempts.get(name, 0) + 1
            if attempts[name] >= args.max_attempts:
                log(args.state_dir, f"step {name} FAILED attempt "
                    f"{attempts[name]}/{args.max_attempts}: {err} — "
                    f"ABANDONED")
                with open(aband_path, "a") as f:
                    f.write(json.dumps({"step": name, "err": err}) + "\n")
                # Reset the count so "delete abandoned.jsonl to retry"
                # grants a full --max-attempts budget again.
                attempts.pop(name, None)
            else:
                log(args.state_dir, f"step {name} FAILED attempt "
                    f"{attempts[name]}/{args.max_attempts}: {err} — "
                    f"requeued at back")
                queue.append(name)
            with open(att_path, "w") as f:
                json.dump(attempts, f)
            # The window probably closed; next loop iteration re-probes.
        else:
            # A success wipes the step's failure history: a later
            # re-measure round (results.jsonl cleared) starts fresh.
            if attempts.pop(name, None) is not None:
                with open(att_path, "w") as f:
                    json.dump(attempts, f)
            val = rec.get("value", rec.get("metric", "?"))
            log(args.state_dir, f"step {name} OK: value={val} "
                                f"(queue: {queue})")
            # Fold into the persisted TPU record immediately (idempotent;
            # re-merges replay the whole results file), so a window that
            # opens unattended still lands in the repo artifact.
            try:
                out = subprocess.run(
                    [sys.executable, "tools/merge_tpu_results.py",
                     "--results",
                     os.path.join(args.state_dir, "results.jsonl")],
                    capture_output=True, text=True, timeout=60, cwd=REPO)
                log(args.state_dir,
                    f"merged into persisted record (rc={out.returncode})")
            except (subprocess.TimeoutExpired, OSError) as e:
                log(args.state_dir, f"merge failed (non-fatal): {e}")
    if queue:
        log(args.state_dir, f"deadline reached; pending={queue}")
        return 3
    # Count abandonments from the FILE, not this process's counter: a
    # resumed run that silently skipped previously abandoned steps must
    # not report full coverage.
    total_abandoned = 0
    if os.path.exists(aband_path):
        with open(aband_path) as f:
            total_abandoned = sum(1 for ln in f if ln.strip())
    if total_abandoned:
        log(args.state_dir, f"queue drained with {total_abandoned} "
                            f"step(s) ABANDONED — see abandoned.jsonl")
        return 4
    log(args.state_dir, "ALL STEPS DONE")
    return 0


if __name__ == "__main__":
    sys.exit(main())
