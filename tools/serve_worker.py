"""Standalone serving worker daemon: dial a gateway, become a replica.

The multi-host half of disaggregated serving.  Where ``--replica-procs``
spawns workers as the gateway's own subprocesses, this CLI runs the
SAME worker loop (``server.worker.run_worker`` — same engine, same
driver, same frame protocol) on any machine and DIALS IN to a gateway
started with ``--listen`` (``server.netpool.NetPool``):

  # gateway host
  python tools/serve_http.py --config llama_tiny --listen 0.0.0.0:9000

  # each worker host
  python tools/serve_worker.py --dial gw-host:9000 --factory llama \\
      --json '{"preset": "llama_tiny", "slots": 8}' --role decode

``--role`` declares the disaggregated-serving role the HELLO carries:
``prefill`` workers only stage prompts and export finished KV rows
(the gateway hands them to a decode worker over a binary KV_HANDOFF
frame), ``decode`` workers only take placements, ``both`` (default)
serves everything.

The engine is built ONCE; the dial loop reconnects with exponential
backoff when the gateway goes away (a gateway restart re-admits the
worker as a re-dial, counted against the pool's restart budget), and
exits cleanly when the gateway DRAINs it (orderly scale-down must not
re-dial) or the ``--redials`` budget runs out.
"""

import argparse
import logging
import os
import socket
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))  # repo root (the package)
sys.path.insert(0, _HERE)                   # tools/ siblings

from tensorflow_train_distributed_tpu.runtime import faults  # noqa: E402
from tensorflow_train_distributed_tpu.runtime.lint.registry import (  # noqa: E402
    thread_role,
)
from tensorflow_train_distributed_tpu.server import proto  # noqa: E402
from tensorflow_train_distributed_tpu.server.worker import (  # noqa: E402
    resolve_factory,
    run_worker,
)

logger = logging.getLogger("serve_worker")


def parse_hostport(s: str) -> tuple:
    host, sep, port = s.rpartition(":")
    if not sep or not port.isdigit():
        raise SystemExit(f"--dial wants HOST:PORT, got {s!r}")
    return host or "127.0.0.1", int(port)


@thread_role("dialer")
def dial_loop(engine, addr: tuple, *, args) -> int:
    """Connect → serve → re-dial until drained, fatal, or out of
    budget.  The backoff resets on every successful connection: only
    CONSECUTIVE failures count against ``--redials`` (a gateway that
    is simply restarting should not permanently strand its fleet)."""
    failures = 0
    backoff = args.redial_backoff
    first = True
    while True:
        try:
            sock = socket.create_connection(addr, timeout=10.0)
        except OSError as e:
            failures += 1
            if failures > args.redials:
                logger.error("gave up dialing %s:%d after %d failures",
                             addr[0], addr[1], failures - 1)
                return 1
            logger.warning("dial %s:%d failed (%s); retry in %.2fs "
                           "(%d/%d)", addr[0], addr[1], e, backoff,
                           failures, args.redials)
            time.sleep(backoff)
            backoff = min(backoff * 2, 10.0)
            continue
        failures = 0
        backoff = args.redial_backoff
        logger.info("%s %s:%d as role=%s",
                    "connected to" if first else "re-dialed",
                    addr[0], addr[1], args.role)
        first = False
        drained = []
        rc = run_worker(engine, sock,
                        replica_id=args.replica_id,
                        max_queue=args.max_queue,
                        stats_interval=args.stats_interval,
                        max_frame=args.max_frame, role=args.role,
                        on_drain=lambda: drained.append(True))
        try:
            sock.close()
        except OSError:
            pass
        if drained:
            logger.info("gateway drained this worker; exiting")
            return 0
        if rc != 0:
            # A protocol failure is OURS to not repeat: a worker the
            # gateway just classified and fenced must not crash-loop
            # against its restart budget.
            logger.error("worker loop failed (rc=%d); not re-dialing",
                         rc)
            return rc
        logger.warning("gateway connection closed; re-dialing")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--dial", required=True, metavar="HOST:PORT",
                   help="gateway worker-listener address (the gateway's "
                        "--listen)")
    p.add_argument("--role", default="both",
                   choices=("prefill", "decode", "both"),
                   help="disaggregated serving role advertised in the "
                        "HELLO: prefill = stage+export KV only, decode "
                        "= placements only, both = everything")
    p.add_argument("--factory", default="stub",
                   help="engine factory: 'stub', 'llama', or an "
                        "importable module:function")
    p.add_argument("--json", default="{}",
                   help="JSON spec handed to the factory (the "
                        "serialized engine flags)")
    p.add_argument("--replica-id", type=int, default=None,
                   help="label for log lines/events (the gateway "
                        "assigns its own replica index regardless)")
    p.add_argument("--max-queue", type=int, default=64)
    p.add_argument("--stats-interval", type=float, default=0.25)
    p.add_argument("--max-frame", type=int,
                   default=proto.MAX_FRAME_BYTES)
    p.add_argument("--redials", type=int, default=8,
                   help="consecutive failed dials tolerated before "
                        "giving up (successful connections reset the "
                        "count)")
    p.add_argument("--redial-backoff", type=float, default=0.5,
                   help="initial re-dial backoff seconds (doubles per "
                        "consecutive failure, capped at 10s)")
    args = p.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO, stream=sys.stderr,
        format=f"serve_worker[{args.replica_id}] %(levelname)s "
               f"%(message)s")
    addr = parse_hostport(args.dial)
    # Chaos plans arm from THIS daemon's environment (TTD_FAULT_PLAN),
    # exactly like a subprocess worker.
    faults.arm_from_env()
    factory = resolve_factory(args.factory)
    try:
        import json as json_mod
        spec = json_mod.loads(args.json)
    except ValueError as e:
        raise SystemExit(f"--json is not valid JSON: {e}")
    # Built ONCE, reused across re-dials: the warm engine (compiled
    # programs, preloaded prefixes) survives a gateway restart.
    engine = factory(spec)
    return dial_loop(engine, addr, args=args)


if __name__ == "__main__":
    sys.exit(main())
