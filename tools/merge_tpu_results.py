#!/usr/bin/env python
"""Fold chip_hunter results into the persisted TPU bench record.

``bench.py`` persists ``profiles/bench/last_tpu_result.json`` only on a
full TPU run, and a partial (``--configs X --no-persist``) run would
otherwise clobber the richer record.  The hunter (tools/chip_hunter.py)
therefore accumulates atomic step results in ``results.jsonl``; this
tool merges them into the persisted record so the driver's end-of-round
``bench.py`` — which embeds ``last_known_tpu`` whenever the tunnel is
dead — carries every number actually measured this round.

Merge semantics:
- a step whose JSON has ``configs`` (a bench.py emit) contributes those
  config entries verbatim;
- a family-tool step (bench_lm / bench_bert / bench_generate emits)
  contributes one entry under a descriptive config key (see STEP_KEYS);
- the headline (metric/value/vs_baseline/mfu_pct/config) is recomputed
  from the freshest resnet configs by the same best-of rule bench.py
  uses;
- ``measured_at`` becomes the newest timestamp among contributions and
  each merged entry keeps its own ``at`` stamp for honesty.

Usage: python tools/merge_tpu_results.py [--results /tmp/chip_hunter/results.jsonl]
                                         [--record profiles/bench/last_tpu_result.json]
                                         [--dry-run]
"""
from __future__ import annotations

import argparse
import json
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TARGET_IMG_PER_SEC_PER_CHIP = 2500.0  # bench.py's north-star target

# hunter step name -> config key in the persisted record.  Steps not
# listed here that carry a bench.py-style "configs" dict are merged by
# their config names; anything else lands under the step name itself.
STEP_KEYS = {
    "lm_pallas_on": "llama_125m",          # matches bench.py FAMILY_CMDS
    "bert": "bert_base",
    "gen": "llama_125m_decode",
    "lm_noffn_b8": "llama_125m_noffn_b8",
    "lm_noffn_b12": "llama_125m_noffn_b12",
    "lm_pallas_off": "llama_125m_nopallas",
    "lm_window": "llama_125m_window512",
    "gen_window": "llama_125m_decode_window256",
    "gen_int8": "llama_125m_decode_int8",
    # One-off manual capture in this round's results.jsonl (decode batch
    # sweep) — kept so re-merges keep resolving it.
    "gen_b32": "llama_125m_decode_b32",
    "vit": "vit_b16",
    "lm_350m": "llama_350m",
    "lm_profile": "llama_125m_noffn_b8_profiled",  # never clobbers the clean bench
    "gen_kv8_b32": "llama_125m_decode_b32_kv8",
    "moe": "moe_370m",
    "lm_window_splash": "llama_125m_window512_splash",
    "lm_window_noffn_splash": "llama_125m_window512_noffn_splash",
    "lm_window_s4096": "llama_125m_window512_s4096",
    "lm_window_splash_s4096": "llama_125m_window512_splash_s4096",
    "moe_gmm": "moe_370m_gmm",
    "serve_engine": "llama_125m_serving_engine",
    "lm_fused_qkv": "llama_125m_noffn_b8_fused_qkv",
    "lm_noscan": "llama_125m_noffn_b8_noscan",
    "serve_spec_self": "llama_125m_serving_spec_self",
    "serve_spec_floor": "llama_125m_serving_spec_floor",
}


def merge(record: dict, step_lines: list[dict]) -> dict:
    record = dict(record)
    configs = dict(record.get("configs", {}))
    newest = record.get("measured_at", "")
    for entry in step_lines:
        step, rec, at = entry["step"], entry["json"], entry.get("at", "")
        if rec.get("backend", "tpu") != "tpu":
            continue
        if rec.get("implausible"):
            continue  # skip BEFORE advancing measured_at: a skipped
            # record must not claim its timestamp for the merge
        newest = max(newest, at)
        if step == "full_bench" or (
                "configs" in rec and isinstance(rec["configs"], dict)
                and step.startswith(("resnet", "full"))):
            for name, cfg in rec.get("configs", {}).items():
                if isinstance(cfg, dict) and cfg.get("implausible"):
                    continue  # flaky-tunnel timing artifact: never merge
                configs[name] = dict(cfg, at=at)
            # A full bench emit also carries a fresh headline; prefer it.
            if step == "full_bench":
                for k in ("metric", "value", "unit", "vs_baseline",
                          "config", "mfu_pct"):
                    if k in rec:
                        record[k] = rec[k]
        else:
            if rec.get("implausible"):
                continue  # roofline-violating timing artifact
            key = STEP_KEYS.get(step, step)
            slim = {k: v for k, v in rec.items()
                    if k not in ("backend", "device_kind")}
            configs[key] = dict(slim, at=at)
    record["configs"] = configs

    # Recompute the resnet headline by bench.py's best-of rule, but only
    # from entries carrying an ``at`` stamp (i.e. actually measured by a
    # hunter step and merged here) — a stale unstamped entry from the
    # base record must never silently take a freshly-stamped headline.
    # Entries merged from a full_bench emit are stamped too, so a faster
    # atomic result can still honestly beat the full capture's headline.
    resnets = {n: c for n, c in configs.items()
               if "images_per_sec_per_chip" in c
               and not c.get("implausible") and c.get("at")}
    if resnets:
        best_name = max(resnets, key=lambda n:
                        resnets[n]["images_per_sec_per_chip"])
        best = resnets[best_name]
        record.update(
            metric="resnet50_train_images_per_sec_per_chip",
            value=best["images_per_sec_per_chip"],
            unit="images/sec/chip",
            vs_baseline=round(best["images_per_sec_per_chip"]
                              / TARGET_IMG_PER_SEC_PER_CHIP, 3),
            config=best_name,
        )
        if "mfu_pct" in best:
            record["mfu_pct"] = best["mfu_pct"]
        else:
            # Never leave the previous headline config's MFU attached to
            # a new headline entry that did not report one.
            record.pop("mfu_pct", None)
    if newest:
        record["measured_at"] = newest
    record["backend"] = "tpu"
    record["merged_from"] = "chip_hunter"
    return record


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--results", default="/tmp/chip_hunter/results.jsonl")
    p.add_argument("--record", default=os.path.join(
        REPO, "profiles", "bench", "last_tpu_result.json"))
    p.add_argument("--dry-run", action="store_true")
    args = p.parse_args(argv)

    try:
        with open(args.record) as f:
            record = json.load(f)
    except (OSError, ValueError):
        record = {}
    try:
        with open(args.results) as f:
            steps = [json.loads(ln) for ln in f if ln.strip()]
    except OSError as e:
        print(json.dumps({"error": f"no hunter results: {e}"}))
        return 1
    if not steps:
        print(json.dumps({"error": "no hunter results to merge"}))
        return 1
    merged = merge(record, steps)
    if not args.dry_run:
        os.makedirs(os.path.dirname(args.record), exist_ok=True)
        with open(args.record, "w") as f:
            json.dump(merged, f)
    print(json.dumps(merged))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
