"""Closed-loop load generator for the HTTP serving gateway.

Drives ``tools/serve_http.py``'s gateway with ``--clients`` concurrent
closed-loop clients (each sends its next request only after the
previous one answers — the canonical serving-latency harness shape) and
reports the bench trajectory's first serving-latency datapoints: p50 /
p99 request latency, generated tokens/sec, mean TTFT and inter-token
latency (scraped from the gateway's own /metrics histograms), and the
shed rate (429s per attempt; a shed client honors Retry-After and
retries, so the loop stays closed under overload).  In-process runs
A/B the engine's async decode pipelining by default — overlap ON is
the headline, OFF lands in a ``no_overlap`` sub-record with the
``ttd_engine_overlap_ratio`` the driver would scrape; ``--no-ab``
skips the OFF leg.

Self-contained by default — builds a random-init ``--preset`` engine
and an in-process gateway on an ephemeral port, so the bench needs no
checkpoint and runs on the CPU mesh (``--platform cpu``) or a real
chip alike.  ``--base-url`` points it at an externally launched
gateway instead (then engine flags here are ignored).

Prints one driver-parsable JSON line (bench_lm.py conventions).
"""

import argparse
import contextlib
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))


def _requests_for(client: int, n: int, plo, phi, glo, ghi, vocab, seed):
    import numpy as np

    rng = np.random.default_rng(seed + 1000 * client)
    return [([int(t) for t in
              rng.integers(1, vocab, int(rng.integers(plo, phi + 1)))],
             int(rng.integers(glo, ghi + 1))) for _ in range(n)]


def _post(base_url: str, body: dict, timeout: float):
    """(status, parsed_json, retry_after_s) — errors surface as status;
    network-level failures (timeout, refused, reset) as status 0, so a
    client thread never dies and every request lands in exactly one of
    n_ok / n_shed / n_failed."""
    req = urllib.request.Request(
        base_url + "/v1/generate", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), 0.0
    except urllib.error.HTTPError as e:
        retry = float(e.headers.get("Retry-After") or 1.0)
        with contextlib.suppress(Exception):
            e.read()
        return e.code, None, retry
    except OSError:       # URLError, socket timeout, connection reset
        return 0, None, 0.0


class _Client(threading.Thread):
    """One closed-loop client: request → wait for answer → next."""

    def __init__(self, base_url, reqs, timeout, max_retries):
        super().__init__(daemon=True)
        self.base_url, self.reqs = base_url, reqs
        self.timeout, self.max_retries = timeout, max_retries
        self.latencies, self.gen_tokens = [], 0
        self.sheds = self.failures = 0

    def run(self):
        for prompt, max_new in self.reqs:
            body = {"prompt": prompt, "max_new": max_new}
            for _ in range(self.max_retries):
                t0 = time.perf_counter()
                status, obj, retry_after = _post(
                    self.base_url, body, self.timeout)
                if status == 200:
                    self.latencies.append(time.perf_counter() - t0)
                    self.gen_tokens += len(obj["tokens"]) - len(prompt)
                    break
                if status == 429:
                    self.sheds += 1
                    time.sleep(retry_after)
                    continue
                self.failures += 1
                break
            else:
                self.failures += 1


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1,
                           int(q * (len(sorted_vals) - 1) + 0.5))]


def _prom_sample(text: str, name: str) -> float:
    """One unlabeled sample value from a Prometheus text body (0.0
    when absent — external gateways may run older builds)."""
    for line in text.splitlines():
        if line.startswith(name + " "):
            return float(line.rsplit(" ", 1)[1])
    return 0.0


def _scrape(base_url: str) -> str:
    try:
        with urllib.request.urlopen(base_url + "/metrics",
                                    timeout=10) as r:
            return r.read().decode()
    except OSError:
        return ""


def _histogram_mean_ms(text: str, name: str, base: str = "") -> float:
    """Mean in ms of a cumulative histogram, optionally net of an
    earlier scrape ``base`` (isolates the timed window)."""
    count = (_prom_sample(text, f"{name}_count")
             - _prom_sample(base, f"{name}_count"))
    total = (_prom_sample(text, f"{name}_sum")
             - _prom_sample(base, f"{name}_sum"))
    return round(1e3 * total / count, 3) if count > 0 else 0.0


def _run_closed_loop(base_url, clients, requests_per_client,
                     prompt_range, new_range, vocab, seed, timeout):
    """Warmup + the closed-loop client fleet against ``base_url``;
    returns the latency/throughput record fields plus the gateway's own
    /metrics-derived TTFT / inter-token means and overlap ratio."""
    # Warmup: ONE request through the full path compiles every program
    # (prefill bucket + decode chunk) before the timed window.
    status, obj, _ = _post(base_url,
                           {"prompt": [1, 2, 3], "max_new": 4}, timeout)
    if status != 200:
        raise RuntimeError(f"warmup request failed with HTTP {status}")
    prom_base = _scrape(base_url)

    workers = [
        _Client(base_url,
                _requests_for(c, requests_per_client, *prompt_range,
                              *new_range, vocab, seed), timeout,
                max_retries=100)
        for c in range(clients)]
    t0 = time.perf_counter()
    for w in workers:
        w.start()
    for w in workers:
        w.join()
    dt = time.perf_counter() - t0

    lats = sorted(l for w in workers for l in w.latencies)
    gen = sum(w.gen_tokens for w in workers)
    sheds = sum(w.sheds for w in workers)
    failures = sum(w.failures for w in workers)
    attempts = len(lats) + sheds + failures
    # TTFT / inter-token come from the gateway's own histograms (the
    # driver observes them chunk-granularly; a closed-loop client
    # cannot see first-token timing without streaming every request).
    # Histograms are cumulative since gateway start, so the means diff
    # the scrape taken before the fleet — the warmup request's
    # compile-laden TTFT never pollutes the numbers.
    prom = _scrape(base_url)
    return {
        "tokens_per_sec": round(gen / dt, 1) if dt else 0.0,
        "wall_s": round(dt, 3),
        "p50_latency_ms": round(1e3 * _percentile(lats, 0.50), 1),
        "p99_latency_ms": round(1e3 * _percentile(lats, 0.99), 1),
        "ttft_ms_mean": _histogram_mean_ms(
            prom, "ttd_gateway_ttft_seconds", prom_base),
        "inter_token_ms_mean": _histogram_mean_ms(
            prom, "ttd_gateway_inter_token_seconds", prom_base),
        "overlap_ratio": _prom_sample(prom, "ttd_engine_overlap_ratio"),
        "shed_rate": round(sheds / attempts, 4) if attempts else 0.0,
        "n_ok": len(lats),
        "n_shed": sheds,
        "n_failed": failures,
        "gen_tokens": gen,
    }


def bench_gateway(base_url, preset, slots, chunk, max_queue, clients,
                  requests_per_client, prompt_range, new_range,
                  cache_len, seed, timeout, overlap_ab=True):
    loop_args = (clients, requests_per_client, prompt_range, new_range)

    def finish(rec):
        rec.update({
            "metric": f"{preset}_gateway_tokens_per_sec",
            "value": rec.pop("tokens_per_sec"),
            "unit": "generated tokens/sec",
            "clients": clients,
            "requests_per_client": requests_per_client,
            "slots": slots,
            "chunk": chunk,
            "max_queue": max_queue,
        })
        return rec

    if base_url:
        # External gateway: its engine is whatever it was launched
        # with — no overlap A/B possible from here.
        vocab = 30_000       # conservative id ceiling
        return finish(_run_closed_loop(base_url, *loop_args, vocab,
                                       seed, timeout))

    import jax
    import jax.numpy as jnp

    from tensorflow_train_distributed_tpu.models.llama import (
        LLAMA_PRESETS, LlamaModel,
    )
    from tensorflow_train_distributed_tpu.server import ServingGateway
    from tensorflow_train_distributed_tpu.serving import ServingEngine

    cfg = LLAMA_PRESETS[preset]
    vocab = min(cfg.vocab_size, 30_000)
    params = LlamaModel(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]

    def one_mode(overlap):
        eng = ServingEngine(cfg, params, slots=slots, chunk=chunk,
                            cache_len=cache_len, overlap=overlap)
        gw = ServingGateway(eng, host="127.0.0.1", port=0,
                            max_queue=max_queue).start()
        try:
            return _run_closed_loop(f"http://127.0.0.1:{gw.port}",
                                    *loop_args, vocab, seed, timeout)
        finally:
            gw.drain(timeout=30)

    rec = finish(one_mode(overlap=True))
    dev = jax.devices()[0]
    rec["backend"] = dev.platform
    rec["device_kind"] = dev.device_kind
    if overlap_ab:
        off = one_mode(overlap=False)
        rec["no_overlap"] = off
        if rec["value"] and off["tokens_per_sec"]:
            rec["overlap_speedup"] = round(
                rec["value"] / off["tokens_per_sec"], 3)
    return rec


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--base-url", default="",
                   help="target an externally launched gateway instead "
                        "of building one in-process")
    p.add_argument("--preset", default="llama_tiny",
                   help="llama preset for the in-process gateway "
                        "(random-init weights — a THROUGHPUT/latency "
                        "harness, not a quality one)")
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--chunk", type=int, default=8)
    p.add_argument("--max-queue", type=int, default=16)
    p.add_argument("--clients", type=int, default=8)
    p.add_argument("--requests-per-client", type=int, default=8)
    p.add_argument("--prompt-range", default="4,24",
                   help="lo,hi inclusive prompt lengths")
    p.add_argument("--new-range", default="8,32",
                   help="lo,hi inclusive max_new_tokens")
    p.add_argument("--cache-len", type=int, default=0,
                   help="0 -> config.max_positions")
    p.add_argument("--timeout", type=float, default=300.0,
                   help="client-side HTTP timeout per request")
    p.add_argument("--no-ab", action="store_true",
                   help="skip the overlap-OFF leg of the async-decode "
                        "pipelining A/B (in-process runs only)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--platform", default="",
                   help="force a jax platform ('cpu' for smoke runs)")
    args = p.parse_args(argv)
    if args.platform:
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            force_platform,
        )

        force_platform(args.platform)
    if args.base_url or (args.platform and args.platform != "tpu"):
        cm = contextlib.nullcontext()
    else:
        from tensorflow_train_distributed_tpu.runtime.chip_lock import (
            chip_lock,
        )

        cm = chip_lock()
    prompt_range = tuple(int(x) for x in args.prompt_range.split(","))
    new_range = tuple(int(x) for x in args.new_range.split(","))
    try:
        with cm:
            rec = bench_gateway(
                args.base_url, args.preset, args.slots, args.chunk,
                args.max_queue, args.clients, args.requests_per_client,
                prompt_range, new_range, args.cache_len or None,
                args.seed, args.timeout, overlap_ab=not args.no_ab)
    except Exception as e:
        print(json.dumps({
            "metric": f"{args.preset}_gateway_tokens_per_sec",
            "value": 0.0, "unit": "generated tokens/sec",
            "error": f"{type(e).__name__}: {e}"}), flush=True)
        return 1
    print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
