"""Closed-loop load generator for the HTTP serving gateway.

Drives ``tools/serve_http.py``'s gateway with ``--clients`` concurrent
closed-loop clients (each sends its next request only after the
previous one answers — the canonical serving-latency harness shape) and
reports the bench trajectory's first serving-latency datapoints: p50 /
p99 request latency, generated tokens/sec, mean TTFT and inter-token
latency (scraped from the gateway's own /metrics histograms), and the
shed rate (429s per attempt; a shed client honors Retry-After and
retries, so the loop stays closed under overload).  In-process runs
A/B the engine's async decode pipelining by default — overlap ON is
the headline, OFF lands in a ``no_overlap`` sub-record with the
``ttd_engine_overlap_ratio`` the driver would scrape; ``--no-ab``
skips the OFF leg.

``--mixed`` instead runs the tail-latency workload: streaming clients
decode on most lanes while one LONG prompt (several prefill-piece
budget installments) is injected mid-stream, A/B'ing the engine's
interleaved prefill scheduler against its atomic-admission kill switch
— reported are the CLIENT-observed p99 inter-token latency of active
lanes during the admission window and the injected requests' TTFTs.

Self-contained by default — builds a random-init ``--preset`` engine
and an in-process gateway on an ephemeral port, so the bench needs no
checkpoint and runs on the CPU mesh (``--platform cpu``) or a real
chip alike.  ``--base-url`` points it at an externally launched
gateway instead (then engine flags here are ignored).

Prints one driver-parsable JSON line (bench_lm.py conventions).
"""

import argparse
import contextlib
import http.client
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))

from tensorflow_train_distributed_tpu.runtime.lint.registry import (  # noqa: E402
    thread_role,
)

# Load-generation threads carry the ``loadgen`` role so the runtime
# lock sanitizer (and the flight recorder's forensics) can tell bench
# traffic from the gateway's own handler threads.
_loadgen_role = thread_role("loadgen")


def _requests_for(client: int, n: int, plo, phi, glo, ghi, vocab, seed):
    import numpy as np

    rng = np.random.default_rng(seed + 1000 * client)
    return [([int(t) for t in
              rng.integers(1, vocab, int(rng.integers(plo, phi + 1)))],
             int(rng.integers(glo, ghi + 1))) for _ in range(n)]


def decode_mbu_fields(cfg, n_params, slots, cache_len,
                      tokens_per_sec, kv_int8=False):
    """Model-bandwidth-utilization fields for a DECODE-side serving
    record — the serving analog of training MFU, so every committed
    engine/gateway record carries the headline metric decode
    optimization is judged by (bench_generate's convention, shared by
    bench_serving and bench_gateway).

    Byte model per decode step (one token for every slot): the cast
    params stream once + the slot-grid KV working set (2 tensors × L ×
    slots × cache_len × kv_heads × head_dim at the cache dtype; int8
    adds its f32 per-row scales).  Steps/sec is tokens_per_sec /
    slots — generated tok/s counts all lanes, a full step emits one
    token per lane.  ``mbu_pct`` is None off-TPU (no bandwidth table —
    the field still lands in every record so TPU reruns of the same
    harness fill it in).
    """
    import jax
    import jax.numpy as jnp

    from tensorflow_train_distributed_tpu.training.memory import (
        hbm_bandwidth_bytes_per_sec,
    )

    itemsize = jnp.dtype(cfg.dtype).itemsize
    kv_heads = cfg.num_kv_heads or cfg.num_heads
    head_dim = cfg.d_model // cfg.num_heads
    kv_rows = 2 * cfg.num_layers * slots * cache_len * kv_heads
    cache_bytes = kv_rows * head_dim * (1 if kv_int8 else itemsize)
    if kv_int8:
        cache_bytes += kv_rows * 4          # f32 per-row scales
    bytes_per_step = n_params * itemsize + cache_bytes
    out = {"decode_bytes_per_step": int(bytes_per_step),
           "mbu_pct": None}
    dev = jax.devices()[0]
    bw = (hbm_bandwidth_bytes_per_sec(dev.device_kind)
          if dev.platform == "tpu" else None)
    if bw and tokens_per_sec:
        steps_per_sec = tokens_per_sec / slots
        out["mbu_pct"] = round(
            100.0 * bytes_per_step * steps_per_sec / bw, 2)
    return out


def _post(base_url: str, body: dict, timeout: float):
    """(status, parsed_json, retry_after_s) — errors surface as status;
    network-level failures (timeout, refused, reset) as status 0, so a
    client thread never dies and every request lands in exactly one of
    n_ok / n_shed / n_failed."""
    req = urllib.request.Request(
        base_url + "/v1/generate", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), 0.0
    except urllib.error.HTTPError as e:
        retry = float(e.headers.get("Retry-After") or 1.0)
        with contextlib.suppress(Exception):
            e.read()
        return e.code, None, retry
    except OSError:       # URLError, socket timeout, connection reset
        return 0, None, 0.0


class _Client(threading.Thread):
    """One closed-loop client: request → wait for answer → next."""

    def __init__(self, base_url, reqs, timeout, max_retries):
        super().__init__(daemon=True)
        self.base_url, self.reqs = base_url, reqs
        self.timeout, self.max_retries = timeout, max_retries
        self.latencies, self.gen_tokens = [], 0
        self.sheds = self.failures = 0

    @_loadgen_role
    def run(self):
        for prompt, max_new in self.reqs:
            body = {"prompt": prompt, "max_new": max_new}
            for _ in range(self.max_retries):
                t0 = time.perf_counter()
                status, obj, retry_after = _post(
                    self.base_url, body, self.timeout)
                if status == 200:
                    self.latencies.append(time.perf_counter() - t0)
                    self.gen_tokens += len(obj["tokens"]) - len(prompt)
                    break
                if status == 429:
                    self.sheds += 1
                    time.sleep(retry_after)
                    continue
                self.failures += 1
                break
            else:
                self.failures += 1


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1,
                           int(q * (len(sorted_vals) - 1) + 0.5))]


def _prom_sample(text: str, name: str) -> float:
    """One unlabeled sample value from a Prometheus text body (0.0
    when absent — external gateways may run older builds)."""
    for line in text.splitlines():
        if line.startswith(name + " "):
            return float(line.rsplit(" ", 1)[1])
    return 0.0


def _scrape(base_url: str) -> str:
    try:
        with urllib.request.urlopen(base_url + "/metrics",
                                    timeout=10) as r:
            return r.read().decode()
    except OSError:
        return ""


def _histogram_mean_ms(text: str, name: str, base: str = "") -> float:
    """Mean in ms of a cumulative histogram, optionally net of an
    earlier scrape ``base`` (isolates the timed window)."""
    count = (_prom_sample(text, f"{name}_count")
             - _prom_sample(base, f"{name}_count"))
    total = (_prom_sample(text, f"{name}_sum")
             - _prom_sample(base, f"{name}_sum"))
    return round(1e3 * total / count, 3) if count > 0 else 0.0


def _run_closed_loop(base_url, clients, requests_per_client,
                     prompt_range, new_range, vocab, seed, timeout):
    """Warmup + the closed-loop client fleet against ``base_url``;
    returns the latency/throughput record fields plus the gateway's own
    /metrics-derived TTFT / inter-token means and overlap ratio."""
    # Warmup: ONE request through the full path compiles every program
    # (prefill bucket + decode chunk) before the timed window.
    status, obj, _ = _post(base_url,
                           {"prompt": [1, 2, 3], "max_new": 4}, timeout)
    if status != 200:
        raise RuntimeError(f"warmup request failed with HTTP {status}")
    prom_base = _scrape(base_url)

    workers = [
        _Client(base_url,
                _requests_for(c, requests_per_client, *prompt_range,
                              *new_range, vocab, seed), timeout,
                max_retries=100)
        for c in range(clients)]
    t0 = time.perf_counter()
    for w in workers:
        w.start()
    for w in workers:
        w.join()
    dt = time.perf_counter() - t0

    lats = sorted(l for w in workers for l in w.latencies)
    gen = sum(w.gen_tokens for w in workers)
    sheds = sum(w.sheds for w in workers)
    failures = sum(w.failures for w in workers)
    attempts = len(lats) + sheds + failures
    # TTFT / inter-token come from the gateway's own histograms (the
    # driver observes them chunk-granularly; a closed-loop client
    # cannot see first-token timing without streaming every request).
    # Histograms are cumulative since gateway start, so the means diff
    # the scrape taken before the fleet — the warmup request's
    # compile-laden TTFT never pollutes the numbers.
    prom = _scrape(base_url)
    return {
        "tokens_per_sec": round(gen / dt, 1) if dt else 0.0,
        "wall_s": round(dt, 3),
        "p50_latency_ms": round(1e3 * _percentile(lats, 0.50), 1),
        "p99_latency_ms": round(1e3 * _percentile(lats, 0.99), 1),
        "ttft_ms_mean": _histogram_mean_ms(
            prom, "ttd_gateway_ttft_seconds", prom_base),
        "inter_token_ms_mean": _histogram_mean_ms(
            prom, "ttd_gateway_inter_token_seconds", prom_base),
        "overlap_ratio": _prom_sample(prom, "ttd_engine_overlap_ratio"),
        "prefill_stall_s": round(
            _prom_sample(prom, "ttd_engine_prefill_stall_seconds")
            - _prom_sample(prom_base,
                           "ttd_engine_prefill_stall_seconds"), 4),
        "shed_rate": round(sheds / attempts, 4) if attempts else 0.0,
        "n_ok": len(lats),
        "n_shed": sheds,
        "n_failed": failures,
        "gen_tokens": gen,
    }


class _StreamLane(threading.Thread):
    """One streaming 'active lane' client: posts a stream=True request
    and records each token chunk's (arrival time, token count) so the
    mixed bench can compute client-observed inter-token gaps around a
    long-prompt injection."""

    def __init__(self, base_url, prompt, max_new, timeout):
        super().__init__(daemon=True)
        self.base_url, self.prompt = base_url, prompt
        self.max_new, self.timeout = max_new, timeout
        self.events: list = []          # (t, n_tokens) per NDJSON chunk
        self.first_token_at = None
        self.error = None

    @_loadgen_role
    def run(self):
        req = urllib.request.Request(
            self.base_url + "/v1/generate",
            data=json.dumps({"prompt": self.prompt,
                             "max_new": self.max_new,
                             "stream": True}).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                for raw in r:
                    obj = json.loads(raw)
                    if "tokens" in obj:
                        now = time.perf_counter()
                        if self.first_token_at is None:
                            self.first_token_at = now
                        self.events.append((now, len(obj["tokens"])))
                    elif "error" in obj:
                        self.error = obj["error"]
        except (OSError, http.client.HTTPException, ValueError) as e:
            # OSError: refused/reset/timeout; HTTPException covers
            # IncompleteRead on a mid-stream drop; ValueError covers a
            # torn NDJSON line.  Anything uncaught would kill the
            # thread with error=None and the pass would misreport a
            # timeout instead of the real failure.
            self.error = f"{type(e).__name__}: {e}"


def _mixed_gateway_pass(base_url, lanes, active_new, long_prompt,
                        tail_prompt, vocab, seed, timeout):
    """Fill ``lanes`` streaming clients, wait for all to be decoding,
    inject one LONG prompt then a trailing short (both streaming, so
    TTFT is client-observable), and measure the active clients'
    per-token gaps during the admission window."""
    import numpy as np

    rng = np.random.default_rng(seed)
    lanes_t = [_StreamLane(base_url,
                           [int(t) for t in rng.integers(1, vocab, 8)],
                           active_new, timeout) for _ in range(lanes)]
    for w in lanes_t:
        w.start()
    deadline = time.perf_counter() + timeout
    while (any(w.first_token_at is None for w in lanes_t)
           and time.perf_counter() < deadline):
        if any(w.error for w in lanes_t):
            raise RuntimeError(
                f"active lane failed: {[w.error for w in lanes_t]}")
        time.sleep(0.002)
    if any(w.first_token_at is None for w in lanes_t):
        raise RuntimeError(
            "active lane produced no token before the timeout "
            "(wedged engine?) — refusing to report a truncated p99")
    t_inject = time.perf_counter()
    long_t = _StreamLane(base_url, long_prompt, 8, timeout)
    long_t.start()
    tail_t = _StreamLane(base_url, tail_prompt, 8, timeout)
    tail_t.start()
    for w in lanes_t + [long_t, tail_t]:
        w.join(timeout)
    failed = [w.error for w in lanes_t + [long_t, tail_t] if w.error]
    if failed:
        # A lane that died mid-stream leaves a truncated event trail;
        # computing a p99 from it would report an optimistic number as
        # if the pass succeeded — fail the pass instead.
        raise RuntimeError(f"mixed pass had failed requests: {failed}")
    if long_t.first_token_at is None or tail_t.first_token_at is None:
        raise RuntimeError("injected request produced no tokens")
    # Active-lane per-token gaps inside [inject, long's first token] —
    # the window a blocking admission would freeze.
    t_end = long_t.first_token_at
    gaps = []
    for w in lanes_t:
        prev = None
        for t, n in w.events:
            if prev is not None and t_inject <= t <= t_end and n:
                gaps.extend([(t - prev) / n] * n)
            prev = t
    gaps.sort()
    return {
        "p99_inter_token_ms_active": round(
            1e3 * _percentile(gaps, 0.99), 3),
        "max_gap_ms_active": round(1e3 * gaps[-1], 3) if gaps else 0.0,
        "ttft_long_ms": round(1e3 * (long_t.first_token_at - t_inject),
                              2),
        "ttft_short_behind_long_ms": round(
            1e3 * (tail_t.first_token_at - t_inject), 2),
    }


def bench_gateway_mixed(preset, slots, chunk, max_queue, seed, timeout,
                        prefill_chunk=16, long_pieces=6, reps=3):
    """The gateway face of the --mixed A/B: same workload as
    bench_serving --mixed but through HTTP streaming clients, so the
    inter-token gaps and TTFTs are what a USER of the gateway observes
    (driver/stream overhead included).  Interleave ON vs the
    prefill_budget=0 kill switch."""
    import jax
    import jax.numpy as jnp

    from tensorflow_train_distributed_tpu.models.llama import (
        LLAMA_PRESETS, LlamaModel,
    )
    from tensorflow_train_distributed_tpu.server import ServingGateway
    from tensorflow_train_distributed_tpu.serving import ServingEngine

    cfg = LLAMA_PRESETS[preset]
    vocab = min(cfg.vocab_size, 30_000)
    params = LlamaModel(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    import numpy as np

    rng = np.random.default_rng(seed + 1)
    lanes = max(1, slots - 2)
    long_len = prefill_chunk * long_pieces
    long_prompt = [int(t) for t in rng.integers(1, vocab, long_len)]
    tail_prompt = [int(t) for t in rng.integers(1, vocab, 8)]
    active_new = chunk * (long_pieces + 6)
    cache_len = max(long_len + 16, 8 + active_new + 8)

    def one_mode(interleave):
        eng = ServingEngine(cfg, params, slots=slots, chunk=chunk,
                            cache_len=cache_len,
                            prefill_chunk=prefill_chunk,
                            prefill_budget=None if interleave else 0)
        gw = ServingGateway(eng, host="127.0.0.1", port=0,
                            max_queue=max_queue).start()
        url = f"http://127.0.0.1:{gw.port}"
        try:
            args = (url, lanes, active_new, long_prompt, tail_prompt,
                    vocab, seed, timeout)
            _mixed_gateway_pass(*args)          # warmup: compiles
            stall0 = eng.prefill_stall_s()      # exclude the warmup
            best = None
            n = max(1, reps)
            for _ in range(n):
                rec = _mixed_gateway_pass(*args)
                if (best is None or rec["p99_inter_token_ms_active"]
                        < best["p99_inter_token_ms_active"]):
                    best = rec
            # MEAN per-pass stall over the timed reps — the same
            # single-pass semantics as bench_serving --mixed's field,
            # so the two tools' A/B records are comparable.
            best["prefill_stall_s"] = round(
                (eng.prefill_stall_s() - stall0) / n, 4)
            return best
        finally:
            gw.drain(timeout=30)

    on = one_mode(True)
    off = one_mode(False)
    dev = jax.devices()[0]
    rec = {
        "metric": f"{preset}_gateway_mixed_p99_inter_token_ms",
        "value": on["p99_inter_token_ms_active"],
        "unit": "ms p99 active-lane inter-token during long admission",
        "slots": slots,
        "chunk": chunk,
        "prefill_chunk": prefill_chunk,
        "long_prompt_len": long_len,
        "long_pieces": long_pieces,
        "interleave": on,
        "no_interleave": off,
        "backend": dev.platform,
        "device_kind": dev.device_kind,
    }
    if on["p99_inter_token_ms_active"]:
        rec["p99_improvement"] = round(
            off["p99_inter_token_ms_active"]
            / on["p99_inter_token_ms_active"], 3)
    return rec


def bench_gateway(base_url, preset, slots, chunk, max_queue, clients,
                  requests_per_client, prompt_range, new_range,
                  cache_len, seed, timeout, overlap_ab=True,
                  replicas=1):
    loop_args = (clients, requests_per_client, prompt_range, new_range)

    def finish(rec):
        rec.update({
            "metric": f"{preset}_gateway_tokens_per_sec",
            "value": rec.pop("tokens_per_sec"),
            "unit": "generated tokens/sec",
            "clients": clients,
            "requests_per_client": requests_per_client,
            "slots": slots,
            "chunk": chunk,
            "max_queue": max_queue,
            "replicas": replicas,
        })
        return rec

    if base_url:
        # External gateway: its engine is whatever it was launched
        # with — no overlap A/B possible from here.
        vocab = 30_000       # conservative id ceiling
        return finish(_run_closed_loop(base_url, *loop_args, vocab,
                                       seed, timeout))

    import jax
    import jax.numpy as jnp

    from tensorflow_train_distributed_tpu.models.llama import (
        LLAMA_PRESETS, LlamaModel,
    )
    from tensorflow_train_distributed_tpu.server import ServingGateway
    from tensorflow_train_distributed_tpu.serving import ServingEngine

    cfg = LLAMA_PRESETS[preset]
    vocab = min(cfg.vocab_size, 30_000)
    params = LlamaModel(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]

    def one_mode(overlap):
        engines = [ServingEngine(cfg, params, slots=slots, chunk=chunk,
                                 cache_len=cache_len, overlap=overlap)
                   for _ in range(replicas)]
        gw = ServingGateway(engines if replicas > 1 else engines[0],
                            host="127.0.0.1", port=0,
                            max_queue=max_queue).start()
        try:
            return _run_closed_loop(f"http://127.0.0.1:{gw.port}",
                                    *loop_args, vocab, seed, timeout)
        finally:
            gw.drain(timeout=30)

    rec = finish(one_mode(overlap=True))
    dev = jax.devices()[0]
    rec["backend"] = dev.platform
    rec["device_kind"] = dev.device_kind
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    rows = cache_len or cfg.max_positions
    rec.update(decode_mbu_fields(cfg, n_params, slots, rows,
                                 rec["value"]))
    if overlap_ab:
        off = one_mode(overlap=False)
        off.update(decode_mbu_fields(cfg, n_params, slots, rows,
                                     off["tokens_per_sec"]))
        rec["no_overlap"] = off
        if rec["value"] and off["tokens_per_sec"]:
            rec["overlap_speedup"] = round(
                rec["value"] / off["tokens_per_sec"], 3)
    return rec


def _recovery_gap_ms(pool, kill, prompt, max_new, reps, timeout):
    """Failover-recovery latency: ONE streaming request; after its
    first committed chunk, ``kill(replica)`` murders the replica
    serving it; the headline is the widest inter-chunk gap the CLIENT
    observed — the failover hole (death detection + re-placement +
    resume prefill).  Median over ``reps`` runs."""
    gaps = []
    for _ in range(reps):
        h = pool.submit(list(prompt), max_new, stream=True,
                        timeout_s=timeout)
        it = h.iter_tokens()
        next(it)                          # first chunk: placed, decoding
        rep = pool._requests[h.id].replica
        t_kill = time.perf_counter()
        kill(rep)
        prev, worst = t_kill, 0.0
        for _chunk in it:
            now = time.perf_counter()
            worst = max(worst, now - prev)
            prev = now
        gaps.append(worst)
    gaps.sort()
    return round(1e3 * gaps[len(gaps) // 2], 1)


def bench_gateway_procs_ab(preset, slots, chunk, max_queue, clients,
                           requests_per_client, prompt_range,
                           new_range, cache_len, seed, timeout,
                           replicas=2, reps=3):
    """Out-of-process vs in-process replicas, one workload: two
    gateways (N in-process engine replicas; N subprocess workers built
    from the same preset/init seed) serve identical closed-loop client
    fleets as leg-order-alternating BACK-TO-BACK PAIRS — the headline
    wall ratio is the MEDIAN of per-pair ratios (the established
    noise discipline), with tok/s and the gateway-observed TTFT per
    leg.  A separate leg measures FAILOVER-RECOVERY latency on each
    pool: a streaming request's replica is killed after its first
    chunk (a real SIGKILL for the subprocess pool, the in-process
    kill9 vanish fault for the other) and the widest client-observed
    inter-chunk gap — death detection + re-placement + resume — is
    the recovery hole."""
    import jax
    import jax.numpy as jnp

    from tensorflow_train_distributed_tpu.models.llama import (
        LLAMA_PRESETS, LlamaModel,
    )
    from tensorflow_train_distributed_tpu.runtime import faults
    from tensorflow_train_distributed_tpu.server import (
        ProcPool, ServingGateway, WorkerSpec,
    )
    from tensorflow_train_distributed_tpu.serving import ServingEngine

    cfg = LLAMA_PRESETS[preset]
    vocab = min(cfg.vocab_size, 30_000)
    cache_len = cache_len or min(256, cfg.max_positions)
    params = LlamaModel(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    loop_args = (clients, requests_per_client, prompt_range, new_range)

    engines = [ServingEngine(cfg, params, slots=slots, chunk=chunk,
                             cache_len=cache_len)
               for _ in range(replicas)]
    for e in engines:                      # warm: compile before timing
        e.submit([1, 2, 3], 5)
        e.run()
    gw_in = ServingGateway(engines, host="127.0.0.1", port=0,
                           max_queue=max_queue).start()
    spec = WorkerSpec(
        factory="llama",
        factory_json=dict(preset=preset, init_seed=0, slots=slots,
                          chunk=chunk, cache_len=cache_len))
    pool = ProcPool(spec, replicas=replicas, max_queue=max_queue,
                    monitor_poll_s=0.02, restart_backoff_s=0.05)
    gw_proc = ServingGateway(pool, host="127.0.0.1", port=0).start()
    urls = {"in_process": f"http://127.0.0.1:{gw_in.port}",
            "procs": f"http://127.0.0.1:{gw_proc.port}"}
    try:
        if not pool.wait_ready(timeout=600.0):
            raise RuntimeError("subprocess workers failed to come up")
        best = {}
        ratios = []
        for i in range(max(1, reps)):
            walls = {}
            order = (("in_process", "procs") if i % 2 == 0
                     else ("procs", "in_process"))
            for leg in order:
                rec = _run_closed_loop(urls[leg], *loop_args, vocab,
                                       seed, timeout)
                walls[leg] = rec["wall_s"]
                if (leg not in best
                        or rec["wall_s"] < best[leg]["wall_s"]):
                    best[leg] = rec
            ratios.append(walls["procs"] / walls["in_process"])
        ratios.sort()

        # Failover-recovery legs (after the timed pairs: they kill
        # replicas).  Subprocess pool first — its scaler respawns the
        # corpse; the in-process pool uses a fresh third gateway so
        # the timed one above stays clean for the record's tok/s.
        rec_prompt = [1, 2, 3, 4]
        rec_new = max(64, new_range[1])
        import os as _os
        import signal as _signal

        recovery = {"procs": _recovery_gap_ms(
            pool, lambda rep: _os.kill(rep.driver.pid, _signal.SIGKILL),
            rec_prompt, rec_new, reps, timeout)}

        gaps = []
        for _ in range(reps):
            # A fresh pool per run: in-process replicas never
            # resurrect, so each kill9 spends one for good (the
            # subprocess pool above respawns its own corpses).  The
            # kill9 vanish fault is the in-process analog of SIGKILL,
            # armed after the first chunk, scoped to the replica
            # serving the stream — same measurement loop as the
            # subprocess leg, different kill.
            eng3 = [ServingEngine(cfg, params, slots=slots,
                                  chunk=chunk, cache_len=cache_len)
                    for _ in range(replicas)]
            for e in eng3:
                e.submit([1, 2, 3], 5)
                e.run()
            gw3 = ServingGateway(eng3, host="127.0.0.1", port=0,
                                 max_queue=max_queue).start()
            try:
                gaps.append(_recovery_gap_ms(
                    gw3.pool,
                    lambda rep: faults.arm(
                        f"serve:dispatch:1:kill9:replica={rep.idx}"),
                    rec_prompt, rec_new, 1, timeout))
            finally:
                faults.disarm()
                gw3.drain(timeout=30)
        gaps.sort()
        recovery["in_process"] = gaps[len(gaps) // 2]
    finally:
        gw_proc.drain(timeout=60)
        gw_in.drain(timeout=30)
    dev = jax.devices()[0]
    rec = {
        "metric": f"{preset}_gateway_proc_replicas_tokens_per_sec",
        "value": best["procs"]["tokens_per_sec"],
        "unit": "generated tokens/sec, subprocess workers "
                "(wall_ratio_median: procs/in-process, median of "
                "per-pair wall ratios)",
        "replicas": replicas,
        "slots": slots,
        "chunk": chunk,
        "cache_len": cache_len,
        "clients": clients,
        "requests_per_client": requests_per_client,
        "max_queue": max_queue,
        "reps": reps,
        "procs": best["procs"],
        "in_process": best["in_process"],
        "wall_ratio_median": round(ratios[len(ratios) // 2], 3),
        "pair_wall_ratios": [round(r, 4) for r in ratios],
        "failover_recovery_ms": recovery,
        "worker_restarts": pool.restarts_total(),
        "backend": dev.platform,
        "device_kind": dev.device_kind,
    }
    return rec


def bench_gateway_disagg_ab(preset, slots, chunk, max_queue, clients,
                            requests_per_client, prompt_range,
                            new_range, cache_len, seed, timeout,
                            decode_workers=2, reps=3):
    """Disaggregated vs co-located TCP fleets, one workload: two
    ``NetPool`` gateways — one behind a 1-prefill + N-decode role
    split, one behind N+1 role-``both`` workers — serve identical
    closed-loop client fleets (long-prompt-heavy, so placements cross
    the KV-block threshold and actually hand off) as
    leg-order-alternating BACK-TO-BACK PAIRS; the headline wall ratio
    is the MEDIAN of per-pair ratios (the established noise
    discipline).  The disagg legs also scrape the gateway's own
    handoff counters: ``handoff_bytes_per_request`` and the handoff
    count — the transfer tax the ratio is buying placement freedom
    with.  Workers are real ``tools/serve_worker.py`` daemons pinned
    to the CPU backend (same-host A/B — the harness measures the
    protocol + routing overhead, not cross-host bandwidth)."""
    import subprocess

    import jax

    from tensorflow_train_distributed_tpu.models.llama import (
        LLAMA_PRESETS,
    )
    from tensorflow_train_distributed_tpu.server import (
        NetPool, ServingGateway,
    )

    cfg = LLAMA_PRESETS[preset]
    vocab = min(cfg.vocab_size, 30_000)
    cache_len = cache_len or (prompt_range[1] + new_range[1] + 24)
    buckets = [8, 16, 32, 48]
    while buckets[-1] < prompt_range[1]:
        buckets.append(buckets[-1] * 2)
    loop_args = (clients, requests_per_client, prompt_range, new_range)
    spec_json = json.dumps(dict(preset=preset, init_seed=0,
                                slots=slots, chunk=chunk,
                                cache_len=cache_len,
                                prompt_buckets=buckets))
    here = os.path.dirname(os.path.abspath(__file__))

    def fleet(roles):
        pool = NetPool(host="127.0.0.1", port=0, scale_min=len(roles),
                       max_workers=len(roles), max_queue=max_queue,
                       monitor_poll_s=0.02)
        gw = ServingGateway(pool, host="127.0.0.1", port=0).start()
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        procs = [subprocess.Popen(
            [sys.executable, os.path.join(here, "serve_worker.py"),
             "--dial", f"127.0.0.1:{pool.port}",
             "--factory", "llama", "--json", spec_json,
             "--replica-id", str(i), "--role", role],
            cwd=os.path.dirname(here), env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
            for i, role in enumerate(roles)]
        return pool, gw, procs

    pool_d, gw_d, procs_d = fleet(
        ["prefill"] + ["decode"] * decode_workers)
    pool_c, gw_c, procs_c = fleet(["both"] * (decode_workers + 1))
    urls = {"disagg": f"http://127.0.0.1:{gw_d.port}",
            "colocated": f"http://127.0.0.1:{gw_c.port}"}
    try:
        for pool, what in ((pool_d, "disagg"), (pool_c, "colocated")):
            if not pool.wait_ready(timeout=600.0):
                raise RuntimeError(f"{what} workers failed to come up")
        if pool_d.workers_by_role() != {"prefill": 1,
                                        "decode": decode_workers}:
            raise RuntimeError("disagg fleet lost its role split")
        best = {}
        ratios = []
        handoffs_total = 0
        handoff_bytes_total = 0
        disagg_ok_total = 0
        for i in range(max(1, reps)):
            walls = {}
            order = (("disagg", "colocated") if i % 2 == 0
                     else ("colocated", "disagg"))
            for leg in order:
                base = _scrape(urls[leg])
                rec = _run_closed_loop(urls[leg], *loop_args, vocab,
                                       seed, timeout)
                prom = _scrape(urls[leg])
                if leg == "disagg":
                    # The transfer tax, from the gateway's own
                    # counters: bytes shipped per completed request
                    # and how many placements actually handed off.
                    rec["handoffs"] = int(
                        _prom_sample(prom,
                                     "ttd_gateway_handoff_seconds"
                                     "_count")
                        - _prom_sample(base,
                                       "ttd_gateway_handoff_seconds"
                                       "_count"))
                    handoffs_total += rec["handoffs"]
                    leg_bytes = int(
                        _prom_sample(prom,
                                     "ttd_gateway_handoff_bytes"
                                     "_total")
                        - _prom_sample(base,
                                       "ttd_gateway_handoff_bytes"
                                       "_total"))
                    handoff_bytes_total += leg_bytes
                    disagg_ok_total += rec["n_ok"]
                    rec["handoff_bytes_per_request"] = round(
                        leg_bytes / max(1, rec["n_ok"]), 1)
                walls[leg] = rec["wall_s"]
                if (leg not in best
                        or rec["wall_s"] < best[leg]["wall_s"]):
                    best[leg] = rec
            ratios.append(walls["disagg"] / walls["colocated"])
        ratios.sort()
        if handoffs_total == 0:
            raise RuntimeError(
                "disagg legs never handed off — the workload's "
                "prompts all fit one KV block; widen --prompt-range")
    finally:
        gw_d.drain(timeout=60)
        gw_c.drain(timeout=60)
        for proc in procs_d + procs_c:
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=15)
    dev = jax.devices()[0]
    return {
        "metric": f"{preset}_gateway_disagg_tokens_per_sec",
        "value": best["disagg"]["tokens_per_sec"],
        "unit": "generated tokens/sec, disaggregated prefill/decode "
                "TCP fleet (wall_ratio_median: disagg/colocated, "
                "median of per-pair wall ratios)",
        "prefill_workers": 1,
        "decode_workers": decode_workers,
        "colocated_workers": decode_workers + 1,
        "slots": slots,
        "chunk": chunk,
        "cache_len": cache_len,
        "prompt_buckets": buckets,
        "clients": clients,
        "requests_per_client": requests_per_client,
        "max_queue": max_queue,
        "reps": reps,
        "disagg": best["disagg"],
        "colocated": best["colocated"],
        "wall_ratio_median": round(ratios[len(ratios) // 2], 3),
        "pair_wall_ratios": [round(r, 4) for r in ratios],
        "handoffs_total": handoffs_total,
        # Aggregated over ALL disagg legs: later legs ride warm
        # prefix caches and hand off less, so a per-leg number from
        # the best (warmest) leg would underreport the transfer tax.
        "handoff_bytes_per_request": round(
            handoff_bytes_total / max(1, disagg_ok_total), 1),
        "backend": dev.platform,
        "device_kind": dev.device_kind,
    }


def bench_gateway_migrate_drain_ab(preset, slots, chunk, max_queue,
                                   cache_len, seed, timeout,
                                   replicas=2, streams=4, max_new=64,
                                   reps=5):
    """Drain-with-migration vs drain-by-failover, one workload: a
    replica serving live streams must go away (the staged-SIGTERM /
    scale-down story).  Leg A evacuates it — every lane live-migrates
    (KV rows shipped, decode resumes warm on the survivor); leg B
    kills it (the in-process kill9 vanish, SIGKILL semantics) so the
    same streams resume via failover re-prefill.  Both legs run as
    leg-order-alternating BACK-TO-BACK PAIRS on fresh gateways; the
    headline is the p99 of the widest client-observed inter-chunk gap
    across the victim's streams — the resume hole — and the MEDIAN of
    per-pair p99 ratios (migrate/failover), with the migrated KV
    bytes per moved request pulled from the flight recorder."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from tensorflow_train_distributed_tpu.models.llama import (
        LLAMA_PRESETS, LlamaModel,
    )
    from tensorflow_train_distributed_tpu.runtime import events, faults
    from tensorflow_train_distributed_tpu.server import ServingGateway
    from tensorflow_train_distributed_tpu.serving import ServingEngine

    cfg = LLAMA_PRESETS[preset]
    vocab = min(cfg.vocab_size, 30_000)
    cache_len = cache_len or min(256, cfg.max_positions)
    params = LlamaModel(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    rng = np.random.default_rng(seed)
    # One prompt shape for every stream and both legs: long enough
    # that a lane holds full KV blocks by disruption time (its export
    # ships real rows), max_new deep enough that every stream is
    # provably mid-generation when the replica goes away.
    prompts = [[int(t) for t in rng.integers(1, vocab, 24)]
               for _ in range(streams)]

    def one_leg(mode):
        engines = [ServingEngine(cfg, params, slots=slots, chunk=chunk,
                                 cache_len=cache_len)
                   for _ in range(replicas)]
        for e in engines:                  # warm: compile off the clock
            e.submit([1, 2, 3], 5)
            e.run()
        gw = ServingGateway(engines, host="127.0.0.1", port=0,
                            max_queue=max_queue).start()
        pool = gw.pool
        rec_ = events.get_recorder()
        cursor, _ = rec_.events_after(0)
        arrivals = [[] for _ in range(streams)]
        first = [threading.Event() for _ in range(streams)]
        errs = [None] * streams

        def consume(i, it):
            try:
                for _chunk in it:
                    arrivals[i].append(time.perf_counter())
                    first[i].set()
            except (RuntimeError, TimeoutError) as e:
                errs[i] = e
            finally:
                first[i].set()
        try:
            handles = [pool.submit(list(p), max_new, stream=True,
                                   timeout_s=timeout) for p in prompts]
            threads = [threading.Thread(
                target=consume, args=(i, h.iter_tokens()), daemon=True)
                for i, h in enumerate(handles)]
            for t in threads:
                t.start()
            for ev in first:
                if not ev.wait(timeout):
                    raise RuntimeError("stream never produced a chunk")
            if any(errs):
                raise RuntimeError(f"stream died pre-kill: {errs}")
            victim = pool._requests[handles[0].id].replica
            affected = [i for i, h in enumerate(handles)
                        if pool._requests[h.id].replica is victim]
            t0 = time.perf_counter()
            if mode == "migrate":
                pool._evacuate(victim)
                victim.driver.drain()
            else:
                faults.arm("serve:dispatch:1:kill9:"
                           f"replica={victim.idx}")
            for t in threads:
                t.join(timeout)
                if t.is_alive():
                    raise RuntimeError(f"{mode} leg: stream wedged")
            if any(errs):
                raise RuntimeError(f"{mode} leg stream error: {errs}")
            # The resume hole per affected stream: the widest
            # inter-chunk gap the CLIENT saw from the disruption on
            # (same shape as the failover-recovery leg of
            # --replica-procs).
            gaps = []
            for i in affected:
                prev, worst = t0, 0.0
                for ts in arrivals[i]:
                    if ts <= t0:
                        continue
                    worst = max(worst, ts - prev)
                    prev = ts
                gaps.append(1e3 * worst)
            gaps.sort()
            _, evs = rec_.events_after(cursor)
            moves = [e[5] for e in evs if e[0] == "request/migrate"]
            return {"p99_ms": round(_percentile(gaps, 0.99), 1),
                    "gaps_ms": [round(g, 1) for g in gaps],
                    "lanes_moved": len(moves),
                    "kv_bytes": sum(m.get("bytes", 0) for m in moves)}
        finally:
            faults.disarm()
            gw.drain(timeout=60)

    legs = {"migrate": [], "failover": []}
    ratios = []
    for i in range(max(1, reps)):
        order = (("migrate", "failover") if i % 2 == 0
                 else ("failover", "migrate"))
        pair = {}
        for leg in order:
            pair[leg] = one_leg(leg)
            legs[leg].append(pair[leg])
        ratios.append(max(1e-3, pair["migrate"]["p99_ms"])
                      / max(1e-3, pair["failover"]["p99_ms"]))
    ratios.sort()

    def med(leg):
        vals = sorted(r["p99_ms"] for r in legs[leg])
        return vals[len(vals) // 2]

    moved = sum(r["lanes_moved"] for r in legs["migrate"])
    kv_bytes = sum(r["kv_bytes"] for r in legs["migrate"])
    dev = jax.devices()[0]
    return {
        "metric": f"{preset}_gateway_migrate_drain_p99_resume_ms",
        "value": med("migrate"),
        "unit": "ms p99 client-observed resume gap, drain WITH live "
                "migration (p99_ratio_median: migrate/failover, "
                "median of per-pair p99 ratios)",
        "replicas": replicas,
        "slots": slots,
        "chunk": chunk,
        "cache_len": cache_len,
        "streams": streams,
        "max_new": max_new,
        "reps": reps,
        "migrate": {
            "p99_resume_ms_median": med("migrate"),
            "per_pair_p99_ms": [r["p99_ms"] for r in legs["migrate"]],
            "lanes_moved_total": moved,
            "kv_bytes_total": kv_bytes,
            "kv_bytes_per_migrated_request": (
                round(kv_bytes / moved) if moved else 0),
        },
        "failover": {
            "p99_resume_ms_median": med("failover"),
            "per_pair_p99_ms": [r["p99_ms"] for r in legs["failover"]],
        },
        "p99_ratio_median": round(ratios[len(ratios) // 2], 3),
        "pair_p99_ratios": [round(r, 4) for r in ratios],
        "backend": dev.platform,
        "device_kind": dev.device_kind,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--base-url", default="",
                   help="target an externally launched gateway instead "
                        "of building one in-process")
    p.add_argument("--preset", default="llama_tiny",
                   help="llama preset for the in-process gateway "
                        "(random-init weights — a THROUGHPUT/latency "
                        "harness, not a quality one)")
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--chunk", type=int, default=8)
    p.add_argument("--replicas", type=int, default=1,
                   help="engine replicas behind the in-process gateway "
                        "(load + KV-affinity routed; ignored with "
                        "--base-url and --mixed)")
    p.add_argument("--replica-procs", action="store_true",
                   help="A/B subprocess replica workers "
                        "(server.procpool) against in-process "
                        "replicas on the same closed-loop workload: "
                        "tok/s + TTFT per leg, the median of per-pair "
                        "wall ratios, and a failover-recovery-latency "
                        "leg (real SIGKILL vs the in-process kill9 "
                        "vanish) — in-process runs only; uses "
                        "--replicas (min 2) workers per leg")
    p.add_argument("--disagg", action="store_true",
                   help="A/B a DISAGGREGATED TCP fleet (1 prefill + "
                        "--replicas decode serve_worker daemons, KV "
                        "handoff on long prompts) against a co-located "
                        "fleet of the same worker count on the same "
                        "closed-loop workload: tok/s + TTFT per leg, "
                        "the median of per-pair wall ratios, and the "
                        "gateway-scraped handoff bytes/request "
                        "(in-process runs only; CPU-pinned workers)")
    p.add_argument("--migrate-drain", action="store_true",
                   help="A/B draining a live replica WITH lane "
                        "migration (evacuation: KV shipped, decode "
                        "resumes warm) against drain-by-failover "
                        "(kill9 vanish: streams re-prefill on the "
                        "survivor) on fresh in-process gateways: "
                        "p99 client-observed resume gap per leg, the "
                        "median of per-pair p99 ratios, and migrated "
                        "KV bytes per moved request (in-process runs "
                        "only; uses --replicas, min 2)")
    p.add_argument("--max-queue", type=int, default=16)
    p.add_argument("--clients", type=int, default=8)
    p.add_argument("--requests-per-client", type=int, default=8)
    p.add_argument("--prompt-range", default="4,24",
                   help="lo,hi inclusive prompt lengths")
    p.add_argument("--new-range", default="8,32",
                   help="lo,hi inclusive max_new_tokens")
    p.add_argument("--cache-len", type=int, default=0,
                   help="0 -> config.max_positions")
    p.add_argument("--timeout", type=float, default=300.0,
                   help="client-side HTTP timeout per request")
    p.add_argument("--no-ab", action="store_true",
                   help="skip the overlap-OFF leg of the async-decode "
                        "pipelining A/B (in-process runs only)")
    p.add_argument("--mixed", action="store_true",
                   help="mixed long/short workload instead of the "
                        "closed loop: streaming clients decode on most "
                        "lanes, one LONG prompt is injected mid-stream, "
                        "and interleaved prefill is A/B'd against the "
                        "atomic-admission kill switch — reports the "
                        "client-observed p99 inter-token latency "
                        "during the admission plus injected TTFTs "
                        "(in-process runs only)")
    p.add_argument("--prefill-chunk", type=int, default=16,
                   help="--mixed only: prefill piece size (one budget "
                        "installment)")
    p.add_argument("--long-pieces", type=int, default=6,
                   help="--mixed only: budget installments the long "
                        "prompt spans")
    p.add_argument("--reps", type=int, default=3,
                   help="--mixed: passes per leg (best p99 wins); "
                        "--replica-procs: back-to-back A/B pairs "
                        "(median of per-pair wall ratios)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--platform", default="",
                   help="force a jax platform ('cpu' for smoke runs)")
    args = p.parse_args(argv)
    if args.platform:
        from tensorflow_train_distributed_tpu.runtime.mesh import (
            force_platform,
        )

        force_platform(args.platform)
    if args.base_url or (args.platform and args.platform != "tpu"):
        cm = contextlib.nullcontext()
    else:
        from tensorflow_train_distributed_tpu.runtime.chip_lock import (
            chip_lock,
        )

        cm = chip_lock()
    prompt_range = tuple(int(x) for x in args.prompt_range.split(","))
    new_range = tuple(int(x) for x in args.new_range.split(","))
    if args.mixed and args.base_url:
        raise SystemExit("--mixed builds its own A/B gateways "
                         "in-process; it cannot target --base-url")
    if args.replica_procs and (args.base_url or args.mixed):
        raise SystemExit("--replica-procs builds its own A/B gateways "
                         "in-process; it composes with neither "
                         "--base-url nor --mixed")
    if args.disagg and (args.base_url or args.mixed
                        or args.replica_procs):
        raise SystemExit("--disagg builds its own A/B fleets "
                         "in-process; it composes with none of "
                         "--base-url, --mixed, --replica-procs")
    if args.migrate_drain and (args.base_url or args.mixed
                               or args.replica_procs or args.disagg):
        raise SystemExit("--migrate-drain builds its own A/B gateways "
                         "in-process; it composes with none of "
                         "--base-url, --mixed, --replica-procs, "
                         "--disagg")
    try:
        with cm:
            if args.migrate_drain:
                rec = bench_gateway_migrate_drain_ab(
                    args.preset, args.slots, args.chunk,
                    args.max_queue, args.cache_len or None,
                    args.seed, args.timeout,
                    replicas=max(2, args.replicas),
                    reps=args.reps)
            elif args.disagg:
                rec = bench_gateway_disagg_ab(
                    args.preset, args.slots, args.chunk,
                    args.max_queue, args.clients,
                    args.requests_per_client, prompt_range, new_range,
                    args.cache_len or None, args.seed, args.timeout,
                    decode_workers=max(2, args.replicas),
                    reps=args.reps)
            elif args.replica_procs:
                rec = bench_gateway_procs_ab(
                    args.preset, args.slots, args.chunk,
                    args.max_queue, args.clients,
                    args.requests_per_client, prompt_range, new_range,
                    args.cache_len or None, args.seed, args.timeout,
                    replicas=max(2, args.replicas),
                    reps=args.reps)
            elif args.mixed:
                rec = bench_gateway_mixed(
                    args.preset, args.slots, args.chunk,
                    args.max_queue, args.seed, args.timeout,
                    prefill_chunk=args.prefill_chunk,
                    long_pieces=args.long_pieces, reps=args.reps)
            else:
                rec = bench_gateway(
                    args.base_url, args.preset, args.slots, args.chunk,
                    args.max_queue, args.clients,
                    args.requests_per_client,
                    prompt_range, new_range, args.cache_len or None,
                    args.seed, args.timeout, overlap_ab=not args.no_ab,
                    replicas=max(1, args.replicas))
    except Exception as e:
        metric = (f"{args.preset}_gateway_mixed_p99_inter_token_ms"
                  if args.mixed
                  else f"{args.preset}_gateway_tokens_per_sec")
        unit = ("ms p99 active-lane inter-token during long admission"
                if args.mixed else "generated tokens/sec")
        print(json.dumps({
            "metric": metric, "value": 0.0, "unit": unit,
            "error": f"{type(e).__name__}: {e}"}), flush=True)
        return 1
    print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
