"""Named collective operations over the device mesh.

The reference implements collectives as a Python orchestration layer over
NCCL/RING C++ kernels: reduction algorithm selection
(``cross_device_ops.py:252,960,1045``), gradient packing
(``cross_device_ops.py:712``, ``cross_device_utils.py:679``), ordering tokens
(``cross_device_utils.py:274``), and graph-level ring/recursive-halving
builders (``distribute/v1/all_reduce.py:250,422``).  On TPU, every one of
those jobs belongs to XLA: collectives are single HLO instructions scheduled
by the compiler, packing/fusion is automatic, and ordering is by construction.

What remains useful at the framework level — and what this module provides —
is a *named, mesh-aware* API for the cases where code is written per-shard
(inside ``shard_map``): ring attention's KV rotation, sequence↔head
all-to-all (Ulysses), expert dispatch, and host-level utilities (variable
broadcast at init, cross-host metric reduction).  Plus the allreduce
bus-bandwidth microbenchmark, which is one of the driver's headline metrics
(BASELINE.md).

All per-shard functions take ``axis`` names bound by an enclosing
``shard_map``/``pjit``; host-level helpers take the ``Mesh`` explicitly.
"""

from __future__ import annotations

import time
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from tensorflow_train_distributed_tpu.runtime.compat import axis_size, shard_map
from tensorflow_train_distributed_tpu.runtime.lint.registry import (
    compile_site,
)

AxisNames = str | Sequence[str]

# --- per-shard collectives (use inside shard_map) ---------------------------


def all_reduce(x: jax.Array, axis: AxisNames, op: str = "sum") -> jax.Array:
    """Reduce ``x`` across ``axis``; the TPU face of CollectiveAllReduce.

    Lowers to a single XLA all-reduce over ICI/DCN (the reference's
    ``CollectiveReduceV2``/NCCL path, ``ops/collective_ops.py:95``).
    """
    if op == "sum":
        return jax.lax.psum(x, axis)
    if op == "mean":
        return jax.lax.pmean(x, axis)
    if op == "max":
        return jax.lax.pmax(x, axis)
    if op == "min":
        return jax.lax.pmin(x, axis)
    raise ValueError(f"Unsupported reduce op: {op!r}")


def all_gather(x: jax.Array, axis: AxisNames, *, gather_dim: int = 0,
               tiled: bool = True) -> jax.Array:
    """Concatenate shards along ``gather_dim`` (``CollectiveGatherV2`` analog)."""
    return jax.lax.all_gather(x, axis, axis=gather_dim, tiled=tiled)


def reduce_scatter(x: jax.Array, axis: AxisNames, *, scatter_dim: int = 0
                   ) -> jax.Array:
    """Sum across ``axis`` and scatter shards of ``scatter_dim`` back."""
    return jax.lax.psum_scatter(x, axis, scatter_dimension=scatter_dim,
                                tiled=True)


def all_to_all(x: jax.Array, axis: str, *, split_dim: int, concat_dim: int
               ) -> jax.Array:
    """Reshard between two tensor dimensions across ``axis``.

    The primitive behind Ulysses-style sequence↔head resharding and MoE
    expert dispatch; the reference's nearest analog is the NCCL all-to-all
    kernel (``core/kernels/collective_nccl.h`` family) which no Python API
    exposed.
    """
    return jax.lax.all_to_all(x, axis, split_axis=split_dim,
                              concat_axis=concat_dim, tiled=True)


def ring_permute(x: jax.Array, axis: str, *, shift: int = 1) -> jax.Array:
    """Rotate shards around the ``axis`` ring (``ppermute``).

    The building block of ring attention (SURVEY.md §5.7): each device passes
    its block to the next neighbour over ICI while computing on the current
    one.
    """
    n = axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis, perm)


# --- host-level helpers -----------------------------------------------------


def broadcast_from_coordinator(tree):
    """Replicate a host-local pytree identically on all processes/devices.

    Reference analog: ``HierarchicalTreeBroadcaster`` /
    ``BroadcastGlobalVariablesHook`` (variable sync at init).  In multi-host
    JAX this is ``multihost_utils.broadcast_one_to_all``; in single-process
    mode it is a no-op identity.
    """
    if jax.process_count() == 1:
        return tree
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(tree)


def host_all_reduce_mean(tree, mesh: Mesh):
    """Fetch a metrics pytree, verifying every leaf is globally replicated.

    The analog of ``Strategy.reduce(MEAN, ...)`` (``distribute_lib.py:
    1675``).  Metrics produced under pjit are already global (replicated)
    arrays — the cross-replica mean happened inside the step — so the host
    side is a fetch.  This seam *verifies* that contract rather than
    assuming it: a sharded leaf reaching here means some step skipped its
    in-graph reduction, and silently fetching would hand back per-shard
    garbage as if it were the global value.
    """
    del mesh  # the leaves' shardings carry their own mesh

    def _fetch(path, x):
        if isinstance(x, jax.Array) and not x.sharding.is_fully_replicated:
            raise ValueError(
                f"host_all_reduce_mean got non-replicated metric leaf "
                f"'{jax.tree_util.keystr(path)}' with sharding spec "
                f"{getattr(x.sharding, 'spec', x.sharding)}; reduce metrics "
                "inside the jitted step (mean over the sharded batch / "
                "psum over mesh axes) so every device holds the global "
                "value")
        return np.asarray(jax.device_get(x))

    return jax.tree_util.tree_map_with_path(_fetch, tree)


# --- microbenchmark ---------------------------------------------------------


def allreduce_bus_bandwidth(
    mesh: Mesh,
    axis: str = "data",
    *,
    size_mb: float = 64.0,
    iters: int = 10,
    warmup: int = 3,
    dtype=jnp.float32,
) -> dict:
    """Measure allreduce algorithmic bus bandwidth over a mesh axis.

    Reports the standard ``2*(k-1)/k * bytes / time`` bus-bandwidth figure
    where ``bytes`` is the per-rank buffer size (``size_mb``) — the NCCL
    benchmark convention, making the number directly comparable to the
    reference's NCCL allreduce measurements (BASELINE.md metric 3).
    """
    k = mesh.shape[axis]
    per_shard = max(1, int(size_mb * 1e6 / np.dtype(dtype).itemsize))
    spec = P(axis)

    @compile_site(site="collectives.allreduce_bench_step",
                  buckets="exact (microbenchmark: one shape per run)",
                  donates=(), statics=(), max_compiles=None)
    @jax.jit
    def step(x):
        def _inner(s):
            return jax.lax.psum(s, axis)

        return shard_map(
            _inner, mesh=mesh, in_specs=spec, out_specs=P(),
            check_vma=False,
        )(x)

    x = jax.device_put(
        jnp.ones((k * per_shard,), dtype),
        NamedSharding(mesh, spec),
    )
    for _ in range(warmup):
        step(x).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(iters):
        out = step(x)
    out.block_until_ready()
    dt = (time.perf_counter() - t0) / iters
    # Per-rank buffer, NOT the k× global array size (NCCL busBW convention).
    nbytes = per_shard * np.dtype(dtype).itemsize
    bus_bw = 2 * (k - 1) / k * nbytes / dt if k > 1 else nbytes / dt
    return {
        "axis": axis,
        "devices": k,
        "message_bytes": nbytes,
        "time_s": dt,
        "bus_bandwidth_gbps": bus_bw / 1e9,
    }
