"""Named collective operations over the device mesh.

The reference implements collectives as a Python orchestration layer over
NCCL/RING C++ kernels: reduction algorithm selection
(``cross_device_ops.py:252,960,1045``), gradient packing
(``cross_device_ops.py:712``, ``cross_device_utils.py:679``), ordering tokens
(``cross_device_utils.py:274``), and graph-level ring/recursive-halving
builders (``distribute/v1/all_reduce.py:250,422``).  On TPU, every one of
those jobs belongs to XLA: collectives are single HLO instructions scheduled
by the compiler, packing/fusion is automatic, and ordering is by construction.

What remains useful at the framework level — and what this module provides —
is a *named, mesh-aware* API for the cases where code is written per-shard
(inside ``shard_map``): ring attention's KV rotation, sequence↔head
all-to-all (Ulysses), expert dispatch, and host-level utilities (variable
broadcast at init, cross-host metric reduction).  Plus the allreduce
bus-bandwidth microbenchmark, which is one of the driver's headline metrics
(BASELINE.md).

All per-shard functions take ``axis`` names bound by an enclosing
``shard_map``/``pjit``; host-level helpers take the ``Mesh`` explicitly.

The quantized-gradient section (``quantize_q8``/``ef_grad_sync``) is the
device face of the EQuARX recipe (arxiv 2506.17615) the native TCP ring
already speaks: ONE quantization recipe — ``native.ringcoll.Q8_BLOCK``
blocks, per-block f32 scale = amax/127 with a fallback to 1, symmetric
round-half-to-even int8 — shared bit-for-bit between
``native/ringcoll.HostRing.allreduce_q8`` (host/DCN path) and the
trainer's gradient pipeline here (device path), pinned against each
other in tests/test_grad_quant.py.
"""

from __future__ import annotations

import math
import time
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from tensorflow_train_distributed_tpu.native.ringcoll import Q8_BLOCK
from tensorflow_train_distributed_tpu.runtime.compat import axis_size, shard_map
from tensorflow_train_distributed_tpu.runtime.lint.registry import (
    compile_site,
)

AxisNames = str | Sequence[str]

# --- per-shard collectives (use inside shard_map) ---------------------------


def all_reduce(x: jax.Array, axis: AxisNames, op: str = "sum") -> jax.Array:
    """Reduce ``x`` across ``axis``; the TPU face of CollectiveAllReduce.

    Lowers to a single XLA all-reduce over ICI/DCN (the reference's
    ``CollectiveReduceV2``/NCCL path, ``ops/collective_ops.py:95``).
    """
    if op == "sum":
        return jax.lax.psum(x, axis)
    if op == "mean":
        return jax.lax.pmean(x, axis)
    if op == "max":
        return jax.lax.pmax(x, axis)
    if op == "min":
        return jax.lax.pmin(x, axis)
    raise ValueError(f"Unsupported reduce op: {op!r}")


def all_gather(x: jax.Array, axis: AxisNames, *, gather_dim: int = 0,
               tiled: bool = True) -> jax.Array:
    """Concatenate shards along ``gather_dim`` (``CollectiveGatherV2`` analog)."""
    return jax.lax.all_gather(x, axis, axis=gather_dim, tiled=tiled)


def reduce_scatter(x: jax.Array, axis: AxisNames, *, scatter_dim: int = 0
                   ) -> jax.Array:
    """Sum across ``axis`` and scatter shards of ``scatter_dim`` back."""
    return jax.lax.psum_scatter(x, axis, scatter_dimension=scatter_dim,
                                tiled=True)


def all_to_all(x: jax.Array, axis: str, *, split_dim: int, concat_dim: int
               ) -> jax.Array:
    """Reshard between two tensor dimensions across ``axis``.

    The primitive behind Ulysses-style sequence↔head resharding and MoE
    expert dispatch; the reference's nearest analog is the NCCL all-to-all
    kernel (``core/kernels/collective_nccl.h`` family) which no Python API
    exposed.
    """
    return jax.lax.all_to_all(x, axis, split_axis=split_dim,
                              concat_axis=concat_dim, tiled=True)


def ring_permute(x: jax.Array, axis: str, *, shift: int = 1) -> jax.Array:
    """Rotate shards around the ``axis`` ring (``ppermute``).

    The building block of ring attention (SURVEY.md §5.7): each device passes
    its block to the next neighbour over ICI while computing on the current
    one.
    """
    n = axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis, perm)


# --- quantized gradient collectives (EQuARX recipe, device face) ------------

#: Leaves smaller than this stay on the exact f32 path: the scale
#: sidecar + quantize/dequant work would cost more than the bytes saved
#: (the EQuARX large-tensor-only convention).  Their residual stays 0.
DEFAULT_MIN_QUANT_ELEMS = 512


def quantize_q8(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Device half of the shared int8 recipe (1-D input).

    Bit-for-bit the same function as ``native.ringcoll.quantize_q8_np``
    and the native ring's ``QuantizeBlocks``: per ``Q8_BLOCK`` block,
    f32 scale = amax/127 falling back to 1.0 when the derived scale/inv
    are zero or non-finite, values clamped to [-127, 127] (NaN → 0),
    rounded half-to-even.  Returns ``(q int8 [n], scales f32 [nb])``.
    """
    x = x.astype(jnp.float32)
    n = x.shape[0]
    nb = max(1, -(-n // Q8_BLOCK))
    xb = jnp.pad(x, (0, nb * Q8_BLOCK - n)).reshape(nb, Q8_BLOCK)
    a = jnp.abs(xb)
    amax = jnp.max(jnp.where(jnp.isnan(a), 0.0, a), axis=1)
    scale = amax / jnp.float32(127.0)
    inv = jnp.float32(1.0) / scale
    bad = ~(scale > 0) | ~jnp.isfinite(inv) | ~jnp.isfinite(scale)
    scale = jnp.where(bad, 1.0, scale)
    inv = jnp.where(bad, 1.0, inv)
    v = xb * inv[:, None]
    v = jnp.where(jnp.isnan(v), 0.0, jnp.clip(v, -127.0, 127.0))
    q = jnp.rint(v).astype(jnp.int8)
    return q.reshape(-1)[:n], scale


def dequantize_q8(q: jax.Array, scales: jax.Array) -> jax.Array:
    """Per-block ``q * scale`` in f32 (1-D; inverse of ``quantize_q8``)."""
    n = q.shape[0]
    nb = scales.shape[0]
    qb = jnp.pad(q, (0, nb * Q8_BLOCK - n)).reshape(nb, Q8_BLOCK)
    out = qb.astype(jnp.float32) * scales[:, None].astype(jnp.float32)
    return out.reshape(-1)[:n]


def _q8_sum(flat: jax.Array, axis: str):
    """int8-wire sum-allreduce of per-shard 1-D ``flat`` (inside
    shard_map over ``axis``), returning the quantization-error terms
    error feedback needs.

    Algorithm (the EQuARX shape, expressed in XLA collectives instead
    of a hand ring): pad to W chunks → per-chunk quantize (shared
    recipe) → ``all_to_all`` of int8+scales (the reduce-scatter: each
    rank receives every rank's copy of ITS chunk) → exact f32
    dequant-sum (no per-hop requantization, so the only phase-1 error
    is each rank's OWN send quantization — cleanly attributable, which
    the native ring's forward-partials formulation is not) → owner
    re-quantizes its reduced chunk once → int8 ``all_gather`` (every
    rank dequantizes identical bytes — bit-consistent across ranks,
    the native ring's phase-2 property).

    Returns ``(summed [n] f32, send_err [W, c], owner_err [c])`` where
    ``send_err`` is this rank's full-vector quantization error (chunk-
    partitioned, padded) and ``owner_err`` the error of its owned
    reduced chunk — together, every quantization error this rank
    introduced, for the caller's residual.
    """
    W = axis_size(axis)
    n = flat.shape[0]
    c = -(-n // W)
    p = jnp.pad(flat, (0, W * c - n)).reshape(W, c)
    q, s = jax.vmap(quantize_q8)(p)                      # (W,c) / (W,nb)
    send_err = p - jax.vmap(dequantize_q8)(q, s)
    tq = jax.lax.all_to_all(q, axis, split_axis=0, concat_axis=0,
                            tiled=True)
    ts = jax.lax.all_to_all(s, axis, split_axis=0, concat_axis=0,
                            tiled=True)
    red = jax.vmap(dequantize_q8)(tq, ts).sum(axis=0)    # (c,) exact f32 sum
    q2, s2 = quantize_q8(red)
    owner_err = red - dequantize_q8(q2, s2)
    fq = jax.lax.all_gather(q2, axis, axis=0, tiled=True)   # (W*c,)
    fs = jax.lax.all_gather(s2, axis, axis=0, tiled=True)   # (W*nb,)
    nb = s2.shape[0]
    summed = jax.vmap(dequantize_q8)(fq.reshape(W, c),
                                     fs.reshape(W, nb)).reshape(-1)[:n]
    return summed, send_err, owner_err


def q8_wire_bytes(n: int, world: int) -> int:
    """Per-rank wire bytes of one ``_q8_sum`` over ``n`` f32 elements:
    phase 1 all_to_all sends ``(W-1)`` of the rank's W chunk rows
    (int8 payload + one f32 scale per Q8 block), phase 2 all_gather
    moves the same volume for the owner chunks.  THE one accounting
    shared by ``grad_sync_wire_bytes`` and the busBW bench."""
    W = max(1, int(world))
    c = -(-int(n) // W)
    nb = max(1, -(-c // Q8_BLOCK))
    return 2 * (W - 1) * (c + 4 * nb)


def q8_all_reduce(x: jax.Array, axis: str) -> jax.Array:
    """int8-wire sum-allreduce of a 1-D per-shard buffer (inside
    shard_map) — the bench/utility face of ``_q8_sum``, errors
    discarded (no feedback)."""
    summed, _, _ = _q8_sum(x, axis)
    return summed


def ef_grad_sync(grads, residual, axis: str, *, wire: str = "int8",
                 min_quant_elems: int = DEFAULT_MIN_QUANT_ELEMS):
    """Error-feedback gradient mean-allreduce, int8 on the wire.

    Call INSIDE shard_map over ``axis`` (the trainer's grad-quant sync
    program).  ``grads``/``residual`` leaves arrive with a leading
    sharded axis of local size 1 (``[1, *shape]``): this rank's local
    gradient of the local-mean loss, and its carried quantization
    residual.  Per leaf: compensate (``g + r``), quantized allreduce
    via ``_q8_sum``, then fold BOTH error terms this rank introduced —
    its send quantization error and, on its owned chunk, the owner
    re-quantization error — into the new residual, so quantization
    error is compensated on later steps rather than accumulated
    (EF14/EQuARX error feedback).  ``wire="f32"`` is the exact-psum
    A/B baseline leg (residual stays zero); leaves smaller than
    ``min_quant_elems`` always take it.

    Returns ``(mean_grads, new_residual, finite)``: the cross-replica
    MEAN gradient (leaves ``[*shape]``, replicated — local losses are
    local means, so the global mean is the mean of shard sums), the
    updated residual (``[1, *shape]``), and an all-replica all-leaves
    finiteness flag computed on the PRE-quantization local grads —
    quantization saturates inf and zeroes NaN, so the loss-scale
    overflow signal must be taken before the wire.  On a non-finite
    step the returned residual is the INPUT residual unchanged: the
    optimizer skips the update (the loss-scale contract), and
    committing this step's error terms would poison the residual with
    the inf/NaN the wire clamped (``inf - 127 = inf`` send error) —
    permanently corrupting every later step's compensation.
    """
    if wire not in ("f32", "int8"):
        raise ValueError(f"wire must be f32|int8, got {wire!r}")
    W = axis_size(axis)
    idx = jax.lax.axis_index(axis)

    finite = jnp.stack(
        [jnp.all(jnp.isfinite(g)) for g in jax.tree.leaves(grads)]).all()
    finite = jax.lax.pmin(finite.astype(jnp.int32), axis).astype(jnp.bool_)

    leaves_g, treedef = jax.tree.flatten(grads)
    leaves_r = treedef.flatten_up_to(residual)
    shapes = [g.shape[1:] for g in leaves_g]
    sizes = [int(np.prod(s, dtype=np.int64)) if s else 1 for s in shapes]
    # One flat vector per group, not one pipeline per leaf: a per-leaf
    # formulation costs ~6 small collectives per leaf (dispatch-bound on
    # small models and wasteful on scale sidecars); concatenation costs
    # one local copy and runs ONE pipeline.  Q8 blocks then span leaf
    # boundaries — fine, the recipe quantizes a buffer, not semantic
    # units, and error feedback compensates either way.
    quant_ix = [i for i, n in enumerate(sizes)
                if wire == "int8" and n >= min_quant_elems and W > 1]
    exact_ix = [i for i in range(len(sizes)) if i not in set(quant_ix)]
    out: list = [None] * len(sizes)
    new_r: list = [jnp.zeros_like(r) for r in leaves_r]

    def _split(flat, ixs):
        offs = np.cumsum([0] + [sizes[i] for i in ixs])
        return [flat[offs[j]:offs[j + 1]].reshape(shapes[i])
                for j, i in enumerate(ixs)]

    if exact_ix:
        cat = jnp.concatenate(
            [leaves_g[i][0].astype(jnp.float32).reshape(-1)
             for i in exact_ix])
        summed = jax.lax.psum(cat, axis)
        for i, piece in zip(exact_ix, _split(summed / W, exact_ix)):
            out[i] = piece
    if quant_ix:
        comp = jnp.concatenate(
            [(leaves_g[i][0].astype(jnp.float32)
              + leaves_r[i][0].astype(jnp.float32)).reshape(-1)
             for i in quant_ix])
        n = comp.shape[0]
        summed, send_err, owner_err = _q8_sum(comp, axis)
        err = send_err.at[idx].add(owner_err).reshape(-1)[:n]
        for i, piece in zip(quant_ix, _split(summed / W, quant_ix)):
            out[i] = piece
        for i, piece in zip(quant_ix, _split(err, quant_ix)):
            new_r[i] = jnp.where(finite, piece[None],
                                 leaves_r[i]).astype(leaves_r[i].dtype)

    mean_grads = treedef.unflatten(out)
    new_residual = treedef.unflatten(new_r)
    return mean_grads, new_residual, finite


def grad_sync_wire_bytes(grads, world: int, wire: str = "int8",
                         min_quant_elems: int = DEFAULT_MIN_QUANT_ELEMS
                         ) -> int:
    """Analytic per-rank wire bytes of one ``ef_grad_sync`` step.

    ``grads`` may be abstract (ShapeDtypeStructs) or concrete; only
    shapes are read.  Mirrors ``ef_grad_sync``'s grouping: leaves below
    ``min_quant_elems`` concatenate onto the exact f32 path (ring
    convention ``2·(W-1)/W · 4n``); quantized leaves concatenate into
    ONE pipeline — phase 1 all_to_all sends ``(W-1)`` of the rank's W
    chunk rows (int8 + f32 scale per Q8 block), phase 2 all_gather
    moves the same wire volume for the owner chunks.
    """
    W = max(1, int(world))
    n_exact = n_quant = 0
    for leaf in jax.tree.leaves(grads):
        n = int(np.prod(np.shape(leaf), dtype=np.int64)) if np.shape(leaf) \
            else 1
        if wire != "int8" or n < min_quant_elems or W <= 1:
            n_exact += n
        else:
            n_quant += n
    total = 2 * (W - 1) / W * 4 * n_exact
    if n_quant:
        total += q8_wire_bytes(n_quant, W)
    return int(math.ceil(total))


# --- bucketed gradient sync (comm/compute overlap) --------------------------


def plan_grad_buckets(grads, k: int) -> list[list[int]]:
    """Partition grad-tree leaves into ``min(k, n)`` contiguous
    byte-balanced buckets.

    Returns bucket index lists in DISPATCH order: buckets are contiguous
    runs of the REVERSED flatten order, so bucket 0 holds the tree's
    last leaves — the first gradients reverse-mode AD materializes
    (backward runs last-layer-first), letting its sync dispatch while
    earlier layers' grads are still computing.  Within a bucket, indices
    are ascending flatten order.  Byte balance is greedy on cumulative
    size: bucket ``j`` closes once cumulative bytes reach ``j/k`` of the
    total, and is force-closed when the remaining leaves are exactly
    enough to give every remaining bucket one leaf — so exactly
    ``min(k, n)`` non-empty buckets always come back (a skewed size
    distribution degrades balance, never the bucket count).  A
    deterministic pure function of (leaf shapes/dtypes, ``k``) —
    abstract leaves (ShapeDtypeStructs) work.
    """
    leaves = jax.tree.leaves(grads)
    n = len(leaves)
    if n == 0:
        return []
    k = max(1, min(int(k), n))

    def _bytes(leaf):
        shape = np.shape(leaf)
        size = int(np.prod(shape, dtype=np.int64)) if shape else 1
        return size * np.dtype(getattr(leaf, "dtype", np.float32)).itemsize

    order = list(range(n))[::-1]
    sizes = [_bytes(leaves[i]) for i in order]
    total = float(sum(sizes))
    buckets: list[list[int]] = [[]]
    cum = 0.0
    for pos, i in enumerate(order):
        buckets[-1].append(i)
        cum += sizes[pos]
        left = n - pos - 1
        if len(buckets) < k and left >= 1 and (
                cum >= len(buckets) * total / k
                or left == k - len(buckets)):
            buckets.append([])
    return [sorted(b) for b in buckets]


def _leaf_rank_chunk(n: int, world: int) -> int:
    """Per-rank chunk length for one quantized leaf of ``n`` elements:
    the smallest whole-``Q8_BLOCK`` multiple covering ``ceil(n/W)``."""
    return Q8_BLOCK * max(1, -(-int(n) // (max(1, int(world)) * Q8_BLOCK)))


def ef_bucket_sync(grads, residual, axis: str, *, wire: str = "int8",
                   min_quant_elems: int = DEFAULT_MIN_QUANT_ELEMS):
    """Error-feedback gradient mean-allreduce of ONE bucket (leaf subset).

    Same call contract and return shape as ``ef_grad_sync`` — call
    INSIDE shard_map over ``axis``, leaves ``[1, *shape]``, returns
    ``(mean_grads, new_residual, finite)`` — but with LEAF-ALIGNED Q8
    layout, which is what makes bucketing legal: each quantized leaf
    (``m`` elements) gets its own per-rank chunk of
    ``c = Q8_BLOCK·ceil(m/(W·Q8_BLOCK))`` (a whole number of Q8
    blocks), is padded to ``(W, c)``, and the bucket's leaves are
    concatenated ALONG THE CHUNK DIM into one ``(W, ΣC)`` pipeline —
    still one all_to_all + one all_gather per bucket, but no Q8 block
    and no rank chunk ever spans a leaf boundary.  Every leaf's
    quantization, wire bytes, reduction order, and residual are
    therefore computed independently of which OTHER leaves share its
    bucket: results are bitwise-invariant to the bucket partition
    (K ∈ {1..n_leaves} all agree; pinned in tests/test_grad_quant.py).

    Two deltas vs ``ef_grad_sync`` (the sequential/kill-switch path,
    which is kept byte-identical to its pre-bucketing form):

    - layout: ``ef_grad_sync`` packs one flat vector whose chunking
      depends on the TOTAL length, so its bytes differ from this
      recipe's (padding to whole blocks costs ≤ ``W·Q8_BLOCK``
      elements per leaf on the wire; both are ~4x under f32).
    - ``finite`` is computed over THIS bucket's leaves only, and gates
      only this bucket's residual commit.  Callers running K buckets
      AND the per-bucket flags together for the optimizer's skip
      decision; residual poisoning (the reason non-finite steps leave
      the residual untouched) is per-leaf, so bucket-local gating
      protects exactly the leaves that need it.
    """
    if wire not in ("f32", "int8"):
        raise ValueError(f"wire must be f32|int8, got {wire!r}")
    W = axis_size(axis)
    idx = jax.lax.axis_index(axis)

    finite = jnp.stack(
        [jnp.all(jnp.isfinite(g)) for g in jax.tree.leaves(grads)]).all()
    finite = jax.lax.pmin(finite.astype(jnp.int32), axis).astype(jnp.bool_)

    leaves_g, treedef = jax.tree.flatten(grads)
    leaves_r = treedef.flatten_up_to(residual)
    shapes = [g.shape[1:] for g in leaves_g]
    sizes = [int(np.prod(s, dtype=np.int64)) if s else 1 for s in shapes]
    quant_ix = [i for i, n in enumerate(sizes)
                if wire == "int8" and n >= min_quant_elems and W > 1]
    exact_ix = [i for i in range(len(sizes)) if i not in set(quant_ix)]
    out: list = [None] * len(sizes)
    new_r: list = [jnp.zeros_like(r) for r in leaves_r]

    if exact_ix:
        cat = jnp.concatenate(
            [leaves_g[i][0].astype(jnp.float32).reshape(-1)
             for i in exact_ix])
        summed = jax.lax.psum(cat, axis)
        offs = np.cumsum([0] + [sizes[i] for i in exact_ix])
        for j, i in enumerate(exact_ix):
            out[i] = (summed[offs[j]:offs[j + 1]] / W).reshape(shapes[i])
    if quant_ix:
        chunks = [_leaf_rank_chunk(sizes[i], W) for i in quant_ix]
        rows = []
        for i, c in zip(quant_ix, chunks):
            comp = (leaves_g[i][0].astype(jnp.float32)
                    + leaves_r[i][0].astype(jnp.float32)).reshape(-1)
            rows.append(jnp.pad(comp, (0, W * c - sizes[i])).reshape(W, c))
        p = jnp.concatenate(rows, axis=1)                # (W, C)
        C = p.shape[1]
        q, s = jax.vmap(quantize_q8)(p)                  # (W,C) / (W,C/blk)
        send_err = p - jax.vmap(dequantize_q8)(q, s)
        tq = jax.lax.all_to_all(q, axis, split_axis=0, concat_axis=0,
                                tiled=True)
        ts = jax.lax.all_to_all(s, axis, split_axis=0, concat_axis=0,
                                tiled=True)
        red = jax.vmap(dequantize_q8)(tq, ts).sum(axis=0)   # (C,) exact f32
        q2, s2 = quantize_q8(red)
        owner_err = red - dequantize_q8(q2, s2)
        fq = jax.lax.all_gather(q2, axis, axis=0, tiled=True)   # (W*C,)
        fs = jax.lax.all_gather(s2, axis, axis=0, tiled=True)
        summed = jax.vmap(dequantize_q8)(fq.reshape(W, C),
                                         fs.reshape(W, s2.shape[0]))
        err = send_err.at[idx].add(owner_err)            # (W, C)
        off = 0
        for i, c in zip(quant_ix, chunks):
            m = sizes[i]
            cols = slice(off, off + c)
            out[i] = (summed[:, cols].reshape(-1)[:m] / W).reshape(shapes[i])
            piece = err[:, cols].reshape(-1)[:m].reshape(shapes[i])
            new_r[i] = jnp.where(finite, piece[None],
                                 leaves_r[i]).astype(leaves_r[i].dtype)
            off += c

    mean_grads = treedef.unflatten(out)
    new_residual = treedef.unflatten(new_r)
    return mean_grads, new_residual, finite


def bucket_sync_wire_bytes(grads, world: int, wire: str = "int8",
                           min_quant_elems: int = DEFAULT_MIN_QUANT_ELEMS
                           ) -> int:
    """Analytic per-rank wire bytes of one ``ef_bucket_sync`` call.

    Mirrors the leaf-aligned layout: every quantized leaf contributes a
    whole-block per-rank chunk ``c = Q8_BLOCK·ceil(m/(W·Q8_BLOCK))``;
    phase 1 all_to_all sends ``(W-1)`` rows of ``(ΣC int8 + one f32
    scale per Q8 block)`` and phase 2 all_gather moves the same volume.
    Exact-path leaves use the ring convention, as in
    ``grad_sync_wire_bytes``.  Because the accounting is per-leaf, the
    total over any bucket partition equals the single-bucket figure.
    """
    W = max(1, int(world))
    n_exact = 0
    C = 0
    for leaf in jax.tree.leaves(grads):
        n = int(np.prod(np.shape(leaf), dtype=np.int64)) if np.shape(leaf) \
            else 1
        if wire != "int8" or n < min_quant_elems or W <= 1:
            n_exact += n
        else:
            C += _leaf_rank_chunk(n, W)
    total = 2 * (W - 1) / W * 4 * n_exact
    if C:
        total += 2 * (W - 1) * (C + 4 * (C // Q8_BLOCK))
    return int(math.ceil(total))


# --- host-level helpers -----------------------------------------------------


def broadcast_from_coordinator(tree):
    """Replicate a host-local pytree identically on all processes/devices.

    Reference analog: ``HierarchicalTreeBroadcaster`` /
    ``BroadcastGlobalVariablesHook`` (variable sync at init).  In multi-host
    JAX this is ``multihost_utils.broadcast_one_to_all``; in single-process
    mode it is a no-op identity.
    """
    if jax.process_count() == 1:
        return tree
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(tree)


def host_all_reduce_mean(tree, mesh: Mesh):
    """Fetch a metrics pytree, verifying every leaf is globally replicated.

    The analog of ``Strategy.reduce(MEAN, ...)`` (``distribute_lib.py:
    1675``).  Metrics produced under pjit are already global (replicated)
    arrays — the cross-replica mean happened inside the step — so the host
    side is a fetch.  This seam *verifies* that contract rather than
    assuming it: a sharded leaf reaching here means some step skipped its
    in-graph reduction, and silently fetching would hand back per-shard
    garbage as if it were the global value.
    """
    del mesh  # the leaves' shardings carry their own mesh

    def _fetch(path, x):
        if isinstance(x, jax.Array) and not x.sharding.is_fully_replicated:
            raise ValueError(
                f"host_all_reduce_mean got non-replicated metric leaf "
                f"'{jax.tree_util.keystr(path)}' with sharding spec "
                f"{getattr(x.sharding, 'spec', x.sharding)}; reduce metrics "
                "inside the jitted step (mean over the sharded batch / "
                "psum over mesh axes) so every device holds the global "
                "value")
        return np.asarray(jax.device_get(x))

    return jax.tree_util.tree_map_with_path(_fetch, tree)


# --- microbenchmark ---------------------------------------------------------


def allreduce_bus_bandwidth(
    mesh: Mesh,
    axis: str = "data",
    *,
    size_mb: float = 64.0,
    iters: int = 10,
    warmup: int = 3,
    dtype=jnp.float32,
    quant: str = "none",
) -> dict:
    """Measure allreduce algorithmic bus bandwidth over a mesh axis.

    Reports the standard ``2*(k-1)/k * bytes / time`` bus-bandwidth figure
    where ``bytes`` is the per-rank buffer size (``size_mb``) — the NCCL
    benchmark convention, making the number directly comparable to the
    reference's NCCL allreduce measurements (BASELINE.md metric 3).

    ``quant="int8"`` benchmarks the quantized leg instead: the
    ``q8_all_reduce`` int8-wire pipeline (the trainer's grad-quant comm
    program).  Its figure is EFFECTIVE f32 bandwidth — f32 payload
    reduced per second, the same numerator as the exact leg — so the
    wire win shows up as a higher number wherever the fabric (not the
    quantize ALU work) is the bottleneck; ``wire_bytes`` reports the
    actual bytes moved (~4x less).
    """
    if quant not in ("none", "int8"):
        raise ValueError(f"quant must be none|int8, got {quant!r}")
    k = mesh.shape[axis]
    per_shard = max(1, int(size_mb * 1e6 / np.dtype(dtype).itemsize))
    spec = P(axis)

    @compile_site(site="collectives.allreduce_bench_step",
                  buckets="exact (microbenchmark: one shape per run)",
                  donates=(), statics=(), max_compiles=None)
    @jax.jit
    def step(x):
        def _inner(s):
            if quant == "int8":
                return q8_all_reduce(s, axis)
            return jax.lax.psum(s, axis)

        return shard_map(
            _inner, mesh=mesh, in_specs=spec, out_specs=P(),
            check_vma=False,
        )(x)

    x = jax.device_put(
        jnp.ones((k * per_shard,), dtype),
        NamedSharding(mesh, spec),
    )
    for _ in range(warmup):
        step(x).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(iters):
        out = step(x)
    out.block_until_ready()
    dt = (time.perf_counter() - t0) / iters
    # Per-rank buffer, NOT the k× global array size (NCCL busBW convention).
    nbytes = per_shard * np.dtype(dtype).itemsize
    bus_bw = 2 * (k - 1) / k * nbytes / dt if k > 1 else nbytes / dt
    out_rec = {
        "axis": axis,
        "devices": k,
        "message_bytes": nbytes,
        "time_s": dt,
        "bus_bandwidth_gbps": bus_bw / 1e9,
        "wire": "f32" if quant == "none" else "int8",
    }
    if quant == "int8":
        out_rec["wire_bytes"] = q8_wire_bytes(per_shard, k)
    return out_rec
