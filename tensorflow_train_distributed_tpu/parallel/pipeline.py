"""Pipeline parallelism: GPipe-style microbatched stages over a mesh axis.

NEW capability relative to the reference — it ships no pipeline layer
(SURVEY.md §2.4: PP absent; nothing GPipe-like is reachable from the
harness).  Included because a complete TPU framework needs all of
dp/fsdp/tp/sp/pp to cover the model scales the flagship configs target.

TPU-native design: the pipeline is ONE SPMD program under ``shard_map``.
Every device holds one stage's parameters (stacked pytree sharded over the
``pipeline`` mesh axis) and runs the same ``lax.scan`` over
``M + S - 1`` ticks (M microbatches, S stages).  Per tick each device

1. selects its input — microbatch ``t`` for stage 0, the activation
   received from its predecessor otherwise;
2. applies the stage function;
3. passes its output to the successor with ``lax.ppermute`` (one ICI hop —
   stages are laid out innermost on the torus by ``runtime.mesh``).

The backward pass needs no scheduler: differentiating the scan replays the
schedule in reverse, with ``ppermute``'s transpose carrying activation
cotangents stage-to-stage — the 1F1B-style interleaving the reference
would have had to hand-build in C++ falls out of autodiff.

Constraint: every stage maps activations of one shape/dtype to the same
shape/dtype (the standard homogeneous-transformer-block contract).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from tensorflow_train_distributed_tpu.runtime.compat import axis_size, shard_map
from jax.sharding import Mesh, PartitionSpec as P


def num_pipeline_ticks(num_microbatches: int, num_stages: int) -> int:
    """Schedule length: M microbatches + (S-1) bubble ticks."""
    return num_microbatches + num_stages - 1


def pipeline_stages(
    stage_fn: Callable[[Any, Any], Any],
    stage_params: Any,
    microbatches: Any,
    *,
    axis: str = "pipeline",
    unstack_params: bool = True,
) -> Any:
    """Run the microbatch pipeline *inside* an enclosing ``shard_map``.

    Args:
      stage_fn: ``(params_one_stage, activation) -> activation`` — one
        stage's compute; activation shape/dtype preserved.
      stage_params: this device's slice of the stacked stage parameters,
        leading dim 1 (sharded over ``axis``).
      microbatches: ``[M, mb, ...]`` pytree of microbatches (replicated or
        data-sharded along ``mb`` — invisible here either way).
      axis: pipeline mesh axis name bound by the enclosing shard_map.
      unstack_params: strip the local leading stage dim (the 1-layer-per-
        stage contract).  ``False`` passes the slice through intact — for
        stages that hold a *group* of layers and scan over them
        (``gpipe_layers``).

    Returns ``[M, mb, ...]`` outputs, valid on every device (the last
    stage's results are broadcast via a masked psum so downstream loss
    code need not care where they landed).
    """
    params = (jax.tree.map(lambda x: x[0], stage_params)
              if unstack_params else stage_params)
    stage = jax.lax.axis_index(axis)
    num_stages = axis_size(axis)
    leaves = jax.tree.leaves(microbatches)
    num_micro = leaves[0].shape[0]
    ticks = num_pipeline_ticks(num_micro, num_stages)

    def tick(act, t):
        # Stage 0 consumes microbatch t (clamped in the bubble tail where
        # its compute is dead anyway); later stages consume what the
        # predecessor sent last tick.
        feed = jax.tree.map(
            lambda m: jax.lax.dynamic_index_in_dim(
                m, jnp.minimum(t, num_micro - 1), 0, keepdims=False),
            microbatches,
        )
        inp = jax.tree.map(
            lambda a, f: jnp.where(stage == 0, f, a), act, feed)
        out = stage_fn(params, inp)
        # Shift every stage's output one hop down the ring; stage 0
        # receives the last stage's (already-harvested) output and
        # overwrites it with the next microbatch.
        perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]
        passed = jax.tree.map(
            lambda o: jax.lax.ppermute(o, axis, perm), out)
        return passed, out

    act0 = jax.tree.map(
        lambda m: jnp.zeros(m.shape[1:], m.dtype), microbatches)
    _, outs = jax.lax.scan(tick, act0, jnp.arange(ticks))
    # Ticks S-1 .. T-1 on the LAST stage are microbatch outputs 0..M-1.
    outs = jax.tree.map(lambda o: o[num_stages - 1:], outs)
    return jax.tree.map(
        lambda o: jax.lax.psum(
            jnp.where(stage == num_stages - 1, o, jnp.zeros_like(o)), axis),
        outs,
    )


def gpipe(
    stage_fn: Callable[[Any, Any], Any],
    stacked_params: Any,
    batch: Any,
    *,
    mesh: Mesh,
    num_microbatches: int,
    axis: str = "pipeline",
    batch_axes: Sequence[str] = (),
    unstack_params: bool = True,
) -> Any:
    """Host-level entry: microbatch ``batch`` and run the full pipeline.

    ``stacked_params`` leaves carry a leading ``num_stages`` dim (build
    with ``init_stage_params``), sharded over ``axis``.  ``batch`` is
    ``[B, ...]``; it is split into ``num_microbatches`` equal microbatches.
    ``batch_axes`` optionally shards the microbatch dim over data-parallel
    mesh axes, composing PP with DP in one program.  Differentiable.
    """
    num_stages = mesh.shape[axis]
    leaves = jax.tree.leaves(batch)
    bsz = leaves[0].shape[0]
    if bsz % num_microbatches:
        raise ValueError(
            f"batch size {bsz} not divisible by "
            f"num_microbatches={num_microbatches}")
    micro = jax.tree.map(
        lambda x: x.reshape(num_microbatches, bsz // num_microbatches,
                            *x.shape[1:]),
        batch,
    )
    mb_spec = P(None, tuple(batch_axes) or None)

    def per_shard(params_local, micro_local):
        return pipeline_stages(stage_fn, params_local, micro_local,
                               axis=axis, unstack_params=unstack_params)

    out = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(axis), mb_spec),
        out_specs=mb_spec,
        check_vma=False,
    )(stacked_params, micro)
    return jax.tree.map(
        lambda o: o.reshape(bsz, *o.shape[2:]), out)


def gpipe_layers(
    layer_fn: Callable[[Any, Any], Any],
    stacked_params: Any,
    batch: Any,
    *,
    mesh: Mesh,
    num_microbatches: int,
    axis: str = "pipeline",
    batch_axes: Sequence[str] = (),
) -> Any:
    """GPipe where each stage holds a contiguous *group* of layers.

    ``stacked_params`` leaves carry a leading ``num_layers`` dim (the
    nn.scan layout — logical axis ``stage``, sharded over ``axis``);
    ``num_layers`` must divide evenly into the axis size, giving each
    stage ``num_layers / num_stages`` layers which it scans sequentially
    per tick.  ``layer_fn(params_one_layer, act) -> act``.  This is the
    entry the scanned-block model families (llama) use: the same stacked
    parameter tree serves the plain depth-scan under dp and the pipeline
    schedule under dp_pp, unchanged.
    """
    num_stages = mesh.shape[axis]
    num_layers = jax.tree.leaves(stacked_params)[0].shape[0]
    if num_layers % num_stages:
        raise ValueError(
            f"num_layers={num_layers} not divisible by the {axis} axis "
            f"size {num_stages}")

    def stage_fn(local_params, h):
        # local_params: this stage's [L/S, ...] slice; apply in depth order.
        def body(carry, one_layer):
            return layer_fn(one_layer, carry), None

        h, _ = jax.lax.scan(body, h, local_params)
        return h

    return gpipe(stage_fn, stacked_params, batch, mesh=mesh,
                 num_microbatches=num_microbatches, axis=axis,
                 batch_axes=batch_axes, unstack_params=False)


def init_stage_params(
    init_fn: Callable[[jax.Array], Any],
    rng: jax.Array,
    num_stages: int,
) -> Any:
    """Stack per-stage params: ``init_fn(rng) -> params`` vmapped over S rngs.

    The result's leading dim is the stage axis; place it on the mesh with
    ``NamedSharding(mesh, P("pipeline"))`` (``sharding.shard_batch``-style
    placement is up to the caller/trainer).
    """
    rngs = jax.random.split(rng, num_stages)
    return jax.vmap(init_fn)(rngs)
