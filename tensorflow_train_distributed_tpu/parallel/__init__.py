"""Parallelism: collectives, sharding rules, sequence/context parallelism.

TPU-native replacement for the reference's cross-device-ops layer
(``tensorflow/python/distribute/cross_device_ops.py``,
``cross_device_utils.py``, ``ops/collective_ops.py``) and the DTensor layout
API (``tensorflow/dtensor/python/layout.py``) — see SURVEY.md §2.2/§5.8.
"""

from tensorflow_train_distributed_tpu.parallel.collectives import (  # noqa: F401
    all_gather,
    all_reduce,
    all_to_all,
    allreduce_bus_bandwidth,
    broadcast_from_coordinator,
    dequantize_q8,
    ef_grad_sync,
    grad_sync_wire_bytes,
    q8_all_reduce,
    quantize_q8,
    reduce_scatter,
    ring_permute,
)
from tensorflow_train_distributed_tpu.parallel.sharding import (  # noqa: F401
    DEFAULT_RULES,
    cross_replica_update_shardings,
    logical_sharding,
    make_state_shardings,
    zero1_opt_shardings,
    shard_batch,
    shard_batch_spec,
    with_logical_rules,
)
