"""Logical-axis sharding rules: the DTensor ``Layout`` replacement.

The reference's model-parallel story is DTensor: every tensor carries a
``Layout`` mapping its dims onto mesh axes (``tensorflow/dtensor/python/
layout.py:54,352``), with ``pack``/``relayout`` (``api.py:192,412``) to move
data between layouts, plus ``ShardedVariable`` for PS-style weight sharding
(``sharded_variable.py:843``).  The TPU-native equivalent is GSPMD: models
annotate parameters/activations with *logical* axis names
(``nn.with_logical_partitioning`` / ``nn.with_logical_constraint``), and one
rules table maps logical names onto mesh axes per strategy.  Change the
rules, not the model — that is how one model definition runs under dp, fsdp,
dp×tp, and dp×tp×sp unchanged.

Logical vocabulary used across our model zoo (models may add their own):

- ``batch``   — the global batch dim; sharded over (data, fsdp).
- ``length``  — sequence/position dim; sharded over seq when SP is on.
- ``embed``   — model/residual dim; fsdp shards params along it.
- ``heads``   — attention heads; tensor-parallel.
- ``kv``      — per-head dim; replicated.
- ``mlp``     — MLP hidden dim; tensor-parallel.
- ``vocab``   — embedding/logits vocab dim; tensor-parallel.
- ``expert``  — MoE expert dim; expert-parallel.
- ``conv_kernel``/``conv_in``/``conv_out`` — conv filters (ResNet family).
- ``stage``   — pipeline stage dim.
"""

from __future__ import annotations

import logging
import math
from typing import Any, Optional, Sequence

import flax.linen as nn
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger(__name__)

# rule table: logical axis → mesh axis (or tuple of mesh axes, or None)
LogicalRules = Sequence[tuple[str, Any]]

DEFAULT_RULES: LogicalRules = (
    ("batch", ("data", "fsdp")),
    ("length", "seq"),
    ("embed", "fsdp"),
    ("heads", "tensor"),
    ("kv", None),
    ("mlp", "tensor"),
    ("vocab", "tensor"),
    ("expert", "expert"),
    ("conv_kernel", None),
    ("conv_in", None),
    ("conv_out", "tensor"),
    ("stage", "pipeline"),
    ("norm", None),
)


def _rules_for_mesh(mesh: Mesh, rules: LogicalRules) -> LogicalRules:
    """Drop mesh axes of size 1 from the rules — sharding over them is a
    no-op and keeping specs minimal gives XLA cleaner HLO shardings."""
    out = []
    for logical, target in rules:
        if target is None:
            out.append((logical, None))
            continue
        axes = (target,) if isinstance(target, str) else tuple(target)
        axes = tuple(a for a in axes if mesh.shape.get(a, 1) > 1)
        if not axes:
            out.append((logical, None))
        elif len(axes) == 1:
            out.append((logical, axes[0]))
        else:
            out.append((logical, axes))
    return tuple(out)


def logical_sharding(
    mesh: Mesh,
    logical_axes: Sequence[Optional[str]],
    rules: LogicalRules = DEFAULT_RULES,
) -> NamedSharding:
    """NamedSharding for one array given its logical axis names.

    The per-tensor analog of DTensor ``Layout(spec, mesh)``.
    """
    table = dict(_rules_for_mesh(mesh, rules))
    used: set[str] = set()
    dims = []
    for a in logical_axes:
        target = table.get(a) if a is not None else None
        if target is None:
            dims.append(None)
            continue
        axes = (target,) if isinstance(target, str) else tuple(target)
        # A mesh axis may appear only once per array; first dim wins (e.g. an
        # array annotated (batch, embed) under fsdp rules keeps batch on
        # (data, fsdp) and leaves embed replicated).
        axes = tuple(x for x in axes if x not in used)
        used.update(axes)
        dims.append(axes[0] if len(axes) == 1 else (axes or None))
    return NamedSharding(mesh, P(*dims))


def with_logical_rules(mesh: Mesh, rules: LogicalRules = DEFAULT_RULES):
    """Context manager binding flax's logical-axis rules for this mesh.

    Inside it, ``nn.with_logical_constraint`` annotations in model code
    resolve against ``rules`` — the mechanism by which one model definition
    serves every strategy preset.
    """
    return nn.logical_axis_rules(_rules_for_mesh(mesh, rules))


def make_state_shardings(
    mesh: Mesh,
    abstract_state: Any,
    rules: LogicalRules = DEFAULT_RULES,
):
    """Sharding pytree for a train state built from flax partition metadata.

    ``abstract_state`` is the ``jax.eval_shape`` of state creation with
    ``nn.Partitioned`` boxes still attached (``nn.get_partition_spec``
    extracts the logical PartitionSpecs).  Leaves without metadata are
    replicated — matching the reference's MirroredVariable default.

    Dims whose size doesn't divide the assigned mesh axes fall back to
    replicated for that dim (e.g. 2 GQA KV heads on a tensor=4 mesh): the
    preset stays usable on any device count, trading sharding for
    replication instead of erroring.
    """
    logical_specs = nn.get_partition_spec(abstract_state)
    shardings = nn.logical_to_mesh_sharding(
        logical_specs, mesh, _rules_for_mesh(mesh, rules)
    )

    def _fit(leaf, sh):
        # Read the boxed value directly: .unbox() on LogicallyPartitioned
        # applies a sharding constraint (a trace-time op, wrong on abstract
        # leaves under an active mesh); we only need the shape.
        val = leaf.value if isinstance(leaf, nn.meta.AxisMetadata) else leaf
        shape = getattr(val, "shape", None)
        if shape is None or not isinstance(sh, NamedSharding):
            return sh
        dims = []
        changed = False
        spec = list(sh.spec) + [None] * (len(shape) - len(sh.spec))
        if len(spec) > len(shape):
            # Optimizer states can carry LOWER-rank leaves than the param
            # whose metadata they inherit (adafactor's factored row/col
            # stats).  Which param dim a reduced leaf corresponds to is
            # not recoverable from shapes (v_row drops the last dim,
            # v_col the second-to-last), so guessing inherits the WRONG
            # dim's mesh axes and forces a reshard every optimizer step.
            # These leaves are O(m+n) vs the param's O(m·n): replicate.
            return NamedSharding(mesh, P())
        for size, assigned in zip(shape, spec):
            if assigned is None:
                dims.append(None)
                continue
            axes = (assigned,) if isinstance(assigned, str) else tuple(assigned)
            # Keep the longest prefix of mesh axes that still divides the
            # dim (partial sharding beats full replication for memory).
            kept: list[str] = []
            prod = 1
            for a in axes:
                if size % (prod * mesh.shape[a]) != 0:
                    break  # prefix semantics: stop at first non-divider
                kept.append(a)
                prod *= mesh.shape[a]
            if len(kept) != len(axes):
                changed = True
                logger.warning(
                    "sharding downgrade: dim of size %d cannot shard over "
                    "mesh axes %s (sizes %s); keeping %s",
                    size, axes, [mesh.shape[a] for a in axes], kept or "none",
                )
            dims.append(kept[0] if len(kept) == 1 else (tuple(kept) or None))
        return NamedSharding(mesh, P(*dims)) if changed else sh

    # Walk per-leaf: shardings tree leaves are NamedShardings positioned at
    # (possibly boxed) state leaves.
    return jax.tree.map(
        _fit, abstract_state, shardings,
        is_leaf=lambda x: isinstance(x, nn.meta.AxisMetadata),
    )


def fold_leading_replicas(arr, w_new: int):
    """Refold a per-replica leading dim from ``W_old`` rows to
    ``w_new``, preserving the row-sum.

    The elastic-restore transform for mesh-size-dependent state (the
    quantized-collectives ``grad_residual``: one row per data replica,
    each row the replica's unsent quantization error).  Error feedback
    only ever consumes the rows by adding them into the pre-sync local
    gradients, whose cross-replica SUM is what reaches the weights —
    so any refold that preserves the total is semantically exact:

    - ``W_old == k·w_new`` — sum groups of k adjacent rows (shrink);
    - ``w_new == k·W_old`` — old rows keep their error, new rows start
      at zero (grow);
    - otherwise (the divisibility degrade) — the whole total lands on
      row 0 and the rest start at zero, instead of raising: restore
      onto ANY surviving mesh beats losing the residual.
    """
    import numpy as np

    arr = np.asarray(arr)
    w_old = arr.shape[0]
    if w_old == w_new:
        return arr
    if w_new < 1:
        raise ValueError(f"w_new must be >= 1, got {w_new}")
    tail = arr.shape[1:]
    if w_old % w_new == 0:
        return arr.reshape((w_new, w_old // w_new) + tail).sum(axis=1)
    out = np.zeros((w_new,) + tail, arr.dtype)
    if w_new % w_old == 0:
        out[:w_old] = arr
    else:
        logger.warning(
            "fold_leading_replicas: %d -> %d rows do not divide; "
            "folding the whole residual into row 0 (sum-preserving "
            "degrade)", w_old, w_new)
        out[0] = arr.sum(axis=0)
    return out


def shard_batch_spec(mesh: Mesh) -> P:
    """PartitionSpec for host batches: leading dim over every DP-like axis."""
    from tensorflow_train_distributed_tpu.runtime.mesh import batch_axes

    return P(batch_axes(mesh))


def shard_batch(mesh: Mesh, batch, *, spec: Optional[P] = None):
    """Place a host-local batch pytree as a globally-sharded array.

    Single-process: a ``device_put`` with the batch spec.  Multi-host: each
    process contributes its local shard of the global batch
    (``jax.make_array_from_process_local_data``) — the TPU-native analog of
    the reference's per-worker dataset sharding (``input_lib.py:729``).
    ``spec`` overrides the default leading-dim placement (e.g.
    ``P(None, ("data",))`` for steps_per_execution super-batches whose dim 0
    is the scan axis).
    """
    sharding = NamedSharding(mesh, shard_batch_spec(mesh) if spec is None
                             else spec)

    def _put(x):
        if jax.process_count() == 1:
            return jax.device_put(x, sharding)
        return jax.make_array_from_process_local_data(sharding, x)

    return jax.tree.map(_put, batch)


def _data_shard_leaf(mesh: Mesh, leaf, sh):
    """Shard one leaf's largest data-divisible unsharded dim over
    ``data`` (the ZeRO family's mechanics, shared by the moment
    shardings and the cross-replica update shardings below).  Rank<2
    leaves and leaves already touching ``data`` come back unchanged."""
    n = mesh.shape["data"]
    val = leaf.value if isinstance(leaf, nn.meta.AxisMetadata) else leaf
    shape = getattr(val, "shape", None)
    if (shape is None or len(shape) < 2
            or not isinstance(sh, NamedSharding)):
        return sh
    # Inputs come from make_state_shardings, which already normalized
    # rank-mismatched leaves to P(); pad the spec to the leaf's rank.
    spec = list(sh.spec) + [None] * (len(shape) - len(sh.spec))
    used = {a for entry in spec if entry is not None
            for a in ((entry,) if isinstance(entry, str) else entry)}
    if "data" in used:
        return sh
    best = None
    for i, (size, assigned) in enumerate(zip(shape, spec)):
        if assigned is None and size % n == 0 and size >= n:
            if best is None or size > shape[best]:
                best = i
    if best is None:
        return sh
    spec[best] = "data"
    return NamedSharding(mesh, P(*spec))


def zero1_opt_shardings(mesh: Mesh, abstract_opt: Any, opt_shardings: Any):
    """ZeRO-1: shard optimizer moments over the ``data`` axis.

    Plain dp replicates params AND optimizer state on every chip — for
    adamw that is 2× params of f32 doing nothing dp-redundant.  The ZeRO-1
    observation (Rajbhandari et al.; the reference has no equivalent —
    this is a TPU-native extra) is that moments are only read/written by
    the elementwise optimizer update, so each data shard can own a slice:
    GSPMD then computes the update sharded and all-gathers the param
    delta, trading one extra all-gather per step for an N×
    moment-memory reduction.

    Mechanics: for every rank≥2 optimizer-state leaf whose sharding
    leaves the ``data`` axis unused, shard its largest data-divisible
    unsharded dim over ``data``.  Rank<2 leaves stay as they are: scalars
    and step counters have nothing to shard, and rank-1 leaves are either
    bias moments (KBs) or adafactor's reduced row/col stats — O(m+n)
    memory where a per-step reshard would cost more than the bytes saved
    (``make_state_shardings`` deliberately replicates those).  fsdp
    meshes are untouched — fsdp already shards state along its own axis.
    """
    if mesh.shape.get("data", 1) <= 1:
        return opt_shardings

    return jax.tree.map(
        lambda leaf, sh: _data_shard_leaf(mesh, leaf, sh),
        abstract_opt, opt_shardings,
        is_leaf=lambda x: isinstance(x, nn.meta.AxisMetadata),
    )


def cross_replica_update_shardings(mesh: Mesh, abstract_params: Any,
                                   param_shardings: Any):
    """The full cross-replica sharded weight update (arxiv 2004.13336),
    ZeRO-1 extended from the moments to the UPDATE COMPUTATION itself.

    ``zero1_opt_shardings`` shards what the optimizer *stores*; this
    shards what it *computes*: per param leaf, the sharding the gradient
    and the new-param value should carry DURING ``tx.update`` /
    ``apply_updates``, so each data replica runs the optimizer math on
    only its 1/N gradient shard (the redundant N-way elementwise apply
    the paper removes) and the trainer all-gathers the updated params
    back to their resting shardings afterwards.  Same leaf mechanics as
    ZeRO-1 — largest data-divisible unsharded dim over ``data``; rank<2
    leaves (biases) update replicated, their math is noise.  Returns
    ``param_shardings`` unchanged on a data<=1 mesh (documented no-op,
    matching ``zero1_opt_shardings``).
    """
    if mesh.shape.get("data", 1) <= 1:
        return param_shardings

    return jax.tree.map(
        lambda leaf, sh: _data_shard_leaf(mesh, leaf, sh),
        abstract_params, param_shardings,
        is_leaf=lambda x: isinstance(x, nn.meta.AxisMetadata),
    )
