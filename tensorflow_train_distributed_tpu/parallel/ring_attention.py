"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

Absent from the reference (SURVEY.md §5.7: sequence length bounded by
per-replica memory; no ring/Ulysses anywhere in the TF tree) — here it is a
first-class capability: shard the sequence axis over the ``seq`` mesh axis
and attend across the full context without any device materializing the
whole KV (ring) or the whole sequence of scores (both).

Both functions are *per-shard* bodies (run inside ``shard_map`` over the
``seq`` axis); ``shard_mapped_attention`` wraps them for global arrays with
batch sharded over (data, fsdp) and heads over tensor — SP composes with DP
and TP.

- **Ring attention**: each device keeps its local Q block; KV blocks make
  n-1 hops around the ICI ring (``collectives.ring_permute``) while a
  flash-style online softmax (m, l, o) accumulates in f32.  GQA rotates the
  *unrepeated* KV (traffic ∝ kv_heads, not heads).  One KV block resident
  per device → O(S/n) memory.
- **Ulysses**: all-to-all (``collectives.all_to_all``) reshards seq↔heads
  so each device runs full-sequence attention for H/n heads locally (the
  pallas flash kernel applies on TPU), then reshards back.  Requires
  heads % n == 0; KV is resharded unrepeated when kv_heads % n == 0.

Expressed with ``lax.scan`` (reverse-differentiable, so the same code path
trains) and bottom-right causal alignment matching ``ops.attention``.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from tensorflow_train_distributed_tpu.runtime.compat import axis_size, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from tensorflow_train_distributed_tpu.parallel.collectives import (
    all_to_all,
    ring_permute,
)

_NEG = float(jnp.finfo(jnp.float32).min) / 2


def _repeat_kv(x: jax.Array, heads: int) -> jax.Array:
    """Broadcast GQA KV heads up to ``heads`` full heads ([B, Hkv, S, D])."""
    if x.shape[1] == heads:
        return x
    return jnp.repeat(x, heads // x.shape[1], axis=1)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis: str = "seq",
    causal: bool = False,
    softmax_scale: Optional[float] = None,
    segment_ids: Optional[jax.Array] = None,
    window: Optional[int] = None,
    sinks: int = 0,
) -> jax.Array:
    """Per-shard ring attention.  q: [B, H, Sq, D]; k/v: [B, Hkv, Sk, D]
    (Hkv may divide H — GQA), all sharded on ``axis``.

    ``segment_ids`` [B, Sq] (this shard's slice, same seq sharding as q)
    restricts attention to same-segment pairs — packed long-context rows:
    the KV shard's segment ids rotate around the ring WITH the k/v blocks
    so every hop masks against the correct metadata.

    ``window`` (sliding-window attention, requires ``causal``) both
    masks the band AND SHORTENS THE RING: only ``ceil((window-1)/Sk)``
    previous blocks can hold in-window keys, so the scan runs that many
    hops instead of n-1 — at 32k over 8 shards with a 4k window, 1 hop
    instead of 7 (7× less ICI for attention).
    """
    n = axis_size(axis)
    idx = jax.lax.axis_index(axis)
    b, h, sq, d = q.shape
    sk = k.shape[2]
    if window is not None and not causal:
        raise ValueError("window (sliding-window attention) requires "
                         "causal=True")
    if sinks and (window is None or sinks > sk):
        raise ValueError(
            f"ring attention sinks need a sliding window and must fit "
            f"one shard (sinks={sinks}, shard span={sk})")
    scale = softmax_scale if softmax_scale is not None else d**-0.5
    q32 = q.astype(jnp.float32) * scale

    def fold(carry_olm, k_blk, v_blk, block_mask):
        """Online-softmax accumulation of one masked KV block — the ONE
        numerically sensitive update, shared by ring hops and the sink
        block."""
        o, m, l = carry_olm
        s = jnp.einsum("bhqd,bhkd->bhqk", q32,
                       _repeat_kv(k_blk, h).astype(jnp.float32))
        s = jnp.where(block_mask, s, _NEG)
        m_new = jnp.maximum(m, s.max(-1, keepdims=True))
        # Mask again on p: a fully-masked block must contribute exactly 0
        # (exp(s - m_new) would be 1 on its own masked rows).
        p = jnp.where(block_mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(-1, keepdims=True)
        o_new = o * alpha + jnp.einsum(
            "bhqk,bhkd->bhqd", p,
            _repeat_kv(v_blk, h).astype(jnp.float32))
        return o_new, m_new, l_new

    def attend_block(carry_olm, k_blk, v_blk, kv_idx, kv_seg):
        if causal:
            q_pos = idx * sq + jnp.arange(sq)[:, None]
            k_pos = kv_idx * sk + jnp.arange(sk)[None, :]
            keep = q_pos >= k_pos
            if window is not None:
                keep = keep & (q_pos - k_pos < window)
            block_mask = keep[None, None]
        else:
            block_mask = jnp.ones((1, 1, sq, sk), bool)
        if kv_seg is not None:
            # [B,1,Sq,Sk] segment mask; & broadcasts the positional mask.
            block_mask = block_mask & (
                segment_ids[:, :, None] == kv_seg[:, None, :])[:, None]
        return fold(carry_olm, k_blk, v_blk, block_mask)

    o0 = jnp.zeros((b, h, sq, d), jnp.float32)
    m0 = jnp.full((b, h, sq, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, sq, 1), jnp.float32)
    # Local block first (no rotation), then n-1 rotate-and-attend hops —
    # the discarded n-th rotation would be pure wasted ICI traffic.  The
    # KV shard's segment ids ride the carry ONLY when packing is active:
    # the unpacked path must not pay an extra ppermute per hop.
    olm = attend_block((o0, m0, l0), k, v, idx, segment_ids)

    if sinks:
        # StreamingLLM sinks: the sequence's first `sinks` keys live on
        # shard 0 — one tiny masked-psum broadcast (sinks·Hkv·D per
        # batch row, negligible next to a KV hop) hands every shard the
        # sink block.  The online softmax folds it in like any other
        # block; exclusivity with the window band: queries that can
        # reach a sink key through the band (q_pos - si < window) mask
        # it here, so no key is double-counted across blocks.
        def bcast0(t):
            return jax.lax.psum(
                jnp.where(idx == 0, t[:, :, :sinks], 0), axis)

        sink_k, sink_v = bcast0(k), bcast0(v)
        sink_seg = (None if segment_ids is None else jax.lax.psum(
            jnp.where(idx == 0, segment_ids[:, :sinks], 0), axis))
        q_pos = idx * sq + jnp.arange(sq)[:, None]
        si = jnp.arange(sinks)[None, :]
        keep = (si <= q_pos) & (q_pos - si >= window)
        if sink_seg is not None:
            keep = keep[None, None] & (
                segment_ids[:, :, None] == sink_seg[:, None, :])[:, None]
        else:
            keep = jnp.broadcast_to(keep[None, None],
                                    (1, 1, sq, sinks))
        olm = fold(olm, sink_k, sink_v, keep)

    def body(carry, step):
        olm, k_blk, v_blk, seg_blk = carry
        k_nxt = ring_permute(k_blk, axis, shift=1)
        v_nxt = ring_permute(v_blk, axis, shift=1)
        seg_nxt = (None if seg_blk is None
                   else ring_permute(seg_blk, axis, shift=1))
        kv_idx = (idx - step - 1) % n
        olm = attend_block(olm, k_nxt, v_nxt, kv_idx, seg_nxt)
        return (olm, k_nxt, v_nxt, seg_nxt), None

    # Window shortens the ring: a block j hops back holds keys at least
    # (j-1)·Sk + 1 positions behind every local query, so blocks beyond
    # ceil((window-1)/Sk) are fully out-of-window — don't rotate them in.
    hops = n - 1
    if window is not None:
        hops = min(hops, -(-(window - 1) // sk))
    if hops > 0:
        (olm, _, _, _), _ = jax.lax.scan(
            body, (olm, k, v, segment_ids), jnp.arange(hops))
    o, _, l = olm
    return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis: str = "seq",
    causal: bool = False,
    softmax_scale: Optional[float] = None,
    segment_ids: Optional[jax.Array] = None,
    window: Optional[int] = None,
    sinks: int = 0,
) -> jax.Array:
    """Per-shard Ulysses attention.  q: [B, H, S_local, D]; k/v may carry
    fewer (GQA) heads.  Requires H % axis_size == 0.  Local attention uses
    the shared kernel dispatch, so the pallas flash path applies on TPU.

    ``segment_ids`` [B, S_local]: after the a2a each shard attends over
    the FULL sequence, so the ids are all-gathered along ``axis`` (int
    [B,S] — negligible next to the a2a'd activations) and handed to the
    kernel's native segment masking (pallas ``SegmentIds`` on TPU).
    """
    from tensorflow_train_distributed_tpu.ops.attention import (
        multihead_attention_kernel,
    )

    n = axis_size(axis)
    h = q.shape[1]
    if h % n:
        raise ValueError(
            f"Ulysses needs heads ({h}) divisible by seq-axis size "
            f"({n}); use ring attention instead")

    def seq_to_heads(x):  # [B, H, S/n, D] → [B, H/n, S, D]
        if x.shape[1] % n:
            # GQA heads not divisible by n: repeat up front (costs traffic,
            # but keeps the a2a well-formed).
            x = _repeat_kv(x, h)
        return all_to_all(x, axis, split_dim=1, concat_dim=2)

    def heads_to_seq(x):  # [B, H/n, S, D] → [B, H, S/n, D]
        return all_to_all(x, axis, split_dim=2, concat_dim=1)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    full_seg = (None if segment_ids is None else jax.lax.all_gather(
        segment_ids, axis, axis=1, tiled=True))
    out = multihead_attention_kernel(
        qg, _repeat_kv(kg, qg.shape[1]), _repeat_kv(vg, qg.shape[1]),
        causal=causal, softmax_scale=softmax_scale,
        segment_ids=full_seg, window=window, sinks=sinks,
    )
    return heads_to_seq(out.astype(q.dtype))


def shard_mapped_attention(
    mesh: Mesh,
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    method: str = "ring",
    causal: bool = False,
    softmax_scale: Optional[float] = None,
    axis: str = "seq",
    segment_ids: Optional[jax.Array] = None,
    window: Optional[int] = None,
    sinks: int = 0,
) -> jax.Array:
    """Global-array entry point: q/k/v [B, H, S, D] with S sharded on
    ``axis``, batch on (data, fsdp), heads on tensor — SP × DP × TP.
    ``segment_ids`` [B, S] (packed rows) shards with the sequence;
    ``window`` = sliding-window attention (ring additionally skips
    out-of-window hops)."""
    fn = {"ring": ring_attention, "ulysses": ulysses_attention}[method]
    batch_dims = tuple(a for a in ("data", "fsdp")
                       if mesh.shape.get(a, 1) > 1) or None
    head_dim = "tensor" if mesh.shape.get("tensor", 1) > 1 else None
    spec = P(batch_dims, head_dim, axis, None)
    args = [q, k, v]
    in_specs = [spec, spec, spec]
    if segment_ids is not None:
        args.append(segment_ids)
        in_specs.append(P(batch_dims, axis))

    def per_shard(q_, k_, v_, seg_=None):
        return fn(q_, k_, v_, axis=axis, causal=causal,
                  softmax_scale=softmax_scale, segment_ids=seg_,
                  window=window, sinks=sinks)

    return shard_map(
        per_shard,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=spec,
        check_vma=False,
    )(*args)
