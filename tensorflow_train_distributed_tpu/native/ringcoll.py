"""Python face of the native TCP host collectives.

Host-side analog of the reference's graph-level allreduce builders
(SURVEY.md §2.2/§2.3): ``HostRing`` is the ring algorithm
(``distribute/v1/all_reduce.py`` ``build_ring_all_reduce:250`` /
``RingReducer``), ``HostMesh`` carries the remaining two —
recursive halving-doubling (``build_recursive_hd_all_reduce:422``) and
shuffle (``build_shuffle_all_reduce:554``).  Used for cross-process host
data (metric fan-in, input-pipeline bookkeeping, toolchain tests) where
pulling the device fabric in would be wrong.  The device path never
touches this — XLA collectives over ICI/DCN own it.
"""

from __future__ import annotations

import ctypes
from typing import Sequence

import numpy as np

from tensorflow_train_distributed_tpu import native

#: Quantization block size of the EQuARX-style int8 wire format — ONE
#: recipe shared by every quantized collective in the stack: the native
#: C++ ring (``kQBlock`` in native/src/ringcoll.cpp), this module's
#: numpy reference below, and the device-side gradient pipeline
#: (``parallel.collectives.quantize_q8``).  A drift between them would
#: silently change the error bound of every quantized allreduce, so the
#: three are pinned against each other in tests/test_grad_quant.py.
Q8_BLOCK = 512


def quantize_q8_np(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Numpy reference of the shared int8 quantization recipe.

    Mirrors ``QuantizeBlocks`` in native/src/ringcoll.cpp exactly
    (float32 arithmetic throughout): per ``Q8_BLOCK``-element block,
    scale = amax/127 with a fallback to 1.0 when the derived scale/inv
    are zero or non-finite (all-zero, subnormal, or non-finite blocks),
    values clamped to [-127, 127] with NaN mapping to 0, rounded
    half-to-even (``lrintf`` semantics).  Returns ``(q int8 [n],
    scales f32 [ceil(n/Q8_BLOCK)])`` for a 1-D input.
    """
    x = np.ascontiguousarray(x, np.float32).reshape(-1)
    n = x.size
    nb = -(-n // Q8_BLOCK) if n else 0
    xb = np.zeros((nb, Q8_BLOCK), np.float32)
    xb.reshape(-1)[:n] = x
    a = np.abs(xb)
    # C's running `if (a > amax)` skips NaN (comparisons are false):
    amax = np.where(np.isnan(a), np.float32(0), a).max(axis=1,
                                                       initial=np.float32(0))
    with np.errstate(divide="ignore", invalid="ignore", over="ignore"):
        scale = (amax / np.float32(127.0)).astype(np.float32)
        inv = (np.float32(1.0) / scale).astype(np.float32)
    bad = ~(scale > 0) | ~np.isfinite(inv) | ~np.isfinite(scale)
    scale = np.where(bad, np.float32(1.0), scale)
    inv = np.where(bad, np.float32(1.0), inv)
    v = xb * inv[:, None]
    v = np.where(np.isnan(v), np.float32(0),
                 np.clip(v, np.float32(-127.0), np.float32(127.0)))
    q = np.rint(v).astype(np.int8)
    return q.reshape(-1)[:n], scale


def dequantize_q8_np(q: np.ndarray, scales: np.ndarray) -> np.ndarray:
    """Inverse of ``quantize_q8_np`` (``DequantInto`` in the native
    ring): per-block ``q * scale`` in float32."""
    q = np.ascontiguousarray(q, np.int8).reshape(-1)
    n = q.size
    nb = -(-n // Q8_BLOCK) if n else 0
    qb = np.zeros((nb, Q8_BLOCK), np.int8)
    qb.reshape(-1)[:n] = q
    out = qb.astype(np.float32) * np.asarray(scales,
                                             np.float32)[:, None]
    return out.reshape(-1)[:n]


class _NativeGroup:
    """Shared lifecycle for ctypes-backed process groups.

    Subclasses set ``_PREFIX`` (the C symbol prefix); create/destroy/rank/
    world symbols follow ``<prefix>_create`` etc.
    """

    _PREFIX = ""
    _KIND = "group"

    def __init__(self, rank: int, peers: Sequence[str], *,
                 timeout_ms: int = 10_000):
        """``peers``: rank-ordered ``host:port`` strings, one per process."""
        lib = native.load_library()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._handle = getattr(lib, f"{self._PREFIX}_create")(
            rank, len(peers), ",".join(peers).encode(), timeout_ms)
        if not self._handle:
            raise RuntimeError(
                f"{self._KIND} setup failed (rank={rank}, "
                f"peers={list(peers)})")

    def _require_handle(self):
        # ctypes would pass NULL straight into native code → segfault.
        if not self._handle:
            raise RuntimeError(f"{type(self).__name__} is closed")
        return self._handle

    @property
    def rank(self) -> int:
        return getattr(self._lib, f"{self._PREFIX}_rank")(
            self._require_handle())

    @property
    def world(self) -> int:
        return getattr(self._lib, f"{self._PREFIX}_world")(
            self._require_handle())

    def _reduce_f32(self, fn, x: np.ndarray) -> np.ndarray:
        """Marshal ``x`` to an owned contiguous f32 buffer, reduce in
        place, reshape back."""
        self._require_handle()
        out = np.array(x, dtype=np.float32, order="C")  # always a copy
        rc = fn(self._handle,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                out.size)
        if rc == -2:
            raise ValueError(
                f"this algorithm requires a power-of-2 world, got "
                f"{self.world}; use HostRing")
        if rc != 0:
            raise RuntimeError(f"{self._KIND} allreduce failed "
                               "(peer died?)")
        return out.reshape(np.shape(x))

    def close(self) -> None:
        if self._handle:
            getattr(self._lib, f"{self._PREFIX}_destroy")(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class HostRing(_NativeGroup):
    """Blocking ring collectives among ``world`` processes over TCP
    (bandwidth-optimal: 2·(W-1)/W · N bytes on the wire per rank)."""

    _PREFIX = "ttd_ring"
    _KIND = "ring"

    def allreduce(self, x: np.ndarray) -> np.ndarray:
        """Sum-allreduce; returns a new float32 array of ``x``'s shape."""
        return self._reduce_f32(self._lib.ttd_ring_allreduce_f32, x)

    def allreduce_q8(self, x: np.ndarray) -> np.ndarray:
        """Quantized sum-allreduce (EQuARX-style): int8 blocks + f32
        scales on the wire — ~4x less traffic than f32, for the
        bandwidth-scarce host/DCN path.  Approximate (per-hop
        requantization in the reduce-scatter phase; error ~(W-1)·
        max|partial|/254 per element) but BIT-CONSISTENT across ranks
        (the all-gather forwards each owner's bytes verbatim).  The
        quantization recipe is the module-level shared one
        (``Q8_BLOCK``/``quantize_q8_np`` above == the device-side
        ``parallel.collectives.quantize_q8``), cross-checked in
        tests/test_grad_quant.py."""
        return self._reduce_f32(self._lib.ttd_ring_allreduce_q8_f32, x)

    def broadcast(self, x: np.ndarray, root: int = 0) -> np.ndarray:
        """Broadcast ``x`` (same shape/dtype everywhere) from ``root``."""
        self._require_handle()
        out = np.ascontiguousarray(x).copy()
        rc = self._lib.ttd_ring_broadcast(
            self._handle,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            out.nbytes, root)
        if rc != 0:
            raise RuntimeError("ring broadcast failed (peer died?)")
        return out


class HostMesh(_NativeGroup):
    """Fully-connected host group: butterfly (recursive halving-doubling)
    and shuffle allreduce.  HD is latency-optimal (2·log2 W exchanges) for
    small messages; the ring stays bandwidth-optimal for large ones.
    Power-of-2 world sizes only — callers fall back to ``HostRing``
    otherwise.
    """

    _PREFIX = "ttd_mesh"
    _KIND = "mesh"

    def allreduce(self, x: np.ndarray, *,
                  algorithm: str = "hd") -> np.ndarray:
        """Sum-allreduce; ``algorithm`` is ``"hd"`` or ``"shuffle"``."""
        fns = {"hd": self._lib.ttd_mesh_allreduce_hd_f32,
               "shuffle": self._lib.ttd_mesh_allreduce_shuffle_f32}
        if algorithm not in fns:
            raise ValueError(f"algorithm must be hd|shuffle, "
                             f"got {algorithm!r}")
        return self._reduce_f32(fns[algorithm], x)
