"""Python face of the native TCP ring collectives.

Host-side analog of the reference's `RingReducer` (SURVEY.md §2.3): used
for cross-process host data (metric fan-in, input-pipeline bookkeeping,
toolchain tests) where pulling the device fabric in would be wrong.  The
device path never touches this — XLA collectives over ICI/DCN own it.
"""

from __future__ import annotations

import ctypes
from typing import Sequence

import numpy as np

from tensorflow_train_distributed_tpu import native


class HostRing:
    """Blocking ring collectives among ``world`` processes over TCP."""

    def __init__(self, rank: int, peers: Sequence[str], *,
                 timeout_ms: int = 10_000):
        """``peers``: rank-ordered ``host:port`` strings, one per process."""
        lib = native.load_library()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._handle = lib.ttd_ring_create(
            rank, len(peers), ",".join(peers).encode(), timeout_ms)
        if not self._handle:
            raise RuntimeError(
                f"ring setup failed (rank={rank}, peers={list(peers)})")

    def _require_handle(self):
        # ctypes would pass NULL straight into native code → segfault.
        if not self._handle:
            raise RuntimeError("HostRing is closed")
        return self._handle

    @property
    def rank(self) -> int:
        return self._lib.ttd_ring_rank(self._require_handle())

    @property
    def world(self) -> int:
        return self._lib.ttd_ring_world(self._require_handle())

    def allreduce(self, x: np.ndarray) -> np.ndarray:
        """Sum-allreduce; returns a new float32 array of ``x``'s shape."""
        self._require_handle()
        out = np.ascontiguousarray(x, dtype=np.float32).copy()
        rc = self._lib.ttd_ring_allreduce_f32(
            self._handle,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            out.size)
        if rc != 0:
            raise RuntimeError("ring allreduce failed (peer died?)")
        return out.reshape(np.shape(x))

    def broadcast(self, x: np.ndarray, root: int = 0) -> np.ndarray:
        """Broadcast ``x`` (same shape/dtype everywhere) from ``root``."""
        self._require_handle()
        out = np.ascontiguousarray(x).copy()
        rc = self._lib.ttd_ring_broadcast(
            self._handle,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            out.nbytes, root)
        if rc != 0:
            raise RuntimeError("ring broadcast failed (peer died?)")
        return out

    def close(self) -> None:
        if self._handle:
            self._lib.ttd_ring_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
