"""Native (C++) runtime components, bound via ctypes.

The reference's runtime layer is C++ (SURVEY.md §2.3): tf.data dataset
kernels, ring collectives, collective executor.  The TPU compute path
needs none of that (XLA owns device collectives and scheduling), but the
host-side runtime around it keeps two native components:

- ``staging``   — threaded, GIL-free batch assembly with a buffer arena
                  (the tf.data-kernel analog), `src/staging.cpp`.
- ``ringcoll``  — TCP ring allreduce/broadcast for host/DCN-side data
                  (the `RingAlg`/`RingReducer` analog), `src/ringcoll.cpp`.
- ``jpegdec``   — libjpeg decode with a GIL-free thread pool + DCT-domain
                  downscaling (the tf.image JPEG-kernel analog),
                  `src/jpegdec.cpp` — built as a SEPARATE library
                  (links -ljpeg) so this one keeps zero external deps.

The shared library builds on demand with g++ (no pybind11 in this
environment — plain C ABI + ctypes).  Environments without a toolchain
get ``None`` from ``load_library`` and pure-Python fallbacks upstream.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

logger = logging.getLogger(__name__)

_SRC_DIR = os.path.join(os.path.dirname(__file__), "src")
_BUILD_DIR = os.path.join(os.path.dirname(__file__), "_build")
_LIB_PATH = os.path.join(_BUILD_DIR, "libttd_native.so")
_SOURCES = ("staging.cpp", "ringcoll.cpp")

_JPEG_LIB_PATH = os.path.join(_BUILD_DIR, "libttd_jpeg.so")
_JPEG_SOURCE = "jpegdec.cpp"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False
_jpeg_lib: Optional[ctypes.CDLL] = None
_jpeg_load_failed = False


def _needs_build() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    return any(
        os.path.getmtime(os.path.join(_SRC_DIR, s)) > lib_mtime
        for s in _SOURCES
    )


def _compile_shared(sources, out_path, extra_flags=()) -> None:
    """g++ → temp file → atomic rename: concurrent processes (e.g. a
    --data-workers fleet all lazily building on first decode) never
    dlopen a half-written .so."""
    os.makedirs(_BUILD_DIR, exist_ok=True)
    tmp = f"{out_path}.tmp.{os.getpid()}"
    cmd = ["g++", "-std=c++17", "-O3", "-fPIC", "-shared", "-pthread",
           *sources, "-o", tmp, *extra_flags]
    logger.info("building native library: %s", " ".join(cmd))
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, out_path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def build(force: bool = False) -> str:
    """Compile the native library (idempotent; mtime-cached)."""
    with _lock:
        if not force and not _needs_build():
            return _LIB_PATH
        _compile_shared(
            [os.path.join(_SRC_DIR, s) for s in _SOURCES], _LIB_PATH)
        return _LIB_PATH


def load_library() -> Optional[ctypes.CDLL]:
    """Build if needed and dlopen; returns None when unavailable."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    try:
        path = build()
        lib = ctypes.CDLL(path)
        _bind_signatures(lib)
        _lib = lib
    except (OSError, subprocess.CalledProcessError) as e:
        detail = getattr(e, "stderr", "") or str(e)
        logger.warning("native library unavailable (%s); using Python "
                       "fallbacks", detail.strip()[:500])
        _load_failed = True
    return _lib


def load_jpeg_library() -> Optional[ctypes.CDLL]:
    """Build (g++ -ljpeg) and dlopen the JPEG decoder; None when the
    toolchain or libjpeg is missing — callers keep the PIL path."""
    global _jpeg_lib, _jpeg_load_failed
    if _jpeg_lib is not None or _jpeg_load_failed:
        return _jpeg_lib
    with _lock:
        if _jpeg_lib is not None or _jpeg_load_failed:
            return _jpeg_lib
        try:
            src = os.path.join(_SRC_DIR, _JPEG_SOURCE)
            if (not os.path.exists(_JPEG_LIB_PATH)
                    or os.path.getmtime(src)
                    > os.path.getmtime(_JPEG_LIB_PATH)):
                _compile_shared([src], _JPEG_LIB_PATH,
                                extra_flags=("-ljpeg",))
            lib = ctypes.CDLL(_JPEG_LIB_PATH)
            _bind_jpeg_signatures(lib)
            _jpeg_lib = lib
        except (OSError, subprocess.CalledProcessError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            logger.warning("jpeg library unavailable (%s); using PIL",
                           detail.strip()[:500])
            _jpeg_load_failed = True
    return _jpeg_lib


def _bind_jpeg_signatures(lib: ctypes.CDLL) -> None:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u8pp = ctypes.POINTER(u8p)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    i32p = ctypes.POINTER(ctypes.c_int)

    lib.ttd_jpeg_dims.argtypes = [
        u8p, ctypes.c_uint64, ctypes.c_int, i32p, i32p]
    lib.ttd_jpeg_dims.restype = ctypes.c_int
    lib.ttd_jpeg_decode_rgb.argtypes = [
        u8p, ctypes.c_uint64, ctypes.c_int, u8p, ctypes.c_uint64,
        i32p, i32p]
    lib.ttd_jpeg_decode_rgb.restype = ctypes.c_int
    lib.ttd_jpeg_decode_batch.argtypes = [
        ctypes.c_int, u8pp, u64p, ctypes.c_int, u8pp, u64p,
        i32p, i32p, i32p, ctypes.c_int]
    lib.ttd_jpeg_decode_batch.restype = ctypes.c_int


def _bind_signatures(lib: ctypes.CDLL) -> None:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    f32p = ctypes.POINTER(ctypes.c_float)

    lib.ttd_stager_create.argtypes = [
        u8p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
        ctypes.c_int, ctypes.c_int]
    lib.ttd_stager_create.restype = ctypes.c_void_p
    lib.ttd_stager_submit.argtypes = [ctypes.c_void_p, u64p]
    lib.ttd_stager_submit.restype = ctypes.c_int
    lib.ttd_stager_acquire.argtypes = [ctypes.c_void_p]
    lib.ttd_stager_acquire.restype = u8p
    lib.ttd_stager_release.argtypes = [ctypes.c_void_p, u8p]
    lib.ttd_stager_release.restype = None
    lib.ttd_stager_batch_bytes.argtypes = [ctypes.c_void_p]
    lib.ttd_stager_batch_bytes.restype = ctypes.c_uint64
    lib.ttd_stager_destroy.argtypes = [ctypes.c_void_p]
    lib.ttd_stager_destroy.restype = None

    lib.ttd_ring_create.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
    lib.ttd_ring_create.restype = ctypes.c_void_p
    lib.ttd_ring_allreduce_f32.argtypes = [
        ctypes.c_void_p, f32p, ctypes.c_uint64]
    lib.ttd_ring_allreduce_f32.restype = ctypes.c_int
    lib.ttd_ring_allreduce_q8_f32.argtypes = \
        lib.ttd_ring_allreduce_f32.argtypes
    lib.ttd_ring_allreduce_q8_f32.restype = ctypes.c_int
    lib.ttd_ring_broadcast.argtypes = [
        ctypes.c_void_p, u8p, ctypes.c_uint64, ctypes.c_int]
    lib.ttd_ring_broadcast.restype = ctypes.c_int
    lib.ttd_ring_rank.argtypes = [ctypes.c_void_p]
    lib.ttd_ring_rank.restype = ctypes.c_int
    lib.ttd_ring_world.argtypes = [ctypes.c_void_p]
    lib.ttd_ring_world.restype = ctypes.c_int
    lib.ttd_ring_destroy.argtypes = [ctypes.c_void_p]
    lib.ttd_ring_destroy.restype = None

    lib.ttd_mesh_create.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
    lib.ttd_mesh_create.restype = ctypes.c_void_p
    lib.ttd_mesh_allreduce_hd_f32.argtypes = [
        ctypes.c_void_p, f32p, ctypes.c_uint64]
    lib.ttd_mesh_allreduce_hd_f32.restype = ctypes.c_int
    lib.ttd_mesh_allreduce_shuffle_f32.argtypes = [
        ctypes.c_void_p, f32p, ctypes.c_uint64]
    lib.ttd_mesh_allreduce_shuffle_f32.restype = ctypes.c_int
    lib.ttd_mesh_rank.argtypes = [ctypes.c_void_p]
    lib.ttd_mesh_rank.restype = ctypes.c_int
    lib.ttd_mesh_world.argtypes = [ctypes.c_void_p]
    lib.ttd_mesh_world.restype = ctypes.c_int
    lib.ttd_mesh_destroy.argtypes = [ctypes.c_void_p]
    lib.ttd_mesh_destroy.restype = None
