"""Python face of the native batch stager: structured batches in, out.

``NativeBatchStager`` serves the hot path of ``data.pipeline``: given a
random-access source flattened to one contiguous ``[N, record_bytes]``
byte matrix, worker threads gather shuffled index lists into pooled
batch buffers off the GIL and deliver them in submission order (the
determinism multi-host SPMD requires).  Field structure (names/dtypes/
shapes) is packed/unpacked at the edges, so consumers still see
``{"image": ..., "label": ...}`` dict batches.

Falls back transparently: ``NativeBatchStager.available()`` is False when
the toolchain/library is missing and callers keep the Python path.
"""

from __future__ import annotations

import ctypes
from typing import Iterator, Optional, Sequence

import numpy as np

from tensorflow_train_distributed_tpu import native


class RecordLayout:
    """Field names/dtypes/shapes ↔ one packed record row."""

    def __init__(self, sample: dict[str, np.ndarray]):
        self.fields = []
        offset = 0
        for name in sorted(sample):
            arr = np.asarray(sample[name])
            nbytes = arr.dtype.itemsize * int(np.prod(arr.shape, dtype=int))
            self.fields.append((name, arr.dtype, tuple(arr.shape),
                                offset, nbytes))
            offset += nbytes
        self.record_bytes = offset

    def pack_source(self, source) -> np.ndarray:
        """Flatten a random-access source into a [N, record_bytes] matrix."""
        n = len(source)
        out = np.empty((n, self.record_bytes), np.uint8)
        for i in range(n):
            rec = source[i]
            for name, dtype, shape, offset, nbytes in self.fields:
                out[i, offset:offset + nbytes] = np.ascontiguousarray(
                    rec[name], dtype=dtype).view(np.uint8).reshape(-1)
        return out

    def unpack_batch(self, flat: np.ndarray) -> dict[str, np.ndarray]:
        """[B, record_bytes] bytes → field dict with leading batch dim."""
        batch = {}
        for name, dtype, shape, offset, nbytes in self.fields:
            field = flat[:, offset:offset + nbytes]
            batch[name] = np.ascontiguousarray(field).view(dtype).reshape(
                (flat.shape[0],) + shape)
        return batch


class NativeBatchStager:
    """Deterministic-order threaded batch assembly over a packed source."""

    def __init__(self, packed: np.ndarray, batch_size: int, *,
                 num_threads: int = 2, pool_size: int = 4):
        lib = native.load_library()
        if lib is None:
            raise RuntimeError("native library unavailable")
        if packed.dtype != np.uint8 or packed.ndim != 2:
            raise ValueError("packed source must be [N, record_bytes] uint8")
        self._lib = lib
        self._packed = np.ascontiguousarray(packed)  # keep alive: borrowed
        self.num_records, self.record_bytes = self._packed.shape
        self.batch_size = batch_size
        self._handle = lib.ttd_stager_create(
            self._packed.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            self.num_records, self.record_bytes, batch_size,
            num_threads, pool_size)
        if not self._handle:
            raise RuntimeError("ttd_stager_create failed")

    @staticmethod
    def available() -> bool:
        return native.load_library() is not None

    def _require_handle(self):
        # ctypes would pass NULL straight into native code → segfault.
        if not self._handle:
            raise RuntimeError("stager is closed")
        return self._handle

    def submit(self, indices: Sequence[int]) -> None:
        self._require_handle()
        idx = np.ascontiguousarray(indices, dtype=np.uint64)
        if idx.shape != (self.batch_size,):
            raise ValueError(
                f"need exactly {self.batch_size} indices, got {idx.shape}")
        rc = self._lib.ttd_stager_submit(
            self._handle, idx.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
        if rc != 0:
            raise ValueError("submit rejected (index out of range or closed)")

    def next_batch(self) -> np.ndarray:
        """Blocking; returns an owned [B, record_bytes] uint8 copy."""
        buf = self._lib.ttd_stager_acquire(self._require_handle())
        if not buf:
            raise StopIteration
        try:
            flat = np.ctypeslib.as_array(
                buf, shape=(self.batch_size, self.record_bytes))
            return flat.copy()
        finally:
            self._lib.ttd_stager_release(self._handle, buf)

    def close(self) -> None:
        if self._handle:
            self._lib.ttd_stager_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def pack_for_staging(source) -> tuple[RecordLayout, np.ndarray]:
    """One-time O(N) flatten of a source for the stager.

    Callers that re-create iterators (periodic eval, preemption restart)
    should pack once and pass the result to ``native_batch_iterator`` —
    packing copies the whole dataset.
    """
    layout = RecordLayout(source[0])
    return layout, layout.pack_source(source)


def native_batch_iterator(
    source,
    order_epochs: Iterator[np.ndarray],
    batch_size: int,
    *,
    num_threads: int = 2,
    lookahead: int = 2,
    packed: Optional[tuple[RecordLayout, np.ndarray]] = None,
) -> Iterator[dict[str, np.ndarray]]:
    """Iterate structured batches drawn via the native stager.

    ``order_epochs`` yields per-epoch index arrays (already sharded/
    shuffled by the caller — ``HostDataLoader`` semantics).  Keeps
    ``lookahead`` submissions in flight so worker threads stay busy one
    batch ahead of the consumer.  ``packed`` is a cached
    ``pack_for_staging`` result; omitted, the source is packed here.
    """
    layout, packed = packed if packed is not None else pack_for_staging(source)
    stager = NativeBatchStager(packed, batch_size,
                               num_threads=num_threads,
                               pool_size=lookahead + 2)
    try:
        pending = 0

        def _batches():
            for order in order_epochs:
                for b in range(len(order) // batch_size):
                    yield order[b * batch_size:(b + 1) * batch_size]

        it = _batches()
        done = False
        while True:
            while pending < 1 + lookahead and not done:
                try:
                    stager.submit(next(it))
                    pending += 1
                except StopIteration:
                    done = True
            if pending == 0:
                return
            flat = stager.next_batch()
            pending -= 1
            yield layout.unpack_batch(flat)
    finally:
        stager.close()
