"""Python face of the native JPEG decoder (``src/jpegdec.cpp``).

The reference decodes JPEG inside tf.data's C++ kernels (SURVEY §2.1);
the rebuild's default is PIL, which holds the GIL for part of each
decode.  This module exposes the libjpeg-backed native path:

- ``decode_rgb``   — one image → uint8 [H, W, 3]; bit-identical to PIL
                     for baseline JPEGs (both are libjpeg underneath).
- ``decode_batch`` — N images decoded by a C++ thread pool while Python
                     holds NO GIL (ctypes releases it for the call): host
                     decode throughput scales with cores in ONE process,
                     where the PIL path needs a process per core.
- ``scale_denom``  — 1/2/4/8 DCT-domain downscale: libjpeg reconstructs
                     at reduced resolution for a fraction of the IDCT
                     work.  Opt-in (changes pixels vs full-size decode).

Falls back transparently: ``available()`` is False when the toolchain or
libjpeg is missing, and callers keep PIL.  Exotic color spaces
(CMYK/YCCK) fail per-image with rc=-1 — use ``decode_image`` (PIL) for
those records.
"""

from __future__ import annotations

import ctypes
from typing import Optional, Sequence

import numpy as np

from tensorflow_train_distributed_tpu import native


def available() -> bool:
    return native.load_jpeg_library() is not None


def output_dims(data: bytes, scale_denom: int = 1) -> tuple[int, int]:
    """(height, width) of the decode at ``scale_denom`` — header-only."""
    lib = native.load_jpeg_library()
    if lib is None:
        raise RuntimeError("native jpeg library unavailable")
    buf = np.frombuffer(data, np.uint8)
    w, h = ctypes.c_int(), ctypes.c_int()
    rc = lib.ttd_jpeg_dims(
        buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(data),
        scale_denom, ctypes.byref(w), ctypes.byref(h))
    if rc != 0:
        raise ValueError(f"not a decodable JPEG (rc={rc})")
    return h.value, w.value


def decode_rgb(data: bytes, scale_denom: int = 1) -> np.ndarray:
    """JPEG bytes → uint8 [H, W, 3] RGB via libjpeg."""
    lib = native.load_jpeg_library()
    if lib is None:
        raise RuntimeError("native jpeg library unavailable")
    hh, ww = output_dims(data, scale_denom)
    out = np.empty((hh, ww, 3), np.uint8)
    buf = np.frombuffer(data, np.uint8)
    w, h = ctypes.c_int(), ctypes.c_int()
    rc = lib.ttd_jpeg_decode_rgb(
        buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(data),
        scale_denom, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out.nbytes, ctypes.byref(w), ctypes.byref(h))
    if rc != 0:
        raise ValueError(f"JPEG decode failed (rc={rc})")
    return out


def decode_batch(datas: Sequence[bytes], scale_denom: int = 1,
                 num_threads: int = 4,
                 ) -> list[Optional[np.ndarray]]:
    """Decode N JPEGs on a C++ thread pool (GIL released for the call).

    Returns one uint8 [H, W, 3] array per input, ``None`` where a record
    failed to decode (corrupt bytes, CMYK, ...) — the caller decides
    whether to PIL-fallback or drop.
    """
    lib = native.load_jpeg_library()
    if lib is None:
        raise RuntimeError("native jpeg library unavailable")
    n = len(datas)
    if n == 0:
        return []
    u8p = ctypes.POINTER(ctypes.c_uint8)
    bufs, outs = [], []
    ptrs, lens, optrs, caps = ((u8p * n)(), (ctypes.c_uint64 * n)(),
                               (u8p * n)(), (ctypes.c_uint64 * n)())
    for i, data in enumerate(datas):
        buf = np.frombuffer(data, np.uint8)
        bufs.append(buf)  # keep alive
        ptrs[i] = buf.ctypes.data_as(u8p)
        lens[i] = len(data)
        try:
            hh, ww = output_dims(data, scale_denom)
            out = np.empty((hh, ww, 3), np.uint8)
        except ValueError:
            out = np.empty((1, 1, 3), np.uint8)  # rc will mark failure
        outs.append(out)
        optrs[i] = out.ctypes.data_as(u8p)
        caps[i] = out.nbytes
    ws = (ctypes.c_int * n)()
    hs = (ctypes.c_int * n)()
    rcs = (ctypes.c_int * n)()
    lib.ttd_jpeg_decode_batch(n, ptrs, lens, scale_denom, optrs, caps,
                              ws, hs, rcs, num_threads)
    return [outs[i] if rcs[i] == 0 else None for i in range(n)]
