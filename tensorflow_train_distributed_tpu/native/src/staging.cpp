// Native batch-staging core: threaded gather/assemble with a reusable
// buffer arena, delivered in deterministic submission order.
//
// TPU-native equivalent of the reference's tf.data C++ runtime hot path
// (dataset kernels behind `tensorflow/python/data`, SURVEY.md §2.3 "tf.data
// runtime" row): the per-step work of turning a shuffled index list into a
// contiguous batch buffer is parallel memcpy that must not hold the Python
// GIL. Python submits index arrays; worker threads gather records from an
// in-memory source into pooled buffers; the consumer blocks on the next
// batch *in submission order* (determinism contract — multi-host SPMD
// requires every process to see identical batch streams).
//
// Exposed as a C ABI for ctypes (no pybind11 in this environment).

#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <thread>
#include <vector>

namespace {

struct Job {
  uint64_t seq;
  std::vector<uint64_t> indices;
};

class Stager {
 public:
  Stager(const uint8_t* source, uint64_t num_records, uint64_t record_bytes,
         uint64_t batch_size, int num_threads, int pool_size)
      : source_(source),
        num_records_(num_records),
        record_bytes_(record_bytes),
        batch_size_(batch_size),
        batch_bytes_(record_bytes * batch_size) {
    if (pool_size < 2) pool_size = 2;
    arena_.resize(static_cast<size_t>(pool_size) * batch_bytes_);
    for (int i = 0; i < pool_size; ++i)
      free_bufs_.push_back(arena_.data() + static_cast<size_t>(i) * batch_bytes_);
    if (num_threads < 1) num_threads = 1;
    for (int i = 0; i < num_threads; ++i)
      workers_.emplace_back([this] { WorkerLoop(); });
  }

  ~Stager() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      closed_ = true;
    }
    cv_work_.notify_all();
    cv_done_.notify_all();
    for (auto& t : workers_) t.join();
  }

  // Returns 0 on success, -1 if out-of-range index or closed.
  int Submit(const uint64_t* indices) {
    std::lock_guard<std::mutex> lk(mu_);
    if (closed_) return -1;
    // Validate before claiming a sequence number: a rejected submit must
    // not leave a gap Acquire would wait on forever.
    for (uint64_t r = 0; r < batch_size_; ++r)
      if (indices[r] >= num_records_) return -1;
    Job j;
    j.seq = next_seq_++;
    j.indices.assign(indices, indices + batch_size_);
    jobs_.push_back(std::move(j));
    cv_work_.notify_one();
    return 0;
  }

  // Blocks until the next batch (submission order) is assembled; returns
  // the buffer pointer, or nullptr if closed with no pending work.
  uint8_t* Acquire() {
    std::unique_lock<std::mutex> lk(mu_);
    uint64_t want = next_deliver_;
    cv_done_.wait(lk, [&] {
      return done_.count(want) > 0 || (closed_ && done_.count(want) == 0 &&
                                       jobs_.empty() && in_flight_ == 0);
    });
    auto it = done_.find(want);
    if (it == done_.end()) return nullptr;
    uint8_t* buf = it->second;
    done_.erase(it);
    ++next_deliver_;
    return buf;
  }

  // Returns a buffer to the pool once the consumer is finished with it.
  void Release(uint8_t* buf) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      free_bufs_.push_back(buf);
    }
    cv_work_.notify_one();
  }

  uint64_t batch_bytes() const { return batch_bytes_; }

 private:
  void WorkerLoop() {
    for (;;) {
      Job job;
      uint8_t* buf = nullptr;
      {
        std::unique_lock<std::mutex> lk(mu_);
        // A job is claimed only together with a buffer, so jobs acquire
        // buffers in seq order — otherwise later-seq jobs could absorb
        // the whole pool while the next-to-deliver job starves and the
        // consumer (who would Release) blocks in Acquire: deadlock.
        cv_work_.wait(lk, [&] {
          return closed_ || (!jobs_.empty() && !free_bufs_.empty());
        });
        if (jobs_.empty() || free_bufs_.empty()) return;  // closing
        job = std::move(jobs_.front());
        jobs_.pop_front();
        buf = free_bufs_.back();
        free_bufs_.pop_back();
        ++in_flight_;
      }
      // The gather itself: GIL-free parallel memcpy.
      for (uint64_t r = 0; r < batch_size_; ++r) {
        std::memcpy(buf + r * record_bytes_,
                    source_ + job.indices[r] * record_bytes_, record_bytes_);
      }
      {
        std::lock_guard<std::mutex> lk(mu_);
        done_[job.seq] = buf;
        --in_flight_;
      }
      cv_done_.notify_all();
    }
  }

  const uint8_t* source_;
  const uint64_t num_records_, record_bytes_, batch_size_, batch_bytes_;
  std::vector<uint8_t> arena_;
  std::vector<uint8_t*> free_bufs_;
  std::deque<Job> jobs_;
  std::map<uint64_t, uint8_t*> done_;
  uint64_t next_seq_ = 0, next_deliver_ = 0;
  int in_flight_ = 0;
  bool closed_ = false;
  std::mutex mu_;
  std::condition_variable cv_work_, cv_done_;
  std::vector<std::thread> workers_;
};

}  // namespace

extern "C" {

void* ttd_stager_create(const uint8_t* source, uint64_t num_records,
                        uint64_t record_bytes, uint64_t batch_size,
                        int num_threads, int pool_size) {
  return new Stager(source, num_records, record_bytes, batch_size,
                    num_threads, pool_size);
}

int ttd_stager_submit(void* s, const uint64_t* indices) {
  return static_cast<Stager*>(s)->Submit(indices);
}

uint8_t* ttd_stager_acquire(void* s) {
  return static_cast<Stager*>(s)->Acquire();
}

void ttd_stager_release(void* s, uint8_t* buf) {
  static_cast<Stager*>(s)->Release(buf);
}

uint64_t ttd_stager_batch_bytes(void* s) {
  return static_cast<Stager*>(s)->batch_bytes();
}

void ttd_stager_destroy(void* s) { delete static_cast<Stager*>(s); }

}  // extern "C"
