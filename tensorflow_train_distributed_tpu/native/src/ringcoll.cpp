// Host-side TCP ring collectives: chunked ring allreduce + ring broadcast.
//
// TPU-native answer to the reference's CPU/DCN collective path (SURVEY.md
// §2.3: `RingAlg`/`RingReducer`, `core/common_runtime/ring_alg.h:32`,
// `ring_reducer.h:32`): device-side collectives are XLA instructions over
// ICI, but host-side coordination data (metrics fan-in, data-pipeline
// bookkeeping, test backends without a device fabric) still wants a ring
// over plain sockets. Classic two-phase algorithm: reduce-scatter then
// all-gather, W-1 steps each, with send-to-next/recv-from-prev overlapped
// via a sender thread per step. Bandwidth-optimal 2·(W-1)/W · N bytes on
// the wire per rank.
//
// C ABI for ctypes. Blocking, single in-flight collective per ring — the
// caller provides ordering (matches how the framework serializes host
// collectives; XLA owns device-side ordering).

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace {

// Sockets are nonblocking (full-duplex Exchange needs it); the *All
// helpers poll on EAGAIN so they present a blocking interface.
bool SendAll(int fd, const void* data, size_t n) {
  const char* p = static_cast<const char*>(data);
  while (n > 0) {
    ssize_t k = ::send(fd, p, n, MSG_NOSIGNAL);
    if (k < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        pollfd pf{fd, POLLOUT, 0};
        ::poll(&pf, 1, -1);
        continue;
      }
      return false;
    }
    if (k == 0) return false;
    p += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

bool RecvAll(int fd, void* data, size_t n) {
  char* p = static_cast<char*>(data);
  while (n > 0) {
    ssize_t k = ::recv(fd, p, n, 0);
    if (k < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        pollfd pf{fd, POLLIN, 0};
        ::poll(&pf, 1, -1);
        continue;
      }
      return false;
    }
    if (k == 0) return false;  // peer closed
    p += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

class Ring {
 public:
  // peers: "host:port" per rank, comma-separated, rank-ordered.
  // Topology: rank r accepts a connection from r-1 and connects to r+1.
  static Ring* Create(int rank, int world, const std::string& peers,
                      int timeout_ms);

  ~Ring() {
    if (send_fd_ >= 0) ::close(send_fd_);
    if (recv_fd_ >= 0) ::close(recv_fd_);
    if (listen_fd_ >= 0) ::close(listen_fd_);
  }

  // In-place sum-allreduce of n floats. Returns 0 on success.
  int AllreduceF32(float* data, uint64_t n) {
    if (world_ == 1) return 0;
    const uint64_t chunks = static_cast<uint64_t>(world_);
    std::vector<uint64_t> ofs(chunks + 1);
    for (uint64_t c = 0; c <= chunks; ++c) ofs[c] = n * c / chunks;
    std::vector<float> inbox(ofs[1] - ofs[0] + n / chunks + 2);

    // Phase 1 — reduce-scatter: after W-1 steps, chunk (r+1)%W on rank r
    // holds the full sum.
    for (int step = 0; step < world_ - 1; ++step) {
      uint64_t sc = (rank_ - step + 2 * world_) % world_;        // send
      uint64_t rc = (rank_ - step - 1 + 2 * world_) % world_;    // recv
      if (!Exchange(data + ofs[sc], (ofs[sc + 1] - ofs[sc]) * 4,
                    inbox.data(), (ofs[rc + 1] - ofs[rc]) * 4))
        return -1;
      float* dst = data + ofs[rc];
      const uint64_t m = ofs[rc + 1] - ofs[rc];
      for (uint64_t i = 0; i < m; ++i) dst[i] += inbox[i];
    }
    // Phase 2 — all-gather the reduced chunks around the ring.
    for (int step = 0; step < world_ - 1; ++step) {
      uint64_t sc = (rank_ + 1 - step + 2 * world_) % world_;
      uint64_t rc = (rank_ - step + 2 * world_) % world_;
      if (!Exchange(data + ofs[sc], (ofs[sc + 1] - ofs[sc]) * 4,
                    data + ofs[rc], (ofs[rc + 1] - ofs[rc]) * 4))
        return -1;
    }
    return 0;
  }

  // Ring broadcast from root: each non-root receives then forwards.
  int Broadcast(uint8_t* data, uint64_t nbytes, int root) {
    if (world_ == 1) return 0;
    if (rank_ == root) {
      return SendAll(send_fd_, data, nbytes) ? 0 : -1;
    }
    if (!RecvAll(recv_fd_, data, nbytes)) return -1;
    // Forward unless the next rank is the root (ring complete).
    if ((rank_ + 1) % world_ != root)
      return SendAll(send_fd_, data, nbytes) ? 0 : -1;
    return 0;
  }

  int rank() const { return rank_; }
  int world() const { return world_; }

 private:
  Ring(int rank, int world) : rank_(rank), world_(world) {}

  // Overlap send-to-next with recv-from-prev: one poll loop over both
  // nonblocking sockets (no per-step thread churn — this runs 2(W-1)
  // times per allreduce on per-step metric paths).
  bool Exchange(const void* sbuf, size_t sn, void* rbuf, size_t rn) {
    const char* sp = static_cast<const char*>(sbuf);
    char* rp = static_cast<char*>(rbuf);
    while (sn > 0 || rn > 0) {
      pollfd fds[2];
      int nf = 0, si = -1, ri = -1;
      if (sn > 0) { fds[nf] = {send_fd_, POLLOUT, 0}; si = nf++; }
      if (rn > 0) { fds[nf] = {recv_fd_, POLLIN, 0}; ri = nf++; }
      if (::poll(fds, nf, -1) < 0) {
        if (errno == EINTR) continue;
        return false;
      }
      if (si >= 0 && (fds[si].revents & (POLLOUT | POLLERR | POLLHUP))) {
        ssize_t k = ::send(send_fd_, sp, sn, MSG_NOSIGNAL);
        if (k < 0 && errno != EINTR && errno != EAGAIN &&
            errno != EWOULDBLOCK)
          return false;
        if (k > 0) { sp += k; sn -= static_cast<size_t>(k); }
      }
      if (ri >= 0 && (fds[ri].revents & (POLLIN | POLLERR | POLLHUP))) {
        ssize_t k = ::recv(recv_fd_, rp, rn, 0);
        if (k == 0) return false;  // peer closed
        if (k < 0 && errno != EINTR && errno != EAGAIN &&
            errno != EWOULDBLOCK)
          return false;
        if (k > 0) { rp += k; rn -= static_cast<size_t>(k); }
      }
    }
    return true;
  }

  int rank_, world_;
  int listen_fd_ = -1, send_fd_ = -1, recv_fd_ = -1;

  friend Ring* MakeRing(int, int, const std::string&, int);
};

std::vector<std::pair<std::string, int>> ParsePeers(const std::string& s) {
  std::vector<std::pair<std::string, int>> out;
  size_t pos = 0;
  while (pos < s.size()) {
    size_t comma = s.find(',', pos);
    if (comma == std::string::npos) comma = s.size();
    std::string item = s.substr(pos, comma - pos);
    size_t colon = item.rfind(':');
    if (colon == std::string::npos) return {};
    out.emplace_back(item.substr(0, colon),
                     std::atoi(item.c_str() + colon + 1));
    pos = comma + 1;
  }
  return out;
}

Ring* MakeRing(int rank, int world, const std::string& peers,
               int timeout_ms) {
  auto addrs = ParsePeers(peers);
  if (static_cast<int>(addrs.size()) != world || rank < 0 || rank >= world)
    return nullptr;
  if (world == 1) {
    Ring* r = new Ring(rank, world);
    return r;
  }

  // Listen on our advertised port for the predecessor.
  int lfd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (lfd < 0) return nullptr;
  int one = 1;
  ::setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = INADDR_ANY;
  addr.sin_port = htons(static_cast<uint16_t>(addrs[rank].second));
  if (::bind(lfd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(lfd, 4) < 0) {
    ::close(lfd);
    return nullptr;
  }

  // Connect to successor (retry until its listener is up or timeout).
  int next = (rank + 1) % world;
  int sfd = -1;
  int waited = 0;
  for (; waited < timeout_ms; waited += 50) {
    sfd = ::socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in peer{};
    peer.sin_family = AF_INET;
    peer.sin_port = htons(static_cast<uint16_t>(addrs[next].second));
    if (::inet_pton(AF_INET, addrs[next].first.c_str(), &peer.sin_addr) != 1) {
      // Resolve "localhost" only; full DNS is the Python layer's job.
      if (addrs[next].first == "localhost")
        ::inet_pton(AF_INET, "127.0.0.1", &peer.sin_addr);
      else {
        ::close(sfd);
        ::close(lfd);
        return nullptr;
      }
    }
    if (::connect(sfd, reinterpret_cast<sockaddr*>(&peer), sizeof(peer)) == 0)
      break;
    ::close(sfd);
    sfd = -1;
    ::usleep(50 * 1000);
  }
  if (sfd < 0) {
    ::close(lfd);
    return nullptr;
  }
  ::setsockopt(sfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));

  // Accept the predecessor, spending whatever remains of the timeout
  // budget — a predecessor that dies after our connect succeeded must
  // surface as setup failure, not an indefinite accept() hang.
  pollfd lpf{lfd, POLLIN, 0};
  int remaining = timeout_ms - waited;
  if (::poll(&lpf, 1, remaining > 0 ? remaining : 1) <= 0) {
    ::close(sfd);
    ::close(lfd);
    return nullptr;
  }
  int rfd = ::accept(lfd, nullptr, nullptr);
  if (rfd < 0) {
    ::close(sfd);
    ::close(lfd);
    return nullptr;
  }
  ::setsockopt(rfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  ::fcntl(sfd, F_SETFL, ::fcntl(sfd, F_GETFL) | O_NONBLOCK);
  ::fcntl(rfd, F_SETFL, ::fcntl(rfd, F_GETFL) | O_NONBLOCK);

  Ring* r = new Ring(rank, world);
  r->listen_fd_ = lfd;
  r->send_fd_ = sfd;
  r->recv_fd_ = rfd;
  return r;
}

}  // namespace

extern "C" {

void* ttd_ring_create(int rank, int world, const char* peers,
                      int timeout_ms) {
  return MakeRing(rank, world, peers ? peers : "", timeout_ms);
}

int ttd_ring_allreduce_f32(void* r, float* data, uint64_t n) {
  return static_cast<Ring*>(r)->AllreduceF32(data, n);
}

int ttd_ring_broadcast(void* r, uint8_t* data, uint64_t nbytes, int root) {
  return static_cast<Ring*>(r)->Broadcast(data, nbytes, root);
}

int ttd_ring_rank(void* r) { return static_cast<Ring*>(r)->rank(); }
int ttd_ring_world(void* r) { return static_cast<Ring*>(r)->world(); }

void ttd_ring_destroy(void* r) { delete static_cast<Ring*>(r); }

}  // extern "C"
