// Host-side TCP ring collectives: chunked ring allreduce + ring broadcast.
//
// TPU-native answer to the reference's CPU/DCN collective path (SURVEY.md
// §2.3: `RingAlg`/`RingReducer`, `core/common_runtime/ring_alg.h:32`,
// `ring_reducer.h:32`): device-side collectives are XLA instructions over
// ICI, but host-side coordination data (metrics fan-in, data-pipeline
// bookkeeping, test backends without a device fabric) still wants a ring
// over plain sockets. Classic two-phase algorithm: reduce-scatter then
// all-gather, W-1 steps each, with send-to-next/recv-from-prev overlapped
// via a sender thread per step. Bandwidth-optimal 2·(W-1)/W · N bytes on
// the wire per rank.
//
// C ABI for ctypes. Blocking, single in-flight collective per ring — the
// caller provides ordering (matches how the framework serializes host
// collectives; XLA owns device-side ordering).

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <string>
#include <vector>

namespace {

// Sockets are nonblocking (full-duplex Exchange needs it); the *All
// helpers poll on EAGAIN so they present a blocking interface.
bool SendAll(int fd, const void* data, size_t n) {
  const char* p = static_cast<const char*>(data);
  while (n > 0) {
    ssize_t k = ::send(fd, p, n, MSG_NOSIGNAL);
    if (k < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        pollfd pf{fd, POLLOUT, 0};
        ::poll(&pf, 1, -1);
        continue;
      }
      return false;
    }
    if (k == 0) return false;
    p += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

bool RecvAll(int fd, void* data, size_t n) {
  char* p = static_cast<char*>(data);
  while (n > 0) {
    ssize_t k = ::recv(fd, p, n, 0);
    if (k < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        pollfd pf{fd, POLLIN, 0};
        ::poll(&pf, 1, -1);
        continue;
      }
      return false;
    }
    if (k == 0) return false;  // peer closed
    p += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

class Ring {
 public:
  // peers: "host:port" per rank, comma-separated, rank-ordered.
  // Topology: rank r accepts a connection from r-1 and connects to r+1.
  static Ring* Create(int rank, int world, const std::string& peers,
                      int timeout_ms);

  ~Ring() {
    if (send_fd_ >= 0) ::close(send_fd_);
    if (recv_fd_ >= 0) ::close(recv_fd_);
    if (listen_fd_ >= 0) ::close(listen_fd_);
  }

  // In-place sum-allreduce of n floats. Returns 0 on success.
  int AllreduceF32(float* data, uint64_t n) {
    if (world_ == 1) return 0;
    const uint64_t chunks = static_cast<uint64_t>(world_);
    std::vector<uint64_t> ofs(chunks + 1);
    for (uint64_t c = 0; c <= chunks; ++c) ofs[c] = n * c / chunks;
    std::vector<float> inbox(ofs[1] - ofs[0] + n / chunks + 2);

    // Phase 1 — reduce-scatter: after W-1 steps, chunk (r+1)%W on rank r
    // holds the full sum.
    for (int step = 0; step < world_ - 1; ++step) {
      uint64_t sc = (rank_ - step + 2 * world_) % world_;        // send
      uint64_t rc = (rank_ - step - 1 + 2 * world_) % world_;    // recv
      if (!Exchange(data + ofs[sc], (ofs[sc + 1] - ofs[sc]) * 4,
                    inbox.data(), (ofs[rc + 1] - ofs[rc]) * 4))
        return -1;
      float* dst = data + ofs[rc];
      const uint64_t m = ofs[rc + 1] - ofs[rc];
      for (uint64_t i = 0; i < m; ++i) dst[i] += inbox[i];
    }
    // Phase 2 — all-gather the reduced chunks around the ring.
    for (int step = 0; step < world_ - 1; ++step) {
      uint64_t sc = (rank_ + 1 - step + 2 * world_) % world_;
      uint64_t rc = (rank_ - step + 2 * world_) % world_;
      if (!Exchange(data + ofs[sc], (ofs[sc + 1] - ofs[sc]) * 4,
                    data + ofs[rc], (ofs[rc + 1] - ofs[rc]) * 4))
        return -1;
    }
    return 0;
  }

  // Quantized ring allreduce (EQuARX-style, PAPERS.md: "Efficient
  // Quantized AllReduce in XLA"): int8 blocks with a shared f32 scale
  // on the wire — ~4x less traffic than f32 at block 512, the lever
  // for the bandwidth-scarce host/DCN path this ring serves.
  //
  // Wire format per block of up to kQBlock floats: [f32 scale][int8 xB].
  // Phase 1 (reduce-scatter) re-quantizes each hop's PARTIAL sums —
  // error grows with hops, bounded by sum of per-hop scale/2 (~(W-1) *
  // max|partial| / 254 per element).  Phase 2 (all-gather) quantizes
  // each reduced chunk ONCE at its owner and forwards the wire bytes
  // verbatim, so every rank dequantizes identical bytes — the
  // allreduce stays BIT-CONSISTENT across ranks (the property XLA
  // collectives guarantee and metric fan-in relies on); the owner also
  // replaces its exact f32 chunk with the dequantized wire values.
  static constexpr uint64_t kQBlock = 512;

  static uint64_t QBytes(uint64_t m) {
    return m + 4 * ((m + kQBlock - 1) / kQBlock);
  }

  static void QuantizeBlocks(const float* src, uint64_t m, uint8_t* wire) {
    for (uint64_t b0 = 0; b0 < m; b0 += kQBlock) {
      const uint64_t bl = (m - b0 < kQBlock) ? (m - b0) : kQBlock;
      float amax = 0.f;
      for (uint64_t i = 0; i < bl; ++i) {
        float a = std::fabs(src[b0 + i]);
        if (a > amax) amax = a;
      }
      // Guard on the DERIVED values, not amax: a subnormal amax gives
      // scale==0 / inv==inf (then 0*inf = NaN and lrintf(NaN) is UB),
      // and a non-finite amax (inf/NaN input) does the same.  Fall back
      // to scale 1 — tiny values quantize to 0 (within their error
      // bound) and non-finite inputs saturate to +/-127 deliberately
      // (an approximate allreduce cannot carry the NaN signal exactly;
      // callers needing NaN propagation use the exact AllreduceF32).
      float scale = amax / 127.f;
      float inv = 1.f / scale;
      if (!(scale > 0.f) || !std::isfinite(inv) || !std::isfinite(scale)) {
        scale = 1.f;
        inv = 1.f;
      }
      std::memcpy(wire, &scale, 4);
      int8_t* q = reinterpret_cast<int8_t*>(wire + 4);
      for (uint64_t i = 0; i < bl; ++i) {
        float v = src[b0 + i] * inv;
        // NaN-safe clamp: comparisons with NaN are false, so order the
        // branches to land on 0 for NaN rather than fall through lrintf.
        if (v > 127.f) v = 127.f;
        else if (v < -127.f) v = -127.f;
        else if (!(v >= -127.f && v <= 127.f)) v = 0.f;  // NaN
        q[i] = static_cast<int8_t>(std::lrintf(v));
      }
      wire += 4 + bl;
    }
  }

  // dst op= dequant(wire): Add accumulates, Copy overwrites.
  template <bool kAdd>
  static void DequantInto(const uint8_t* wire, uint64_t m, float* dst) {
    for (uint64_t b0 = 0; b0 < m; b0 += kQBlock) {
      const uint64_t bl = (m - b0 < kQBlock) ? (m - b0) : kQBlock;
      float scale;
      std::memcpy(&scale, wire, 4);
      const int8_t* q = reinterpret_cast<const int8_t*>(wire + 4);
      for (uint64_t i = 0; i < bl; ++i) {
        const float v = static_cast<float>(q[i]) * scale;
        if (kAdd) dst[b0 + i] += v; else dst[b0 + i] = v;
      }
      wire += 4 + bl;
    }
  }

  int AllreduceQ8F32(float* data, uint64_t n) {
    if (world_ == 1) return 0;
    const uint64_t chunks = static_cast<uint64_t>(world_);
    std::vector<uint64_t> ofs(chunks + 1);
    for (uint64_t c = 0; c <= chunks; ++c) ofs[c] = n * c / chunks;
    // Whole-tensor wire buffer, chunk-addressable (phase 2 forwards
    // received chunks verbatim from it).
    std::vector<uint64_t> wofs(chunks + 1);
    wofs[0] = 0;
    for (uint64_t c = 0; c < chunks; ++c)
      wofs[c + 1] = wofs[c] + QBytes(ofs[c + 1] - ofs[c]);
    std::vector<uint8_t> wire(wofs[chunks]);
    std::vector<uint8_t> sendbuf(QBytes(n / chunks + n % chunks + 1));

    // Phase 1 — reduce-scatter with per-hop requantization.
    for (int step = 0; step < world_ - 1; ++step) {
      uint64_t sc = (rank_ - step + 2 * world_) % world_;
      uint64_t rc = (rank_ - step - 1 + 2 * world_) % world_;
      const uint64_t sm = ofs[sc + 1] - ofs[sc];
      const uint64_t rm = ofs[rc + 1] - ofs[rc];
      QuantizeBlocks(data + ofs[sc], sm, sendbuf.data());
      if (!Exchange(sendbuf.data(), QBytes(sm),
                    wire.data() + wofs[rc], QBytes(rm)))
        return -1;
      DequantInto<true>(wire.data() + wofs[rc], rm, data + ofs[rc]);
    }
    // Phase 2 — all-gather: own reduced chunk quantized ONCE, then
    // wire bytes forwarded verbatim (bit-consistency across ranks).
    {
      const uint64_t oc = (rank_ + 1) % world_;
      const uint64_t om = ofs[oc + 1] - ofs[oc];
      QuantizeBlocks(data + ofs[oc], om, wire.data() + wofs[oc]);
      DequantInto<false>(wire.data() + wofs[oc], om, data + ofs[oc]);
    }
    for (int step = 0; step < world_ - 1; ++step) {
      uint64_t sc = (rank_ + 1 - step + 2 * world_) % world_;
      uint64_t rc = (rank_ - step + 2 * world_) % world_;
      const uint64_t sm = ofs[sc + 1] - ofs[sc];
      const uint64_t rm = ofs[rc + 1] - ofs[rc];
      if (!Exchange(wire.data() + wofs[sc], QBytes(sm),
                    wire.data() + wofs[rc], QBytes(rm)))
        return -1;
      DequantInto<false>(wire.data() + wofs[rc], rm, data + ofs[rc]);
    }
    return 0;
  }

  // Ring broadcast from root: each non-root receives then forwards.
  int Broadcast(uint8_t* data, uint64_t nbytes, int root) {
    if (world_ == 1) return 0;
    if (rank_ == root) {
      return SendAll(send_fd_, data, nbytes) ? 0 : -1;
    }
    if (!RecvAll(recv_fd_, data, nbytes)) return -1;
    // Forward unless the next rank is the root (ring complete).
    if ((rank_ + 1) % world_ != root)
      return SendAll(send_fd_, data, nbytes) ? 0 : -1;
    return 0;
  }

  int rank() const { return rank_; }
  int world() const { return world_; }

 private:
  Ring(int rank, int world) : rank_(rank), world_(world) {}

  // Overlap send-to-next with recv-from-prev: one poll loop over both
  // nonblocking sockets (no per-step thread churn — this runs 2(W-1)
  // times per allreduce on per-step metric paths).
  bool Exchange(const void* sbuf, size_t sn, void* rbuf, size_t rn) {
    const char* sp = static_cast<const char*>(sbuf);
    char* rp = static_cast<char*>(rbuf);
    while (sn > 0 || rn > 0) {
      pollfd fds[2];
      int nf = 0, si = -1, ri = -1;
      if (sn > 0) { fds[nf] = {send_fd_, POLLOUT, 0}; si = nf++; }
      if (rn > 0) { fds[nf] = {recv_fd_, POLLIN, 0}; ri = nf++; }
      if (::poll(fds, nf, -1) < 0) {
        if (errno == EINTR) continue;
        return false;
      }
      if (si >= 0 && (fds[si].revents & (POLLOUT | POLLERR | POLLHUP))) {
        ssize_t k = ::send(send_fd_, sp, sn, MSG_NOSIGNAL);
        if (k < 0 && errno != EINTR && errno != EAGAIN &&
            errno != EWOULDBLOCK)
          return false;
        if (k > 0) { sp += k; sn -= static_cast<size_t>(k); }
      }
      if (ri >= 0 && (fds[ri].revents & (POLLIN | POLLERR | POLLHUP))) {
        ssize_t k = ::recv(recv_fd_, rp, rn, 0);
        if (k == 0) return false;  // peer closed
        if (k < 0 && errno != EINTR && errno != EAGAIN &&
            errno != EWOULDBLOCK)
          return false;
        if (k > 0) { rp += k; rn -= static_cast<size_t>(k); }
      }
    }
    return true;
  }

  int rank_, world_;
  int listen_fd_ = -1, send_fd_ = -1, recv_fd_ = -1;

  friend Ring* MakeRing(int, int, const std::string&, int);
};

// Full-duplex exchange over ONE socket (butterfly/mesh links are a single
// bidirectional connection per partner, unlike the ring's two).
bool ExchangeFd(int fd, const void* sbuf, size_t sn, void* rbuf, size_t rn) {
  const char* sp = static_cast<const char*>(sbuf);
  char* rp = static_cast<char*>(rbuf);
  while (sn > 0 || rn > 0) {
    short ev = 0;
    if (sn > 0) ev |= POLLOUT;
    if (rn > 0) ev |= POLLIN;
    pollfd pf{fd, ev, 0};
    if (::poll(&pf, 1, -1) < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    if (sn > 0 && (pf.revents & (POLLOUT | POLLERR | POLLHUP))) {
      ssize_t k = ::send(fd, sp, sn, MSG_NOSIGNAL);
      if (k < 0 && errno != EINTR && errno != EAGAIN && errno != EWOULDBLOCK)
        return false;
      if (k > 0) { sp += k; sn -= static_cast<size_t>(k); }
    }
    if (rn > 0 && (pf.revents & (POLLIN | POLLERR | POLLHUP))) {
      ssize_t k = ::recv(fd, rp, rn, 0);
      if (k == 0) return false;
      if (k < 0 && errno != EINTR && errno != EAGAIN && errno != EWOULDBLOCK)
        return false;
      if (k > 0) { rp += k; rn -= static_cast<size_t>(k); }
    }
  }
  return true;
}

// Fully-connected host group for the butterfly/shuffle algorithms the
// reference ships as graph builders (`distribute/v1/all_reduce.py`:
// `build_recursive_hd_all_reduce:422`, `build_shuffle_all_reduce:554`).
// One bidirectional TCP connection per peer pair; rank i initiates to all
// j > i (kernel backlog makes connect-before-accept safe), identifying
// itself with a 4-byte rank handshake.
class MeshGroup {
 public:
  static MeshGroup* Create(int rank, int world, const std::string& peers,
                           int timeout_ms);

  ~MeshGroup() {
    for (int fd : fds_)
      if (fd >= 0) ::close(fd);
    if (listen_fd_ >= 0) ::close(listen_fd_);
  }

  // Recursive halving-doubling allreduce (Rabenseifner): latency-optimal
  // 2·log2(W) exchanges. Requires power-of-2 world (callers fall back to
  // the ring otherwise, like the reference's upfront_shuffle pad).
  int AllreduceHdF32(float* data, uint64_t n) {
    if (world_ == 1) return 0;
    if (world_ & (world_ - 1)) return -2;  // not a power of 2
    uint64_t lo = 0, hi = n;
    std::vector<uint64_t> los, his;  // segment stack for the gather phase
    std::vector<float> inbox(n / 2 + 1);
    // Reduce-scatter by recursive halving. rank and rank^mask share the
    // same active segment (it is determined by already-processed bits),
    // so both compute the same midpoint.
    for (int mask = 1; mask < world_; mask <<= 1) {
      los.push_back(lo);
      his.push_back(hi);
      const uint64_t mid = lo + (hi - lo) / 2;
      const int partner_fd = fds_[rank_ ^ mask];
      uint64_t keep_lo, keep_hi, send_lo, send_hi;
      if (rank_ & mask) {  // keep upper half
        keep_lo = mid; keep_hi = hi; send_lo = lo; send_hi = mid;
      } else {             // keep lower half
        keep_lo = lo; keep_hi = mid; send_lo = mid; send_hi = hi;
      }
      if (!ExchangeFd(partner_fd, data + send_lo, (send_hi - send_lo) * 4,
                      inbox.data(), (keep_hi - keep_lo) * 4))
        return -1;
      float* dst = data + keep_lo;
      const uint64_t m = keep_hi - keep_lo;
      for (uint64_t i = 0; i < m; ++i) dst[i] += inbox[i];
      lo = keep_lo;
      hi = keep_hi;
    }
    // All-gather by recursive doubling (reverse the split stack).
    for (int mask = world_ >> 1; mask >= 1; mask >>= 1) {
      const uint64_t plo = los.back(), phi = his.back();
      los.pop_back();
      his.pop_back();
      const uint64_t mid = plo + (phi - plo) / 2;
      const int partner_fd = fds_[rank_ ^ mask];
      // Which half we kept is decided by the rank bit (same rule as the
      // halving phase) — comparing lo against plo is ambiguous when a
      // split produced an empty segment (mid == plo).
      uint64_t other_lo, other_hi;
      if (rank_ & mask) {  // we kept upper; partner holds lower
        other_lo = plo; other_hi = mid;
      } else {
        other_lo = mid; other_hi = phi;
      }
      if (!ExchangeFd(partner_fd, data + lo, (hi - lo) * 4,
                      data + other_lo, (other_hi - other_lo) * 4))
        return -1;
      lo = plo;
      hi = phi;
    }
    return 0;
  }

  // Shuffle allreduce: direct reduce-scatter (every rank sends chunk c to
  // its owner) then direct all-gather — 2(W-1) single-hop messages, the
  // reference's `build_shuffle_all_reduce` with gather shards == ranks.
  // Rounds use XOR perfect matchings (partner = rank ^ s) so both ends of
  // every exchange are in the same round — any other schedule can deadlock
  // once messages exceed kernel socket buffers.  Power-of-2 world only.
  int AllreduceShuffleF32(float* data, uint64_t n) {
    if (world_ == 1) return 0;
    if (world_ & (world_ - 1)) return -2;  // not a power of 2
    const uint64_t W = static_cast<uint64_t>(world_);
    std::vector<uint64_t> ofs(W + 1);
    for (uint64_t c = 0; c <= W; ++c) ofs[c] = n * c / W;
    const uint64_t own_lo = ofs[rank_], own_hi = ofs[rank_ + 1];
    std::vector<float> inbox(own_hi - own_lo);
    // Phase 1: pairwise-exchange chunks toward their owners, accumulate.
    for (int s = 1; s < world_; ++s) {
      const int p = rank_ ^ s;
      if (!ExchangeFd(fds_[p], data + ofs[p], (ofs[p + 1] - ofs[p]) * 4,
                      inbox.data(), (own_hi - own_lo) * 4))
        return -1;
      for (uint64_t i = 0; i < own_hi - own_lo; ++i)
        data[own_lo + i] += inbox[i];
    }
    // Phase 2: exchange reduced chunks until everyone has all of them.
    for (int s = 1; s < world_; ++s) {
      const int p = rank_ ^ s;
      if (!ExchangeFd(fds_[p], data + own_lo, (own_hi - own_lo) * 4,
                      data + ofs[p], (ofs[p + 1] - ofs[p]) * 4))
        return -1;
    }
    return 0;
  }

  int rank() const { return rank_; }
  int world() const { return world_; }

 private:
  MeshGroup(int rank, int world) : rank_(rank), world_(world) {
    fds_.assign(world, -1);
  }

  int rank_, world_;
  int listen_fd_ = -1;
  std::vector<int> fds_;  // per-peer connection; own slot stays -1

  friend MeshGroup* MakeMesh(int, int, const std::string&, int);
};

std::vector<std::pair<std::string, int>> ParsePeers(const std::string& s) {
  std::vector<std::pair<std::string, int>> out;
  size_t pos = 0;
  while (pos < s.size()) {
    size_t comma = s.find(',', pos);
    if (comma == std::string::npos) comma = s.size();
    std::string item = s.substr(pos, comma - pos);
    size_t colon = item.rfind(':');
    if (colon == std::string::npos) return {};
    out.emplace_back(item.substr(0, colon),
                     std::atoi(item.c_str() + colon + 1));
    pos = comma + 1;
  }
  return out;
}

Ring* MakeRing(int rank, int world, const std::string& peers,
               int timeout_ms) {
  auto addrs = ParsePeers(peers);
  if (static_cast<int>(addrs.size()) != world || rank < 0 || rank >= world)
    return nullptr;
  if (world == 1) {
    Ring* r = new Ring(rank, world);
    return r;
  }

  // Listen on our advertised port for the predecessor.
  int lfd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (lfd < 0) return nullptr;
  int one = 1;
  ::setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = INADDR_ANY;
  addr.sin_port = htons(static_cast<uint16_t>(addrs[rank].second));
  if (::bind(lfd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(lfd, 4) < 0) {
    ::close(lfd);
    return nullptr;
  }

  // Connect to successor (retry until its listener is up or timeout).
  int next = (rank + 1) % world;
  int sfd = -1;
  int waited = 0;
  for (; waited < timeout_ms; waited += 50) {
    sfd = ::socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in peer{};
    peer.sin_family = AF_INET;
    peer.sin_port = htons(static_cast<uint16_t>(addrs[next].second));
    if (::inet_pton(AF_INET, addrs[next].first.c_str(), &peer.sin_addr) != 1) {
      // Resolve "localhost" only; full DNS is the Python layer's job.
      if (addrs[next].first == "localhost")
        ::inet_pton(AF_INET, "127.0.0.1", &peer.sin_addr);
      else {
        ::close(sfd);
        ::close(lfd);
        return nullptr;
      }
    }
    if (::connect(sfd, reinterpret_cast<sockaddr*>(&peer), sizeof(peer)) == 0)
      break;
    ::close(sfd);
    sfd = -1;
    ::usleep(50 * 1000);
  }
  if (sfd < 0) {
    ::close(lfd);
    return nullptr;
  }
  ::setsockopt(sfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));

  // Accept the predecessor, spending whatever remains of the timeout
  // budget — a predecessor that dies after our connect succeeded must
  // surface as setup failure, not an indefinite accept() hang.
  pollfd lpf{lfd, POLLIN, 0};
  int remaining = timeout_ms - waited;
  if (::poll(&lpf, 1, remaining > 0 ? remaining : 1) <= 0) {
    ::close(sfd);
    ::close(lfd);
    return nullptr;
  }
  int rfd = ::accept(lfd, nullptr, nullptr);
  if (rfd < 0) {
    ::close(sfd);
    ::close(lfd);
    return nullptr;
  }
  ::setsockopt(rfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  ::fcntl(sfd, F_SETFL, ::fcntl(sfd, F_GETFL) | O_NONBLOCK);
  ::fcntl(rfd, F_SETFL, ::fcntl(rfd, F_GETFL) | O_NONBLOCK);

  Ring* r = new Ring(rank, world);
  r->listen_fd_ = lfd;
  r->send_fd_ = sfd;
  r->recv_fd_ = rfd;
  return r;
}

MeshGroup* MakeMesh(int rank, int world, const std::string& peers,
                    int timeout_ms) {
  auto addrs = ParsePeers(peers);
  if (static_cast<int>(addrs.size()) != world || rank < 0 || rank >= world)
    return nullptr;
  MeshGroup* g = new MeshGroup(rank, world);
  if (world == 1) return g;

  int lfd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (lfd < 0) { delete g; return nullptr; }
  int one = 1;
  ::setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = INADDR_ANY;
  addr.sin_port = htons(static_cast<uint16_t>(addrs[rank].second));
  if (::bind(lfd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(lfd, world) < 0) {
    ::close(lfd);
    delete g;
    return nullptr;
  }
  g->listen_fd_ = lfd;

  // Outbound to every higher rank (connect succeeds once the peer's
  // listener is bound, even before it calls accept — kernel backlog).
  // Wall-clock deadline shared across all setup; every peer is guaranteed
  // at least one connect attempt even if earlier peers ate the budget.
  auto fail = [&]() { delete g; return static_cast<MeshGroup*>(nullptr); };
  auto now_ms = []() {
    timespec ts;
    ::clock_gettime(CLOCK_MONOTONIC, &ts);
    return static_cast<int64_t>(ts.tv_sec) * 1000 + ts.tv_nsec / 1000000;
  };
  const int64_t deadline = now_ms() + timeout_ms;
  for (int p = rank + 1; p < world; ++p) {
    int sfd = -1;
    for (bool first = true; first || now_ms() < deadline; first = false) {
      sfd = ::socket(AF_INET, SOCK_STREAM, 0);
      sockaddr_in peer{};
      peer.sin_family = AF_INET;
      peer.sin_port = htons(static_cast<uint16_t>(addrs[p].second));
      const std::string& host =
          addrs[p].first == "localhost" ? "127.0.0.1" : addrs[p].first;
      if (::inet_pton(AF_INET, host.c_str(), &peer.sin_addr) != 1) {
        ::close(sfd);
        return fail();
      }
      if (::connect(sfd, reinterpret_cast<sockaddr*>(&peer),
                    sizeof(peer)) == 0)
        break;
      ::close(sfd);
      sfd = -1;
      ::usleep(50 * 1000);
    }
    if (sfd < 0) return fail();
    ::setsockopt(sfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    uint32_t me = static_cast<uint32_t>(rank);
    if (!SendAll(sfd, &me, 4)) { ::close(sfd); return fail(); }
    g->fds_[p] = sfd;
  }
  // Inbound from every lower rank, identified by handshake.
  for (int i = 0; i < rank; ++i) {
    pollfd lpf{lfd, POLLIN, 0};
    int64_t remaining = deadline - now_ms();
    if (::poll(&lpf, 1, remaining > 0
                            ? static_cast<int>(remaining)
                            : 1) <= 0)
      return fail();
    int rfd = ::accept(lfd, nullptr, nullptr);
    if (rfd < 0) return fail();
    ::setsockopt(rfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    // Deadline-bounded handshake read: a stray connection (port scanner,
    // stale peer from a crashed run) that never sends its rank must not
    // hang setup past timeout_ms.
    uint32_t who = 0;
    char* hp = reinterpret_cast<char*>(&who);
    size_t hn = 4;
    bool hs_ok = true;
    while (hn > 0) {
      pollfd hpf{rfd, POLLIN, 0};
      int64_t hrem = deadline - now_ms();
      if (::poll(&hpf, 1, hrem > 0 ? static_cast<int>(hrem) : 1) <= 0) {
        hs_ok = false;
        break;
      }
      ssize_t k = ::recv(rfd, hp, hn, 0);
      if (k <= 0) {
        if (k < 0 && errno == EINTR) continue;
        hs_ok = false;
        break;
      }
      hp += k;
      hn -= static_cast<size_t>(k);
    }
    if (!hs_ok || who >= static_cast<uint32_t>(rank) ||
        g->fds_[who] != -1) {
      ::close(rfd);
      return fail();
    }
    g->fds_[who] = rfd;
  }
  for (int p = 0; p < world; ++p) {
    if (p == rank) continue;
    ::fcntl(g->fds_[p], F_SETFL, ::fcntl(g->fds_[p], F_GETFL) | O_NONBLOCK);
  }
  return g;
}

}  // namespace

extern "C" {

void* ttd_mesh_create(int rank, int world, const char* peers,
                      int timeout_ms) {
  return MakeMesh(rank, world, peers ? peers : "", timeout_ms);
}

int ttd_mesh_allreduce_hd_f32(void* g, float* data, uint64_t n) {
  return static_cast<MeshGroup*>(g)->AllreduceHdF32(data, n);
}

int ttd_mesh_allreduce_shuffle_f32(void* g, float* data, uint64_t n) {
  return static_cast<MeshGroup*>(g)->AllreduceShuffleF32(data, n);
}

int ttd_mesh_rank(void* g) { return static_cast<MeshGroup*>(g)->rank(); }
int ttd_mesh_world(void* g) { return static_cast<MeshGroup*>(g)->world(); }

void ttd_mesh_destroy(void* g) { delete static_cast<MeshGroup*>(g); }

void* ttd_ring_create(int rank, int world, const char* peers,
                      int timeout_ms) {
  return MakeRing(rank, world, peers ? peers : "", timeout_ms);
}

int ttd_ring_allreduce_q8_f32(void* r, float* data, uint64_t n) {
  return static_cast<Ring*>(r)->AllreduceQ8F32(data, n);
}

int ttd_ring_allreduce_f32(void* r, float* data, uint64_t n) {
  return static_cast<Ring*>(r)->AllreduceF32(data, n);
}

int ttd_ring_broadcast(void* r, uint8_t* data, uint64_t nbytes, int root) {
  return static_cast<Ring*>(r)->Broadcast(data, nbytes, root);
}

int ttd_ring_rank(void* r) { return static_cast<Ring*>(r)->rank(); }
int ttd_ring_world(void* r) { return static_cast<Ring*>(r)->world(); }

void ttd_ring_destroy(void* r) { delete static_cast<Ring*>(r); }

}  // extern "C"
