// Native JPEG decode stage for the input pipeline.
//
// The reference's image input runs tf.image's C++ JPEG kernels inside
// tf.data (SURVEY §2.1 "tf.data input pipelines" / §2.3 dataset
// kernels); the rebuild's Python path decodes through PIL, which holds
// the GIL for part of each decode and caps one process at ~one core.
// This unit is the native analog: libjpeg decode behind a plain C ABI,
// with
//
//   - a thread-pool batch entry point (ttd_jpeg_decode_batch) that
//     decodes N records concurrently while Python has released the GIL
//     in the ctypes call — host decode scales with cores, not processes;
//   - DCT-domain downscaling (scale_denom in {1,2,4,8}): libjpeg
//     reconstructs at 1/2, 1/4, 1/8 resolution for a fraction of the
//     IDCT + color-convert work — the cheap first step when the model
//     only needs a 224px crop from a multi-megapixel JPEG.
//
// Built as a SEPARATE shared library (libttd_jpeg.so, linked -ljpeg) so
// the main native library keeps zero external dependencies; environments
// without libjpeg simply fall back to PIL (native/jpeg.py returns
// unavailable).  Color handling: grayscale and YCbCr convert to RGB in
// libjpeg; exotic spaces (CMYK/YCCK) return an error and the Python
// caller falls back to PIL.

#include <cstddef>
#include <cstdio>  // jpeglib.h uses FILE/size_t without including them

#include <jpeglib.h>

#include <atomic>
#include <csetjmp>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

struct ErrorTrap {
  jpeg_error_mgr mgr;
  jmp_buf jump;
};

void on_error(j_common_ptr cinfo) {
  ErrorTrap* trap = reinterpret_cast<ErrorTrap*>(cinfo->err);
  longjmp(trap->jump, 1);
}

void silence(j_common_ptr, int) {}
void silence_msg(j_common_ptr) {}

// Shared decode core.  mode 0: dims only.  mode 1: full decode into out.
// Returns 0 ok, -1 corrupt/unsupported, -2 out buffer too small.
int decode_impl(const uint8_t* data, uint64_t len, int scale_denom,
                uint8_t* out, uint64_t cap, int* w, int* h, int mode) {
  if (data == nullptr || len == 0) return -1;
  if (scale_denom != 1 && scale_denom != 2 && scale_denom != 4 &&
      scale_denom != 8)
    return -1;
  jpeg_decompress_struct cinfo;
  ErrorTrap trap;
  cinfo.err = jpeg_std_error(&trap.mgr);
  trap.mgr.error_exit = on_error;
  trap.mgr.emit_message = silence;
  trap.mgr.output_message = silence_msg;
  if (setjmp(trap.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(data),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  cinfo.scale_num = 1;
  cinfo.scale_denom = static_cast<unsigned>(scale_denom);
  cinfo.out_color_space = JCS_RGB;  // converts grayscale/YCbCr; not CMYK
  if (cinfo.jpeg_color_space == JCS_CMYK ||
      cinfo.jpeg_color_space == JCS_YCCK) {
    jpeg_destroy_decompress(&cinfo);
    return -1;  // caller falls back to PIL
  }
  jpeg_calc_output_dimensions(&cinfo);
  if (w) *w = static_cast<int>(cinfo.output_width);
  if (h) *h = static_cast<int>(cinfo.output_height);
  if (mode == 0) {
    jpeg_destroy_decompress(&cinfo);
    return 0;
  }
  const uint64_t row_bytes = 3ull * cinfo.output_width;
  if (cap < row_bytes * cinfo.output_height) {
    jpeg_destroy_decompress(&cinfo);
    return -2;
  }
  jpeg_start_decompress(&cinfo);
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = out + row_bytes * cinfo.output_scanline;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

}  // namespace

extern "C" {

// Output dimensions at scale_denom WITHOUT decoding pixel data.
int ttd_jpeg_dims(const uint8_t* data, uint64_t len, int scale_denom,
                  int* w, int* h) {
  return decode_impl(data, len, scale_denom, nullptr, 0, w, h, 0);
}

// Decode to tightly-packed RGB8 rows. Returns 0 / -1 / -2 (see above).
int ttd_jpeg_decode_rgb(const uint8_t* data, uint64_t len, int scale_denom,
                        uint8_t* out, uint64_t cap, int* w, int* h) {
  return decode_impl(data, len, scale_denom, out, cap, w, h, 1);
}

// Thread-pool batch decode: element i of datas/lens decodes into outs[i]
// (capacity caps[i]); ws/hs receive dims; rcs (optional) per-image codes.
// Returns the number of failed images.
int ttd_jpeg_decode_batch(int n, const uint8_t* const* datas,
                          const uint64_t* lens, int scale_denom,
                          uint8_t* const* outs, const uint64_t* caps,
                          int* ws, int* hs, int* rcs, int num_threads) {
  if (n <= 0) return 0;
  if (num_threads < 1) num_threads = 1;
  if (num_threads > n) num_threads = n;
  std::atomic<int> next{0};
  std::atomic<int> failures{0};
  auto worker = [&]() {
    for (;;) {
      int i = next.fetch_add(1);
      if (i >= n) return;
      int rc = decode_impl(datas[i], lens[i], scale_denom,
                           outs[i], caps[i], ws ? ws + i : nullptr,
                           hs ? hs + i : nullptr, 1);
      if (rcs) rcs[i] = rc;
      if (rc != 0) failures.fetch_add(1);
    }
  };
  if (num_threads == 1) {
    worker();
  } else {
    std::vector<std::thread> pool;
    pool.reserve(num_threads);
    for (int t = 0; t < num_threads; ++t) pool.emplace_back(worker);
    for (auto& th : pool) th.join();
  }
  return failures.load();
}

}  // extern "C"
