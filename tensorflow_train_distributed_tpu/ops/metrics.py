"""Host-side evaluation metrics (numpy; run on decoded outputs, not in jit).

BLEU for the WMT config — the reference's Transformer-big target metric
(SURVEY.md §2.1 config[3] trains WMT but never evaluates translation in the
harness; pairing ``models.transformer.greedy_translate`` with corpus BLEU
closes that loop).  Standard BLEU-4: modified n-gram precision with
clipping, geometric mean, brevity penalty (Papineni et al. 2002).
"""

from __future__ import annotations

import collections
import math
from typing import Iterable, Sequence


def _ngrams(tokens: Sequence, n: int) -> collections.Counter:
    return collections.Counter(
        tuple(tokens[i:i + n]) for i in range(len(tokens) - n + 1))


def corpus_bleu(hypotheses: Iterable[Sequence],
                references: Iterable[Sequence],
                *, max_order: int = 4, smooth: bool = False) -> float:
    """Corpus-level BLEU in [0, 100] over token-id (or str) sequences.

    One reference per hypothesis (the WMT newstest convention this harness
    needs).  ``smooth``: add-one smoothing on higher-order precisions
    (Lin & Och 2004) for tiny corpora where 4-gram matches may be zero.
    """
    hyps, refs = list(hypotheses), list(references)
    if len(hyps) != len(refs):
        raise ValueError(
            f"{len(hyps)} hypotheses vs {len(refs)} references")
    if not hyps:
        return 0.0
    matches = [0] * max_order
    totals = [0] * max_order
    hyp_len = ref_len = 0
    for hyp, ref in zip(hyps, refs):
        hyp, ref = list(hyp), list(ref)
        hyp_len += len(hyp)
        ref_len += len(ref)
        for n in range(1, max_order + 1):
            h, r = _ngrams(hyp, n), _ngrams(ref, n)
            matches[n - 1] += sum((h & r).values())
            totals[n - 1] += max(len(hyp) - n + 1, 0)
    log_precisions = []
    for order0, (m, t) in enumerate(zip(matches, totals)):
        if smooth and order0 > 0:  # Lin & Och smooth orders > 1 only
            m, t = m + 1, t + 1
        if m == 0 or t == 0:
            return 0.0
        log_precisions.append(math.log(m / t))
    geo = math.exp(sum(log_precisions) / max_order)
    bp = (1.0 if hyp_len >= ref_len
          else math.exp(1.0 - ref_len / max(hyp_len, 1)))
    return 100.0 * bp * geo


def strip_after_eos(ids: Sequence[int], eos_id: int) -> list[int]:
    """Token ids up to (excluding) the first EOS.

    Deliberately does NOT drop any other id: token 0 is a legitimate
    vocab id (<unk>/<pad> conventions vary), and ``greedy_translate``
    only writes padding AFTER the first EOS, so truncation alone is the
    correct cleanup for its output.
    """
    out = []
    for t in ids:
        if t == eos_id:
            break
        out.append(int(t))
    return out
