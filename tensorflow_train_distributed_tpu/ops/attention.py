"""Attention: pure-jax reference + pallas flash-attention TPU fast path.

The reference framework has no attention kernel of its own (BERT/Transformer
configs ride stock Keras layers → cuDNN).  TPU-first, attention is the one
op worth a hand kernel: the pallas flash attention
(``jax/experimental/pallas/ops/tpu/flash_attention.py``) streams KV blocks
through VMEM without materializing the S×S score matrix, which is what makes
long-context training feasible at all (SURVEY.md §5.7 — a capability the
reference lacks).

Dispatch contract: ``multihead_attention_kernel`` takes [B, H, S, D] q/k/v
and routes to pallas on TPU when shapes are kernel-friendly, else to the
reference einsum path (always used on CPU test meshes — it is also the
numerics oracle the kernel is tested against).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    mask: Optional[jax.Array] = None,
    softmax_scale: Optional[float] = None,
) -> jax.Array:
    """Reference attention. q/k/v: [B, H, S, D] (q may have different S)."""
    *_, q_len, head_dim = q.shape
    kv_len = k.shape[-2]
    scale = softmax_scale if softmax_scale is not None else head_dim**-0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    logits = logits.astype(jnp.float32)
    # Large finite negative, not -inf: a fully-masked query row must produce
    # ~zeros after softmax, not NaN (all--inf rows NaN out the whole batch).
    mask_value = jnp.finfo(jnp.float32).min / 2
    if causal:
        # Bottom-right aligned causal mask (supports q_len != kv_len).
        q_pos = jnp.arange(q_len)[:, None] + (kv_len - q_len)
        k_pos = jnp.arange(kv_len)[None, :]
        logits = jnp.where(q_pos >= k_pos, logits, mask_value)
    if mask is not None:
        logits = jnp.where(mask, logits, mask_value)
    weights = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", weights.astype(v.dtype), v)


def _pallas_friendly(q, k, v) -> bool:
    """Pallas flash kernel wants seq multiples of 128 and head_dim >= 128-
    lane tiling; fall back cleanly otherwise."""
    if jax.default_backend() != "tpu":
        return False
    q_len, kv_len = q.shape[-2], k.shape[-2]
    # q_len == kv_len: the pallas kernel's causal mask is top-left aligned;
    # our reference semantics are bottom-right — they only coincide for
    # equal lengths, so unequal lengths take the reference path.
    return (
        q_len == kv_len
        and q_len % 128 == 0
        and q.shape[-1] in (64, 128, 256)
        and q.dtype in (jnp.float32, jnp.bfloat16)
    )


def multihead_attention_kernel(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    mask: Optional[jax.Array] = None,
    segment_ids: Optional[jax.Array] = None,
    softmax_scale: Optional[float] = None,
    force_reference: bool = False,
) -> jax.Array:
    """Flash attention on TPU, reference path elsewhere.

    ``segment_ids`` [B, S]: restrict attention to same-segment pairs (the
    sequence-packing mask) — structured, so the pallas kernel handles it
    natively (``SegmentIds``); an arbitrary dense ``mask`` forces the
    reference path instead.
    """
    if force_reference or mask is not None or not _pallas_friendly(q, k, v):
        if segment_ids is not None:
            seg = (segment_ids[:, None, :, None]
                   == segment_ids[:, None, None, :])  # [B, 1, Sq, Skv]
            mask = seg if mask is None else jnp.logical_and(mask, seg)
        return dot_product_attention(
            q, k, v, causal=causal, mask=mask, softmax_scale=softmax_scale
        )
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        SegmentIds, flash_attention,
    )

    scale = (softmax_scale if softmax_scale is not None
             else q.shape[-1] ** -0.5)
    return flash_attention(
        q, k, v,
        segment_ids=(None if segment_ids is None
                     else SegmentIds(q=segment_ids, kv=segment_ids)),
        causal=causal, sm_scale=scale)
