"""Attention: pure-jax reference + pallas flash-attention TPU fast path.

The reference framework has no attention kernel of its own (BERT/Transformer
configs ride stock Keras layers → cuDNN).  TPU-first, attention is the one
op worth a hand kernel: the pallas flash attention
(``jax/experimental/pallas/ops/tpu/flash_attention.py``) streams KV blocks
through VMEM without materializing the S×S score matrix, which is what makes
long-context training feasible at all (SURVEY.md §5.7 — a capability the
reference lacks).

Dispatch contract: ``multihead_attention_kernel`` takes [B, H, S, D] q/k/v
and routes to pallas on TPU when shapes are kernel-friendly, else to the
reference einsum path (always used on CPU test meshes — it is also the
numerics oracle the kernel is tested against).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    mask: Optional[jax.Array] = None,
    window: Optional[int] = None,
    sinks: int = 0,
    softmax_scale: Optional[float] = None,
) -> jax.Array:
    """Reference attention. q/k/v: [B, H, S, D] (q may have different S).

    ``window`` (requires ``causal``): sliding-window attention — each
    query sees only the last ``window`` keys including itself (the
    Mistral convention), masked here exactly; this is the numerics
    oracle for ``local_attention_chunked``.  ``sinks`` (StreamingLLM):
    the first ``sinks`` absolute positions stay attendable past the
    window — the attention-sink trick that keeps streaming decode
    stable.
    """
    *_, q_len, head_dim = q.shape
    kv_len = k.shape[-2]
    scale = softmax_scale if softmax_scale is not None else head_dim**-0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    logits = logits.astype(jnp.float32)
    # Large finite negative, not -inf: a fully-masked query row must produce
    # ~zeros after softmax, not NaN (all--inf rows NaN out the whole batch).
    mask_value = jnp.finfo(jnp.float32).min / 2
    if window is not None and not causal:
        raise ValueError("window (sliding-window attention) requires "
                         "causal=True")
    if sinks and window is None:
        raise ValueError("sinks (attention sinks) only apply with a "
                         "sliding window")
    if causal:
        # Bottom-right aligned causal mask (supports q_len != kv_len).
        q_pos = jnp.arange(q_len)[:, None] + (kv_len - q_len)
        k_pos = jnp.arange(kv_len)[None, :]
        keep = q_pos >= k_pos
        if window is not None:
            band = q_pos - k_pos < window
            if sinks:
                band = jnp.logical_or(band, k_pos < sinks)
            keep = jnp.logical_and(keep, band)
        logits = jnp.where(keep, logits, mask_value)
    if mask is not None:
        logits = jnp.where(mask, logits, mask_value)
    weights = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", weights.astype(v.dtype), v)


def local_attention_chunked(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    window: int,
    segment_ids: Optional[jax.Array] = None,
    sinks: int = 0,
    softmax_scale: Optional[float] = None,
) -> jax.Array:
    """Sliding-window causal self-attention in O(S·window), TPU-native.

    Chunks the sequence into ``window``-sized blocks; each query block
    attends to (previous block, own block) — exactly the keys its
    sliding window can reach — so scores are [.., nc, w, 2w] instead of
    [.., S, S]: no quadratic materialization, static shapes, plain
    einsums XLA tiles onto the MXU.  Numerically matches
    ``dot_product_attention(causal=True, window=w)`` (oracle-tested).

    ``segment_ids`` [B, S] (sequence packing) stays structured: ids ride
    the same shift-concat as the keys, so packing composes WITHOUT the
    dense S×S mask.  ``sinks`` prepends the sequence's first ``sinks``
    keys to every chunk's key set (StreamingLLM attention sinks) — cost
    grows to O(S·(window+sinks)), still linear.  Requires q_len ==
    kv_len and q_len % window == 0 (the dispatcher falls back to the
    masked oracle otherwise).
    """
    *lead, s, d = q.shape
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    if not 0 <= sinks <= window:
        raise ValueError(
            f"sinks must be in [0, window], got sinks={sinks} "
            f"window={window}")
    if s % window or k.shape[-2] != s:
        raise ValueError(
            f"local_attention_chunked wants self-attention with seq "
            f"divisible by window, got seq={s} window={window}")
    w = window
    nc = s // w
    scale = softmax_scale if softmax_scale is not None else d**-0.5

    def chunk(t):  # [..., S, D] → [..., nc, w, D]
        return t.reshape(*lead, nc, w, d)

    def shift_concat(tc, pad_axes):
        """(chunk i-1, chunk i) along the chunk axis; chunk -1 is zeros
        (masked by pad_slot below)."""
        prev = jnp.pad(tc[..., :-1, :, :] if tc.ndim > 3
                       else tc[:, :-1, :], pad_axes)
        return jnp.concatenate([prev, tc], axis=-2 if tc.ndim > 3 else -1)

    qc = chunk(q)
    pad4 = [(0, 0)] * len(lead) + [(1, 0), (0, 0), (0, 0)]
    kwin = shift_concat(chunk(k), pad4)                  # [.., nc, 2w, D]
    vwin = shift_concat(chunk(v), pad4)
    kv = 2 * w
    if sinks:
        # Every chunk also sees the sequence's first `sinks` keys —
        # broadcast along the chunk axis (zero-copy under XLA).
        def with_sinks(twin, t):
            sink = jnp.broadcast_to(
                t[..., None, :sinks, :],
                (*lead, nc, sinks, d))
            return jnp.concatenate([sink, twin], axis=-2)

        kwin = with_sinks(kwin, k)
        vwin = with_sinks(vwin, v)
        kv += sinks
    logits = jnp.einsum("...cqd,...ckd->...cqk", qc, kwin) * scale
    logits = logits.astype(jnp.float32)
    mask_value = jnp.finfo(jnp.float32).min / 2
    qi = jnp.arange(w)[:, None]          # query pos within chunk
    kj = jnp.arange(2 * w)[None, :]      # key pos within (prev, own)
    # Window band: key global = base + kj - w, query global = base + qi;
    # keep 0 <= qi - (kj - w) < w  ⇔  qi < kj <= qi + w.
    band = jnp.logical_and(kj > qi, kj <= qi + w)        # [w, 2w]
    # Chunk 0 has no previous block: its first w key slots are padding.
    first = (jnp.arange(nc) == 0)[:, None, None]         # [nc, 1, 1]
    pad_slot = (kj < w)[None, :, :] & first              # [nc, w, 2w]
    keep = band[None, :, :] & ~pad_slot                  # [nc, w, 2w]
    if sinks:
        # Sink columns: key global = si (< sinks), query global =
        # base + qi.  Keep when causal (si <= base+qi) and NOT already
        # a band key of this chunk (the band covers globals
        # > base+qi-w >= base-w; sinks overlap only for chunks 0/1 where
        # base - w < sinks is possible) — dedupe by excluding sink
        # columns the band already reaches: si > base + qi - w.
        base = (jnp.arange(nc) * w)[:, None, None]       # [nc, 1, 1]
        si = jnp.arange(sinks)[None, None, :]            # [1, 1, sinks]
        qg = base + qi[None]                             # [nc, w, 1]
        sink_keep = (si <= qg) & (si <= qg - w)          # causal & not-in-band
        keep = jnp.concatenate(
            [jnp.broadcast_to(sink_keep, (nc, w, sinks)),
             jnp.broadcast_to(keep, (nc, w, 2 * w))], axis=-1)
    if segment_ids is not None:
        b = segment_ids.shape[0]
        segc = segment_ids.reshape(b, nc, w)
        seg_win = shift_concat(segc, [(0, 0), (1, 0), (0, 0)])
        if sinks:
            sink_seg = jnp.broadcast_to(
                segment_ids[:, None, :sinks], (b, nc, sinks))
            seg_win = jnp.concatenate([sink_seg, seg_win], axis=-1)
        seg_keep = segc[..., :, None] == seg_win[..., None, :]
        # [B, nc, w, kv] → broadcast over the head axis.
        keep = keep[None, None] & seg_keep[:, None]
    logits = jnp.where(keep, logits, mask_value)
    weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("...cqk,...ckd->...cqd", weights.astype(vwin.dtype),
                     vwin)
    return out.reshape(*lead, s, d)


def _pallas_friendly(q, k, v) -> bool:
    """Pallas flash kernel wants seq multiples of 128 and head_dim >= 128-
    lane tiling; fall back cleanly otherwise."""
    if jax.default_backend() != "tpu":
        return False
    q_len, kv_len = q.shape[-2], k.shape[-2]
    # q_len == kv_len: the pallas kernel's causal mask is top-left aligned;
    # our reference semantics are bottom-right — they only coincide for
    # equal lengths, so unequal lengths take the reference path.
    return (
        q_len == kv_len
        and q_len % 128 == 0
        and q.shape[-1] in (64, 128, 256)
        and q.dtype in (jnp.float32, jnp.bfloat16)
    )


def _splash_window_friendly(q, k, sinks, mask, force_reference) -> bool:
    """Whether the splash local-attention kernel takes this call.

    OPT-IN (``TTD_SPLASH=1``), not the default: on silicon the chunked
    jnp path beat splash at the measured shape — llama_125m b8×s2048
    w512: chunked 58.1k tok/s (full remat) vs splash 43.8k (full remat)
    / 53.7k (+no_ffn, which splash alone enables) — PROFILE.md round-4.
    Splash's remat freedom did not make up the kernel gap there; until a
    shape is measured where it wins, the measured winner stays default.
    """
    from tensorflow_train_distributed_tpu.ops.pallas_kernels import (
        env_flag,
    )

    # env_flag is the one shared parser ("0"/"false"/empty mean OFF —
    # the TTD_NO_PALLAS lesson).  TTD_NO_SPLASH still forces it off even
    # if TTD_SPLASH is set (kill switch wins).
    if env_flag("TTD_NO_SPLASH") or not env_flag("TTD_SPLASH"):
        return False
    if force_reference or mask is not None or sinks:
        return False
    # Same kernel-friendliness rules as the flash path (one source).
    return _pallas_friendly(q, k, q)


def splash_window_attention(q, k, v, *, window: int,
                            segment_ids=None,
                            softmax_scale: Optional[float] = None,
                            interpret: bool = False) -> jax.Array:
    """Sliding-window causal attention via the library SPLASH kernel.

    Splash supports local masks NATIVELY (``LocalMask``), streaming KV
    blocks through VMEM and SKIPPING fully-masked blocks — so unlike the
    jnp chunked path nothing [B,H,chunks,c,c+w]-shaped ever
    materializes, which removes the full-remat pairing constraint the
    chunked path has (PROFILE.md: its saved f32 score stacks OOM a 16
    GiB chip under no-remat/no_ffn).  q/k/v: [B, H, S, D] with KV
    already repeated to full heads (the caller's GQA contract).

    ``interpret=True`` runs the kernel in pallas interpret mode — the
    CPU parity-test path (slow; tiny shapes only).
    """
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as _sk,
        splash_attention_mask as _sm,
    )

    b, h, s, d = q.shape
    scale = softmax_scale if softmax_scale is not None else d ** -0.5
    # LocalMask window_size is (left, right) EXCLUSIVE of self; our
    # ``window`` counts the query itself (Mistral), hence window - 1.
    mask = _sm.MultiHeadMask(
        [_sm.LocalMask((s, s), (window - 1, 0), 0) for _ in range(h)])
    kernel = _sk.make_splash_mha(
        mask, head_shards=1, q_seq_shards=1, interpret=interpret)
    qs = (q * scale).astype(q.dtype)  # splash does not scale internally

    if segment_ids is None:
        def one(qi, ki, vi):
            return kernel(qi, ki, vi)

        return jax.vmap(one)(qs, k, v)

    def one_seg(qi, ki, vi, si):
        return kernel(qi, ki, vi,
                      segment_ids=_sk.SegmentIds(q=si, kv=si))

    return jax.vmap(one_seg)(qs, k, v, segment_ids)


def multihead_attention_kernel(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    mask: Optional[jax.Array] = None,
    segment_ids: Optional[jax.Array] = None,
    window: Optional[int] = None,
    sinks: int = 0,
    softmax_scale: Optional[float] = None,
    force_reference: bool = False,
) -> jax.Array:
    """Flash attention on TPU, reference path elsewhere.

    ``segment_ids`` [B, S]: restrict attention to same-segment pairs (the
    sequence-packing mask) — structured, so the pallas kernel handles it
    natively (``SegmentIds``); an arbitrary dense ``mask`` forces the
    reference path instead.

    ``window``: sliding-window causal attention (Mistral convention —
    each query sees the last ``window`` keys including itself).  Plain
    long self-attention takes the O(S·window) chunked path
    (``local_attention_chunked``); combinations with packing/masks/
    cross-length fall back to the exactly-masked oracle.  ``sinks``
    (StreamingLLM attention sinks, needs ``window``): the first
    ``sinks`` positions stay attendable past the window.
    """
    def _fold_segments(mask):
        """Dense same-segment mask (the packing restriction) — only for
        the S×S fallback paths; the chunked path keeps ids structured."""
        if segment_ids is None:
            return mask
        seg = (segment_ids[:, None, :, None]
               == segment_ids[:, None, None, :])  # [B, 1, Sq, Skv]
        return seg if mask is None else jnp.logical_and(mask, seg)

    if sinks and window is None:
        raise ValueError("sinks (attention sinks) only apply with a "
                         "sliding window")
    if window is not None:
        if not causal:
            raise ValueError("window (sliding-window attention) requires "
                             "causal=True")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if (_splash_window_friendly(q, k, sinks, mask, force_reference)
                and q.shape[-2] > window):
            # TPU: the splash kernel handles the local mask natively —
            # no score materialization, no remat pairing constraint.
            return splash_window_attention(
                q, k, v, window=window, segment_ids=segment_ids,
                softmax_scale=softmax_scale)
        chunkable = (mask is None and not force_reference
                     and q.shape[-2] == k.shape[-2]
                     and q.shape[-2] % window == 0
                     and q.shape[-2] > window
                     and sinks <= window)
        if chunkable:
            return local_attention_chunked(
                q, k, v, window=window, segment_ids=segment_ids,
                sinks=sinks, softmax_scale=softmax_scale)
        if q.shape[-2] >= 4 * window and not force_reference:
            import warnings

            warnings.warn(
                f"sliding-window attention fell back to the DENSE "
                f"S×S path (seq={q.shape[-2]}, window={window}: "
                f"seq not divisible by window, a dense mask, or "
                f"cross-length) — the O(S·window) chunked path "
                f"needs seq %% window == 0; at long context this "
                f"fallback can OOM", stacklevel=2)
        return dot_product_attention(
            q, k, v, causal=True, mask=_fold_segments(mask), window=window,
            sinks=sinks, softmax_scale=softmax_scale)
    if force_reference or mask is not None or not _pallas_friendly(q, k, v):
        return dot_product_attention(
            q, k, v, causal=causal, mask=_fold_segments(mask),
            softmax_scale=softmax_scale
        )
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        SegmentIds, flash_attention,
    )

    scale = (softmax_scale if softmax_scale is not None
             else q.shape[-1] ** -0.5)
    return flash_attention(
        q, k, v,
        segment_ids=(None if segment_ids is None
                     else SegmentIds(q=segment_ids, kv=segment_ids)),
        causal=causal, sm_scale=scale)
