"""Shared classification losses (single source for every task).

One implementation of (optionally label-smoothed, optionally weighted)
softmax cross-entropy + accuracy, used by the vision, seq2seq, MLM and LM
tasks — so fixes (padding masks, z-loss, ...) land everywhere at once.
"""

from __future__ import annotations

from typing import Optional

import jax
from tensorflow_train_distributed_tpu.runtime import compat
import jax.numpy as jnp
import optax


def fold_sample_weight(batch, targets_shape,
                       weights: Optional[jax.Array] = None
                       ) -> Optional[jax.Array]:
    """Fold the optional ``sample_weight`` batch key into ``weights``.

    ``sample_weight`` ([B] f32, 1.0 real / 0.0 pad) is the padded-eval
    contract (``data.pipeline`` ``drop_remainder=False``): pad rows must
    contribute nothing to any loss or metric.  One implementation shared
    by every task loss_fn so the composition rule can't drift between
    families.  Returns per-position weights shaped/broadcastable to
    ``targets_shape`` (``weights`` with pad rows zeroed, or the pad mask
    alone), or None when neither weighting applies.  Tasks report
    ``weights.sum()`` UNCLAMPED as ``metrics["loss_weight"]`` so an
    all-pad batch (weight 0) is skipped by the metric accumulator.
    """
    sw = batch.get("sample_weight")
    if sw is None:
        return None if weights is None else weights.astype(jnp.float32)
    base = (jnp.ones(targets_shape, jnp.float32) if weights is None
            else weights.astype(jnp.float32))
    sw = sw.astype(jnp.float32).reshape(
        sw.shape + (1,) * (len(targets_shape) - sw.ndim))
    return base * sw


def _fused_ce_usable() -> bool:
    """Fused pallas CE on TPU — except under tensor parallelism, where
    logits are vocab-sharded and the GSPMD jnp path keeps the logsumexp
    sharded (one pallas_call would gather full logits per device)."""
    if jax.default_backend() != "tpu":
        return False
    mesh = compat.get_abstract_mesh()
    if mesh is not None and not mesh.empty and mesh.shape.get("tensor", 1) > 1:
        return False
    return True


def softmax_cross_entropy(
    logits: jax.Array,
    labels: jax.Array,
    *,
    label_smoothing: float = 0.0,
    weights: Optional[jax.Array] = None,
) -> tuple[jax.Array, jax.Array]:
    """Mean CE loss and accuracy over ``labels``.

    ``logits``: [..., num_classes] (f32 recommended); ``labels``: integer
    [...]; ``weights``: optional per-example/token weights (e.g. MLM mask) —
    the mean is over total weight, matching the reference's weighted-metric
    semantics.
    """
    logits = logits.astype(jnp.float32)
    if label_smoothing > 0.0:
        onehot = optax.smooth_labels(
            jax.nn.one_hot(labels, logits.shape[-1], dtype=jnp.float32),
            label_smoothing,
        )
        per_example = optax.softmax_cross_entropy(logits, onehot)
    elif _fused_ce_usable():
        # Pallas fused CE: streams vocab blocks through VMEM instead of
        # materializing an f32 [tokens, vocab] log-softmax in HBM — the
        # dominant activation at LM scale (ops.pallas_kernels docstring).
        from tensorflow_train_distributed_tpu.ops.pallas_kernels import (
            fused_cross_entropy,
        )

        per_example = fused_cross_entropy(logits, labels)
    else:
        per_example = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels)
    correct = (logits.argmax(-1) == labels).astype(jnp.float32)
    if weights is None:
        return per_example.mean(), correct.mean()
    w = weights.astype(jnp.float32)
    denom = jnp.maximum(w.sum(), 1.0)
    return (per_example * w).sum() / denom, (correct * w).sum() / denom
