"""Hand-rolled pallas TPU kernels: fused RMSNorm and fused softmax-CE.

The reference's hot ops live in cuDNN/cuBLAS; its framework code never hand-
writes kernels.  TPU-first, the two ops worth owning beyond attention are:

- **RMSNorm** (Llama-family norm, run 2×/layer): fusing square-mean,
  rsqrt and the scale multiply into one VMEM pass removes two HBM round
  trips of the [tokens, d_model] activation that unfused XLA sometimes
  leaves behind around the f32 upcast.  Custom VJP keeps the backward to
  one kernel + one einsum (dscale), saving the re-normalization recompute.
- **Softmax cross-entropy** over large vocab (the LM loss): the jnp path
  materializes an f32 [tokens, vocab] log-softmax (and its transpose flow
  in backward) in HBM — at Llama scale (8k tokens × 32k vocab × 4B ≈ 1 GB)
  that dwarfs the model's activations.  The fused kernel streams vocab
  blocks through VMEM with an online (max, sumexp) accumulator — flash
  attention's trick applied to the loss — and the backward recomputes
  softmax blockwise from the saved logsumexp, so HBM cost is the logits
  themselves and [tokens]-sized residuals.

Two serving-side kernels back the engine's paged KV cache:

- **Paged KV gather** (``paged_kv_gather``): the decode step reads each
  lane's KV through a block table (physical blocks of ``block_size``
  rows in one fixed pool — serving.ServingEngine's paged cache).  The
  jnp reference materializes the gather through XLA's generic scatter/
  gather lowering; the kernel is a block-copy loop whose source block
  index comes from a SCALAR-PREFETCHED table (``PrefetchScalarGridSpec``
  — the index map reads ``table[lane, slot]`` before the body runs), so
  each grid step is one contiguous [block_size, kv_heads·head_dim] VMEM
  copy at the natural tile shape, no per-row index math on the vector
  units.
- **Fused paged attention** (``paged_attention``): the gather above
  still MATERIALIZES a dense [lanes, cache_len, kv_heads, head_dim]
  KV view in HBM before attention ever runs — doubling HBM traffic on
  the one resource decode is bound by (the paged_kv_ab residual).
  This kernel computes flash-style decode attention DIRECTLY through
  the block table: grid (lane, logical block) with the same
  scalar-prefetched table steering each block's DMA, an online
  (max, sumexp, acc) accumulator per (head, query row) carried across
  blocks in VMEM scratch, per-lane causal masking from a prefetched
  length vector, GQA handled per kv-head group in-kernel, and optional
  int8-pool dequant fused into the block read (per-row symmetric
  scales ride in a parallel scale pool) — the dense per-lane view is
  never materialized.  ``TTD_NO_FUSED_ATTN=1`` restores the
  gather-then-attend path (the byte-comparable A/B leg);
  ``TTD_FUSED_ATTN_INTERPRET=1`` forces the kernel in interpret mode
  off-TPU (the CPU parity-test path).

Both have pure-jax references (the CPU path and the numerics oracle) and
run in interpreter mode in tests (``interpret=True``); kernel layout
follows ``/opt/skills/guides/pallas_guide.md`` (f32 accumulation, 128-lane
blocks, grid innermost over the reduction axis).
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG = -1e30  # big finite negative: avoids -inf − -inf = NaN in masking


def env_flag(name: str) -> bool:
    """True when the A/B kill-switch env var ``name`` is SET (on).

    "", "0", and "false" (any case) mean OFF — a raw truthiness check
    would make NAME=0 silently flip the A/B (the TTD_NO_PALLAS lesson).
    One parser for every switch so the semantics cannot diverge.
    """
    return os.environ.get(name, "").lower() not in ("", "0", "false")


def _use_pallas(override: Optional[bool]) -> bool:
    if override is not None:
        return override
    # Kill switch for on-chip A/B (tools/chip_playbook.sh): the custom-VJP
    # kernels block XLA fusion around them, so their win must be measured,
    # not assumed — TTD_NO_PALLAS=1 falls back to the pure-jax path.
    # ("0"/"false"/empty mean OFF — a raw truthiness check would make
    # TTD_NO_PALLAS=0 silently disable the kernels and corrupt the A/B.)
    if env_flag("TTD_NO_PALLAS"):
        return False
    return jax.default_backend() == "tpu"


# ---------------------------------------------------------------------------
# Paged KV gather (serving.ServingEngine paged cache)
# ---------------------------------------------------------------------------


def paged_kv_gather_reference(pool, table, cache_len: int):
    """Pure-jax oracle: gather each lane's logical KV rows.

    ``pool``: [num_blocks, block_size, kv_heads, head_dim] physical
    rows; ``table``: [lanes, n_blk] int32 physical block per logical
    block.  Returns [lanes, cache_len, kv_heads, head_dim] — lane b's
    logical row p is ``pool[table[b, p // bs], p % bs]``.
    """
    nb, bs, kvh, hd = pool.shape
    lanes = table.shape[0]
    # Gather whole BLOCKS (lanes * n_blk indices, contiguous
    # [bs, kvh, hd] slices each) rather than per-row (lanes * cache_len
    # indices): same bytes, far less index math — XLA lowers this to
    # slice copies, which keeps the paged read from taxing decode.
    blocks = jnp.take(pool, table, axis=0)     # [lanes, n_blk, bs, ...]
    return blocks.reshape(lanes, -1, kvh, hd)[:, :cache_len]


def _paged_gather_kernel(tbl_ref, pool_ref, out_ref):
    # The index map already steered the DMA to the right physical
    # block (scalar-prefetched table); the body is a straight copy.
    del tbl_ref
    out_ref[:] = pool_ref[:]


def paged_kv_gather(pool, table, cache_len: int, *,
                    use_pallas: Optional[bool] = None,
                    interpret: bool = False):
    """Block-table KV gather: [num_blocks, bs, kvh, hd] pool + [lanes,
    n_blk] table → [lanes, cache_len, kvh, hd] per-lane linear view
    (bit-identical to the reference: a gather moves bytes, no math)."""
    if not _use_pallas(use_pallas) and not interpret:
        return paged_kv_gather_reference(pool, table, cache_len)
    from jax.experimental.pallas import tpu as pltpu

    nb, bs, kvh, hd = pool.shape
    lanes, n_blk = table.shape
    flat = pool.reshape(nb, bs, kvh * hd)
    out = pl.pallas_call(
        _paged_gather_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(lanes, n_blk),
            in_specs=[
                pl.BlockSpec((1, bs, kvh * hd),
                             lambda i, j, tbl: (tbl[i, j], 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, bs, kvh * hd),
                                   lambda i, j, tbl: (i, j, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((lanes, n_blk * bs, kvh * hd),
                                       pool.dtype),
        interpret=interpret,
    )(table, flat)
    return out[:, :cache_len].reshape(lanes, cache_len, kvh, hd)


# ---------------------------------------------------------------------------
# Fused paged attention (serving.ServingEngine paged decode)
# ---------------------------------------------------------------------------


def use_fused_paged_attention() -> bool:
    """Whether the paged decode step should run the FUSED kernel
    (``paged_attention``) instead of gather-then-attend.

    ``TTD_NO_FUSED_ATTN=1`` is the production kill switch (wins over
    everything — restores the XLA block-gather path, byte-comparable as
    the A/B leg); ``TTD_FUSED_ATTN_INTERPRET=1`` forces the kernel ON
    in interpret mode off-TPU (the CPU parity-test path — slow, tiny
    shapes only); otherwise the decision is the standard pallas one
    (TPU backend, TTD_NO_PALLAS respected).  Read at TRACE time — flip
    before the engine compiles its decode programs."""
    if env_flag("TTD_NO_FUSED_ATTN"):
        return False
    if env_flag("TTD_FUSED_ATTN_INTERPRET"):
        return True
    return _use_pallas(None)


def fused_attn_interpret() -> bool:
    """True when the fused kernel should run INTERPRETED (the
    TTD_FUSED_ATTN_INTERPRET CPU test path; on a real TPU the flag is
    ignored — the compiled kernel is the thing being shipped)."""
    return (env_flag("TTD_FUSED_ATTN_INTERPRET")
            and jax.default_backend() != "tpu")


def paged_attention_reference(q, k_pool, v_pool, table, lengths, *,
                              k_scales=None, v_scales=None,
                              cache_len: Optional[int] = None):
    """Pure-jax oracle: gather-then-attend, the exact math of the
    engine's XLA block-gather leg (``models.layers`` ``_cache_attend``
    minus the sharding constraints, which are numerically no-ops).

    ``q``: [lanes, q_len, heads, head_dim] (RoPE already applied);
    ``k_pool``/``v_pool``: [num_blocks, block_size, kv_heads, head_dim]
    (int8 when ``k_scales``/``v_scales`` [num_blocks, block_size,
    kv_heads] are given — per-row symmetric dequant, the linear-cache
    kv8 recipe); ``table``: [lanes, n_blk] int32; ``lengths``: [lanes]
    int32, each lane's pre-call row count (query i sits at position
    ``lengths[lane] + i`` and sees rows ``<=`` it).  Returns
    [lanes, q_len, heads, head_dim]."""
    from tensorflow_train_distributed_tpu.ops.attention import (
        dot_product_attention,
    )

    nb, bs, kvh, hd = k_pool.shape
    lanes, q_len, heads, _ = q.shape
    c = cache_len if cache_len is not None else table.shape[1] * bs
    kc = paged_kv_gather_reference(k_pool, table, c)
    vc = paged_kv_gather_reference(v_pool, table, c)
    if k_scales is not None:
        ks = paged_kv_gather_reference(k_scales[..., None], table, c)
        vs = paged_kv_gather_reference(v_scales[..., None], table, c)
        kc = kc.astype(q.dtype) * ks.astype(q.dtype)
        vc = vc.astype(q.dtype) * vs.astype(q.dtype)
    if kvh != heads:
        rep = heads // kvh
        kc = jnp.repeat(kc, rep, axis=2)
        vc = jnp.repeat(vc, rep, axis=2)
    positions = lengths[:, None] + jnp.arange(q_len)        # [B, q]
    mask = jnp.arange(c)[None, None, :] <= positions[:, :, None]
    out = dot_product_attention(
        q.transpose(0, 2, 1, 3), kc.transpose(0, 2, 1, 3),
        vc.transpose(0, 2, 1, 3), mask=mask[:, None])
    return out.transpose(0, 2, 1, 3)


def _paged_attn_kernel(tbl_ref, len_ref, q_ref, k_ref, v_ref, *rest,
                       bs, kvh, rep, q_len, hd, scale, int8):
    """Grid (lane, logical block), block innermost: the index maps
    already steered this step's K/V (and scale) DMA to physical block
    ``table[lane, j]``; the body folds the block into each query row's
    online (max, sumexp, acc) accumulator.  Row layout is
    [heads·q_len, hd] with row = head·q_len + qi, so each GQA group's
    rows are one contiguous slice and the per-row query position is
    ``row % q_len``."""
    if int8:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    cur = len_ref[i]
    qf = q_ref[0].astype(jnp.float32)        # [heads*q_len, hd]
    kf = k_ref[0]                            # [bs, kvh*hd]
    vf = v_ref[0]
    r = rep * q_len                          # rows per kv-head group
    for g in range(kvh):                     # static: tiny head count
        kg = kf[:, g * hd:(g + 1) * hd].astype(jnp.float32)
        vg = vf[:, g * hd:(g + 1) * hd].astype(jnp.float32)
        if int8:
            # Per-row symmetric dequant fused into the block read —
            # int8 bytes came off HBM, f32 math from here.
            kg = kg * ks_ref[0][:, g:g + 1]
            vg = vg * vs_ref[0][:, g:g + 1]
        qg = qf[g * r:(g + 1) * r]           # [r, hd]
        logits = jax.lax.dot_general(
            qg, kg, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale      # [r, bs]
        pos = j * bs + jax.lax.broadcasted_iota(jnp.int32, (r, bs), 1)
        qi = jax.lax.broadcasted_iota(jnp.int32, (r, bs), 0) % q_len
        # Causal through the table: row p visible to query qi iff
        # p <= cur + qi.  Rows past the lane's length (incl. the whole
        # scratch block a reset lane's table points at) mask out here;
        # block 0 always has a visible row for every query (p=0), so
        # the accumulator never divides by an all-masked zero.
        logits = jnp.where(pos <= cur + qi, logits, _NEG)
        rows = slice(g * r, (g + 1) * r)
        m_prev = m_ref[rows]
        m_new = jnp.maximum(m_prev,
                            jnp.max(logits, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(logits - m_new)
        l_ref[rows] = (l_ref[rows] * alpha
                       + jnp.sum(p, axis=-1, keepdims=True))
        acc_ref[rows] = acc_ref[rows] * alpha + jax.lax.dot_general(
            p, vg, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[rows] = m_new

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        o_ref[0] = (acc_ref[:] / l_ref[:]).astype(o_ref.dtype)


def paged_attention(q, k_pool, v_pool, table, lengths, *,
                    k_scales=None, v_scales=None,
                    cache_len: Optional[int] = None,
                    use_pallas: Optional[bool] = None,
                    interpret: bool = False):
    """Flash-style decode attention DIRECTLY through the block table —
    the dense per-lane KV view ``paged_kv_gather`` materializes never
    exists.  Arguments as ``paged_attention_reference`` (the pure-jax
    oracle this is tested against; also the CPU path).  One grid step
    DMAs exactly one physical block per lane, so HBM reads are the
    pool bytes once instead of pool-bytes + dense-copy twice."""
    if not _use_pallas(use_pallas) and not interpret:
        return paged_attention_reference(
            q, k_pool, v_pool, table, lengths, k_scales=k_scales,
            v_scales=v_scales, cache_len=cache_len)
    from jax.experimental.pallas import tpu as pltpu

    nb, bs, kvh, hd = k_pool.shape
    lanes, q_len, heads, _ = q.shape
    n_blk = table.shape[1]
    if heads % kvh:
        raise ValueError(f"heads {heads} not a multiple of kv_heads "
                         f"{kvh}")
    rep = heads // kvh
    int8 = k_scales is not None
    # [lanes, q_len, H, hd] → [lanes, H*q_len, hd]: row = h*q_len + qi,
    # so each kv-head group's rows are contiguous in the kernel.
    qt = q.transpose(0, 2, 1, 3).reshape(lanes, heads * q_len, hd)
    kf = k_pool.reshape(nb, bs, kvh * hd)
    vf = v_pool.reshape(nb, bs, kvh * hd)
    in_specs = [
        pl.BlockSpec((1, heads * q_len, hd),
                     lambda i, j, tbl, lens: (i, 0, 0)),
        pl.BlockSpec((1, bs, kvh * hd),
                     lambda i, j, tbl, lens: (tbl[i, j], 0, 0)),
        pl.BlockSpec((1, bs, kvh * hd),
                     lambda i, j, tbl, lens: (tbl[i, j], 0, 0)),
    ]
    args = [table, lengths.astype(jnp.int32), qt, kf, vf]
    if int8:
        in_specs += [
            pl.BlockSpec((1, bs, kvh),
                         lambda i, j, tbl, lens: (tbl[i, j], 0, 0)),
            pl.BlockSpec((1, bs, kvh),
                         lambda i, j, tbl, lens: (tbl[i, j], 0, 0)),
        ]
        args += [k_scales, v_scales]
    out = pl.pallas_call(
        functools.partial(
            _paged_attn_kernel, bs=bs, kvh=kvh, rep=rep, q_len=q_len,
            hd=hd, scale=hd ** -0.5, int8=int8),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(lanes, n_blk),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, heads * q_len, hd),
                                   lambda i, j, tbl, lens: (i, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((heads * q_len, 1), jnp.float32),
                pltpu.VMEM((heads * q_len, 1), jnp.float32),
                pltpu.VMEM((heads * q_len, hd), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((lanes, heads * q_len, hd),
                                       q.dtype),
        interpret=interpret,
    )(*args)
    return out.reshape(lanes, heads, q_len, hd).transpose(0, 2, 1, 3)


# ---------------------------------------------------------------------------
# RMSNorm
# ---------------------------------------------------------------------------


def rms_norm_reference(x, scale, *, epsilon=1e-5):
    """Pure-jax oracle (matches ``models.layers.RMSNorm`` numerics)."""
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    y = x32 * jax.lax.rsqrt(var + epsilon)
    return (y * scale.astype(jnp.float32)).astype(x.dtype)


def _rmsnorm_fwd_kernel(x_ref, s_ref, y_ref, r_ref, *, epsilon):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    r = jax.lax.rsqrt(var + epsilon)
    y_ref[:] = (x * r * s_ref[:].astype(jnp.float32)).astype(y_ref.dtype)
    r_ref[:] = r


def _rmsnorm_bwd_kernel(x_ref, s_ref, r_ref, g_ref, dx_ref):
    # y = x·r·s with r = rsqrt(mean x² + eps):
    #   dx = r·(g·s) − x · r³ · mean((g·s)·x)
    x = x_ref[:].astype(jnp.float32)
    gs = g_ref[:].astype(jnp.float32) * s_ref[:].astype(jnp.float32)
    r = r_ref[:]
    c = jnp.mean(gs * x, axis=-1, keepdims=True)
    dx = r * gs - x * (r * r * r) * c
    dx_ref[:] = dx.astype(dx_ref.dtype)


def _rmsnorm_rows(n_rows: int) -> int:
    return min(256, max(8, n_rows))


def _rmsnorm_fwd_call(x2, s2, *, epsilon, interpret):
    n, d = x2.shape
    bn = _rmsnorm_rows(n)
    return pl.pallas_call(
        functools.partial(_rmsnorm_fwd_kernel, epsilon=epsilon),
        grid=(pl.cdiv(n, bn),),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, d), x2.dtype),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x2, s2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _rms_norm_pallas(x2, s2, epsilon, interpret):
    y, _ = _rmsnorm_fwd_call(x2, s2, epsilon=epsilon, interpret=interpret)
    return y


def _rms_norm_pallas_fwd(x2, s2, epsilon, interpret):
    y, r = _rmsnorm_fwd_call(x2, s2, epsilon=epsilon, interpret=interpret)
    return y, (x2, s2, r)


def _rms_norm_pallas_bwd(epsilon, interpret, res, g):
    x2, s2, r = res
    n, d = x2.shape
    bn = _rmsnorm_rows(n)
    dx = pl.pallas_call(
        _rmsnorm_bwd_kernel,
        grid=(pl.cdiv(n, bn),),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bn, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), x2.dtype),
        interpret=interpret,
    )(x2, s2, r, g)
    # dscale_j = Σ_rows g_ij · x_ij · r_i — one dense reduction; XLA emits
    # the optimal column-sum, no kernel needed.
    ds = jnp.einsum(
        "nd,nd->d",
        g.astype(jnp.float32),
        x2.astype(jnp.float32) * r,
    ).astype(s2.dtype)
    return dx, ds[None, :]


_rms_norm_pallas.defvjp(_rms_norm_pallas_fwd, _rms_norm_pallas_bwd)


def rms_norm(x, scale, *, epsilon: float = 1e-5,
             use_pallas: Optional[bool] = None,
             interpret: bool = False):
    """Fused RMSNorm. ``x``: [..., D]; ``scale``: [D]."""
    if not _use_pallas(use_pallas):
        return rms_norm_reference(x, scale, epsilon=epsilon)
    d = x.shape[-1]
    x2 = x.reshape(-1, d)
    y = _rms_norm_pallas(x2, scale.reshape(1, d), epsilon, interpret)
    return y.reshape(x.shape)


# ---------------------------------------------------------------------------
# Fused softmax cross-entropy (integer labels)
# ---------------------------------------------------------------------------


def cross_entropy_reference(logits, labels):
    """Per-example CE via the standard log-softmax (the memory-hungry path)."""
    logits = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    ll = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return lse - ll


def _ce_block_cols(v: int) -> int:
    return min(2048, max(128, v))


def _ce_fwd_kernel(logits_ref, labels_ref, loss_ref, lse_ref,
                   m_ref, l_ref, ll_ref, *, vocab, block_v):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        l_ref[:] = jnp.zeros_like(l_ref)
        ll_ref[:] = jnp.zeros_like(ll_ref)

    block = logits_ref[:].astype(jnp.float32)
    bn, bv = block.shape
    cols = j * block_v + jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
    block = jnp.where(cols < vocab, block, _NEG)

    m_prev = m_ref[:]
    m_new = jnp.maximum(m_prev, jnp.max(block, axis=-1, keepdims=True))
    l_ref[:] = (l_ref[:] * jnp.exp(m_prev - m_new)
                + jnp.sum(jnp.exp(block - m_new), axis=-1, keepdims=True))
    m_ref[:] = m_new
    hit = cols == labels_ref[:]
    ll_ref[:] += jnp.sum(jnp.where(hit, block, 0.0), axis=-1, keepdims=True)

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        lse = m_ref[:] + jnp.log(l_ref[:])
        lse_ref[:] = lse
        loss_ref[:] = lse - ll_ref[:]


def _ce_bwd_kernel(logits_ref, labels_ref, lse_ref, g_ref, dlogits_ref,
                   *, vocab, block_v):
    j = pl.program_id(1)
    block = logits_ref[:].astype(jnp.float32)
    bn, bv = block.shape
    cols = j * block_v + jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
    p = jnp.exp(block - lse_ref[:])
    hit = (cols == labels_ref[:]).astype(jnp.float32)
    d = (p - hit) * g_ref[:]
    dlogits_ref[:] = jnp.where(
        cols < vocab, d, 0.0).astype(dlogits_ref.dtype)


def _ce_specs(n, v, bn, bv):
    return dict(
        grid=(pl.cdiv(n, bn), pl.cdiv(v, bv)),
        in_specs=[
            pl.BlockSpec((bn, bv), lambda i, j: (i, j)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
        ],
    )


def _ce_rows(n: int) -> int:
    return min(256, max(8, n))


def _ce_fwd(logits, labels2, *, interpret):
    from jax.experimental.pallas import tpu as pltpu

    n, v = logits.shape
    bn, bv = _ce_rows(n), _ce_block_cols(v)
    sp = _ce_specs(n, v, bn, bv)
    loss, lse = pl.pallas_call(
        functools.partial(_ce_fwd_kernel, vocab=v, block_v=bv),
        grid=sp["grid"],
        in_specs=sp["in_specs"],
        out_specs=[
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bn, 1), jnp.float32),
            pltpu.VMEM((bn, 1), jnp.float32),
            pltpu.VMEM((bn, 1), jnp.float32),
        ],
        interpret=interpret,
    )(logits, labels2)
    return loss, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _cross_entropy_pallas(logits, labels2, interpret):
    loss, _ = _ce_fwd(logits, labels2, interpret=interpret)
    return loss[:, 0]


def _cross_entropy_pallas_fwd(logits, labels2, interpret):
    loss, lse = _ce_fwd(logits, labels2, interpret=interpret)
    return loss[:, 0], (logits, labels2, lse)


def _cross_entropy_pallas_bwd(interpret, res, g):
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    logits, labels2, lse = res
    n, v = logits.shape
    bn, bv = _ce_rows(n), _ce_block_cols(v)
    sp = _ce_specs(n, v, bn, bv)
    dlogits = pl.pallas_call(
        functools.partial(_ce_bwd_kernel, vocab=v, block_v=bv),
        grid=sp["grid"],
        in_specs=sp["in_specs"] + [
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bn, bv), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((n, v), logits.dtype),
        interpret=interpret,
    )(logits, labels2, lse, g[:, None].astype(jnp.float32))
    return dlogits, None


_cross_entropy_pallas.defvjp(_cross_entropy_pallas_fwd,
                             _cross_entropy_pallas_bwd)


def fused_cross_entropy(logits, labels, *,
                        use_pallas: Optional[bool] = None,
                        interpret: bool = False):
    """Per-example softmax CE with integer labels, never materializing
    softmax in HBM.  ``logits``: [..., V]; ``labels``: int [...]."""
    if not _use_pallas(use_pallas):
        return cross_entropy_reference(logits, labels)
    v = logits.shape[-1]
    flat = logits.reshape(-1, v)
    lab = labels.reshape(-1, 1).astype(jnp.int32)
    out = _cross_entropy_pallas(flat, lab, interpret)
    return out.reshape(labels.shape)
