"""Hot ops: attention kernels and fused layers.

The reference has no custom kernels — its hot ops are cuDNN/NCCL inside the
TF runtime.  On TPU the equivalents are pallas kernels (flash/splash
attention, grouped matmul) plus XLA fusion for everything else.  Every op
here has a pure-jax reference implementation (used on CPU test meshes and as
the numerics oracle) and a TPU fast path.
"""

from tensorflow_train_distributed_tpu.ops.attention import (  # noqa: F401
    dot_product_attention,
    multihead_attention_kernel,
)
from tensorflow_train_distributed_tpu.ops.pallas_kernels import (  # noqa: F401
    fused_cross_entropy,
    rms_norm,
)
from tensorflow_train_distributed_tpu.ops.embedding import (  # noqa: F401
    EmbeddingCollection,
    FeatureSpec,
    TableSpec,
    sharded_lookup,
)
