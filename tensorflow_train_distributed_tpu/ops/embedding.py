"""Sharded embedding tables: the TPUEmbedding answer.

Reference capability being matched: ``TPUEmbeddingV2``/``V3``
(``tensorflow/python/tpu/tpu_embedding_v3.py:498``, ``tpu_embedding_v2.py:76``)
— large embedding tables sharded across TPU devices, looked up by integer
feature ids, with per-feature combiners (sum/mean/sqrtn) and table sharing
between features.  The reference reaches SparseCore hardware; here tables
live in HBM sharded over a mesh axis and lookups ride ICI collectives.

Two lookup paths, same numerics:

- **shard_map path** (TPU-native, used when the ambient mesh shards the
  table axis): the table is mod-the-mesh row-sharded; every device clips the
  global ids into its own row range, does a *local* ``take`` (rows it does
  not own contribute zeros), and one ``psum`` over the table axis sums the
  one non-zero contribution per id.  No device ever materializes the full
  table or an all-gathered id-row matrix — traffic is O(batch × dim), the
  activation size, independent of vocab.
- **GSPMD path** (fallback, also the numerics oracle in tests): a plain
  ``jnp.take`` with logical-axis constraints; XLA partitions the gather.

Multi-valent features are [B, L] id matrices with negative padding; the
combiner reduces L.  Gradients flow through both paths (``psum`` and
``take`` are linear), giving the sparse-gradient-allreduce semantics of the
reference's embedding optimizer without any custom backward pass.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
from tensorflow_train_distributed_tpu.runtime import compat
from tensorflow_train_distributed_tpu.runtime.compat import shard_map
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class TableSpec:
    """One embedding table (reference: ``tpu_embedding_v2_utils.TableConfig``)."""

    name: str
    vocab_size: int
    dim: int
    initializer_stddev: float = 1.0


@dataclasses.dataclass(frozen=True)
class FeatureSpec:
    """One input feature routed to a table (ref: ``FeatureConfig``).

    Several features may name the same table — that is table sharing (e.g.
    query-id and doc-id over one id space).  ``combiner`` reduces the valence
    dim of [B, L] multi-valent inputs; scalar [B] inputs skip combining.
    """

    name: str
    table: str
    combiner: str = "mean"  # "sum" | "mean" | "sqrtn"


def _combine(rows: jax.Array, valid: jax.Array, combiner: str) -> jax.Array:
    """Reduce the valence dim. rows: [B, L, D]; valid: [B, L] bool."""
    w = valid.astype(rows.dtype)
    total = jnp.einsum("bld,bl->bd", rows, w)
    if combiner == "sum":
        return total
    count = jnp.maximum(jnp.sum(w, axis=-1, keepdims=True), 1.0)
    if combiner == "mean":
        return total / count
    if combiner == "sqrtn":
        return total / jnp.sqrt(count)
    raise ValueError(f"Unknown combiner {combiner!r}")


def _local_take(local_table: jax.Array, ids: jax.Array, axis: str):
    """Per-shard lookup body: rows this shard owns, zeros elsewhere.

    ``local_table`` is this device's row block of the mod-sharded table;
    global row r lives on shard r // rows_per_shard at local row
    r % rows_per_shard.
    """
    rows_per_shard = local_table.shape[0]
    shard = jax.lax.axis_index(axis)
    local_ids = ids - shard * rows_per_shard
    owned = (local_ids >= 0) & (local_ids < rows_per_shard)
    rows = jnp.take(local_table, jnp.clip(local_ids, 0, rows_per_shard - 1),
                    axis=0)
    return jnp.where(owned[..., None], rows, 0)


def sharded_lookup(
    table: jax.Array,
    ids: jax.Array,
    *,
    mesh=None,
    table_axis: str = "tensor",
) -> jax.Array:
    """Embedding rows for ``ids`` from a row-sharded ``table``.

    ``table``: [vocab, dim] sharded over ``table_axis`` (rows).  ``ids``: any
    integer shape; out-of-range/negative ids return zero rows.  When ``mesh``
    is None or doesn't shard ``table_axis``, falls back to masked
    ``jnp.take`` (GSPMD partitions it).
    """
    valid = (ids >= 0) & (ids < table.shape[0])
    safe = jnp.where(valid, ids, 0)
    if mesh is None or mesh.shape.get(table_axis, 1) <= 1:
        rows = jnp.take(table, safe, axis=0)
        return jnp.where(valid[..., None], rows, 0)
    if table.shape[0] % mesh.shape[table_axis]:
        raise ValueError(
            f"vocab {table.shape[0]} not divisible by mesh axis "
            f"{table_axis}={mesh.shape[table_axis]}")

    def body(local_table, ids_rep, valid_rep):
        rows = _local_take(local_table, ids_rep, table_axis)
        rows = jax.lax.psum(rows, table_axis)
        return jnp.where(valid_rep[..., None], rows, 0)

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(P(table_axis, None), P(), P()),
        out_specs=P(),
        check_vma=False,
    )(table, safe, valid)


def _ambient_mesh(table_axis: str):
    mesh = compat.get_abstract_mesh()
    if mesh is None or mesh.empty or mesh.shape.get(table_axis, 1) <= 1:
        return None
    return mesh


class EmbeddingCollection(nn.Module):
    """Feature→table embedding bank (reference: ``TPUEmbedding`` API shape).

    ``__call__`` takes ``{feature_name: ids}`` ([B] scalar or [B, L]
    multi-valent with negative padding) and returns ``{feature_name:
    [B, dim]}``.  Tables are mod-row-sharded over ``table_axis`` when the
    ambient mesh (bound by the Trainer via ``jax.set_mesh``) has it.
    """

    tables: Sequence[TableSpec]
    features: Sequence[FeatureSpec]
    table_axis: str = "tensor"
    dtype: Any = jnp.float32

    def setup(self):
        by_name = {t.name: t for t in self.tables}
        if len(by_name) != len(self.tables):
            raise ValueError("Duplicate table names")
        for f in self.features:
            if f.table not in by_name:
                raise ValueError(
                    f"Feature {f.name!r} routes to unknown table {f.table!r}")
        params = {}
        for t in self.tables:
            params[t.name] = self.param(
                t.name,
                nn.with_logical_partitioning(
                    nn.initializers.normal(stddev=t.initializer_stddev),
                    ("vocab", "embed")),
                (t.vocab_size, t.dim),
            )
        self._params = params
        self._specs = by_name

    def __call__(self, feature_ids: Mapping[str, jax.Array],
                 ) -> dict[str, jax.Array]:
        mesh = _ambient_mesh(self.table_axis)
        out = {}
        for f in self.features:
            if f.name not in feature_ids:
                continue
            ids = feature_ids[f.name]
            table = self._params[f.table].astype(self.dtype)
            scalar = ids.ndim == 1
            ids2d = ids[:, None] if scalar else ids
            rows = sharded_lookup(table, ids2d, mesh=mesh,
                                  table_axis=self.table_axis)
            if scalar:
                out[f.name] = rows[:, 0, :]
            else:
                valid = (ids2d >= 0) & (ids2d < table.shape[0])
                out[f.name] = _combine(rows, valid, f.combiner)
        return out
