"""Train state: the SPMD replacement for the reference's distributed values.

The reference materializes training state as distributed variable wrappers —
``MirroredVariable``/``SyncOnReadVariable`` (``distribute/values.py``),
``TPUVariableMixin`` (``tpu_values.py``), packed vars
(``packed_distributed_variable.py``) — created under ``strategy.scope()``.
In SPMD-JAX, state is one pytree of *global* jax.Arrays whose NamedShardings
say how they live on the mesh; there is nothing to wrap.  ``TrainState``
bundles the pytree; sharding comes from ``parallel.sharding``.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import optax
from flax import struct

from tensorflow_train_distributed_tpu.training.mixed_precision import (
    LossScaleState,
)


class TrainState(struct.PyTreeNode):
    """Step counter, params, mutable model collections, optimizer state.

    ``model_state`` carries non-trainable collections (e.g. ResNet
    ``batch_stats`` — the analog of the reference's sync-on-read BN
    variables).  ``loss_scale`` is present only under float16 policy.
    ``grad_residual`` is present only under quantized gradient
    collectives (``TrainerConfig.grad_quant``): the per-replica
    error-feedback residual, one f32 leaf per param leaf with a leading
    data-axis dim of the mesh's dp degree (sharded ``P("data")``, so
    per-device it costs one f32 param copy).  Checkpoints saved before
    this field existed restore with residuals zero-initialized
    (``training.checkpoint`` handles the compat).
    """

    step: jax.Array
    params: Any
    model_state: Any
    opt_state: optax.OptState
    loss_scale: Optional[LossScaleState] = None
    grad_residual: Any = None

    @classmethod
    def create(cls, *, params, model_state=None, tx: optax.GradientTransformation,
               loss_scale: Optional[LossScaleState] = None,
               grad_residual: Any = None) -> "TrainState":
        import jax.numpy as jnp

        return cls(
            step=jnp.int32(0),
            params=params,
            model_state={} if model_state is None else model_state,
            opt_state=tx.init(params),
            loss_scale=loss_scale,
            grad_residual=grad_residual,
        )

    def num_params(self) -> int:
        return sum(x.size for x in jax.tree.leaves(self.params))
