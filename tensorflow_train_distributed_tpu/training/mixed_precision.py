"""Mixed-precision policy + dynamic loss scaling.

Reference surface: Keras ``Policy`` (``tf_keras/src/mixed_precision/
policy.py:32``) and ``LossScaleOptimizer`` (``loss_scale_optimizer.py:587``).
On TPU the native story is simpler: bfloat16 has fp32's exponent range, so
the standard policy is params/optimizer in float32, compute in bfloat16, and
**no loss scaling needed**.  Dynamic loss scaling is still provided for
float16 parity (and numerics experiments): scale the loss, unscale grads,
skip the update and halve the scale on non-finite grads, double after
``growth_interval`` good steps — the same contract as the reference's
``DynamicLossScale``, expressed as pure functions over a small state.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import struct


@dataclasses.dataclass(frozen=True)
class Policy:
    """Dtype policy: where params live, where compute happens."""

    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    output_dtype: Any = jnp.float32
    # Loss scaling: None → disabled (the right default for bf16 on TPU).
    initial_loss_scale: Optional[float] = None
    growth_interval: int = 2000
    scale_factor: float = 2.0

    @classmethod
    def from_name(cls, name: str) -> "Policy":
        """Named policies matching the Keras policy strings."""
        if name in ("float32", "fp32"):
            return cls(compute_dtype=jnp.float32)
        if name in ("bfloat16", "mixed_bfloat16", "bf16"):
            return cls(compute_dtype=jnp.bfloat16)
        if name in ("float16", "mixed_float16", "fp16"):
            return cls(compute_dtype=jnp.float16, initial_loss_scale=2.0**15)
        raise ValueError(f"Unknown precision policy {name!r}")

    @property
    def uses_loss_scaling(self) -> bool:
        return self.initial_loss_scale is not None

    def cast_to_compute(self, tree):
        return jax.tree.map(
            lambda x: x.astype(self.compute_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x,
            tree,
        )

    def cast_to_output(self, tree):
        return jax.tree.map(
            lambda x: x.astype(self.output_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x,
            tree,
        )


class LossScaleState(struct.PyTreeNode):
    """Dynamic loss-scale state (scale, consecutive-finite counter)."""

    scale: jax.Array
    good_steps: jax.Array

    @classmethod
    def create(cls, policy: Policy) -> Optional["LossScaleState"]:
        if not policy.uses_loss_scaling:
            return None
        return cls(
            scale=jnp.float32(policy.initial_loss_scale),
            good_steps=jnp.int32(0),
        )


def scale_loss(loss: jax.Array, ls: Optional[LossScaleState]) -> jax.Array:
    return loss if ls is None else loss * ls.scale.astype(loss.dtype)


def unscale_grads(grads, ls: Optional[LossScaleState]):
    if ls is None:
        return grads
    inv = (1.0 / ls.scale).astype(jnp.float32)
    return jax.tree.map(lambda g: (g.astype(jnp.float32) * inv), grads)


def grads_finite(grads) -> jax.Array:
    leaves = jax.tree.leaves(grads)
    return jnp.stack([jnp.all(jnp.isfinite(g)) for g in leaves]).all()


def update_loss_scale(
    ls: Optional[LossScaleState], finite: jax.Array, policy: Policy
) -> Optional[LossScaleState]:
    """Halve on overflow; double after ``growth_interval`` clean steps."""
    if ls is None:
        return None
    grow = ls.good_steps + 1 >= policy.growth_interval
    new_scale = jnp.where(
        finite,
        jnp.where(grow, ls.scale * policy.scale_factor, ls.scale),
        ls.scale / policy.scale_factor,
    )
    new_scale = jnp.maximum(new_scale, 1.0)
    new_good = jnp.where(finite & ~grow, ls.good_steps + 1, 0)
    return LossScaleState(scale=new_scale, good_steps=new_good)
