"""Callback seam for the training loop.

Mirrors the Keras callback contract the reference trains through
(``tf_keras/src/callbacks.py``: ``CallbackList:202``, ``History:1189``,
``EarlyStopping:2002``, ``TensorBoard:2371``) with the hooks the SPMD loop
actually has: train begin/end, step end (post-metrics), epoch end, and
checkpoint events.  Chief-only side effects are each callback's own
responsibility via ``jax.process_index() == 0`` — the analog of the
reference's ``is_chief`` writer gating (``multi_worker_util.py:108``).
"""

from __future__ import annotations

import json
import logging
import sys
import time
from typing import Any, Mapping, Optional

import jax

logger = logging.getLogger(__name__)


class Callback:
    """Base class; all hooks optional."""

    def set_trainer(self, trainer):
        self.trainer = trainer

    def on_train_begin(self, state):
        pass

    def on_step_end(self, step: int, metrics: Mapping[str, float]) -> Optional[bool]:
        """Return True to request an early stop."""

    def on_epoch_end(self, epoch: int, metrics: Mapping[str, float]) -> Optional[bool]:
        pass

    def on_eval_begin(self):
        """Mid-training evaluation window opens (no step heartbeats)."""

    def on_eval_end(self):
        pass

    def transform_state(self, state):
        """Return a replacement TrainState, or None to leave it alone.

        Called between jitted steps after metric/eval dispatch — the ONE
        sanctioned seam for callbacks that must mutate training state
        (dynamic LR, hyperparameter schedules keyed on metrics).  The
        replacement must preserve tree structure, shapes and shardings;
        the next step runs on it unchanged (no recompile: same avals).
        """
        return None

    def on_train_end(self, state):
        pass


class CallbackList:
    def __init__(self, callbacks, trainer=None):
        self.callbacks = list(callbacks)
        if trainer is not None:
            for c in self.callbacks:
                c.set_trainer(trainer)

    def train_begin(self, state):
        for c in self.callbacks:
            c.on_train_begin(state)

    def step_end(self, step, metrics) -> bool:
        stop = False
        for c in self.callbacks:
            stop |= bool(c.on_step_end(step, metrics))
        return stop

    def epoch_end(self, epoch, metrics) -> bool:
        stop = False
        for c in self.callbacks:
            stop |= bool(c.on_epoch_end(epoch, metrics))
        return stop

    def eval_begin(self):
        for c in self.callbacks:
            c.on_eval_begin()

    def eval_end(self):
        for c in self.callbacks:
            c.on_eval_end()

    def apply_state_transforms(self, state):
        # getattr: callbacks are duck-typed (PreemptionCheckpointCallback
        # and user callbacks need not subclass Callback).
        for c in self.callbacks:
            fn = getattr(c, "transform_state", None)
            out = fn(state) if fn is not None else None
            if out is not None:
                state = out
        return state

    def train_end(self, state):
        for c in self.callbacks:
            c.on_train_end(state)


class History(Callback):
    """Accumulates per-log-interval metrics (Keras ``History`` analog)."""

    def __init__(self):
        self.steps: list[int] = []
        self.history: dict[str, list[float]] = {}

    def on_step_end(self, step, metrics):
        self.steps.append(step)
        for k, v in metrics.items():
            self.history.setdefault(k, []).append(float(v))


class StepRateTracker:
    """Wall-time per optimizer step, burst-aware.

    ``Trainer.fit`` drains metrics in ``log_every`` windows, so callbacks
    see bursts of ``on_step_end`` calls microseconds apart — the naive
    consecutive-call delta is garbage (µs inside a burst, the whole window
    attributed to one step at its edge).  A burst shares one drain
    timestamp, which is when the window's last step finished; the honest
    rate is therefore (drain_t − prev_drain_t) / (drain_step −
    prev_drain_step), computed when a new burst begins.
    """

    BURST_GAP_S = 5e-4

    def __init__(self):
        self._prev = None   # (t, step) at the end of the last closed burst
        self._cur = None    # (t, step) latest call in the current burst
        self.last_ms_per_step: Optional[float] = None

    def update(self, step: int) -> Optional[float]:
        """Record a step report; returns a fresh ms/step when a window closes."""
        now = time.perf_counter()
        emitted = None
        if self._cur is not None and now - self._cur[0] > self.BURST_GAP_S:
            t1, s1 = self._cur
            if self._prev is not None and s1 > self._prev[1]:
                emitted = (t1 - self._prev[0]) / (s1 - self._prev[1]) * 1e3
                self.last_ms_per_step = emitted
            self._prev = (t1, s1)
        self._cur = (now, step)
        return emitted


class ProgressLogger(Callback):
    """Stdout progress lines with step time + throughput (chief only)."""

    def __init__(self, examples_per_step: Optional[int] = None):
        self.examples_per_step = examples_per_step
        self._tracker = StepRateTracker()

    def on_step_end(self, step, metrics):
        if jax.process_index() != 0:
            return
        self._tracker.update(step)
        line = f"step {step}"
        ms = self._tracker.last_ms_per_step
        if ms is not None:
            line += f" | {ms:.1f} ms/step"
            if self.examples_per_step:
                line += f" | {self.examples_per_step / (ms / 1e3):,.0f} ex/s"
        for k, v in metrics.items():
            line += f" | {k}={float(v):.4f}"
        print(line, flush=True)


class JsonlLogger(Callback):
    """One JSON object per log event — the machine-readable metric stream
    (replaces tf.summary scalar writing for headless runs); chief only."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._fh = None

    def on_train_begin(self, state):
        if jax.process_index() == 0 and self.path:
            self._fh = open(self.path, "a")

    def on_step_end(self, step, metrics):
        if jax.process_index() != 0:
            return
        rec = {"step": step, **{k: float(v) for k, v in metrics.items()},
               "ts": time.time()}
        out = self._fh or sys.stdout
        out.write(json.dumps(rec) + "\n")
        out.flush()

    def on_train_end(self, state):
        if self._fh:
            self._fh.close()
            self._fh = None


class EarlyStopping(Callback):
    """Stop when ``monitor`` hasn't improved for ``patience`` events
    (Keras ``EarlyStopping:2002`` analog, evaluated per log interval)."""

    def __init__(self, monitor: str = "loss", patience: int = 10,
                 min_delta: float = 0.0, mode: str = "min"):
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be min|max, got {mode!r}")
        self.monitor, self.patience = monitor, patience
        self.min_delta, self.mode = min_delta, mode
        self.best: Optional[float] = None
        self.wait = 0

    def on_step_end(self, step, metrics):
        if self.monitor not in metrics:
            return
        cur = float(metrics[self.monitor])
        better = (
            self.best is None
            or (self.mode == "min" and cur < self.best - self.min_delta)
            or (self.mode == "max" and cur > self.best + self.min_delta)
        )
        if better:
            self.best, self.wait = cur, 0
            return
        self.wait += 1
        if self.wait >= self.patience:
            logger.info("EarlyStopping: %s plateaued at %s", self.monitor,
                        self.best)
            return True


def set_injected_hyperparam(opt_state, name: str, value):
    """Functionally set an ``optax.inject_hyperparams`` hyperparameter.

    Walks the (possibly chained/nested) optimizer state for
    ``InjectHyperparamsState``-shaped nodes whose ``hyperparams`` dict
    carries ``name`` and rewrites the entry, preserving dtype and
    sharding (replicated scalar).  Returns ``(new_opt_state, n_set)`` —
    callers decide whether ``n_set == 0`` is an error.
    """
    import jax.numpy as jnp

    n_set = 0

    def rec(node):
        nonlocal n_set
        hp = getattr(node, "hyperparams", None)
        if isinstance(hp, dict) and name in hp:
            n_set += 1
            old = hp[name]
            new = jnp.asarray(value, dtype=old.dtype)
            if isinstance(old, jax.Array) and hasattr(old, "sharding"):
                new = jax.device_put(new, old.sharding)
            return node._replace(hyperparams={**hp, name: new})
        if isinstance(node, tuple) and hasattr(node, "_fields"):
            return type(node)(*(rec(getattr(node, f))
                                for f in node._fields))
        if isinstance(node, (tuple, list)):
            return type(node)(rec(x) for x in node)
        if isinstance(node, dict):
            # dict-valued state nodes (optax.multi_transform inner_states,
            # masked wrappers) — without this branch an inject_hyperparams
            # nested under them is unreachable.  type(node) preserves
            # dict subclasses (OrderedDict params → same treedef).
            return type(node)((k, rec(v)) for k, v in node.items())
        return node

    return rec(opt_state), n_set


def get_injected_hyperparam(opt_state, name: str):
    """First ``inject_hyperparams`` entry named ``name``, or None."""
    hp = getattr(opt_state, "hyperparams", None)
    if isinstance(hp, dict) and name in hp:
        return hp[name]
    if isinstance(opt_state, tuple):
        fields = (getattr(opt_state, f) for f in opt_state._fields) \
            if hasattr(opt_state, "_fields") else iter(opt_state)
        for sub in fields:
            found = get_injected_hyperparam(sub, name)
            if found is not None:
                return found
    if isinstance(opt_state, dict):
        # Mirror the setter: descend through dict-valued state nodes
        # (multi_transform inner_states, masked wrappers).
        for sub in opt_state.values():
            found = get_injected_hyperparam(sub, name)
            if found is not None:
                return found
    return None


class ReduceLROnPlateau(Callback):
    """Drop the learning rate when ``monitor`` stops improving (Keras
    ``ReduceLROnPlateau`` analog, ``tf_keras/src/callbacks.py:2915``).

    Needs the optimizer built with ``optax.inject_hyperparams`` so the
    LR lives in optimizer STATE (the CLI's ``--reduce-lr-factor`` does
    this); the reduction is then a functional state rewrite through the
    ``transform_state`` seam — no recompile, checkpoint/resume carries
    the reduced LR automatically because it IS state.
    """

    def __init__(self, monitor: str = "val_loss", factor: float = 0.1,
                 patience: int = 10, min_delta: float = 1e-4,
                 cooldown: int = 0, min_lr: float = 0.0,
                 mode: str = "min"):
        if not 0.0 < factor < 1.0:
            raise ValueError(f"factor must be in (0, 1), got {factor}")
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be min|max, got {mode!r}")
        self.monitor, self.factor, self.patience = monitor, factor, patience
        self.min_delta, self.cooldown = min_delta, cooldown
        self.min_lr, self.mode = min_lr, mode
        self.best: Optional[float] = None
        self.wait = 0
        self.cooldown_left = 0
        # COUNT, not flag: step events flush in log_every windows, so
        # several patience expirations can precede one transform_state —
        # each must apply its factor.
        self._reductions_pending = 0

    def on_train_begin(self, state):
        if get_injected_hyperparam(state.opt_state,
                                   "learning_rate") is None:
            raise ValueError(
                "ReduceLROnPlateau needs the optimizer wrapped with "
                "optax.inject_hyperparams(...)(learning_rate=...) so the "
                "LR lives in optimizer state (CLI: --reduce-lr-factor "
                "builds it that way); none found in opt_state")

    def on_step_end(self, step, metrics):
        if self.monitor not in metrics:
            return
        cur = float(metrics[self.monitor])
        better = (
            self.best is None
            or (self.mode == "min" and cur < self.best - self.min_delta)
            or (self.mode == "max" and cur > self.best + self.min_delta)
        )
        if self.cooldown_left > 0:
            self.cooldown_left -= 1
            self.wait = 0
        if better:
            self.best, self.wait = cur, 0
            return
        if self.cooldown_left > 0:
            return
        self.wait += 1
        if self.wait >= self.patience:
            self._reductions_pending += 1
            self.wait = 0
            self.cooldown_left = self.cooldown

    def transform_state(self, state):
        if not self._reductions_pending:
            return None
        pending, self._reductions_pending = self._reductions_pending, 0
        old = get_injected_hyperparam(state.opt_state, "learning_rate")
        new_lr = max(float(old) * self.factor**pending, self.min_lr)
        if new_lr >= float(old):
            return None  # already at the floor
        new_opt, n_set = set_injected_hyperparam(state.opt_state,
                                                 "learning_rate", new_lr)
        if n_set == 0:  # guarded at train_begin; belt and braces
            return None
        logger.info("ReduceLROnPlateau: %s plateaued (best %.5g) — lr "
                    "%.3g → %.3g", self.monitor, self.best, float(old),
                    new_lr)
        return state.replace(opt_state=new_opt)


class BestCheckpoint(Callback):
    """Keep the best-``monitor`` checkpoint (Keras ``ModelCheckpoint``
    ``save_best_only=True`` analog, ``tf_keras/src/callbacks.py:1233``).

    Saves into its OWN directory (default ``<dir>/best``), separate from
    the trainer's periodic keep-N manager: rolling saves must never evict
    the best state, and the best save must never count against keep-N.

    Save timing: step metrics flush in ``log_every`` windows AFTER the
    window's last step executed — earlier states no longer exist (the
    step donates them).  So only the window's LAST metric event is a save
    candidate (its step IS the live state's step), saved through the
    ``transform_state`` seam where the current state is authoritative.
    "Best" therefore means best among flush boundaries; run with
    ``log_every=1`` (or monitor ``val_*`` events, which always carry the
    evaluated state) for per-step granularity.
    """

    def __init__(self, directory: str, monitor: str = "val_loss",
                 mode: str = "min", min_delta: float = 0.0):
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be min|max, got {mode!r}")
        from tensorflow_train_distributed_tpu.training.checkpoint import (
            CheckpointManager,
        )

        self.monitor, self.mode, self.min_delta = monitor, mode, min_delta
        self.best: Optional[float] = None
        self.best_step: Optional[int] = None
        self._candidate: Optional[float] = None
        self._mgr = CheckpointManager(directory, max_to_keep=1)

    def on_step_end(self, step, metrics):
        if self.monitor in metrics:
            # Last writer wins: within one flush window only the final
            # event's metric belongs to a state that still exists.
            self._candidate = float(metrics[self.monitor])

    def transform_state(self, state):
        if self._candidate is None:
            return None
        cur, self._candidate = self._candidate, None
        better = (
            self.best is None
            or (self.mode == "min" and cur < self.best - self.min_delta)
            or (self.mode == "max" and cur > self.best + self.min_delta)
        )
        if not better:
            return None
        if getattr(getattr(self, "trainer", None), "state_poisoned",
                   False):
            return None  # never immortalize a non-finite state
        step = int(state.step)
        self.best, self.best_step = cur, step
        self._mgr.save(step, state, force=True)
        logger.info("BestCheckpoint: %s=%.5g at step %d", self.monitor,
                    cur, step)
        return None  # observation only; the state itself is unchanged

    def on_train_end(self, state):
        self._mgr.wait_until_finished()

    def wait_until_finished(self):
        self._mgr.wait_until_finished()


class TerminateOnNaN(Callback):
    """Stop training when a monitored metric goes non-finite (Keras
    ``TerminateOnNaN`` analog, ``tf_keras/src/callbacks.py``)."""

    def __init__(self, monitor: str = "loss"):
        self.monitor = monitor

    def on_step_end(self, step, metrics):
        from tensorflow_train_distributed_tpu.runtime.debug import (
            is_finite_scalar,
        )

        if self.monitor in metrics and not is_finite_scalar(
                metrics[self.monitor]):
            logger.error("TerminateOnNaN: step %d %s=%r — stopping", step,
                         self.monitor, metrics[self.monitor])
            # Veto further checkpoint writes: the state is poisoned and must
            # not overwrite retained good saves.
            if getattr(self, "trainer", None) is not None:
                self.trainer.state_poisoned = True
            return True


class TensorBoardScalars(Callback):
    """Write scalars to TensorBoard event files via flax's writer.

    Same viewer the reference's ``TensorBoard`` callback feeds; import is
    lazy and failure-tolerant because the summary writer is an optional
    dependency surface.
    """

    def __init__(self, logdir: str):
        self.logdir = logdir
        self._writer = None

    def on_train_begin(self, state):
        if jax.process_index() != 0:
            return
        try:
            from flax.metrics import tensorboard

            self._writer = tensorboard.SummaryWriter(self.logdir)
        except Exception as e:  # no TB backend in env → degrade gracefully
            logger.warning("TensorBoard writer unavailable (%s); skipping", e)

    def on_step_end(self, step, metrics):
        if self._writer is None:
            return
        for k, v in metrics.items():
            self._writer.scalar(k, float(v), step)

    def on_train_end(self, state):
        if self._writer is not None:
            self._writer.flush()


class StallWatchdog(Callback):
    """Dump stacks and warn when no step completes for ``timeout_s``.

    The reference's ClusterCoordinator ships a hang watchdog
    (``coordinator/watchdog.py``: a daemon thread that periodically dumps
    all thread stacks when progress stalls); SPMD training hangs the same
    way in practice — a wedged collective, a dead host in the process
    group, an input pipeline deadlock.  This is the trainer-side analog:
    armed from ``on_train_begin``, petted by every completed step, barking
    (log + ``faulthandler`` stack dump to stderr) every ``timeout_s`` of
    silence.  Observability only — it never kills the run.
    """

    def __init__(self, timeout_s: float = 300.0):
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
        self.timeout_s = timeout_s
        self._stop = None
        self._last_beat = None
        self._paused = False
        self.stall_count = 0  # exposed for tests/metrics

    def _dump_stacks(self):
        # faulthandler needs a real fd; pytest capture / notebooks swap
        # sys.stderr for fd-less streams — fall back to the pure-Python
        # dump, and never let a dump failure kill the watchdog thread.
        import faulthandler
        import traceback

        try:
            faulthandler.dump_traceback(file=sys.stderr)
        except Exception:
            try:
                for tid, frame in sys._current_frames().items():
                    print(f"--- thread {tid} ---", file=sys.stderr)
                    traceback.print_stack(frame, file=sys.stderr)
            except Exception:
                pass

    def _loop(self):
        while not self._stop.wait(min(self.timeout_s / 4, 10.0)):
            if self._paused:
                continue
            if time.monotonic() - self._last_beat > self.timeout_s:
                self.stall_count += 1
                logger.warning(
                    "StallWatchdog: no training step completed in %.0f s "
                    "(stall #%d) — dumping thread stacks to stderr",
                    self.timeout_s, self.stall_count)
                self._dump_stacks()
                self._last_beat = time.monotonic()  # re-arm, don't spam

    def on_train_begin(self, state):
        import threading

        # monotonic: a wall-clock NTP step must neither fake a stall nor
        # mask a real one.
        self._last_beat = time.monotonic()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="stall-watchdog", daemon=True)
        self._thread.start()

    def on_step_end(self, step, metrics):
        self._last_beat = time.monotonic()

    def on_eval_begin(self):
        # Evaluation produces no step heartbeats; a long eval window is
        # not a stall.
        self._paused = True

    def on_eval_end(self):
        self._last_beat = time.monotonic()
        self._paused = False

    def on_train_end(self, state):
        if self._stop is not None:
            self._stop.set()
            self._thread.join(timeout=5)
            if not self._thread.is_alive():
                # Only forget the event once the thread is confirmed gone —
                # a loop blocked in a stack dump still reads self._stop.
                self._stop = None
