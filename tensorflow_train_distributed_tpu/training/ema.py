"""Exponential moving average of parameters (Polyak averaging).

The reference's Keras stack ships this as the `ExponentialMovingAverage`
optimizer wrapper / `tf.train.ExponentialMovingAverage` (average the
post-update variables each step; evaluate/export the averages).  The
TPU-native form keeps the running average INSIDE the jitted train step as
optimizer state — no per-step host round trip, checkpointed and sharded
exactly like the Adam moments (zero1 included), and the whole update is
one fused elementwise pass over the params.

Usage:

    tx = wrap_with_ema(optax.adamw(1e-3), decay=0.999)
    ...train as usual...
    eval_state = swap_ema_params(state)          # read-only view for
    trainer.evaluate(loader, eval_state)         # evaluate/predict/export

``wrap_with_ema`` appends the tracker LAST in the chain, so it sees the
final (clipped, scaled) updates and averages the exact post-update
parameters: ``ema_t = decay·ema_{t-1} + (1-decay)·params_t``, with
``ema_0 = params_0`` (the Keras init convention — no debias needed).
CLI: ``--ema-decay 0.999``.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import chex
import jax
import jax.numpy as jnp
import optax


class EmaParamsState(NamedTuple):
    """Optax state for :func:`ema_of_params` (found by tree search in
    :func:`find_ema_params`, so keep the class identity stable)."""

    ema: chex.ArrayTree
    count: chex.Array  # steps applied; informational


def ema_of_params(decay: float = 0.999) -> optax.GradientTransformation:
    """A transform that is the identity on updates but maintains an EMA
    of the POST-update params in its state.

    Must run LAST in the chain (after clipping/optimizer), so the updates
    it sees are exactly what ``apply_updates`` will add; place it via
    :func:`wrap_with_ema` to get this right by construction.
    """
    if not 0.0 < decay < 1.0:
        raise ValueError(f"decay must be in (0, 1), got {decay}")

    def init_fn(params):
        return EmaParamsState(
            ema=jax.tree.map(jnp.asarray, params),
            count=jnp.zeros((), jnp.int32))

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError(
                "ema_of_params needs params; call optax update with "
                "params= (the Trainer does)")
        post = optax.apply_updates(params, updates)
        ema = jax.tree.map(
            lambda e, p: (decay * e + (1.0 - decay)
                          * p.astype(e.dtype)).astype(e.dtype),
            state.ema, post)
        return updates, EmaParamsState(ema=ema, count=state.count + 1)

    return optax.GradientTransformation(init_fn, update_fn)


def wrap_with_ema(tx: optax.GradientTransformation,
                  decay: float = 0.999) -> optax.GradientTransformation:
    """``optax.chain(tx, ema_of_params(decay))`` — the tracker last, so
    it averages the true post-update parameters."""
    return optax.chain(tx, ema_of_params(decay))


def find_ema_params(opt_state) -> Optional[chex.ArrayTree]:
    """The EMA param tree inside an optimizer state, or None.

    Walks tuples/lists/dicts (``optax.chain``, ``inject_hyperparams``,
    ``multi_transform`` nest states in all three) and returns the FIRST
    EmaParamsState's averages.
    """
    def rec(node):
        if isinstance(node, EmaParamsState):
            return node.ema
        if isinstance(node, (tuple, list)):
            for child in node:
                got = rec(child)
                if got is not None:
                    return got
        elif isinstance(node, dict):
            for child in node.values():
                got = rec(child)
                if got is not None:
                    return got
        return None

    return rec(opt_state)


def swap_ema_params(state):
    """A read-only view of a TrainState with params replaced by their
    EMA (for evaluate/predict/export).  Training must continue from the
    ORIGINAL state — the swap is not an optimizer step.

    Raises if the optimizer was not wrapped with :func:`wrap_with_ema`.
    """
    ema = find_ema_params(state.opt_state)
    if ema is None:
        raise ValueError(
            "no EmaParamsState in opt_state — build the optimizer with "
            "wrap_with_ema(tx, decay) (CLI: --ema-decay)")
    return state.replace(params=ema)
