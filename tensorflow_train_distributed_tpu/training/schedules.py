"""Learning-rate schedules: the Keras ``LearningRateScheduler`` answer.

The reference schedules LR two ways: a ``LearningRateScheduler`` callback
mutating ``optimizer.lr`` per epoch (``tf_keras/src/callbacks.py:2250``) and
per-model conventions in its configs.  In optax, a schedule is a pure
``step -> lr`` function baked into the optimizer — XLA-compatible (the whole
fit loop stays one jitted program, no host mutation of hyperparams), so the
callback becomes a function and this module provides the conventions:

- ``warmup_cosine``  — linear warmup → cosine decay (LLM/SFT convention,
  reference config[4]).
- ``warmup_linear``  — linear warmup → linear decay to 0 (BERT convention,
  reference config[2]).
- ``noam``           — the Transformer-big convention (Vaswani et al.):
  d_model^-0.5 · min(step^-0.5, step · warmup^-1.5); reference config[3].
- ``resnet_steps``   — linear warmup then 10× drops at fractional
  milestones (the MLPerf/90-epoch ResNet recipe; reference config[1]).
- ``constant``       — optionally warmed up (reference default).

Every schedule is a plain ``optax.Schedule``; the trainer evaluates it at
``state.step`` to log ``lr`` alongside loss (the reference logs lr via the
callback/TensorBoard).
"""

from __future__ import annotations

from typing import Optional, Sequence

import optax


def constant(peak_lr: float, *, warmup_steps: int = 0, **_) -> optax.Schedule:
    if warmup_steps <= 0:
        return optax.constant_schedule(peak_lr)
    return optax.join_schedules(
        [optax.linear_schedule(0.0, peak_lr, warmup_steps),
         optax.constant_schedule(peak_lr)],
        [warmup_steps],
    )


def warmup_cosine(peak_lr: float, total_steps: int, *,
                  warmup_steps: int = 0, end_lr_ratio: float = 0.0,
                  **_) -> optax.Schedule:
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=peak_lr,
        warmup_steps=max(warmup_steps, 1),
        decay_steps=max(total_steps, warmup_steps + 1),
        end_value=peak_lr * end_lr_ratio,
    )


def warmup_linear(peak_lr: float, total_steps: int, *,
                  warmup_steps: int = 0, **_) -> optax.Schedule:
    warmup_steps = max(warmup_steps, 1)
    return optax.join_schedules(
        [optax.linear_schedule(0.0, peak_lr, warmup_steps),
         optax.linear_schedule(
             peak_lr, 0.0, max(total_steps - warmup_steps, 1))],
        [warmup_steps],
    )


def noam(peak_lr: float, *, d_model: int = 1024, warmup_steps: int = 4000,
         **_) -> optax.Schedule:
    """Transformer-big LR: ``peak_lr`` acts as a multiplier (1.0 = paper)."""

    warmup_steps = max(warmup_steps, 1)

    def schedule(step):
        import jax.numpy as jnp

        s = (step + 1) * 1.0
        return peak_lr * d_model**-0.5 * jnp.minimum(
            s**-0.5, s * warmup_steps**-1.5)

    return schedule


def resnet_steps(peak_lr: float, total_steps: int, *,
                 warmup_steps: int = 0,
                 milestones: Sequence[float] = (0.33, 0.67, 0.89),
                 decay: float = 0.1, **_) -> optax.Schedule:
    """Warmup then stepwise 10× drops at fractions of the run (30/60/80-of-90
    epochs scaled to any ``total_steps``)."""
    boundaries = {
        max(int(m * total_steps), warmup_steps + 1): decay
        for m in milestones
    }
    stepped = optax.piecewise_constant_schedule(peak_lr, boundaries)
    if warmup_steps <= 0:
        return stepped
    return optax.join_schedules(
        [optax.linear_schedule(0.0, peak_lr, warmup_steps),
         lambda s: stepped(s + warmup_steps)],
        [warmup_steps],
    )


SCHEDULES = {
    "constant": constant,
    "warmup_cosine": warmup_cosine,
    "warmup_linear": warmup_linear,
    "noam": noam,
    "resnet_steps": resnet_steps,
}


def by_name(name: str, peak_lr: float, total_steps: int,
            *, warmup_steps: int = 0,
            **kwargs) -> optax.Schedule:
    if name not in SCHEDULES:
        raise ValueError(
            f"Unknown schedule {name!r}; available: {sorted(SCHEDULES)}")
    return SCHEDULES[name](
        peak_lr, total_steps=total_steps, warmup_steps=warmup_steps, **kwargs)
