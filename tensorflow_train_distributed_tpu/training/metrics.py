"""Step-metric accumulation (host side).

Replaces the Keras metric/History plumbing (``tf_keras/src/callbacks.py:1189``)
with a running-mean accumulator over the scalar dict each jitted step
returns.  Metrics under pjit are global (already cross-replica reduced inside
the step via the mean over the sharded batch), so host aggregation is an
average across steps.

Weighted-mean tasks (the Task ``loss_weight`` contract — e.g. MLM metrics
over masked tokens) aggregate as the true weighted mean across batches,
matching Keras's weighted-metric semantics: a batch with twice the masked
tokens counts twice.  ``loss_weight`` itself reports the *total* weight
evaluated.
"""

from __future__ import annotations

from typing import Mapping

import numpy as np


class MetricAccumulator:
    def __init__(self):
        self._sums: dict[str, float] = {}
        self._weights: dict[str, float] = {}
        self._weight_total = 0.0
        self._saw_weight = False

    def update(self, metrics: Mapping[str, float]):
        w = float(np.asarray(metrics.get("loss_weight", 1.0)))
        if "loss_weight" in metrics:
            self._weight_total += w
            self._saw_weight = True
        if w <= 0.0:
            # A zero-weight batch (e.g. no masked tokens) carries no metric
            # information: its values are 0/0 artifacts — adding them would
            # poison the sums (NaN·0) or the denominator.
            return
        for k, v in metrics.items():
            if k == "loss_weight":
                continue
            v = float(np.asarray(v))
            self._sums[k] = self._sums.get(k, 0.0) + v * w
            self._weights[k] = self._weights.get(k, 0.0) + w

    def result(self) -> dict[str, float]:
        out = {k: self._sums[k] / self._weights[k] for k in self._sums}
        if self._saw_weight:
            out["loss_weight"] = self._weight_total
        return out

    def reset(self):
        self._sums.clear()
        self._weights.clear()
        self._weight_total = 0.0
        self._saw_weight = False
