"""Step-metric accumulation (host side).

Replaces the Keras metric/History plumbing (``tf_keras/src/callbacks.py:1189``)
with a plain running-mean accumulator over the scalar dict each jitted step
returns.  Metrics under pjit are global (already cross-replica reduced inside
the step via the mean over the sharded batch), so host aggregation is a
simple average across steps.
"""

from __future__ import annotations

from typing import Mapping

import numpy as np


class MetricAccumulator:
    def __init__(self):
        self._sums: dict[str, float] = {}
        self._counts: dict[str, int] = {}

    def update(self, metrics: Mapping[str, float]):
        for k, v in metrics.items():
            v = float(np.asarray(v))
            self._sums[k] = self._sums.get(k, 0.0) + v
            self._counts[k] = self._counts.get(k, 0) + 1

    def result(self) -> dict[str, float]:
        return {k: self._sums[k] / self._counts[k] for k in self._sums}

    def reset(self):
        self._sums.clear()
        self._counts.clear()
