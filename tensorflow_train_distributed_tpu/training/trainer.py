"""The SPMD training loop: ``Model.fit`` rebuilt TPU-first.

Reference call stack being replaced (SURVEY.md §3.1/§3.2):
``Model.fit`` → ``make_train_function`` → ``strategy.run(step)`` →
per-replica ``train_step`` → optimizer ``aggregate_gradients`` allreduce
(``tf_keras/src/engine/training.py:1453,1338,1118``;
``optimizers/utils.py:23``).  Here the whole stack is ONE jitted function
over global arrays: the gradient allreduce is inserted by GSPMD because the
loss is a mean over the batch axis (sharded over data/fsdp) while params are
replicated (or fsdp-sharded, in which case it becomes reduce-scatter +
all-gather automatically).  There are no per-replica values, no strategy.run
dispatch, no gradient packing — XLA owns all of it.

``steps_per_execution`` (reference: ``training.py`` fit arg) maps to an
inner ``lax.scan`` over a stacked super-batch: k steps per dispatch,
amortizing host→device latency exactly like the reference amortizes
tf.function dispatch.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
from typing import Any, Callable, Iterable, Mapping, Optional, Protocol, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from tensorflow_train_distributed_tpu.runtime import compat, events, faults
from tensorflow_train_distributed_tpu.runtime.lint import (
    compilecheck,
    memcheck,
)
from tensorflow_train_distributed_tpu.runtime.lint.registry import (
    memory_budget,
    thread_role,
)
from tensorflow_train_distributed_tpu.parallel import collectives
from tensorflow_train_distributed_tpu.parallel import sharding as sharding_lib
from tensorflow_train_distributed_tpu.parallel.sharding import (
    DEFAULT_RULES, LogicalRules,
)
from tensorflow_train_distributed_tpu.runtime.mesh import batch_axes
from tensorflow_train_distributed_tpu.training import mixed_precision as mp
from tensorflow_train_distributed_tpu.training.callbacks import (
    Callback, CallbackList,
)
from tensorflow_train_distributed_tpu.training.metrics import MetricAccumulator
from tensorflow_train_distributed_tpu.training.mixed_precision import Policy
from tensorflow_train_distributed_tpu.training.train_state import TrainState

import flax.linen as nn

logger = logging.getLogger(__name__)


class Task(Protocol):
    """What a model config provides to the trainer.

    ``init_variables`` returns the flax variable collections
    (``{"params": ..., "batch_stats": ...}``); ``loss_fn`` returns
    ``(scalar_loss, (metrics_dict, new_model_state))``.  The loss must be a
    mean over the *global* batch — that is the contract that makes GSPMD
    insert the cross-replica gradient reduction (the reference's
    ``all_reduce_sum_gradients``).

    Tasks whose loss is a *weighted* mean (e.g. MLM loss over masked tokens)
    must report the total weight as ``metrics["loss_weight"]`` — gradient
    accumulation uses it to combine microbatches as the true global weighted
    mean instead of a uniform average.  Optional ``predict_fn(params,
    model_state, batch)`` enables ``Trainer.predict``.
    """

    def init_variables(self, rng: jax.Array, batch) -> Any: ...

    def loss_fn(self, params, model_state, batch, rng: jax.Array,
                train: bool) -> tuple[jax.Array, tuple[dict, Any]]: ...


@dataclasses.dataclass(frozen=True)
class TrainerConfig:
    seed: int = 0
    steps_per_execution: int = 1
    # Microbatch count for gradient accumulation: each optimizer step splits
    # the batch into `grad_accum` microbatches and scans over them, so peak
    # activation memory is one microbatch's worth (reference analog: Horovod
    # `backward_passes_per_step`, [SPEC] config[3]).  Grads accumulate in
    # fp32; BN statistics update sequentially per microbatch.
    grad_accum: int = 1
    log_every: int = 10
    checkpoint_every: Optional[int] = None
    donate_state: bool = True
    # Adds a ``grad_norm`` metric (global norm of the unscaled, averaged
    # grads, measured BEFORE any optimizer-chain clipping — the signal
    # used to choose a --grad-clip-norm).  Off by default: it is an extra
    # all-params reduction per step.
    log_grad_norm: bool = False
    # ZeRO-1: shard optimizer moments over the ``data`` mesh axis
    # (parallel.sharding.zero1_opt_shardings).  N× less moment memory on
    # an N-way dp mesh for one extra all-gather per step; numerically
    # identical (parity-tested).
    zero1: bool = False
    # Quantized gradient collectives (EQuARX, arxiv 2506.17615): replace
    # the implicit GSPMD gradient allreduce with an explicit per-shard
    # pipeline — reduce-scatter via int8+scales all_to_all (the shared
    # native-ring recipe), exact f32 dequant-sum, int8 all-gather — with
    # a per-leaf error-feedback residual carried in the train state so
    # quantization error is compensated, not accumulated.  "int8" is the
    # quantized wire, "f32" the explicit-pipeline exact baseline (the
    # A/B leg), "none" (default) today's single-program GSPMD step,
    # bitwise-unchanged.  TTD_NO_GRAD_QUANT=1 (read at Trainer
    # construction — the residual leaves compile into the state) forces
    # "none".  Requires data>1 (model-parallel axes — fsdp/tensor — are
    # supported via partial manualization: only "data" is manual inside
    # the pipeline's shard_maps, GSPMD keeps handling the rest),
    # steps_per_execution=1, and a task with no mutable model
    # collections (BN batch_stats are reduced by GSPMD in the implicit
    # path; the per-shard pipeline has no equivalent).  grad_accum>1
    # composes: microbatch grads accumulate in fp32 inside the per-shard
    # program and the wire sees ONE quantized sync per optimizer step.
    grad_quant: str = "none"
    # Comm/compute overlap for the explicit grad-quant pipeline
    # (ROADMAP item 3): partition the grad pytree into ≤K byte-balanced
    # buckets in reverse-backward layer order and dispatch each bucket's
    # int8 reduce-scatter (collectives.ef_bucket_sync) and optimizer
    # apply as in-flight async programs, barriering ONCE at step end —
    # the fabric works while later buckets compute and the blocking
    # comm-fraction drops to dispatch time.  0/1 = the sequential
    # three-program pipeline (grad_step → ef_grad_sync → apply_step),
    # byte-for-byte the pre-overlap step.  TTD_NO_GRAD_OVERLAP=1 (read
    # at Trainer construction) forces 0.  Only meaningful with
    # grad_quant != "none".  NOTE the per-bucket apply is bitwise-equal
    # to the whole-tree apply for per-leaf optimizers (adam/sgd/adamw);
    # transforms coupling leaves globally (clip_by_global_norm) would
    # clip per-bucket — keep grad_overlap=0 with those.
    grad_overlap: int = 4
    # Cross-replica sharded weight update (arxiv 2004.13336):
    # zero1 extended from the moments to the update computation — each
    # data replica runs the optimizer math on only its gradient shard
    # (sharding constraints around tx.update) and the new params are
    # all-gathered back, removing the redundant N-way elementwise
    # apply.  Implies zero1's moment shardings.  Composes with
    # grad_quant; numerically identical to the replicated apply up to
    # reduction order.
    sharded_update: bool = False
    # View applied to the state for EVERY eval fit runs (mid-training
    # eval_every AND the final one launch.py drives): e.g. EMA weight
    # swapping (training.ema.swap_ema_params), so val_* metrics feeding
    # EarlyStopping/ReduceLROnPlateau score the same model the final
    # eval/export does.  None = identity.
    eval_state_view: Optional[Callable] = None
    # HBM budget for the trainer's declared memory pool (memcheck):
    # the GLOBAL byte ceiling the train state — params, optimizer
    # moments, mutable collections, grad-quant EF residuals — is held
    # to at creation.  None = track-only: the TTD_MEMCHECK=1 sanitizer
    # still ledgers the state under pool "trainer_state" (the
    # ttd_engine_hbm_bytes gauge feed) but never raises; with a budget
    # set, an over-budget create_state raises MemoryBudgetError BEFORE
    # materializing anything (projection is the same eval_shape
    # plan_state_memory uses).
    hbm_budget_bytes: Optional[int] = None


class _BucketPlan:
    """Host-side bookkeeping for the bucketed-overlap step.

    Built ONCE from the first step's concrete state (leaf structure is
    static across a fit): the leaf buckets
    (``collectives.plan_grad_buckets`` — reverse-backward order,
    byte-balanced), per-bucket wire MB, and the opt-state split/merge
    index maps.

    The opt-state maps exploit that pytree flattening is DFS: the full
    flatten of ``opt_state`` is the concatenation of each node's own
    flatten, so every params-structured sub-tree (adam's ``mu``/``nu``)
    occupies one CONTIGUOUS run of param-ordered leaf slots.  Walking
    the nodes once (with the param treedef as the ``is_leaf`` match)
    yields, per bucket, the flat indices its opt sub-state takes.
    Leaves OUTSIDE param-structured sub-trees — step counts, injected
    hyperparams — are SHARED: they ride along whole in every bucket's
    sub-state (so ``tx.update`` sees a structurally-complete state) and
    must never be donated (bucket b+1 still reads the buffer bucket b
    was handed).  Merge takes each bucket's copy of its own param
    leaves and any bucket's copy of the shared leaves (identical by
    construction: every bucket computes them from the same inputs).
    """

    def __init__(self, state, k: int, world: int, wire: str):
        params = state.params
        self.treedef = jax.tree.structure(params)
        self.n_leaves = self.treedef.num_leaves
        self.buckets = collectives.plan_grad_buckets(params, k)
        self.k = len(self.buckets)
        p_flat = jax.tree.leaves(params)
        self.bucket_mb = [
            collectives.bucket_sync_wire_bytes(
                [p_flat[i] for i in ix], world, wire) / 1e6
            for ix in self.buckets]

        pdef = self.treedef

        def is_match(n):
            return jax.tree.structure(n) == pdef

        self._is_match = is_match
        nodes = jax.tree.flatten(state.opt_state, is_leaf=is_match)[0]
        ix: list = [[] for _ in self.buckets]
        off = 0
        for node in nodes:
            if is_match(node):
                for b, bix in enumerate(self.buckets):
                    ix[b].extend(off + i for i in bix)
                off += self.n_leaves
            else:
                for b in range(self.k):
                    ix[b].append(off)
                off += 1
        self.opt_leaf_ix = ix
        self.n_opt_leaves = off
        assert off == jax.tree.structure(state.opt_state).num_leaves
        self.bucket_opt_defs = []
        for bix in self.buckets:
            t = jax.tree.map(
                lambda n, _bix=bix: (
                    [pdef.flatten_up_to(n)[i] for i in _bix]
                    if is_match(n) else n),
                state.opt_state, is_leaf=is_match)
            self.bucket_opt_defs.append(jax.tree.structure(t))

    def split_opt(self, opt_state):
        """Per-bucket opt sub-states (param sub-trees → bucket leaf
        lists; shared leaves replicated into every bucket)."""
        flat = jax.tree.leaves(opt_state)
        return [d.unflatten([flat[j] for j in ixs])
                for d, ixs in zip(self.bucket_opt_defs, self.opt_leaf_ix)]

    def merge_opt(self, opt_state_template, outs):
        """Reassemble the full new opt_state (``opt_state_template``'s
        structure) from the per-bucket apply outputs.  Shared leaves are
        written by every bucket with identical values; param leaves by
        exactly their owning bucket."""
        flat = [None] * self.n_opt_leaves
        for ixs, out in zip(self.opt_leaf_ix, outs):
            oflat = jax.tree.leaves(out)
            for pos, j in enumerate(ixs):
                flat[j] = oflat[pos]
        return jax.tree.structure(opt_state_template).unflatten(flat)

    def shardings_for(self, tree, bucket: int):
        """Slice a params-structured sharding tree (or None) to one
        bucket's leaf list."""
        if tree is None:
            return None
        flat = self.treedef.flatten_up_to(tree)
        return [flat[i] for i in self.buckets[bucket]]


class Trainer:
    """Owns state creation, the jitted step, and the fit/evaluate loops."""

    def __init__(
        self,
        task: Task,
        optimizer: optax.GradientTransformation,
        mesh,
        *,
        rules: LogicalRules = DEFAULT_RULES,
        policy: Policy = Policy(),
        config: TrainerConfig = TrainerConfig(),
        callbacks: Sequence[Callback] = (),
        checkpoint_manager=None,
        lr_schedule=None,
    ):
        self.task = task
        self.tx = optimizer
        # Optional step->lr fn (training.schedules); purely observational —
        # the optimizer already owns the schedule — so `lr` shows up in
        # metrics/TensorBoard like the reference's LearningRateScheduler logs.
        self.lr_schedule = lr_schedule
        self.mesh = mesh
        self.rules = rules
        self.policy = policy
        self.config = config
        self.callbacks = CallbackList(callbacks, trainer=self)
        self.checkpoint_manager = checkpoint_manager
        self._train_step = None
        self._eval_step = None
        self._predict_step = None
        self.state_shardings = None
        self._live_state = None
        # Cross-replica sharded update: per-leaf shardings the gradient
        # and the new params carry DURING the optimizer apply (None =
        # replicated apply, today's path).  Resolved with the state
        # shardings in _abstract_state_and_shardings.
        self._update_shardings = None
        self._param_shardings = None
        # Guard callbacks (TerminateOnNaN) set this to veto further
        # checkpoint writes of a numerically-poisoned state.
        self.state_poisoned = False
        # Quantized gradient collectives: resolve the flag ONCE at
        # construction (the kill switch must win before the residual
        # leaves are compiled into the state).
        self.grad_quant = self._resolve_grad_quant(config, mesh)
        # Bucketed comm/compute overlap: resolved once at construction
        # like grad_quant (the kill switch picks the step builder).
        self.grad_overlap = self._resolve_grad_overlap(config,
                                                       self.grad_quant)

    @staticmethod
    def _resolve_grad_overlap(config: TrainerConfig, grad_quant: str) -> int:
        k = int(config.grad_overlap)
        if k < 0:
            raise ValueError(f"grad_overlap must be >= 0, got {k}")
        if grad_quant == "none" or k <= 1:
            return 0
        if os.environ.get("TTD_NO_GRAD_OVERLAP", "0") not in ("", "0"):
            logger.warning(
                "TTD_NO_GRAD_OVERLAP=1: bucketed comm/compute overlap "
                "disabled — sequential three-program grad-quant pipeline "
                "(set before Trainer construction; the choice compiles "
                "in)")
            return 0
        return k

    @staticmethod
    def _resolve_grad_quant(config: TrainerConfig, mesh) -> str:
        gq = config.grad_quant
        if gq not in ("none", "f32", "int8"):
            raise ValueError(
                f"grad_quant must be none|f32|int8, got {gq!r}")
        if gq == "none":
            return gq
        if os.environ.get("TTD_NO_GRAD_QUANT", "0") not in ("", "0"):
            logger.warning(
                "TTD_NO_GRAD_QUANT=1: quantized gradient collectives "
                "disabled — exact single-program GSPMD step (set before "
                "Trainer construction; the choice compiles in)")
            return "none"
        sizes = dict(mesh.shape)
        if sizes.get("data", 1) <= 1:
            logger.warning(
                "grad_quant=%r is a no-op on a data=1 mesh; using the "
                "exact single-program step", gq)
            return "none"
        if config.steps_per_execution > 1:
            raise ValueError(
                "grad_quant does not compose with steps_per_execution>1 "
                "(the comm program is dispatched separately per step); "
                "drop one of the two")
        return gq

    # -- state ---------------------------------------------------------------

    def _abstract_state_and_shardings(self, sample_batch):
        """(create_fn, abstract_state, state_shardings) for this trainer.

        Single source of the state-creation closure and its sharding
        resolution, shared by ``create_state`` (which executes it) and
        ``lower_train_step`` (which only traces it) — the AOT proof must
        lower exactly the program the trainer runs.
        """
        rng = jax.random.key(self.config.seed)
        batch_shapes = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype),
            sample_batch,
        )

        is_boxed = (lambda x:  # noqa: E731
                    isinstance(x, nn.meta.AxisMetadata))

        def _create():
            # Zeros with the batch's shapes/dtypes: tasks get real traced
            # arrays (the natural `model.init(rng, batch["x"])` idiom works)
            # without baking a real data batch into the init computation.
            init_batch = jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype), batch_shapes
            )
            variables = self.task.init_variables(rng, init_batch)
            variables = dict(variables)
            params = variables.pop("params")
            residual = None
            if self.grad_quant != "none":
                # Error-feedback residual: one f32 leaf per param leaf
                # with a leading per-replica dim (sharded over data
                # below — per-device cost is one f32 param copy).
                W = self.mesh.shape["data"]
                residual = jax.tree.map(
                    lambda p: jnp.zeros(
                        (W,) + tuple((p.value if is_boxed(p) else p).shape),
                        jnp.float32),
                    params, is_leaf=is_boxed)
            return TrainState.create(
                params=params,
                model_state=variables,
                tx=self.tx,
                loss_scale=mp.LossScaleState.create(self.policy),
                grad_residual=residual,
            )

        with sharding_lib.with_logical_rules(self.mesh, self.rules), \
                compat.set_mesh(self.mesh):
            abstract = jax.eval_shape(_create)
            if (self.grad_quant != "none"
                    and jax.tree.leaves(abstract.model_state)):
                raise ValueError(
                    "grad_quant requires a task with no mutable model "
                    "collections (e.g. BatchNorm batch_stats): the "
                    "implicit GSPMD path reduces them across the batch "
                    "axis, which the per-shard gradient pipeline does "
                    "not reproduce — drop grad-quant for this task")
            shardings = sharding_lib.make_state_shardings(
                self.mesh, abstract, self.rules
            )
            if self.config.zero1 or self.config.sharded_update:
                shardings = shardings.replace(
                    opt_state=sharding_lib.zero1_opt_shardings(
                        self.mesh, abstract.opt_state,
                        shardings.opt_state))
            if abstract.grad_residual is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P

                shardings = shardings.replace(
                    grad_residual=jax.tree.map(
                        lambda _: NamedSharding(self.mesh, P("data")),
                        abstract.grad_residual))
            if self.config.sharded_update:
                # The cross-replica sharded weight update's compute
                # shardings (arxiv 2004.13336): resolved once, used by
                # every step build.
                self._param_shardings = shardings.params
                self._update_shardings = (
                    sharding_lib.cross_replica_update_shardings(
                        self.mesh, abstract.params, shardings.params))
        return _create, abstract, shardings

    # Memory discipline (ttd-lint memcheck): the trainer's ONE big
    # device allocation — params + optimizer moments + grad-quant EF
    # residuals — declared as pool "trainer_state".  Projection reuses
    # the abstract state the sharding resolution already traces, so an
    # over-budget config raises BEFORE a single buffer materializes
    # (a 7B f32 state is ~84 GB; the error beats the OOM by the whole
    # allocation).  Owner lifetime: a rebuilt state on the same
    # trainer replaces its charge instead of double-counting.
    @memory_budget(
        pool="trainer_state",
        budget_fn=lambda self, *a, **k: self.config.hbm_budget_bytes,
        project_fn=lambda self, sample_batch, params=None:
            memcheck.tree_bytes(
                self._abstract_state_and_shardings(sample_batch)[1]),
        lifetime="owner")
    def create_state(self, sample_batch, params=None) -> TrainState:
        """Init params on-device directly into their target shardings.

        The jit-with-out_shardings pattern means a 7B-param model never
        materializes unsharded on one chip — the analog of the reference
        creating variables under ``strategy.scope()`` (``distribute_lib.py:
        1223``) but placement-correct from the first byte.

        ``params``: optional pre-trained parameter tree (e.g. from
        ``models.import_hf``) replacing the random init; leaves are cast to
        the init dtypes and placed into the same target shardings, so
        fine-tuning from a checkpoint shards identically to from-scratch.
        """
        _create, abstract, shardings = self._abstract_state_and_shardings(
            sample_batch)
        with sharding_lib.with_logical_rules(self.mesh, self.rules), \
                compat.set_mesh(self.mesh):
            self.state_shardings = shardings
            # Through the compilecheck seam (not raw jax.jit): state
            # creation is a one-shot compile per trainer, and the
            # sanitizer holds it to that.
            state = compilecheck.jit(
                _create, site="trainer.create_state", group=self,
                out_shardings=self.state_shardings)()
        state = nn.unbox(state)
        self.state_shardings = jax.tree.map(lambda x: x.sharding, state)
        if self.config.sharded_update:
            # Re-resolve from the PLACED state: make_state_shardings may
            # have downgraded dims that don't divide the mesh.
            self._param_shardings = self.state_shardings.params
            self._update_shardings = (
                sharding_lib.cross_replica_update_shardings(
                    self.mesh, state.params, self.state_shardings.params))
        if params is not None:
            # Cast on HOST, then device_put straight into the target
            # sharding: a jnp cast would materialize each full leaf on one
            # device first — a 7B scan-stacked FFN kernel is ~5.8 GB/leaf,
            # which must never exist unsharded on a 16 GB chip.  The random
            # init is dropped (and its buffers freed) BEFORE the imported
            # copy lands, so peak HBM is params + opt state — not 2×params.
            flat_init, treedef = jax.tree_util.tree_flatten(state.params)
            specs = [(x.dtype, x.sharding, x.shape) for x in flat_init]
            del flat_init
            flat_p, treedef_p = jax.tree_util.tree_flatten(params)
            if treedef_p != treedef:
                raise ValueError(
                    f"imported param tree structure does not match the "
                    f"model's:\n  imported: {treedef_p}\n  model: "
                    f"{treedef}")
            state = state.replace(params=None)  # free the random init
            loaded = []
            for p, (dtype, sharding, shape) in zip(flat_p, specs):
                host = np.asarray(p)
                if host.shape != shape:
                    raise ValueError(
                        f"imported param shape {host.shape} != model "
                        f"shape {shape}")
                loaded.append(
                    jax.device_put(host.astype(dtype), sharding))
            state = state.replace(
                params=jax.tree_util.tree_unflatten(treedef, loaded))
        logger.info("created state: %.2fM params", state.num_params() / 1e6)
        return state

    def lower_train_step(self, sample_batch):
        """AOT-lower the jitted train step on ABSTRACT state — the
        compile-level proof that a config partitions over this trainer's
        mesh, with nothing materialized (a 7B f32 train state is ~84 GB;
        tracing is shape arithmetic).  Returns the ``jax.stages.Lowered``;
        ``.compile()`` then runs the full XLA SPMD pipeline, so collective
        structure and per-device buffer sizes can be asserted without one
        real chip (SURVEY §7 hard-part 3).  ``mesh`` may use devices this
        host doesn't have (virtual CPU mesh) — the lowering never executes.
        """
        from jax.sharding import PartitionSpec as P

        from tensorflow_train_distributed_tpu.parallel.sharding import (
            shard_batch_spec,
        )

        if self.grad_quant != "none":
            raise ValueError(
                "lower_train_step lowers the single-program GSPMD step; "
                f"grad_quant={self.grad_quant!r} runs a three-program "
                "pipeline (grad_step/grad_sync/apply_step) with no "
                "single lowering — lower with grad_quant='none' "
                "(numerics-identical off-path) for the AOT proof")

        k = self.config.steps_per_execution

        def step(state, batch):
            with sharding_lib.with_logical_rules(self.mesh, self.rules):
                if k == 1:
                    return self._single_step(state, batch)
                new_state, ms = jax.lax.scan(self._single_step, state,
                                             batch)
                return new_state, jax.tree.map(lambda m: m[-1], ms)

        _, abstract, shardings = self._abstract_state_and_shardings(
            sample_batch)
        with sharding_lib.with_logical_rules(self.mesh, self.rules), \
                compat.set_mesh(self.mesh):
            # Strip metadata boxes WITHOUT nn.unbox: unbox() applies
            # sharding constraints, which is illegal on abstract values.
            is_boxed = (lambda x:  # noqa: E731
                        isinstance(x, nn.meta.AxisMetadata))
            plain = jax.tree.map(lambda x: x.value if is_boxed(x) else x,
                                 abstract, is_leaf=is_boxed)
            state_in = jax.tree.map(
                lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                                   sharding=sh),
                plain, shardings)
            # Same batch layout as the live path: ``sample_batch`` is a
            # regular batch (the create_state contract); with
            # steps_per_execution > 1 fit stacks k of them with the scan
            # axis at dim 0 and shards dim 1 (the prefetch spec) — mirror
            # both the stacking and the spec here.
            spec = (shard_batch_spec(self.mesh) if k == 1
                    else P(None, batch_axes(self.mesh)))
            batch_sharding = jax.sharding.NamedSharding(self.mesh, spec)
            batch_in = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(
                    (np.shape(x) if k == 1 else (k,) + np.shape(x)),
                    np.asarray(x).dtype, sharding=batch_sharding),
                sample_batch)
            donate = (0,) if self.config.donate_state else ()
            # SAME instrumented site as the live train step: the AOT
            # proof must not bypass the compile-discipline seam — a
            # ``.lower()`` is a compile, recorded and budgeted like a
            # live dispatch (regression-pinned in
            # tests/test_compilecheck.py).
            return compilecheck.jit(
                step, site="trainer.train_step", group=self,
                donate_argnums=donate).lower(state_in, batch_in)

    # -- step functions ------------------------------------------------------

    def _make_loss_fn(self, model_state, batch, rng, train: bool):
        def loss_fn(params):
            p = self.policy.cast_to_compute(params)
            b = self.policy.cast_to_compute(batch)
            loss, (metrics, new_ms) = self.task.loss_fn(
                p, model_state, b, rng, train
            )
            return loss.astype(jnp.float32), (metrics, new_ms)

        return loss_fn

    def _microbatch_grads(self, params, model_state, batch, rng, loss_scale):
        """value_and_grad on one (micro)batch, unscaled; shared by both the
        direct path and the grad-accumulation scan."""
        loss_fn = self._make_loss_fn(model_state, batch, rng, True)

        def scaled(p):
            loss, aux = loss_fn(p)
            return mp.scale_loss(loss, loss_scale), (loss, aux)

        grad_fn = jax.value_and_grad(scaled, has_aux=True)
        (_, (loss, (metrics, new_ms))), grads = grad_fn(params)
        return mp.unscale_grads(grads, loss_scale), loss, metrics, new_ms

    def _accumulated_grads(self, state: TrainState, batch, rng):
        """Scan `grad_accum` microbatches, averaging grads in fp32."""
        from jax.sharding import PartitionSpec as P

        a = self.config.grad_accum
        bsz = jax.tree.leaves(batch)[0].shape[0]
        if bsz % a:
            raise ValueError(
                f"batch size {bsz} not divisible by grad_accum={a}")
        # Microbatch axis in front; the global batch axis moves to dim 1, so
        # re-pin its sharding there (one reshard per step, amortized by the
        # microbatched compute it enables).
        spec = P(None, batch_axes(self.mesh))
        micro = jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(
                x.reshape((a, x.shape[0] // a) + x.shape[1:]), spec),
            batch,
        )

        return self._grad_accum_scan(state.params, state.model_state,
                                     state.loss_scale, micro, rng)

    def _grad_accum_scan(self, params, model_state, loss_scale, micro, rng):
        """Scan a pre-split microbatch axis, averaging grads in fp32 —
        the shared core of the implicit path's ``_accumulated_grads``
        and the quant pipeline's per-shard accumulation (which feeds
        the ONE post-scan quantization, so accumulation never stacks
        quantization error)."""
        a = jax.tree.leaves(micro)[0].shape[0]

        def body(carry, xs):
            ms, acc = carry
            mb, idx = xs
            grads, loss, metrics, new_ms = self._microbatch_grads(
                params, ms, mb, jax.random.fold_in(rng, idx), loss_scale)
            # Weighted-mean losses (Task contract): each microbatch's
            # gradient is d(weighted mean)/dp, so the global gradient is the
            # weight-weighted mean of microbatch gradients.
            w = jnp.asarray(metrics.get("loss_weight", 1.0), jnp.float32)
            acc = jax.tree.map(
                lambda s, g: s + g.astype(jnp.float32) * w, acc, grads)
            return (new_ms, acc), (loss, metrics, w)

        zeros = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        (new_ms, grads), (losses, stacked, ws) = jax.lax.scan(
            body, (model_state, zeros), (micro, jnp.arange(a)))
        # Tasks report UNclamped weights (an all-pad batch is weight 0);
        # guard the division — zero-weight microbatches contribute 0·loss,
        # so the epsilon never changes a batch that has any real weight.
        w_total = jnp.maximum(jnp.sum(ws), 1e-6)
        grads = jax.tree.map(
            lambda g, p: (g / w_total).astype(p.dtype), grads, params)
        metrics = jax.tree.map(
            lambda m: jnp.sum(m * ws, axis=0) / w_total, stacked)
        if "loss_weight" in metrics:
            metrics["loss_weight"] = w_total  # total, as one big batch would
        return grads, jnp.sum(losses * ws) / w_total, metrics, new_ms

    def _apply_grad_parts(self, params, opt_state, loss_scale, step, grads,
                          finite, update_shardings, param_shardings):
        """Tree-shape-agnostic core of the optimizer apply.

        ``params``/``grads`` (and the param-structured parts of
        ``opt_state``) may be the full model tree or any bucket's leaf
        list — optax transformations are pytree-generic, so per-leaf
        optimizers (sgd/adam/adamw, per-value clipping) compute bitwise
        the same values bucketed as whole; transforms that couple
        leaves globally (clip_by_global_norm) are the documented
        exception (their norm would be per-bucket — see the
        ``grad_overlap`` config note).  ``update_shardings`` /
        ``param_shardings`` are the cross-replica sharded-update
        constraint trees matching ``params``' shape (None = replicated
        apply).  Returns ``(new_params, new_opt, new_ls, metrics)``.
        """
        if update_shardings is not None:
            # Cross-replica sharded weight update, entry half: pin the
            # gradients to the per-leaf ``data``-sharded update
            # shardings so GSPMD turns the gradient all-reduce into
            # reduce-scatter and the optimizer math that follows runs
            # on 1/N elements per replica (arxiv 2004.13336).
            grads = jax.tree.map(jax.lax.with_sharding_constraint, grads,
                                 update_shardings)
        metrics = {}
        if loss_scale is not None:
            if finite is None:
                finite = mp.grads_finite(grads)
            updates, new_opt = self.tx.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            # Skip the update entirely on overflow (LossScaleOptimizer
            # contract: no param/opt-state change on non-finite grads).
            new_params = jax.tree.map(
                lambda n, o: jnp.where(finite, n, o), new_params, params)
            new_opt = jax.tree.map(
                lambda n, o: jnp.where(finite, n, o), new_opt, opt_state)
            new_ls = mp.update_loss_scale(loss_scale, finite, self.policy)
            metrics = dict(metrics, loss_scale=new_ls.scale,
                           grads_finite=finite.astype(jnp.float32))
        else:
            updates, new_opt = self.tx.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            new_ls = None
        if param_shardings is not None:
            # Exit half: all-gather the shard-updated params back to
            # their resting shardings.
            new_params = jax.tree.map(jax.lax.with_sharding_constraint,
                                      new_params, param_shardings)

        if self.config.log_grad_norm:
            metrics["grad_norm"] = optax.global_norm(grads)
        if self.lr_schedule is not None:
            metrics["lr"] = jnp.asarray(self.lr_schedule(step), jnp.float32)
        else:
            # Dynamic LR (inject_hyperparams + ReduceLROnPlateau): the LR
            # lives in optimizer state — surface it so TensorBoard/JSONL
            # keep an lr series exactly when it starts moving.
            from tensorflow_train_distributed_tpu.training.callbacks import (
                get_injected_hyperparam,
            )

            inj = get_injected_hyperparam(opt_state, "learning_rate")
            if inj is not None:
                metrics["lr"] = jnp.asarray(inj, jnp.float32)
        return new_params, new_opt, new_ls, metrics

    def _apply_grads(self, state: TrainState, grads, finite=None):
        """The optimizer-apply half of a train step, shared VERBATIM by
        the implicit single-program step and the quant pipeline's apply
        program — the loss-scale overflow contract and the lr/grad_norm
        metric surface must never fork between the two (the kill-switch
        bitwise-parity guarantee rides on it).

        ``finite``: precomputed all-finite flag (the quant path, where
        it must be taken on the PRE-quantization local grads); None =
        compute from ``grads`` here (the implicit path).  Returns
        ``(new_params, new_opt, new_ls, extra_metrics)``; the caller
        assembles the state (model_state/residual differ per path).
        """
        return self._apply_grad_parts(
            state.params, state.opt_state, state.loss_scale, state.step,
            grads, finite, self._update_shardings, self._param_shardings)

    def _single_step(self, state: TrainState, batch):
        rng = jax.random.fold_in(jax.random.key(self.config.seed), state.step)
        if self.config.grad_accum > 1:
            grads, loss, metrics, new_ms = self._accumulated_grads(
                state, batch, rng)
        else:
            grads, loss, metrics, new_ms = self._microbatch_grads(
                state.params, state.model_state, batch, rng,
                state.loss_scale)
        new_params, new_opt, new_ls, extra = self._apply_grads(state, grads)
        metrics = dict(metrics, loss=loss, **extra)
        new_state = state.replace(
            step=state.step + 1,
            params=new_params,
            model_state=new_ms,
            opt_state=new_opt,
            loss_scale=new_ls,
        )
        return new_state, metrics

    def _jit_step(self, fn, *, site, donate=(), max_compiles=8):
        """jit ``fn(*args)`` with the trainer's mesh + logical rules.

        set_mesh must wrap the *call* (it is illegal inside jit): it binds
        the abstract mesh at trace time so mesh-aware ops (seq-parallel
        attention) see it regardless of call site.

        Every trainer program routes through the compilecheck seam
        under its declared ``site`` (budget grouped per trainer): a
        step that silently recompiles mid-fit — a batch shape drifting,
        a donated state replaced by an undonated copy — raises under
        ``TTD_COMPILECHECK=1`` instead of eating the step budget.
        """

        def step(*args):
            with sharding_lib.with_logical_rules(self.mesh, self.rules):
                return fn(*args)

        jitted = compilecheck.jit(step, site=f"trainer.{site}",
                                  group=self, donate_argnums=donate,
                                  max_compiles=max_compiles)

        def call(*args):
            with compat.set_mesh(self.mesh):
                return jitted(*args)

        return call

    def _compiled_train_step(self):
        if self._train_step is not None:
            return self._train_step
        if self.grad_quant != "none":
            self._train_step = (self._build_overlap_step()
                                if self.grad_overlap > 1
                                else self._build_quant_step())
            return self._train_step
        k = self.config.steps_per_execution

        def step(state, batch):
            if k == 1:
                return self._single_step(state, batch)
            new_state, ms = jax.lax.scan(self._single_step, state, batch)
            return new_state, jax.tree.map(lambda m: m[-1], ms)

        donate = (0,) if self.config.donate_state else ()
        self._train_step = self._jit_step(step, site="train_step",
                                          donate=donate)
        return self._train_step

    # -- quantized gradient collectives (grad_quant != "none") ---------------

    def _quant_model_axes(self) -> tuple:
        """Model-parallel mesh axes (>1, not "data"): non-empty picks
        the GSPMD row-vmap grad program over the fully-manual shard_map
        one — on a pure data-parallel mesh this is empty and the
        lowering stays byte-identical to the pre-overlap pipeline (the
        kill-switch parity guarantee rides on that)."""
        return tuple(a for a, s in dict(self.mesh.shape).items()
                     if a != "data" and s > 1)

    def _quant_grad_prog(self):
        """Build ``grad_prog(state, batch) -> (local_grads, metrics)`` —
        the fwd/bwd program shared by the sequential three-program
        pipeline and the bucketed overlap step.  Local grads leave with
        a leading per-data-replica dim (global ``[W, *shape]``, sharded
        over "data"); no cross-"data" reduction happens here — that is
        the sync program's job.  With ``grad_accum>1`` the local batch
        is scanned in ``a`` microbatches, accumulating in fp32 with the
        same weighted-mean algebra as ``_accumulated_grads`` — the wire
        then sees ONE quantized sync of the accumulated gradient per
        optimizer step.

        Two lowerings, one contract:

        - pure data-parallel mesh: per-shard code inside a fully-manual
          shard_map (byte-identical to the pre-overlap pipeline).
        - model-parallel axes present (dp×fsdp / dp×tp): a PLAIN GSPMD
          jit — the batch is reshaped to ``(W, B/W)`` rows constrained
          over "data" and the per-row gradient is vmapped, so GSPMD
          keeps sharding params/activations over fsdp/tensor exactly as
          in the implicit step (logical rules stay live; no manual
          region).  Per-row grads are then constrained to ``P("data")``
          — replicated over model axes, the layout the wire recipe and
          the EF residual already use.
        """
        from jax.sharding import NamedSharding, PartitionSpec as P

        from tensorflow_train_distributed_tpu.parallel.sharding import (
            shard_batch_spec,
        )

        mesh = self.mesh
        W = mesh.shape["data"]
        seed = self.config.seed
        accum = self.config.grad_accum
        model_axes = self._quant_model_axes()
        batch_spec = shard_batch_spec(mesh)

        def local_accum(params, model_state, loss_scale, local_batch, rng):
            bsz = jax.tree.leaves(local_batch)[0].shape[0]
            if bsz % accum:
                raise ValueError(
                    f"per-shard batch size {bsz} not divisible by "
                    f"grad_accum={accum}")
            # No sharding re-pin here (unlike _accumulated_grads): the
            # batch is already the shard-local slice.  The returned
            # loss_weight is the shard's TOTAL weight, so the
            # cross-shard pre-scaling below weights shards exactly as
            # one big batch would.
            micro = jax.tree.map(
                lambda x: x.reshape((accum, bsz // accum) + x.shape[1:]),
                local_batch)
            return self._grad_accum_scan(params, model_state, loss_scale,
                                         micro, rng)

        def per_shard_grads(params, model_state, loss_scale, step,
                            local_batch):
            rng = jax.random.fold_in(jax.random.key(seed), step)
            # Decorrelate per-shard randomness (dropout): the implicit
            # path generates masks globally and shards them; per-shard
            # tracing would otherwise repeat one mask on every shard.
            rng = jax.random.fold_in(rng, jax.lax.axis_index("data"))
            # Logical sharding rules are meaningless inside the manual
            # region (every >1 axis is manualized): null them so model
            # constraint annotations no-op instead of naming manual
            # axes.  (With auto axes present GSPMD still propagates
            # model-parallel shardings from the param inputs.)
            with nn.logical_axis_rules(()):
                if accum > 1:
                    grads, loss, metrics, _ = local_accum(
                        params, model_state, loss_scale, local_batch, rng)
                else:
                    grads, loss, metrics, _ = self._microbatch_grads(
                        params, model_state, local_batch, rng, loss_scale)
            metrics = dict(metrics, loss=loss)
            w = metrics.get("loss_weight")
            if w is None:
                metrics = jax.tree.map(
                    lambda m: jax.lax.pmean(
                        jnp.asarray(m, jnp.float32), "data"), metrics)
            else:
                # Weighted-mean tasks (the Task contract): the global
                # gradient is the weight-weighted mean of shard
                # gradients — pre-scale so the sync's uniform mean
                # comes out as the true weighted mean; metrics combine
                # the same way.
                w = jnp.asarray(w, jnp.float32)
                w_total = jnp.maximum(jax.lax.psum(w, "data"), 1e-6)
                scale = w * W / w_total
                grads = jax.tree.map(lambda g: g * scale.astype(g.dtype),
                                     grads)
                metrics = {
                    kk: (w_total if kk == "loss_weight"
                         else jax.lax.psum(
                             jnp.asarray(m, jnp.float32) * w,
                             "data") / w_total)
                    for kk, m in metrics.items()}
            return jax.tree.map(lambda g: g[None], grads), metrics

        def grad_prog(state, batch):
            sm = compat.shard_map(
                per_shard_grads, mesh=mesh,
                in_specs=(P(), P(), P(), P(), batch_spec),
                out_specs=(P("data"), P()),
                check_vma=False)
            return sm(state.params, state.model_state, state.loss_scale,
                      state.step, batch)

        if not model_axes:
            return grad_prog

        # GSPMD row-vmap lowering for dp×fsdp / dp×tp meshes: rows keep
        # any fsdp batch split on dim 1; grads leave replicated over the
        # model axes (the wire/EF-residual layout).
        row_axes = tuple(a for a in ("fsdp",) if a in model_axes)
        row_spec = P("data", row_axes) if row_axes else P("data")
        grads_sharding = NamedSharding(mesh, P("data"))

        def grad_prog_rows(state, batch):
            bsz = jax.tree.leaves(batch)[0].shape[0]
            if bsz % W:
                raise ValueError(
                    f"global batch size {bsz} not divisible by "
                    f"data-parallel degree {W} (grad_quant pipeline)")
            rows = jax.tree.map(
                lambda x: jax.lax.with_sharding_constraint(
                    x.reshape((W, x.shape[0] // W) + x.shape[1:]),
                    NamedSharding(mesh, row_spec)),
                batch)
            base = jax.random.fold_in(jax.random.key(seed), state.step)
            rngs = jax.vmap(lambda i: jax.random.fold_in(base, i))(
                jnp.arange(W))

            def one_row(row, rng):
                if accum > 1:
                    g, loss, m, _ = local_accum(
                        state.params, state.model_state, state.loss_scale,
                        row, rng)
                else:
                    g, loss, m, _ = self._microbatch_grads(
                        state.params, state.model_state, row, rng,
                        state.loss_scale)
                return g, dict(m, loss=loss)

            grads, metrics = jax.vmap(one_row)(rows, rngs)
            w = metrics.get("loss_weight")
            if w is None:
                metrics = jax.tree.map(
                    lambda m: jnp.mean(jnp.asarray(m, jnp.float32), axis=0),
                    metrics)
            else:
                # Same weighted-mean pre-scaling as the manual path —
                # row reductions instead of psum over "data".
                w = jnp.asarray(w, jnp.float32)
                w_total = jnp.maximum(jnp.sum(w), 1e-6)
                scale = w * W / w_total
                grads = jax.tree.map(
                    lambda g: g * scale.reshape(
                        (W,) + (1,) * (g.ndim - 1)).astype(g.dtype),
                    grads)
                metrics = {
                    kk: (w_total if kk == "loss_weight"
                         else jnp.sum(jnp.asarray(m, jnp.float32) * w,
                                      axis=0) / w_total)
                    for kk, m in metrics.items()}
            grads = jax.tree.map(
                lambda g: jax.lax.with_sharding_constraint(g,
                                                           grads_sharding),
                grads)
            return grads, metrics

        return grad_prog_rows

    def _build_quant_step(self):
        """The explicit-gradient-exchange step: THREE jitted programs
        instead of one, so the gradient communication is a separate
        dispatch the flight recorder can meter (``train/grad_comm`` vs
        ``train/optimizer_apply`` sub-spans inside ``step_dispatch``).

        1. ``trainer.grad_step`` — fwd/bwd per data shard inside
           shard_map (the loss is the LOCAL mean; no cross-replica
           reduction happens here, unlike the implicit GSPMD step);
           local grads leave with a leading per-replica dim, sharded.
        2. ``trainer.grad_sync`` — ``collectives.ef_grad_sync``: the
           error-feedback int8-wire allreduce (or the exact-psum f32
           A/B leg).  The only cross-replica traffic of the step.
           BOTH inputs are donated: the residual buffers alias their
           outputs, or peak HBM grows by a full f32 param copy.
        3. ``trainer.apply_step`` — the optimizer apply (with the
           cross-replica sharded-update constraints when configured),
           donating the state.

        The composite blocks at each program boundary so the sub-span
        durations are real device time, not dispatch time — the price
        of a meterable comm fraction (documented in README; the
        ``none`` path keeps today's fully-async single dispatch).
        ``grad_overlap>1`` swaps this builder for ``_build_overlap_step``
        (bucketed, in-flight); this sequential form is the
        ``TTD_NO_GRAD_OVERLAP=1`` / ``grad_overlap=0`` kill-switch path
        and stays byte-for-byte the pre-overlap pipeline.
        """
        from jax.sharding import PartitionSpec as P

        mesh = self.mesh
        W = mesh.shape["data"]
        wire = self.grad_quant
        grad_prog = self._quant_grad_prog()

        def sync_prog(local_grads, residual):
            # Fully-manual even on model-parallel meshes: grads/residual
            # arrive replicated over non-"data" axes (the grad program's
            # output constraint), so every model shard runs the same
            # wire math and the unmentioned manual axes stay replicated.
            sm = compat.shard_map(
                lambda g, r: collectives.ef_grad_sync(g, r, "data",
                                                      wire=wire),
                mesh=mesh, in_specs=(P("data"), P("data")),
                out_specs=(P(), P("data"), P()),
                check_vma=False)
            return sm(local_grads, residual)

        def apply_prog(state, grads, finite):
            grads = jax.tree.map(lambda g, p: g.astype(p.dtype), grads,
                                 state.params)
            # ``finite`` was computed on the PRE-quantization local
            # grads (the wire saturates inf and zeroes NaN, so post-
            # sync grads can no longer carry the overflow signal);
            # _apply_grads enforces the same skip-on-overflow contract
            # as the implicit step.
            new_params, new_opt, new_ls, metrics = self._apply_grads(
                state, grads, finite=finite)
            new_state = state.replace(
                step=state.step + 1,
                params=new_params,
                opt_state=new_opt,
                loss_scale=new_ls,
            )
            return new_state, metrics

        g_jit = self._jit_step(grad_prog, site="grad_step")
        # local_grads (arg 0) are pipeline-internal — always donated.
        # The residual (arg 1) is PART OF THE CALLER'S STATE: donating
        # it under donate_state=False would delete buffers of a state
        # the caller explicitly asked to keep (rollback, checkpoint-on-
        # failure).  With donation off you pay one extra residual copy
        # per step — the same trade the undonated state makes.
        sync_donate = (0, 1) if self.config.donate_state else (0,)
        sync_jit = self._jit_step(sync_prog, site="grad_sync",
                                  donate=sync_donate)
        apply_donate = (0, 1) if self.config.donate_state else (1,)
        apply_jit = self._jit_step(apply_prog, site="apply_step",
                                   donate=apply_donate)
        wire_mb_cell: list = []

        def step(state, batch):
            if not wire_mb_cell:
                wire_mb_cell.append(collectives.grad_sync_wire_bytes(
                    state.params, W, wire) / 1e6)
            residual = state.grad_residual
            lean = state.replace(grad_residual=None)
            with events.span("train/grad_fwdbwd"):
                local_grads, metrics = g_jit(lean, batch)
                jax.block_until_ready(local_grads)
            with events.span("train/grad_comm", wire=wire,
                             mb=wire_mb_cell[0]):
                synced, new_residual, finite = sync_jit(local_grads,
                                                        residual)
                jax.block_until_ready(synced)
            with events.span("train/optimizer_apply"):
                new_lean, extra = apply_jit(lean, synced, finite)
            metrics = dict(metrics, **extra)
            metrics["grad_comm_mb"] = wire_mb_cell[0]
            return new_lean.replace(grad_residual=new_residual), metrics

        return step

    def _build_overlap_step(self):
        """Bucketed comm/compute overlap (ROADMAP item 3): the quant
        pipeline with the grad tree split into K byte-balanced buckets
        (reverse-backward layer order) and the per-bucket sync + apply
        programs dispatched IN-FLIGHT.

        The step dispatches 1 grad program, then K sync programs
        (``collectives.ef_bucket_sync`` — leaf-aligned Q8 blocking, so
        results are bitwise-invariant to the bucket partition), then K
        apply programs (``_apply_grad_parts`` on bucket leaf lists, the
        opt state split along param-structured sub-trees), WITHOUT
        blocking between any of them — jax async dispatch queues all
        2K+1 programs and XLA overlaps bucket b's collective with
        bucket b+1's compute.  One barrier at step end
        (``train/step_barrier``) replaces the sequential pipeline's
        per-phase blocking: the ``train/grad_comm`` sub-spans now meter
        DISPATCH time (near-zero — the acceptance metric: blocking
        comm-fraction, vs the sequential pipeline where the span is the
        full device sync time).

        Donation: per-bucket grads and residual leaf lists alias
        through sync as in the sequential path; params donate through
        apply under ``donate_state``.  The opt sub-state is NOT donated
        — its shared leaves (step count, injected hyperparams) are
        handed to all K apply programs, and donating bucket 0's would
        free buffers bucket 1 still reads.  Transient cost: one
        bucket's worth (~1/K) of new moment buffers before the old full
        moments release.

        The loss-scale decision needs the GLOBAL finite flag, so every
        bucket's apply takes all K per-bucket flags and ANDs them
        in-graph (no host sync); each bucket's residual commit is gated
        on its bucket-LOCAL flag inside ``ef_bucket_sync``.
        """
        from jax.sharding import PartitionSpec as P

        mesh = self.mesh
        W = mesh.shape["data"]
        wire = self.grad_quant
        k_cfg = self.grad_overlap
        budget = max(8, k_cfg + 2)

        g_jit = self._jit_step(self._quant_grad_prog(), site="grad_step")

        def sync_bucket_prog(grads_b, residual_b):
            sm = compat.shard_map(
                lambda g, r: collectives.ef_bucket_sync(g, r, "data",
                                                        wire=wire),
                mesh=mesh, in_specs=(P("data"), P("data")),
                out_specs=(P(), P("data"), P()),
                check_vma=False)
            return sm(grads_b, residual_b)

        # K distinct bucket shapes land on ONE site: the compile budget
        # scales with the bucket count (bucket count is a static).
        sync_donate = (0, 1) if self.config.donate_state else (0,)
        sync_jit = self._jit_step(sync_bucket_prog, site="grad_sync_bucket",
                                  donate=sync_donate, max_compiles=budget)

        def make_apply(us_b, ps_b):
            def apply_bucket(params_b, opt_b, loss_scale, step, grads_b,
                             finites):
                grads_b = [g.astype(p.dtype)
                           for g, p in zip(grads_b, params_b)]
                finite = jnp.stack(finites).all()
                return self._apply_grad_parts(
                    params_b, opt_b, loss_scale, step, grads_b, finite,
                    us_b, ps_b)
            return apply_bucket

        apply_donate = (0, 4) if self.config.donate_state else (4,)
        plan_cell: list = []

        def _build_plan(state):
            plan = _BucketPlan(state, k_cfg, W, wire)
            apply_jits = [
                self._jit_step(
                    make_apply(plan.shardings_for(self._update_shardings, b),
                               plan.shardings_for(self._param_shardings, b)),
                    site="apply_step_bucket", donate=apply_donate,
                    max_compiles=budget)
                for b in range(plan.k)]
            return plan, apply_jits

        def step(state, batch):
            if not plan_cell:
                plan_cell.append(_build_plan(state))
            plan, apply_jits = plan_cell[0]
            residual = state.grad_residual
            lean = state.replace(grad_residual=None)
            with events.span("train/grad_fwdbwd", overlap=1):
                local_grads, metrics = g_jit(lean, batch)
            g_flat = jax.tree.leaves(local_grads)
            r_flat = jax.tree.leaves(residual)
            synced: list = [None] * plan.n_leaves
            new_r: list = [None] * plan.n_leaves
            finites = []
            for b, ix in enumerate(plan.buckets):
                with events.span("train/grad_comm", wire=wire,
                                 mb=plan.bucket_mb[b], bucket=b,
                                 buckets=plan.k):
                    s_b, r_b, f_b = sync_jit([g_flat[i] for i in ix],
                                             [r_flat[i] for i in ix])
                for pos, i in enumerate(ix):
                    synced[i] = s_b[pos]
                    new_r[i] = r_b[pos]
                finites.append(f_b)
            p_flat = jax.tree.leaves(lean.params)
            opt_bs = plan.split_opt(lean.opt_state)
            new_p: list = [None] * plan.n_leaves
            opt_outs = []
            extras = []
            new_ls = None
            for b, ix in enumerate(plan.buckets):
                with events.span("train/optimizer_apply", bucket=b,
                                 buckets=plan.k):
                    np_b, no_b, ls_b, m_b = apply_jits[b](
                        [p_flat[i] for i in ix], opt_bs[b],
                        lean.loss_scale, lean.step,
                        [synced[i] for i in ix], finites)
                for pos, i in enumerate(ix):
                    new_p[i] = np_b[pos]
                opt_outs.append(no_b)
                extras.append(m_b)
                if b == 0:
                    new_ls = ls_b
            new_state = lean.replace(
                step=lean.step + 1,
                params=plan.treedef.unflatten(new_p),
                opt_state=plan.merge_opt(lean.opt_state, opt_outs),
                loss_scale=new_ls,
                grad_residual=plan.treedef.unflatten(new_r),
            )
            # THE step barrier: the only host-blocking point — everything
            # above was async dispatch.  Its span is the realized
            # overlapped device time.
            with events.span("train/step_barrier", buckets=plan.k):
                jax.block_until_ready((new_p, new_r, opt_outs))
            extra = dict(extras[0])
            if self.config.log_grad_norm and "grad_norm" in extra:
                # Per-bucket norms combine exactly: ||g||² = Σ_b ||g_b||².
                extra["grad_norm"] = jnp.sqrt(
                    sum(m["grad_norm"] ** 2 for m in extras))
            metrics = dict(metrics, **extra)
            metrics["grad_comm_mb"] = float(sum(plan.bucket_mb))
            metrics["grad_buckets"] = plan.k
            return new_state, metrics

        return step

    def _compiled_eval_step(self):
        if self._eval_step is not None:
            return self._eval_step

        def step(state, batch):
            rng = jax.random.fold_in(
                jax.random.key(self.config.seed + 1), state.step)
            loss_fn = self._make_loss_fn(state.model_state, batch, rng,
                                         False)
            loss, (metrics, _) = loss_fn(state.params)
            return dict(metrics, loss=loss)

        self._eval_step = self._jit_step(step, site="eval_step")
        return self._eval_step

    def _compiled_predict_step(self):
        if self._predict_step is not None:
            return self._predict_step
        if not hasattr(self.task, "predict_fn"):
            raise NotImplementedError(
                f"{type(self.task).__name__} has no predict_fn(params, "
                "model_state, batch); implement it to use Trainer.predict")

        def step(state, batch):
            p = self.policy.cast_to_compute(state.params)
            b = self.policy.cast_to_compute(batch)
            return self.task.predict_fn(p, state.model_state, b)

        self._predict_step = self._jit_step(step, site="predict_step")
        return self._predict_step

    # -- loops ---------------------------------------------------------------

    def _stack_batches(self, it, k: int):
        """Group k host batches into one super-batch for the scan path."""
        while True:
            group = []
            for _ in range(k):
                try:
                    group.append(next(it))
                except StopIteration:
                    return
            yield jax.tree.map(lambda *xs: np.stack(xs), *group)

    @thread_role("trainer")
    def fit(
        self,
        batches: Iterable[Mapping[str, np.ndarray]],
        *,
        steps: int,
        state: Optional[TrainState] = None,
        steps_per_epoch: Optional[int] = None,
        eval_batches=None,
        eval_every: Optional[int] = None,
        eval_steps: Optional[int] = None,
    ) -> TrainState:
        """Run ``steps`` optimizer steps over ``batches`` (host iterator).

        ``batches`` yields host-local numpy batches (e.g. ``HostDataLoader``);
        sharding to the mesh happens via prefetch.  ``steps_per_epoch`` marks
        epoch boundaries for ``on_epoch_end`` callbacks (loaders may be
        infinite, so epochs are declared, not discovered).  Returns the final
        state.

        ``eval_batches`` (Keras ``validation_data``): a re-iterable batch
        source or zero-arg factory; every ``eval_every`` steps (default:
        each epoch boundary, else end of training) ``evaluate`` runs for
        ``eval_steps`` batches and the results reach callbacks as
        ``val_``-prefixed metrics — ``EarlyStopping(monitor="val_loss")``
        is the Keras idiom this reproduces.
        """
        from tensorflow_train_distributed_tpu.data.pipeline import (
            prefetch_to_device,
        )

        k = self.config.steps_per_execution
        if steps % k:
            raise ValueError(
                f"steps={steps} must be a multiple of "
                f"steps_per_execution={k} (each dispatch runs exactly k "
                "optimizer steps)"
            )
        # A poisoned verdict belongs to the *previous* run's state: a Trainer
        # reused after TerminateOnNaN (e.g. restarted from a good restored
        # checkpoint) must checkpoint normally again.
        self.state_poisoned = False
        it = iter(batches)
        if state is None:
            first = next(it)
            state = self.create_state(first)
            it = _chain_first(first, it)
        if k > 1:
            it = self._stack_batches(it, k)

        step_fn = self._compiled_train_step()
        self.callbacks.train_begin(state)
        start_step = int(state.step)

        from jax.sharding import PartitionSpec as P

        # Super-batches (k>1) carry the scan axis at dim 0; the batch dim —
        # the one sharded over the mesh — is dim 1.
        spec = None if k == 1 else P(None, batch_axes(self.mesh))
        device_iter = prefetch_to_device(it, self.mesh, spec=spec)
        try:
            self._fit_loop(device_iter, step_fn, state_box := [state],
                           steps, k, start_step, steps_per_epoch,
                           eval_batches, eval_every, eval_steps)
            state = state_box[0]
        finally:
            # train_end must run even when a step raises (OOM, NaN guard,
            # shape error): cleanup callbacks (StallWatchdog's thread,
            # TensorBoard flush) otherwise leak into the rest of the
            # process.
            self.callbacks.train_end(state_box[0])
        return state_box[0]

    def _fit_loop(self, device_iter, step_fn, state_box, steps, k,
                  start_step, steps_per_epoch, eval_batches, eval_every,
                  eval_steps):
        state = state_box[0]
        done = 0
        epoch = 0
        last_metrics: dict[str, float] = {}
        pending: list[tuple[int, Any]] = []
        stop = False
        batch_iter = iter(device_iter)
        _END = object()
        try:
            while True:
                # Flight-recorder step anatomy (runtime.events): data
                # wait vs step dispatch vs host-callback flush vs
                # checkpoint save — the "why was step N slow" timeline,
                # exported via tools/trace_report.py.
                with events.span("train/data_wait"):
                    dev_batch = next(batch_iter, _END)
                if dev_batch is _END:
                    break
                cur = start_step + done + k
                with events.span("train/step_dispatch", step=cur):
                    try:
                        state, metrics = step_fn(state, dev_batch)
                    except Exception as e:
                        # Device-loss classification at the dispatch
                        # boundary: a runtime error matching the known
                        # device-failure signatures re-raises as
                        # DeviceLost so launch.py exits with the
                        # device-loss contract (supervisor relaunches
                        # onto the survivors) instead of spending the
                        # crash budget on dead hardware.
                        dl = faults.as_device_loss(e)
                        if dl is not None:
                            raise dl from e
                        raise
                # Callbacks that checkpoint (preemption handler) read the
                # current state from here — fit's loop variable is otherwise
                # invisible to them.
                self._live_state = state
                done += k
                if faults.ARMED:    # zero-cost seam: one attr read when off
                    faults.step_boundary(cur)
                pending.append((cur, metrics))
                if done >= steps:
                    stop = True
                will_ckpt = (self.checkpoint_manager is not None
                             and self.config.checkpoint_every
                             and cur % self.config.checkpoint_every < k)
                eval_due = eval_batches is not None and (
                    (eval_every and cur % eval_every < k)
                    or (not eval_every and steps_per_epoch
                        and done % steps_per_epoch < k)
                    or (not eval_every and not steps_per_epoch and stop))
                # Flush before a checkpoint (guard callbacks must see this
                # window first so a poisoned state is never written over
                # retained good saves) and before eval (val_* events must
                # follow the train metrics of the same step, in order).
                if (len(pending) * k >= self.config.log_every or stop
                        or will_ckpt or eval_due):
                    # One device fetch for the whole pending window, via
                    # the guarded seam: a sharded metric leaf means a step
                    # skipped its in-graph reduction and must fail loudly,
                    # not flow per-shard garbage into callbacks.
                    with events.span("train/host_callbacks", step=cur,
                                     steps=len(pending) * k):
                        host = collectives.host_all_reduce_mean(
                            [m for _, m in pending], self.mesh)
                        for (s, _), m in zip(pending, host):
                            host_m = {kk: float(v)
                                      for kk, v in m.items()}
                            stop |= self.callbacks.step_end(s, host_m)
                            last_metrics = host_m
                        pending.clear()
                if eval_due:
                    src = (eval_batches() if callable(eval_batches)
                           else eval_batches)
                    view = self.config.eval_state_view
                    eval_state = view(state) if view is not None else state
                    self.callbacks.eval_begin()
                    try:
                        with events.span("train/eval", step=cur):
                            val = {f"val_{kk}": v for kk, v in
                                   self.evaluate(
                                       src, eval_state,
                                       steps=eval_steps).items()}
                    finally:
                        self.callbacks.eval_end()
                    last_metrics = dict(last_metrics, **val)
                    # Dedicated callback event carrying only val_* metrics:
                    # EarlyStopping(monitor="val_loss") sees them;
                    # train-metric monitors ignore the event.
                    stop |= self.callbacks.step_end(cur, val)
                while (steps_per_epoch
                       and done >= (epoch + 1) * steps_per_epoch):
                    epoch += 1
                    stop |= self.callbacks.epoch_end(epoch, last_metrics)
                # The sanctioned state-mutation seam (dynamic LR et al.):
                # runs between jitted steps, after this window's metrics
                # and val_* events reached the callbacks.
                state = self.callbacks.apply_state_transforms(state)
                if will_ckpt and not stop and not self.state_poisoned:
                    with events.span("train/checkpoint_save", step=cur):
                        self.checkpoint_manager.save(cur, state)
                state_box[0] = state
                if stop:
                    break
        finally:
            state_box[0] = state
            device_iter.close()
        if self.checkpoint_manager is not None:
            if not self.state_poisoned:
                with events.span("train/checkpoint_save",
                                 step=int(state.step), final=True):
                    self.checkpoint_manager.save(int(state.step), state,
                                                 force=True)
            # Always await in-flight async saves: an earlier GOOD periodic
            # checkpoint may still be committing and must not be lost just
            # because a later step went non-finite.
            self.checkpoint_manager.wait_until_finished()

    def _forward_loop(self, batches, state, step_fn, steps: Optional[int],
                      fetch=jax.device_get) -> list:
        """Drive a jitted forward step over prefetched batches, collecting
        host results (shared by evaluate/predict).  ``fetch`` maps device
        results to host values — evaluate passes the replication-guarded
        metric fetch; predict keeps the plain device_get (its outputs are
        data and may be legitimately sharded)."""
        from tensorflow_train_distributed_tpu.data.pipeline import (
            prefetch_to_device,
        )

        results = []
        device_iter = prefetch_to_device(iter(batches), self.mesh)
        try:
            for dev_batch in device_iter:
                results.append(fetch(step_fn(state, dev_batch)))
                if steps is not None and len(results) >= steps:
                    break
        finally:
            device_iter.close()
        return results

    def evaluate(
        self,
        batches: Iterable[Mapping[str, np.ndarray]],
        state: TrainState,
        *,
        steps: Optional[int] = None,
    ) -> dict[str, float]:
        acc = MetricAccumulator()
        for metrics in self._forward_loop(
                batches, state, self._compiled_eval_step(), steps,
                fetch=lambda m: collectives.host_all_reduce_mean(
                    m, self.mesh)):
            acc.update({k: float(np.asarray(v)) for k, v in metrics.items()})
        out = acc.result()
        if getattr(self.task, "report_perplexity", False) and "loss" in out:
            # exp of the aggregated mean loss (NOT the mean of per-batch
            # exps — Jensen would bias it high); LM/MLM convention.
            out["perplexity"] = float(np.exp(min(out["loss"], 30.0)))
        return out

    def predict(
        self,
        batches: Iterable[Mapping[str, np.ndarray]],
        state: TrainState,
        *,
        steps: Optional[int] = None,
    ):
        """``Model.predict`` analog (``tf_keras/src/engine/training.py``):
        run the task's forward pass over ``batches`` and return host numpy
        outputs concatenated along the batch axis (pytree-valued outputs
        are concatenated leaf-wise).  Padded-eval batches
        (``drop_remainder=False`` loaders) are handled: pad rows
        (``sample_weight`` 0) are dropped from the output, so predicting
        a finite split returns exactly one row per real example."""
        masks: list = []

        def spy(it):
            for b in it:
                masks.append(np.asarray(b["sample_weight"]) > 0
                             if "sample_weight" in b else None)
                yield b

        outs = self._forward_loop(
            spy(iter(batches)), state, self._compiled_predict_step(),
            steps)
        if not outs:
            raise ValueError("predict got an empty batch iterator")
        out = jax.tree.map(lambda *xs: np.concatenate(xs, axis=0), *outs)
        # Prefetch may have pulled (and spied) more batches than were
        # consumed — align masks with the results actually produced.
        used = masks[:len(outs)]
        if not any(m is not None for m in used):
            return out
        counts = [np.shape(jax.tree.leaves(o)[0])[0] for o in outs]
        keep = np.concatenate([m if m is not None else np.ones(c, bool)
                               for m, c in zip(used, counts)])
        return jax.tree.map(lambda x: x[keep], out)


def _chain_first(first, rest):
    yield first
    yield from rest


def plan_state_memory(
    task: Task,
    sample_batch,
    tx: optax.GradientTransformation,
    mesh,
    *,
    rules: LogicalRules = DEFAULT_RULES,
    policy: Policy = Policy(),
    zero1: bool = False,
) -> dict[str, float]:
    """AOT memory plan: per-device bytes of params + optimizer state.

    Pure shape arithmetic — ``jax.eval_shape`` over state creation plus the
    same sharding resolution ``Trainer.create_state`` uses — so a 7B config
    can be validated against an HBM budget with no chips and no memory
    (``mesh`` may be a ``jax.sharding.AbstractMesh`` for device counts this
    host doesn't have).  The reference answers "does it fit" only by OOM
    trial on real hardware; this is the planning tool SURVEY §7 calls
    make-or-break for the Llama config.

    Returns ``{"total_bytes", "per_device_bytes", "replicated_bytes"}``
    (replicated = leaves no mesh axis shards — the irreducible floor).
    """
    batch_shapes = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype),
        sample_batch,
    )

    def _create():
        init_batch = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), batch_shapes)
        variables = dict(task.init_variables(
            jax.random.key(0), init_batch))
        params = variables.pop("params")
        return TrainState.create(
            params=params, model_state=variables, tx=tx,
            loss_scale=mp.LossScaleState.create(policy))

    abstract = jax.eval_shape(_create)
    shardings = sharding_lib.make_state_shardings(mesh, abstract, rules)
    if zero1:
        shardings = shardings.replace(
            opt_state=sharding_lib.zero1_opt_shardings(
                mesh, abstract.opt_state, shardings.opt_state))
    is_boxed = lambda x: isinstance(x, nn.meta.AxisMetadata)  # noqa: E731
    leaves = jax.tree.leaves(abstract, is_leaf=is_boxed)
    shard_leaves = jax.tree.leaves(
        shardings, is_leaf=lambda x: hasattr(x, "spec"))
    total = per_device = replicated = 0.0
    for leaf, sh in zip(leaves, shard_leaves):
        val = leaf.value if is_boxed(leaf) else leaf
        nbytes = val.dtype.itemsize * int(np.prod(val.shape, dtype=int))
        factor = 1
        for entry in getattr(sh, "spec", ()):
            if entry is None:
                continue
            for axis in (entry,) if isinstance(entry, str) else entry:
                factor *= mesh.shape[axis]
        total += nbytes
        per_device += nbytes / factor
        if factor == 1:
            replicated += nbytes
    return {"total_bytes": total, "per_device_bytes": per_device,
            "replicated_bytes": replicated}
