"""The SPMD training loop: ``Model.fit`` rebuilt TPU-first.

Reference call stack being replaced (SURVEY.md §3.1/§3.2):
``Model.fit`` → ``make_train_function`` → ``strategy.run(step)`` →
per-replica ``train_step`` → optimizer ``aggregate_gradients`` allreduce
(``tf_keras/src/engine/training.py:1453,1338,1118``;
``optimizers/utils.py:23``).  Here the whole stack is ONE jitted function
over global arrays: the gradient allreduce is inserted by GSPMD because the
loss is a mean over the batch axis (sharded over data/fsdp) while params are
replicated (or fsdp-sharded, in which case it becomes reduce-scatter +
all-gather automatically).  There are no per-replica values, no strategy.run
dispatch, no gradient packing — XLA owns all of it.

``steps_per_execution`` (reference: ``training.py`` fit arg) maps to an
inner ``lax.scan`` over a stacked super-batch: k steps per dispatch,
amortizing host→device latency exactly like the reference amortizes
tf.function dispatch.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Callable, Iterable, Mapping, Optional, Protocol, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from tensorflow_train_distributed_tpu.parallel import sharding as sharding_lib
from tensorflow_train_distributed_tpu.parallel.sharding import (
    DEFAULT_RULES, LogicalRules,
)
from tensorflow_train_distributed_tpu.runtime.mesh import batch_axes
from tensorflow_train_distributed_tpu.training import mixed_precision as mp
from tensorflow_train_distributed_tpu.training.callbacks import (
    Callback, CallbackList,
)
from tensorflow_train_distributed_tpu.training.metrics import MetricAccumulator
from tensorflow_train_distributed_tpu.training.mixed_precision import Policy
from tensorflow_train_distributed_tpu.training.train_state import TrainState

import flax.linen as nn

logger = logging.getLogger(__name__)


class Task(Protocol):
    """What a model config provides to the trainer.

    ``init_variables`` returns the flax variable collections
    (``{"params": ..., "batch_stats": ...}``); ``loss_fn`` returns
    ``(scalar_loss, (metrics_dict, new_model_state))``.  The loss must be a
    mean over the *global* batch — that is the contract that makes GSPMD
    insert the cross-replica gradient reduction (the reference's
    ``all_reduce_sum_gradients``).
    """

    def init_variables(self, rng: jax.Array, batch) -> Any: ...

    def loss_fn(self, params, model_state, batch, rng: jax.Array,
                train: bool) -> tuple[jax.Array, tuple[dict, Any]]: ...


@dataclasses.dataclass(frozen=True)
class TrainerConfig:
    seed: int = 0
    steps_per_execution: int = 1
    log_every: int = 10
    checkpoint_every: Optional[int] = None
    donate_state: bool = True


class Trainer:
    """Owns state creation, the jitted step, and the fit/evaluate loops."""

    def __init__(
        self,
        task: Task,
        optimizer: optax.GradientTransformation,
        mesh,
        *,
        rules: LogicalRules = DEFAULT_RULES,
        policy: Policy = Policy(),
        config: TrainerConfig = TrainerConfig(),
        callbacks: Sequence[Callback] = (),
        checkpoint_manager=None,
        lr_schedule=None,
    ):
        self.task = task
        self.tx = optimizer
        # Optional step->lr fn (training.schedules); purely observational —
        # the optimizer already owns the schedule — so `lr` shows up in
        # metrics/TensorBoard like the reference's LearningRateScheduler logs.
        self.lr_schedule = lr_schedule
        self.mesh = mesh
        self.rules = rules
        self.policy = policy
        self.config = config
        self.callbacks = CallbackList(callbacks, trainer=self)
        self.checkpoint_manager = checkpoint_manager
        self._train_step = None
        self._eval_step = None
        self.state_shardings = None
        self._live_state = None

    # -- state ---------------------------------------------------------------

    def create_state(self, sample_batch) -> TrainState:
        """Init params on-device directly into their target shardings.

        The jit-with-out_shardings pattern means a 7B-param model never
        materializes unsharded on one chip — the analog of the reference
        creating variables under ``strategy.scope()`` (``distribute_lib.py:
        1223``) but placement-correct from the first byte.
        """
        rng = jax.random.key(self.config.seed)
        batch_shapes = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype),
            sample_batch,
        )

        def _create():
            # Zeros with the batch's shapes/dtypes: tasks get real traced
            # arrays (the natural `model.init(rng, batch["x"])` idiom works)
            # without baking a real data batch into the init computation.
            init_batch = jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype), batch_shapes
            )
            variables = self.task.init_variables(rng, init_batch)
            variables = dict(variables)
            params = variables.pop("params")
            return TrainState.create(
                params=params,
                model_state=variables,
                tx=self.tx,
                loss_scale=mp.LossScaleState.create(self.policy),
            )

        with sharding_lib.with_logical_rules(self.mesh, self.rules), \
                jax.set_mesh(self.mesh):
            abstract = jax.eval_shape(_create)
            self.state_shardings = sharding_lib.make_state_shardings(
                self.mesh, abstract, self.rules
            )
            state = jax.jit(_create, out_shardings=self.state_shardings)()
        state = nn.unbox(state)
        self.state_shardings = jax.tree.map(lambda x: x.sharding, state)
        logger.info("created state: %.2fM params", state.num_params() / 1e6)
        return state

    # -- step functions ------------------------------------------------------

    def _make_loss_fn(self, model_state, batch, rng, train: bool):
        def loss_fn(params):
            p = self.policy.cast_to_compute(params)
            b = self.policy.cast_to_compute(batch)
            loss, (metrics, new_ms) = self.task.loss_fn(
                p, model_state, b, rng, train
            )
            return loss.astype(jnp.float32), (metrics, new_ms)

        return loss_fn

    def _single_step(self, state: TrainState, batch):
        rng = jax.random.fold_in(jax.random.key(self.config.seed), state.step)
        loss_fn = self._make_loss_fn(state.model_state, batch, rng, True)

        def scaled(params):
            loss, aux = loss_fn(params)
            return mp.scale_loss(loss, state.loss_scale), (loss, aux)

        grad_fn = jax.value_and_grad(scaled, has_aux=True)
        (_, (loss, (metrics, new_ms))), grads = grad_fn(state.params)
        grads = mp.unscale_grads(grads, state.loss_scale)

        if state.loss_scale is not None:
            finite = mp.grads_finite(grads)
            updates, new_opt = self.tx.update(grads, state.opt_state,
                                              state.params)
            new_params = optax.apply_updates(state.params, updates)
            # Skip the update entirely on overflow (LossScaleOptimizer
            # contract: no param/opt-state change on non-finite grads).
            new_params = jax.tree.map(
                lambda n, o: jnp.where(finite, n, o), new_params, state.params)
            new_opt = jax.tree.map(
                lambda n, o: jnp.where(finite, n, o), new_opt, state.opt_state)
            new_ls = mp.update_loss_scale(state.loss_scale, finite,
                                          self.policy)
            metrics = dict(metrics, loss_scale=new_ls.scale,
                           grads_finite=finite.astype(jnp.float32))
        else:
            updates, new_opt = self.tx.update(grads, state.opt_state,
                                              state.params)
            new_params = optax.apply_updates(state.params, updates)
            new_ls = None

        metrics = dict(metrics, loss=loss)
        if self.lr_schedule is not None:
            metrics["lr"] = jnp.asarray(self.lr_schedule(state.step),
                                        jnp.float32)
        new_state = state.replace(
            step=state.step + 1,
            params=new_params,
            model_state=new_ms,
            opt_state=new_opt,
            loss_scale=new_ls,
        )
        return new_state, metrics

    def _compiled_train_step(self):
        if self._train_step is not None:
            return self._train_step
        k = self.config.steps_per_execution
        mesh, rules = self.mesh, self.rules

        def step(state, batch):
            with sharding_lib.with_logical_rules(mesh, rules):
                if k == 1:
                    return self._single_step(state, batch)
                new_state, ms = jax.lax.scan(
                    self._single_step, state, batch
                )
                return new_state, jax.tree.map(lambda m: m[-1], ms)

        donate = (0,) if self.config.donate_state else ()
        jitted = jax.jit(step, donate_argnums=donate)

        def call(state, batch):
            # set_mesh must wrap the call (it is illegal inside jit): it
            # binds the abstract mesh at trace time so mesh-aware ops
            # (seq-parallel attention) see it regardless of call site.
            with jax.set_mesh(self.mesh):
                return jitted(state, batch)

        self._train_step = call
        return self._train_step

    def _compiled_eval_step(self):
        if self._eval_step is not None:
            return self._eval_step

        def step(state, batch):
            with sharding_lib.with_logical_rules(self.mesh, self.rules):
                rng = jax.random.fold_in(
                    jax.random.key(self.config.seed + 1), state.step)
                loss_fn = self._make_loss_fn(state.model_state, batch, rng,
                                             False)
                loss, (metrics, _) = loss_fn(state.params)
                return dict(metrics, loss=loss)

        jitted = jax.jit(step)

        def call(state, batch):
            with jax.set_mesh(self.mesh):
                return jitted(state, batch)

        self._eval_step = call
        return self._eval_step

    # -- loops ---------------------------------------------------------------

    def _stack_batches(self, it, k: int):
        """Group k host batches into one super-batch for the scan path."""
        while True:
            group = []
            for _ in range(k):
                try:
                    group.append(next(it))
                except StopIteration:
                    return
            yield jax.tree.map(lambda *xs: np.stack(xs), *group)

    def fit(
        self,
        batches: Iterable[Mapping[str, np.ndarray]],
        *,
        steps: int,
        state: Optional[TrainState] = None,
        steps_per_epoch: Optional[int] = None,
    ) -> TrainState:
        """Run ``steps`` optimizer steps over ``batches`` (host iterator).

        ``batches`` yields host-local numpy batches (e.g. ``HostDataLoader``);
        sharding to the mesh happens via prefetch.  ``steps_per_epoch`` marks
        epoch boundaries for ``on_epoch_end`` callbacks (loaders may be
        infinite, so epochs are declared, not discovered).  Returns the final
        state.
        """
        from tensorflow_train_distributed_tpu.data.pipeline import (
            prefetch_to_device,
        )

        k = self.config.steps_per_execution
        if steps % k:
            raise ValueError(
                f"steps={steps} must be a multiple of "
                f"steps_per_execution={k} (each dispatch runs exactly k "
                "optimizer steps)"
            )
        it = iter(batches)
        if state is None:
            first = next(it)
            state = self.create_state(first)
            it = _chain_first(first, it)
        if k > 1:
            it = self._stack_batches(it, k)

        step_fn = self._compiled_train_step()
        self.callbacks.train_begin(state)
        start_step = int(state.step)
        done = 0
        epoch = 0
        last_metrics: dict[str, float] = {}
        pending: list[tuple[int, Any]] = []
        stop = False

        from jax.sharding import PartitionSpec as P

        # Super-batches (k>1) carry the scan axis at dim 0; the batch dim —
        # the one sharded over the mesh — is dim 1.
        spec = None if k == 1 else P(None, batch_axes(self.mesh))
        device_iter = prefetch_to_device(it, self.mesh, spec=spec)
        try:
            for dev_batch in device_iter:
                state, metrics = step_fn(state, dev_batch)
                # Callbacks that checkpoint (preemption handler) read the
                # current state from here — fit's loop variable is otherwise
                # invisible to them.
                self._live_state = state
                done += k
                cur = start_step + done
                pending.append((cur, metrics))
                if done >= steps:
                    stop = True
                if len(pending) * k >= self.config.log_every or stop:
                    # One device fetch for the whole pending window.
                    host = jax.device_get([m for _, m in pending])
                    for (s, _), m in zip(pending, host):
                        host_m = {kk: float(v) for kk, v in m.items()}
                        stop |= self.callbacks.step_end(s, host_m)
                        last_metrics = host_m
                    pending.clear()
                while (steps_per_epoch
                       and done >= (epoch + 1) * steps_per_epoch):
                    epoch += 1
                    stop |= self.callbacks.epoch_end(epoch, last_metrics)
                if (self.checkpoint_manager is not None
                        and self.config.checkpoint_every
                        and cur % self.config.checkpoint_every < k):
                    self.checkpoint_manager.save(cur, state)
                if stop:
                    break
        finally:
            device_iter.close()
        if self.checkpoint_manager is not None:
            self.checkpoint_manager.save(int(state.step), state, force=True)
            self.checkpoint_manager.wait_until_finished()
        self.callbacks.train_end(state)
        return state

    def evaluate(
        self,
        batches: Iterable[Mapping[str, np.ndarray]],
        state: TrainState,
        *,
        steps: Optional[int] = None,
    ) -> dict[str, float]:
        from tensorflow_train_distributed_tpu.data.pipeline import (
            prefetch_to_device,
        )

        step_fn = self._compiled_eval_step()
        acc = MetricAccumulator()
        n = 0
        device_iter = prefetch_to_device(iter(batches), self.mesh)
        try:
            with jax.set_mesh(self.mesh):
                for dev_batch in device_iter:
                    metrics = step_fn(state, dev_batch)
                    acc.update({k: float(np.asarray(v))
                                for k, v in metrics.items()})
                    n += 1
                    if steps is not None and n >= steps:
                        break
        finally:
            device_iter.close()
        return acc.result()


def _chain_first(first, rest):
    yield first
    yield from rest
