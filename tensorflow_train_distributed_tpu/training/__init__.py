"""Training loops, callbacks, mixed precision, checkpointing.

TPU-native replacement for the reference's L4 layer: Keras ``Model.fit`` /
``train_step`` / ``make_train_function`` (``tf_keras/src/engine/
training.py:1453,1118,1338``), the callback system (``callbacks.py:202``),
optimizer gradient aggregation (``optimizers/utils.py:23``) and mixed
precision (``mixed_precision/loss_scale_optimizer.py:587``) — rebuilt as one
jitted SPMD step function with donation, an optional ``lax.scan`` inner loop
(the ``steps_per_execution`` analog), and orbax checkpointing.
"""

from tensorflow_train_distributed_tpu.training.mixed_precision import (  # noqa: F401
    Policy,
)
from tensorflow_train_distributed_tpu.training.train_state import (  # noqa: F401
    TrainState,
)
from tensorflow_train_distributed_tpu.training.trainer import (  # noqa: F401
    Trainer,
    TrainerConfig,
    plan_state_memory,
)
from tensorflow_train_distributed_tpu.training.memory import (  # noqa: F401
    decoder_activation_bytes,
    hbm_budget_bytes,
    plan_train_memory,
)
from tensorflow_train_distributed_tpu.training.callbacks import (  # noqa: F401
    BestCheckpoint,
    Callback,
    EarlyStopping,
    History,
    JsonlLogger,
    ProgressLogger,
    ReduceLROnPlateau,
    StallWatchdog,
    TensorBoardScalars,
    TerminateOnNaN,
)
from tensorflow_train_distributed_tpu.training import schedules  # noqa: F401
from tensorflow_train_distributed_tpu.training.ema import (  # noqa: F401
    ema_of_params,
    find_ema_params,
    swap_ema_params,
    wrap_with_ema,
)
