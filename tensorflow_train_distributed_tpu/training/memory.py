"""Memory planning: exact state arithmetic + calibrated activation model.

``plan_state_memory`` (training.trainer) answers "do params + optimizer
state fit" by pure shape arithmetic.  PROFILE.md's measured OOMs show the
*activation working set* is what actually kills large-batch decoder
training, so this module adds an empirical activation estimate and a
combined per-device plan — the make-or-break planning tool SURVEY §7
hard-part 3 calls for (the reference answers "does it fit" only by OOM
trial on real hardware).

The activation model is calibrated against observed XLA allocations on a
real v5e chip (three measured points, pinned by tests):
- llama_125m seq2048 batch8 no-remat: fits (est 14.9 GiB of 15.75);
- llama_125m seq2048 batch16 no-remat: OOM, 26.4 GiB requested (est 28);
- llama_1b batch16 no-remat: state alone exceeds the chip (est > 17).

An HBM-OOM *compile request* has twice killed this environment's chip
tunnel (PROFILE.md) — planning before compiling is not an optimization,
it is how the chip stays alive.
"""

from __future__ import annotations

from typing import Optional

# Usable HBM per chip after the runtime's reserve, by device_kind
# substring (v5e observed directly in OOM reports: 15.75 GiB of 16).
HBM_BUDGET_GIB_BY_KIND = {
    "v5 lite": 15.75,
    "v5e": 15.75,
    "v4": 31.25,
    "v5p": 94.75,
    "v6": 31.25,
}

# bf16 peak TFLOP/s by TPU generation — kept beside the HBM table so
# roofline/MFU consumers (bench tools) share one source.
PEAK_TFLOPS_BY_KIND = {
    "v5 lite": 197.0,
    "v5e": 197.0,
    "v5p": 459.0,
    "v4": 275.0,
    "v6": 918.0,
}

# HBM bandwidth GB/s by generation (public spec sheets) — the roofline
# for bandwidth-bound regimes (BN statistics, autoregressive decode).
HBM_GBPS_BY_KIND = {
    "v5 lite": 819.0,
    "v5e": 819.0,
    "v5p": 2765.0,
    "v4": 1228.0,
    "v6": 1640.0,
}

# Bytes of optimizer+param state per parameter under the mixed-bf16 adam
# recipe: bf16 compute copy + f32 master + 2×f32 moments + grads in
# flight.
STATE_BYTES_PER_PARAM = 14


def hbm_budget_bytes(device_kind: str) -> Optional[float]:
    """Per-chip HBM budget for a device kind, or None when unknown."""
    kind = device_kind.lower()
    for sub, gib in HBM_BUDGET_GIB_BY_KIND.items():
        if sub in kind:
            return gib * 2**30
    return None


def peak_tflops(device_kind: str) -> Optional[float]:
    kind = device_kind.lower()
    for sub, peak in PEAK_TFLOPS_BY_KIND.items():
        if sub in kind:
            return peak
    return None


def hbm_bandwidth_bytes_per_sec(device_kind: str) -> Optional[float]:
    kind = device_kind.lower()
    for sub, gbps in HBM_GBPS_BY_KIND.items():
        if sub in kind:
            return gbps * 1e9
    return None


def decoder_activation_bytes(num_layers: int, d_model: int, batch: int,
                             seq: int, *, remat: bool, causal: bool = True,
                             score_heads: int = 1,
                             ffn_size: Optional[int] = None,
                             save_ffn_hiddens: bool = True) -> int:
    """Empirical activation working set of one train step, in bytes.

    ``batch``/``seq`` are PER-DEVICE extents (divide global dims by the
    mesh's batch/seq shard degrees first — ``plan_train_memory`` does).

    remat:    ~6 residual passes of bf16 [B,S,d] per layer (layer inputs
              + flash l/m/out saved across the scan).
    no-remat: adds ~24 [B,S,d] passes per layer (q/k/v/o + SwiGLU gate/up
              hiddens saved for backward) and ~6 score-sized temps per
              layer stack.  ``score_heads=1`` models the flash path (no
              materialized [S,S] per head); pass ``num_heads`` for models
              on the reference einsum attention (BERT), which saves
              per-head [B,H,S,S] logits/probs for backward.
    """
    act = num_layers * batch * seq * d_model * 2 * 6
    score_term = (6 * score_heads * batch * seq * seq * 2
                  // (2 if causal else 1))
    if not remat:
        passes = 24
        if not save_ffn_hiddens:
            # remat_policy="no_ffn": the ~3 [B,S,ffn] hidden tensors are
            # re-computed, not saved — subtract their d_model-equivalent
            # passes (3·ffn/d; the SwiGLU default ffn≈2.67d gives 8).
            ffn = ffn_size if ffn_size else int(8 * d_model / 3)
            passes -= min(passes - 4, int(round(3 * ffn / d_model)))
        act += num_layers * batch * seq * d_model * 2 * passes
        act += num_layers * score_term
    elif score_heads > 1:
        # Per-layer remat still rematerializes ONE layer's einsum-attention
        # score buffers during its backward — a transient, but it peaks
        # alongside the saved boundaries, so large-seq configs can OOM the
        # compile even though nothing seq²-sized is *saved*.
        act += score_term
    return act


def _model_dims(task):
    """Activation-model inputs (a dict of dims/flags) from a task config.

    Decoder families (llama/moe) run the flash kernel (score_heads=1,
    causal); BERT runs the reference einsum attention (per-head scores,
    bidirectional).  Raises for configs the activation model doesn't
    cover — a wrong estimate is worse than none (it green-lights a
    tunnel-killing compile).
    """
    cfg = getattr(task, "config", None)
    if cfg is None:
        raise ValueError(
            f"{type(task).__name__} has no .config; pass explicit dims "
            "via decoder_activation_bytes instead")
    if hasattr(cfg, "num_experts"):
        raise ValueError(
            "the activation model is calibrated for dense decoders/"
            "encoders only — MoE adds [G,S,E,C] dispatch/combine tensors "
            "and expert buffers it has no term for, so an estimate here "
            "would green-light OOM compiles; budget MoE configs by AOT "
            "compile (Trainer.lower_train_step + memory_analysis) instead")
    num_layers = getattr(cfg, "num_layers", None)
    width = getattr(cfg, "d_model", None) or getattr(cfg, "hidden_size",
                                                     None)
    if num_layers is None or width is None:
        raise ValueError(
            f"{type(cfg).__name__} lacks num_layers/d_model dims for the "
            "activation model")
    remat = bool(getattr(cfg, "remat", False))
    # Policy-aware budgeting (mirrors bench_lm): "dots" saves the SwiGLU
    # hiddens so it budgets as no-remat; "no_ffn" is no-remat MINUS the
    # hiddens it re-computes.
    remat_policy = getattr(cfg, "remat_policy", "full")
    effective_remat = remat and remat_policy not in ("dots", "no_ffn")
    save_ffn = not (remat and remat_policy == "no_ffn")
    ffn = (getattr(cfg, "ffn_size", None)
           or getattr(cfg, "intermediate_size", None))
    bidirectional = hasattr(cfg, "intermediate_size")  # BERT-shaped
    score_heads = cfg.num_heads if bidirectional else 1
    return {"num_layers": num_layers, "width": width,
            "remat": effective_remat, "causal": not bidirectional,
            "score_heads": score_heads, "ffn_size": ffn,
            "save_ffn_hiddens": save_ffn}


def plan_train_memory(task, sample_batch, tx, mesh, *,
                      rules=None, policy=None, zero1: bool = False,
                      device_kind: Optional[str] = None) -> dict:
    """Combined per-device plan: exact state + estimated activations.

    Returns ``plan_state_memory``'s dict extended with
    ``activation_bytes_per_device``, ``step_bytes_per_device`` (state +
    activations) and, when ``device_kind`` names a known TPU generation,
    ``budget_bytes`` and ``fits`` — the pre-flight answer for "can this
    config's train step compile on that chip without gambling the
    tunnel".
    """
    import numpy as np

    from tensorflow_train_distributed_tpu.runtime.mesh import batch_axes
    from tensorflow_train_distributed_tpu.training.mixed_precision import (
        Policy,
    )
    from tensorflow_train_distributed_tpu.training.trainer import (
        DEFAULT_RULES, plan_state_memory,
    )

    rules = DEFAULT_RULES if rules is None else rules
    policy = Policy() if policy is None else policy
    plan = plan_state_memory(task, sample_batch, tx, mesh, rules=rules,
                             policy=policy, zero1=zero1)
    dims = _model_dims(task)
    tokens = next(v for k, v in sorted(sample_batch.items())
                  if np.ndim(v) >= 2)
    global_batch, seq = np.shape(tokens)[:2]
    batch_shards = 1
    for axis in batch_axes(mesh):
        batch_shards *= mesh.shape[axis]
    seq_shards = dict(mesh.shape).get("seq", 1)
    per_dev_batch = max(1, global_batch // batch_shards)
    per_dev_seq = max(1, seq // seq_shards)
    act = decoder_activation_bytes(
        dims["num_layers"], dims["width"], per_dev_batch, per_dev_seq,
        remat=dims["remat"], causal=dims["causal"],
        score_heads=dims["score_heads"], ffn_size=dims["ffn_size"],
        save_ffn_hiddens=dims["save_ffn_hiddens"])
    plan["activation_bytes_per_device"] = float(act)
    plan["step_bytes_per_device"] = plan["per_device_bytes"] + act
    if device_kind is not None:
        budget = hbm_budget_bytes(device_kind)
        if budget is not None:
            plan["budget_bytes"] = budget
            plan["fits"] = plan["step_bytes_per_device"] <= budget
    return plan
