"""Checkpoint/resume on orbax — sharded, async, multi-host.

Reference surface being replaced (SURVEY.md §5.4): ``tf.train.Checkpoint``
(``python/checkpoint/checkpoint.py:2061``), ``CheckpointManager`` keep-N /
step numbering (``checkpoint_management.py:519``), chief-only writes
(``multi_worker_util.py:270``), mid-run resume via ``BackupAndRestore``
(``tf_keras/src/callbacks.py:1755``), and preemption-coordinated saves
(``failure_handling/failure_handling.py:337``).

Orbax gives the multi-host rules for free: every process participates in
writing its shards (strictly better than chief-only for sharded state),
atomicity via commit markers, async so the TPU never waits on GCS/disk.
"""

from __future__ import annotations

import logging
from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

logger = logging.getLogger(__name__)


class CheckpointManager:
    """Keep-N async checkpointing of ``TrainState`` pytrees."""

    def __init__(
        self,
        directory: str,
        *,
        max_to_keep: int = 3,
        save_interval_steps: int = 1,
        async_save: bool = True,
    ):
        self._mgr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=async_save,
                create=True,
            ),
        )

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        if step in self._mgr.all_steps():
            return False  # already saved (e.g. periodic save + end-of-fit)
        saved = self._mgr.save(
            step, args=ocp.args.StandardSave(state), force=force
        )
        if saved:
            logger.info("checkpoint saved at step %d", step)
        return saved

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, abstract_state: Any, step: Optional[int] = None):
        """Restore into the shardings/dtypes of ``abstract_state``.

        ``abstract_state`` may be a concrete state (its arrays' shardings are
        reused — the mid-run ``BackupAndRestore`` path) or a tree of
        ShapeDtypeStructs with shardings attached.  Returns None when no
        checkpoint exists (caller starts fresh).
        """
        step = self._mgr.latest_step() if step is None else step
        if step is None:
            return None
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=getattr(x, "sharding", None))
            if hasattr(x, "shape") else x,
            abstract_state,
        )
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(abstract)
        )
        logger.info("restored checkpoint step %d", step)
        return restored

    def _restore_subtrees(self, step: int, names: tuple,
                          required: tuple):
        """Partial restore of top-level ``TrainState`` subtrees.

        Deserializes ONLY the named subtrees — a full ``restore(step)``
        would materialize the optimizer moments too (~3× params of host
        RAM under adamw, enough to OOM an export host at 7B scale) just to
        throw them away.  Subtrees in ``names`` but not in ``required``
        are optional: absent or empty in the checkpoint → ``{}``.
        """
        import os

        item_dir = os.path.join(str(self._mgr.directory), str(step),
                                "default")
        # Metadata straight from the item dir: the manager's
        # ``item_metadata`` comes back None on a freshly opened manager
        # (handler registry only populates after a save/restore call).
        # Old orbax returns the tree dict directly; new orbax wraps it
        # in a CheckpointMetadata whose ``item_metadata`` is the tree.
        meta = ocp.StandardCheckpointer().metadata(item_dir)
        meta = getattr(meta, "item_metadata", meta)
        item = {}
        for name in names:
            try:
                sub_meta = meta[name]
            except KeyError:
                if name in required:
                    raise KeyError(
                        f"checkpoint step {step} has no {name!r} subtree; "
                        f"keys: {sorted(meta.keys())}") from None
                continue  # optional subtree (e.g. empty model_state)
            item[name] = jax.tree.map(
                lambda m: jax.ShapeDtypeStruct(m.shape, m.dtype), sub_meta)
        restored = ocp.PyTreeCheckpointer().restore(
            item_dir,
            args=ocp.args.PyTreeRestore(
                item=item,
                restore_args=jax.tree.map(lambda _: ocp.RestoreArgs(),
                                          item),
                transforms={},
            ),
        )
        logger.info("restored %s subtrees from step %d",
                    "/".join(sorted(item)), step)
        return {name: restored.get(name, {}) for name in names}

    def restore_params(self, step: Optional[int] = None):
        """Raw ``params`` subtree as host arrays, no state template.

        For consumers that need only the weights (analysis tools):
        restoring through ``restore`` requires rebuilding the exact
        optimizer/loss-scale state the run trained with, which a tool
        cannot know.  Returns None when no checkpoint exists.
        """
        step = self._mgr.latest_step() if step is None else step
        if step is None:
            return None
        return self._restore_subtrees(
            step, ("params",), required=("params",))["params"]

    def restore_inference_state(self, step: Optional[int] = None):
        """``(params, model_state)`` for inference/export consumers.

        ``model_state`` carries the trained non-trainable collections
        (BatchNorm running statistics) — exporting with fresh-init stats
        would serve garbage for BN models.  It restores as ``{}`` when the
        model has no mutable collections (the subtree is empty, so orbax
        never wrote it).  Returns None when no checkpoint exists.
        """
        step = self._mgr.latest_step() if step is None else step
        if step is None:
            return None
        tree = self._restore_subtrees(
            step, ("params", "model_state"), required=("params",))
        return tree["params"], tree["model_state"]

    def wait_until_finished(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()
