"""Checkpoint/resume on orbax — sharded, async, multi-host.

Reference surface being replaced (SURVEY.md §5.4): ``tf.train.Checkpoint``
(``python/checkpoint/checkpoint.py:2061``), ``CheckpointManager`` keep-N /
step numbering (``checkpoint_management.py:519``), chief-only writes
(``multi_worker_util.py:270``), mid-run resume via ``BackupAndRestore``
(``tf_keras/src/callbacks.py:1755``), and preemption-coordinated saves
(``failure_handling/failure_handling.py:337``).

Orbax gives the multi-host rules for free: every process participates in
writing its shards (strictly better than chief-only for sharded state),
atomicity via commit markers, async so the TPU never waits on GCS/disk.
"""

from __future__ import annotations

import logging
import os
import shutil
from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from tensorflow_train_distributed_tpu.runtime import faults

logger = logging.getLogger(__name__)

# Orbax's per-step commit marker: written last, so a step dir missing it
# is a torn save from a crashed/killed writer, never a complete one.
COMMIT_MARKER = "_CHECKPOINT_METADATA"
QUARANTINE_DIR = "corrupt"


class CheckpointManager:
    """Keep-N async checkpointing of ``TrainState`` pytrees."""

    def __init__(
        self,
        directory: str,
        *,
        max_to_keep: int = 3,
        save_interval_steps: int = 1,
        async_save: bool = True,
    ):
        self._mgr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=async_save,
                create=True,
            ),
        )

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        if step in self._mgr.all_steps():
            return False  # already saved (e.g. periodic save + end-of-fit)
        saved = self._mgr.save(
            step, args=ocp.args.StandardSave(state), force=force
        )
        if saved:
            logger.info("checkpoint saved at step %d", step)
            if faults.ARMED:
                faults.on_checkpoint_save(
                    step, self._step_dir(step), manager=self)
        return saved

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def _step_dir(self, step: int) -> str:
        return os.path.join(str(self._mgr.directory), str(step))

    def _quarantine(self, step: int) -> str:
        """Move a bad step dir to ``<dir>/corrupt/<step>`` (keeping the
        evidence for post-mortem instead of deleting it) and refresh the
        manager's cached step list so keep-N GC and ``latest_step`` stop
        seeing the step."""
        src = self._step_dir(step)
        qroot = os.path.join(str(self._mgr.directory), QUARANTINE_DIR)
        os.makedirs(qroot, exist_ok=True)
        dst = os.path.join(qroot, str(step))
        n = 0
        while os.path.exists(dst):      # re-corrupted resave of a step
            n += 1
            dst = os.path.join(qroot, f"{step}.{n}")
        shutil.move(src, dst)
        self._mgr.reload()
        return dst

    def _probe_residual_meta(self, step: int):
        """One best-effort orbax metadata probe of the saved
        ``grad_residual``: ``(True, subtree)`` when the saved tree
        carries residual LEAVES (leaf objects carry
        ``.shape``/``.dtype``), ``(True, None)`` when it does not, and
        ``(False, None)`` when the probe itself fails (callers fall
        back to a plain restore).  A state saved with
        grad_residual=None keeps the KEY with a None value in the
        metadata tree — presence means leaves, not key membership."""
        item_dir = os.path.join(self._step_dir(step), "default")
        try:
            meta = ocp.StandardCheckpointer().metadata(item_dir)
            meta = getattr(meta, "item_metadata", meta)
            sub = meta["grad_residual"] if "grad_residual" in meta else None
            return True, (sub if jax.tree.leaves(sub) else None)
        except Exception:  # noqa: BLE001 — metadata probe is best-effort
            return False, None

    def _split_missing_residual(self, step: int, abstract,
                                probed=None):
        """Back-compat for checkpoints saved before the train state
        carried ``grad_residual`` (quantized gradient collectives'
        error-feedback buffers): when the template asks for residual
        leaves but the saved tree has no ``grad_residual`` subtree,
        return ``(abstract_without_residual, residual_template)`` so
        the caller restores the old layout and zero-fills the residual
        — a pre-quant run's checkpoint resumes into a grad-quant
        trainer with error feedback starting from zero (its exact
        semantics at step 0).  ``(abstract, None)`` when nothing to do.
        ``probed`` reuses a caller's ``_probe_residual_meta`` result
        instead of probing the same directory twice.
        """
        res = getattr(abstract, "grad_residual", None)
        if res is None or not jax.tree.leaves(res):
            return abstract, None
        ok, saved = probed if probed is not None \
            else self._probe_residual_meta(step)
        if not ok or saved is not None:
            return abstract, None
        return abstract.replace(grad_residual=None), res

    def _restore_without_residual(self, step: int, abstract):
        """Partial restore of every top-level subtree EXCEPT
        ``grad_residual`` into the template's shardings; the state
        comes back with ``grad_residual=None`` and the residual bytes
        are never deserialized.  Shared by the drop-residual compat
        path and the mesh-resize reshard path (which reattaches a
        refolded residual afterwards)."""
        import dataclasses as _dc

        item_dir = os.path.join(self._step_dir(step), "default")
        item = {}
        rest = {}
        for f in _dc.fields(abstract):
            sub = getattr(abstract, f.name)
            if f.name != "grad_residual" and jax.tree.leaves(sub):
                item[f.name] = sub
            else:
                rest[f.name] = None if f.name == "grad_residual" else sub

        def _ra(s):
            sharding = getattr(s, "sharding", None)
            if sharding is not None:
                return ocp.ArrayRestoreArgs(sharding=sharding)
            return ocp.RestoreArgs()

        restored = ocp.PyTreeCheckpointer().restore(
            item_dir,
            args=ocp.args.PyTreeRestore(
                item=item,
                restore_args=jax.tree.map(_ra, item),
                transforms={},
            ),
        )
        return type(abstract)(**{**rest, **restored})

    def _restore_dropping_residual(self, step: int, abstract):
        """The reverse compat direction: the saved tree CARRIES
        ``grad_residual`` leaves (a grad-quant run's checkpoint) but
        the template does not (``--grad-quant none`` or the
        ``TTD_NO_GRAD_QUANT=1`` kill-switch restart).  A
        ``StandardRestore`` of the leafless template would trip over
        the extra subtree, so restore every OTHER top-level subtree
        via a partial ``PyTreeRestore`` into the template's shardings
        — the residual bytes are never even deserialized (error
        feedback restarts from zero if quant is re-enabled later,
        which is what dropping the residual means)."""
        restored = self._restore_without_residual(step, abstract)
        logger.info(
            "checkpoint carries grad_residual but the trainer runs "
            "without grad-quant: restored dropping the residual "
            "(error feedback restarts from zero if re-enabled)")
        return restored

    def _restore_resharded_residual(self, step: int, abstract,
                                    saved_meta):
        """Mesh-resize restore for the one shape-dependent leaf family:
        ``grad_residual`` rows are PER DATA REPLICA (leading dim = the
        saving mesh's dp degree), so an N-chip checkpoint's residual
        cannot StandardRestore into an M-chip template.  Restore
        everything else into the template's shardings, deserialize the
        residual at its SAVED shape (host arrays), refold the leading
        dim sum-preservingly (``sharding.fold_leading_replicas`` — the
        cross-replica sum is all error feedback ever consumes), and
        place the result into the template's shardings."""
        from tensorflow_train_distributed_tpu.parallel.sharding import (
            fold_leading_replicas,
        )

        restored = self._restore_without_residual(step, abstract)
        item_dir = os.path.join(self._step_dir(step), "default")
        old_abstract = jax.tree.map(
            lambda m: jax.ShapeDtypeStruct(m.shape, m.dtype), saved_meta)
        item = {"grad_residual": old_abstract}
        raw = ocp.PyTreeCheckpointer().restore(
            item_dir,
            args=ocp.args.PyTreeRestore(
                item=item,
                restore_args=jax.tree.map(lambda _: ocp.RestoreArgs(),
                                          item),
                transforms={},
            ),
        )["grad_residual"]

        template = abstract.grad_residual
        w_old = jax.tree.leaves(old_abstract)[0].shape[0]
        w_new = jax.tree.leaves(template)[0].shape[0]

        def _place(old, tmpl):
            folded = fold_leading_replicas(np.asarray(old),
                                           tmpl.shape[0])
            if folded.shape != tmpl.shape:
                raise ValueError(
                    f"resharded grad_residual leaf {folded.shape} does "
                    f"not match the template's {tmpl.shape}: the "
                    "per-replica rows reshard, the per-param tail must "
                    "match (different model?)")
            folded = folded.astype(tmpl.dtype)
            sharding = getattr(tmpl, "sharding", None)
            if sharding is not None:
                return jax.device_put(folded, sharding)
            return folded

        residual = jax.tree.map(_place, raw, template)
        logger.info(
            "restored checkpoint step %d with grad_residual resharded "
            "%d -> %d data replicas (sum-preserving refold)", step,
            w_old, w_new)
        return restored.replace(grad_residual=residual)

    @staticmethod
    def _zero_residual(restored, residual_abstract):
        """Reattach zero-initialized residual leaves in the template's
        shardings (the quant trainer created them P("data")-sharded).
        Zeros are created ON DEVICE into the target sharding: a host
        np.zeros of the GLOBAL residual (world x params, f32) could
        OOM the host for models whose sharded state fits fine."""
        import functools

        import jax.numpy as jnp

        from tensorflow_train_distributed_tpu.runtime.lint import (
            compilecheck,
        )

        def _zeros(s):
            sharding = getattr(s, "sharding", None)
            if sharding is None:
                return np.zeros(s.shape, s.dtype)
            return compilecheck.jit(
                functools.partial(jnp.zeros, s.shape, s.dtype),
                site="checkpoint.zero_residual", max_compiles=None,
                out_shardings=sharding)()

        zeros = jax.tree.map(_zeros, residual_abstract)
        logger.info(
            "checkpoint predates grad_residual: restored with "
            "error-feedback residuals zero-initialized")
        return restored.replace(grad_residual=zeros)

    def _restore_adapted(self, step: int, abstract):
        """One orbax restore with grad_residual compat in every
        direction: template-has/saved-lacks → restore old layout +
        zero-fill; template-lacks/saved-has → partial restore dropping
        the residual; both-have but the per-replica leading dim differs
        (an N-chip checkpoint restoring onto an M-chip mesh — the
        elastic reshard) → refold the residual; otherwise a plain
        StandardRestore.  Every other leaf is mesh-shape-independent:
        orbax reshards it into the template's shardings natively."""
        import dataclasses as _dc

        probed = None
        if (_dc.is_dataclass(abstract)
                and hasattr(abstract, "grad_residual")):
            probed = self._probe_residual_meta(step)
            ok, saved_meta = probed
            template_res = getattr(abstract, "grad_residual", None)
            if not jax.tree.leaves(template_res):
                if ok and saved_meta is not None:
                    return self._restore_dropping_residual(step, abstract)
            elif ok and saved_meta is not None:
                saved_w = jax.tree.leaves(saved_meta)[0].shape[0]
                tmpl_w = jax.tree.leaves(template_res)[0].shape[0]
                if saved_w != tmpl_w:
                    return self._restore_resharded_residual(
                        step, abstract, saved_meta)
        abstract, res = self._split_missing_residual(step, abstract,
                                                     probed)
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(abstract))
        if res is not None:
            restored = self._zero_residual(restored, res)
        return restored

    def _restore_step(self, step: int, abstract):
        """One restore attempt, validated: a missing commit marker is a
        torn save (crashed writer) and fails *before* orbax walks the
        tree — the cheap pre-check; everything else surfaces as
        whatever tensorstore/orbax raises on torn array data."""
        if not os.path.exists(os.path.join(self._step_dir(step),
                                           COMMIT_MARKER)):
            raise ValueError(
                f"checkpoint step {step} has no {COMMIT_MARKER} commit "
                "marker (torn save)")
        return self._restore_adapted(step, abstract)

    def restore(self, abstract_state: Any, step: Optional[int] = None):
        """Restore into the shardings/dtypes of ``abstract_state``.

        ``abstract_state`` may be a concrete state (its arrays' shardings are
        reused — the mid-run ``BackupAndRestore`` path) or a tree of
        ShapeDtypeStructs with shardings attached.  Returns None when no
        checkpoint exists (caller starts fresh).

        Reshard-on-resize: the template's shardings may target a mesh
        of a DIFFERENT size/shape than the one that saved (the elastic
        relaunch after device loss, or a deliberate resize) — orbax
        reads each leaf straight into the target sharding, and the one
        mesh-shape-dependent leaf family (the quantized-collectives
        ``grad_residual``, one row per data replica) is refolded
        sum-preservingly (``_restore_resharded_residual``).  Covered
        layouts: dp, dp×fsdp/tp, zero1 moments, residual-carrying
        quant states.

        Crash-consistent fallback (``step=None`` — the relaunch path): a
        step that fails to restore (torn save from a kill -9, truncated
        arrays from a flaky disk) is moved to ``<dir>/corrupt/<step>``
        and the previous retained step is tried, oldest-good wins —
        a supervisor relaunch must never crash-loop on a bad latest
        step when an older good one exists.  A missing commit marker is
        *definitive* corruption (the marker is written last) and
        quarantines immediately; a step whose marker is intact but
        whose restore raises is only quarantined once an OLDER step
        restores successfully — proof the failure is per-step
        corruption.  If NO retained step restores and any failed with
        an intact marker, the error re-raises with every step dir left
        in place: that shape of failure is systemic (changed model
        config, unreadable mount), and quarantining good checkpoints to
        silently restart from init would destroy the run's resume
        state.  An explicitly requested ``step`` still fails hard: the
        caller asked for *that* state, and silently handing back a
        different one would corrupt anything keyed on it (eval-only,
        export).
        """
        if step is not None:
            restored = self._restore_step(step, self._abstract(
                abstract_state))
            logger.info("restored checkpoint step %d", step)
            return restored
        abstract = self._abstract(abstract_state)
        deferred = []        # (step, error): marker-intact failures
        while True:
            skip = {s for s, _ in deferred}
            steps = [s for s in self._mgr.all_steps() if s not in skip]
            if not steps:
                if deferred:
                    bad_step, err = deferred[0]      # the newest failure
                    logger.error(
                        "no retained checkpoint restores, and step %d "
                        "failed with an INTACT commit marker (%s: %s) — "
                        "refusing to quarantine or fall back to fresh "
                        "init: this looks systemic (changed model "
                        "config, unreadable mount), not per-step "
                        "corruption", bad_step, type(err).__name__, err)
                    raise err
                return None
            step = max(steps)
            if not os.path.exists(os.path.join(self._step_dir(step),
                                               COMMIT_MARKER)):
                quarantined = self._quarantine(step)
                logger.error(
                    "checkpoint step %d has no %s commit marker (torn "
                    "save); quarantined to %s and falling back to the "
                    "previous retained step", step, COMMIT_MARKER,
                    quarantined)
                continue
            try:
                restored = self._restore_adapted(step, abstract)
            except Exception as e:      # noqa: BLE001 — any torn read
                deferred.append((step, e))
                logger.error(
                    "checkpoint step %d failed to restore (%s: %s); "
                    "trying the previous retained step", step,
                    type(e).__name__, e)
                continue
            for bad_step, err in deferred:
                quarantined = self._quarantine(bad_step)
                logger.error(
                    "checkpoint step %d failed to restore (%s: %s) "
                    "while step %d restored cleanly — per-step "
                    "corruption; quarantined to %s", bad_step,
                    type(err).__name__, err, step, quarantined)
            logger.info("restored checkpoint step %d", step)
            return restored

    @staticmethod
    def _abstract(abstract_state: Any):
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=getattr(x, "sharding", None))
            if hasattr(x, "shape") else x,
            abstract_state,
        )

    def _restore_subtrees(self, step: int, names: tuple,
                          required: tuple):
        """Partial restore of top-level ``TrainState`` subtrees.

        Deserializes ONLY the named subtrees — a full ``restore(step)``
        would materialize the optimizer moments too (~3× params of host
        RAM under adamw, enough to OOM an export host at 7B scale) just to
        throw them away.  Subtrees in ``names`` but not in ``required``
        are optional: absent or empty in the checkpoint → ``{}``.
        """
        import os

        item_dir = os.path.join(str(self._mgr.directory), str(step),
                                "default")
        # Metadata straight from the item dir: the manager's
        # ``item_metadata`` comes back None on a freshly opened manager
        # (handler registry only populates after a save/restore call).
        # Old orbax returns the tree dict directly; new orbax wraps it
        # in a CheckpointMetadata whose ``item_metadata`` is the tree.
        meta = ocp.StandardCheckpointer().metadata(item_dir)
        meta = getattr(meta, "item_metadata", meta)
        item = {}
        for name in names:
            try:
                sub_meta = meta[name]
            except KeyError:
                if name in required:
                    raise KeyError(
                        f"checkpoint step {step} has no {name!r} subtree; "
                        f"keys: {sorted(meta.keys())}") from None
                continue  # optional subtree (e.g. empty model_state)
            item[name] = jax.tree.map(
                lambda m: jax.ShapeDtypeStruct(m.shape, m.dtype), sub_meta)
        restored = ocp.PyTreeCheckpointer().restore(
            item_dir,
            args=ocp.args.PyTreeRestore(
                item=item,
                restore_args=jax.tree.map(lambda _: ocp.RestoreArgs(),
                                          item),
                transforms={},
            ),
        )
        logger.info("restored %s subtrees from step %d",
                    "/".join(sorted(item)), step)
        return {name: restored.get(name, {}) for name in names}

    def restore_params(self, step: Optional[int] = None):
        """Raw ``params`` subtree as host arrays, no state template.

        For consumers that need only the weights (analysis tools):
        restoring through ``restore`` requires rebuilding the exact
        optimizer/loss-scale state the run trained with, which a tool
        cannot know.  Returns None when no checkpoint exists.
        """
        step = self._mgr.latest_step() if step is None else step
        if step is None:
            return None
        return self._restore_subtrees(
            step, ("params",), required=("params",))["params"]

    def restore_inference_state(self, step: Optional[int] = None):
        """``(params, model_state)`` for inference/export consumers.

        ``model_state`` carries the trained non-trainable collections
        (BatchNorm running statistics) — exporting with fresh-init stats
        would serve garbage for BN models.  It restores as ``{}`` when the
        model has no mutable collections (the subtree is empty, so orbax
        never wrote it).  Returns None when no checkpoint exists.
        """
        step = self._mgr.latest_step() if step is None else step
        if step is None:
            return None
        tree = self._restore_subtrees(
            step, ("params", "model_state"), required=("params",))
        return tree["params"], tree["model_state"]

    def wait_until_finished(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()
