"""Sequence packing: variable-length documents → fixed [seq] LM rows.

Real corpora are variable-length; TPU training wants static shapes and no
wasted positions.  Packing concatenates documents into fixed-length rows
with three side arrays the model consumes:

- ``segment_ids``  — which document each position belongs to (1-based;
  0 marks padding).  Attention is restricted to same-segment pairs (the
  pallas flash kernel handles this natively via ``SegmentIds``), so a
  packed row trains *identically* to each document alone.
- positions are derived in-model (``segment_relative_positions``): RoPE
  restarts at each document boundary.
- ``loss_weights`` — 1.0 where ``targets`` is a real next-token label,
  0.0 at document-final positions (the "next token" would be the next
  document's first token) and padding.

The reference has no packing story (its corpora are pre-batched fixed
shapes); this is the long-context-first-class piece of the rebuild.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np


def pack_documents(docs: Sequence[np.ndarray], seq_len: int,
                   *, pad_id: int = 0):
    """Greedy sequential packing → list of LM records.

    Documents are laid into rows in order; a document longer than the
    remaining space is split across rows (its continuation keeps a fresh
    segment id — attention never crosses a row boundary anyway).  Each
    record: ``tokens``/``targets`` [seq_len] int32, ``segment_ids``
    [seq_len] int32 (0 = padding), ``loss_weights`` [seq_len] float32.
    Targets are the next token *within* the document; the final position
    of each document (and padding) carries weight 0.
    """
    if seq_len < 2:
        raise ValueError(f"seq_len must be >= 2, got {seq_len}")
    records = []
    row_toks: list[np.ndarray] = []
    row_segs: list[np.ndarray] = []
    row_tgts: list[np.ndarray] = []
    row_wts: list[np.ndarray] = []
    used = 0
    seg = 0

    def flush():
        nonlocal used
        if used == 0:
            return
        pad = seq_len - used
        toks = np.concatenate(row_toks + [np.full(pad, pad_id, np.int32)])
        segs = np.concatenate(row_segs + [np.zeros(pad, np.int32)])
        tgts = np.concatenate(row_tgts + [np.full(pad, pad_id, np.int32)])
        wts = np.concatenate(row_wts + [np.zeros(pad, np.float32)])
        records.append({"tokens": toks, "targets": tgts,
                        "segment_ids": segs, "loss_weights": wts})
        row_toks.clear(), row_segs.clear(), row_tgts.clear(), row_wts.clear()
        used = 0

    def _take(remaining: int, space: int) -> int:
        """Tokens to place now.  Never leaves a 1-token continuation: its
        lone position would carry loss weight 0 (dead packed capacity), so
        the split point moves back one and the continuation keeps a
        labeled next-token pair."""
        take = min(remaining, space)
        if remaining - take == 1:
            take -= 1
        return take

    for doc in docs:
        doc = np.asarray(doc, np.int32).ravel()
        if doc.size < 2:
            # A 1-token document has no next-token pair to learn from.
            continue
        start = 0
        while start < doc.size:
            if used == seq_len:
                flush()
            take = _take(doc.size - start, seq_len - used)
            if take < 2 and doc.size - start >= 2:
                # Don't strand a <2-token piece at a row end (a sliver, or
                # the split-back above) — start a fresh row instead.  In a
                # fresh row take ≥ 2 for seq_len ≥ 3; the seq_len == 2
                # degenerate edge can still yield a labeled 1-token piece.
                flush()
                take = _take(doc.size - start, seq_len)
            piece = doc[start:start + take]
            seg += 1
            row_toks.append(piece)
            row_segs.append(np.full(take, seg, np.int32))
            tgt = np.concatenate([piece[1:], [pad_id]]).astype(np.int32)
            wt = np.ones(take, np.float32)
            if start + take < doc.size:
                # Split mid-document: the true next token exists (the
                # continuation's first token) — keep it as a labeled
                # position; the prefix context is all same-document.
                tgt[-1] = doc[start + take]
            else:
                wt[-1] = 0.0  # document end: "next" is another document
            row_tgts.append(tgt)
            row_wts.append(wt)
            used += take
            start += take
    flush()
    return records


class PackedLmSource:
    """``RandomAccessSource`` over packed documents (packs at open).

    For corpora that fit host memory as token arrays; convert to the mmap
    format for anything bigger.  Deterministic: the packing is a pure
    function of the doc sequence and ``seq_len``.
    """

    def __init__(self, docs: Sequence[np.ndarray], seq_len: int,
                 *, pad_id: int = 0):
        self._records = pack_documents(docs, seq_len, pad_id=pad_id)
        if not self._records:
            raise ValueError("no packable documents (all < 2 tokens?)")
        # O(1) vocab-range validation for launchers: the max id over the
        # packed corpus, tracked here so callers never re-scan it.
        self.max_token_id = max(
            int(r["tokens"].max()) for r in self._records)

    @classmethod
    def from_source(cls, source, seq_len: int, *, key: str = "tokens",
                    pad_id: int = 0) -> "PackedLmSource":
        """Pack variable-length docs out of any ``RandomAccessSource``.

        The natural producer is ``TFRecordSource(paths, features=None)``:
        without a fixed spec it returns each Example's raw flat arrays,
        which is exactly what a varlen tokenized corpus is — so real
        TFRecord document corpora feed packed LM training directly.
        """
        docs = []
        for i in range(len(source)):
            rec = source[i]
            if key not in rec:
                raise KeyError(
                    f"record {i} has no feature {key!r} (has "
                    f"{sorted(rec)}); pass key=/--pack-key naming the "
                    "token feature")
            docs.append(np.asarray(rec[key]).ravel())
        return cls(docs, seq_len, pad_id=pad_id)

    def __len__(self) -> int:
        return len(self._records)

    def __getitem__(self, idx: int):
        if idx < 0 or idx >= len(self._records):
            raise IndexError(idx)
        return self._records[idx]
