"""Host data loading: deterministic sharded batching + device prefetch.

Reference semantics being reproduced (SURVEY.md §3.5):

- **autoshard** (``input_lib.py:729``; policy ``data/ops/options.py:89``):
  each worker sees a disjoint 1/num_processes slice of the data.  We shard
  *by index stride* (DATA policy); FILE-policy sharding belongs to the
  source.
- **rebatch** (``batch_sizes_for_worker``
  ``data/experimental/ops/distribute.py:219``): users specify the *global*
  batch size; each host produces global/num_processes examples, and the
  per-device shard falls out of the batch ``NamedSharding``.
- **prefetch-to-device**: tf.data's device prefetch becomes an async
  double-buffer pushing the next batch to device while the current step
  runs (works because jax dispatch is async).

Determinism: shuffling is a seeded per-epoch permutation computed
identically on every host (same seed ⇒ same permutation ⇒ consistent
global batches), the analog of tf.data's ``shard-then-shuffle`` with a
shared seed.
"""

from __future__ import annotations

import dataclasses
import threading
import queue as queue_lib
from typing import Any, Iterator, Optional, Protocol

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec

from tensorflow_train_distributed_tpu.parallel.sharding import shard_batch


class RandomAccessSource(Protocol):
    """Minimal source protocol (grain-compatible): len + indexed record."""

    def __len__(self) -> int: ...

    def __getitem__(self, idx: int) -> dict[str, np.ndarray]: ...


def fetch_record(source, idx: int, epoch: int = 0) -> dict:
    """Fetch ``source[idx]`` with the epoch threaded to epoch-aware
    transforms (fresh-per-epoch augmentation, reference tf.data
    semantics).  The epoch travels WITH the call — no mutable source
    state — so interleaved iterators over one source (periodic eval,
    ``iter_from`` probes, prefetch threads) can never corrupt each
    other's augmentation epoch.  Sources without the ``get_record`` hook
    fall back to plain indexing (their transforms, if any, are
    epoch-independent)."""
    g = getattr(source, "get_record", None)
    if g is not None:
        return g(idx, epoch)
    return source[idx]


class ConcatSource:
    """Concatenation of per-file sources — the FILE-autoshard unit.

    The reference's ``AutoShardPolicy.FILE`` (``data/ops/options.py:89``)
    assigns whole input files to workers; here a "file" is any
    ``RandomAccessSource`` and this class is the file list.  Use with
    ``DataConfig(shard_policy="file")``.
    """

    def __init__(self, parts):
        if not parts:
            raise ValueError("ConcatSource needs at least one part")
        self.parts = list(parts)
        self._offsets = np.cumsum([0] + [len(p) for p in self.parts])

    def __len__(self) -> int:
        return int(self._offsets[-1])

    def __getitem__(self, idx: int) -> dict[str, np.ndarray]:
        return self.get_record(idx, 0)

    def get_record(self, idx: int, epoch: int = 0) -> dict[str, np.ndarray]:
        """Indexed fetch with the epoch threaded to epoch-aware parts
        (``fetch_record`` semantics)."""
        if idx < 0 or idx >= len(self):
            raise IndexError(idx)
        f = int(np.searchsorted(self._offsets, idx, side="right")) - 1
        return fetch_record(self.parts[f], int(idx - self._offsets[f]), epoch)

    @property
    def epoch_aware(self) -> bool:
        return any(getattr(p, "epoch_aware", False) for p in self.parts)

    def part_indices(self, part: int) -> np.ndarray:
        """Global record indices belonging to file ``part``."""
        return np.arange(self._offsets[part], self._offsets[part + 1])


class MixtureSource:
    """Weighted mixture of sources — the LLM-pretrain data-mixture unit.

    Record ``i`` deterministically comes from one component (chosen by a
    seeded weighted draw) at that component's next sequential position,
    wrapping when a smaller corpus is exhausted (components repeat at
    their weight's rate — the standard mixture semantics; beyond the
    reference, which has no multi-corpus story).  The schedule is drawn
    once from ``seed`` at open (a longer ``num_examples`` with the same
    seed extends the schedule without rescrambling its prefix), making
    the source random-access like any other: DATA autoshard, shuffling,
    and deterministic mid-epoch resume compose unchanged.  (FILE
    autoshard wants a ``ConcatSource`` of per-file parts — mix *inside*
    each part, or shard the mixture with the DATA policy.)

    ``num_examples`` defaults to the total across components (each seen
    ~once at equal weights); set it explicitly for weighted runs where
    "one epoch" is a token budget, not a corpus pass.
    """

    def __init__(self, sources, weights=None, *, seed: int = 0,
                 num_examples: int | None = None):
        if not sources:
            raise ValueError("MixtureSource needs at least one source")
        self.sources = list(sources)
        k = len(self.sources)
        empty = [i for i, s in enumerate(self.sources) if len(s) == 0]
        if empty:
            raise ValueError(
                f"mixture components {empty} are empty (every component "
                "must have at least one record)")
        if weights is None:
            weights = [1.0] * k
        if len(weights) != k:
            raise ValueError(
                f"{k} sources but {len(weights)} weights")
        w = np.asarray(weights, np.float64)
        if (w <= 0).any():
            raise ValueError(f"weights must be > 0, got {weights}")
        self.weights = w / w.sum()
        n = sum(len(s) for s in self.sources) if num_examples is None \
            else num_examples
        if n <= 0:
            raise ValueError(f"num_examples must be > 0, got {n}")
        # Seeded by `seed` alone: rng.choice draws sequentially, so a
        # longer num_examples with the same seed keeps the prefix stable
        # (extending a token budget must not rescramble history).
        rng = np.random.default_rng(np.random.SeedSequence([seed]))
        # Materialized schedule: component id per record + running
        # within-component position.  int8+int32 per record (~5 B/record)
        # — 100M-record mixtures cost ~500 MB of host index, same order
        # as the offset indexes the file sources already keep.
        if k > 127:
            raise ValueError(f"at most 127 mixture components, got {k}")
        self._assignment = rng.choice(
            k, size=n, p=self.weights).astype(np.int8)
        # Within-component cumcount in one stable-argsort pass (a
        # per-component mask loop would be O(k·n) — hundreds of array
        # sweeps at the 100M-record/127-component scale budgeted above).
        order = np.argsort(self._assignment, kind="stable")
        counts = np.bincount(self._assignment, minlength=k)
        starts = np.repeat(np.concatenate(
            [[0], np.cumsum(counts)[:-1]]), counts)
        self._within = np.empty(n, np.int32)
        self._within[order] = (np.arange(n) - starts).astype(np.int32)
        self._n = n

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, idx: int) -> dict[str, np.ndarray]:
        return self.get_record(idx, 0)

    def get_record(self, idx: int, epoch: int = 0) -> dict[str, np.ndarray]:
        """Indexed fetch with the epoch threaded to epoch-aware
        components (``fetch_record`` semantics)."""
        if idx < 0 or idx >= self._n:
            raise IndexError(idx)
        src = self.sources[int(self._assignment[idx])]
        return fetch_record(src, int(self._within[idx]) % len(src), epoch)

    @property
    def epoch_aware(self) -> bool:
        return any(getattr(s, "epoch_aware", False) for s in self.sources)


@dataclasses.dataclass(frozen=True)
class DataConfig:
    """Pipeline configuration (global batch semantics, like the reference)."""

    global_batch_size: int = 32
    shuffle: bool = True
    seed: int = 0
    # True (training default): truncate each epoch to whole batches —
    # static SPMD shapes, no partial batch.  False (evaluation): PAD the
    # final batch to full size instead of dropping it; every batch gains a
    # ``sample_weight`` key ([B] f32, 1.0 real / 0.0 pad) that the Task
    # loss_fns fold into their weighting, so a finite split's metrics
    # cover every example exactly while shapes stay static (SURVEY §7
    # hard-part 2, the reference input layer's last-batch semantics).
    drop_remainder: bool = True
    num_epochs: Optional[int] = None  # None = repeat forever
    prefetch: int = 2
    # Autoshard policy (reference ``AutoShardPolicy``, options.py:89):
    # "data" = index-stride over records (default); "file" = whole files
    # per process (source must be a ``ConcatSource``).  FILE keeps each
    # worker reading only its own files — the policy the reference uses
    # when record-level sharding would defeat sequential file reads.
    shard_policy: str = "data"
    # Native (C++) batch assembly: threaded GIL-free gather via
    # ``native.staging`` — same batches, same order, off the Python hot
    # path. Requires the in-memory source to fit packed in host RAM.
    use_native: bool = False
    native_threads: int = 2


class HostDataLoader:
    """Iterates host-local batches of a sharded source.

    Each process yields ``global_batch_size / process_count`` examples per
    step, drawn from its index-stride shard — together the processes cover
    each epoch exactly once (reference DATA autoshard policy).
    """

    def __init__(
        self,
        source: RandomAccessSource,
        config: DataConfig,
        *,
        process_index: Optional[int] = None,
        process_count: Optional[int] = None,
    ):
        self.source = source
        self.config = config
        self.process_index = (
            jax.process_index() if process_index is None else process_index
        )
        self.process_count = (
            jax.process_count() if process_count is None else process_count
        )
        if config.global_batch_size % self.process_count:
            raise ValueError(
                f"global_batch_size={config.global_batch_size} not divisible "
                f"by process_count={self.process_count}"
            )
        self.host_batch_size = config.global_batch_size // self.process_count
        self._native_packed = None  # pack_for_staging cache (use_native)
        if config.shard_policy not in ("data", "file"):
            raise ValueError(
                f"shard_policy must be data|file, got "
                f"{config.shard_policy!r}")
        if config.shard_policy == "file":
            if not isinstance(source, ConcatSource):
                raise ValueError(
                    "shard_policy='file' needs a ConcatSource (the file "
                    f"list); got {type(source).__name__}")
            if len(source.parts) < self.process_count:
                raise ValueError(
                    f"FILE autoshard needs >= one file per process: "
                    f"{len(source.parts)} files < {self.process_count} "
                    "processes")
            # File f belongs to process f % P (reference FILE policy).
            # Every process computes every shard's size so steps_per_epoch
            # agrees everywhere without communication.
            self._file_shards = [
                np.concatenate([source.part_indices(f)
                                for f in range(q, len(source.parts),
                                               self.process_count)])
                for q in range(self.process_count)
            ]
        if self.steps_per_epoch() == 0:
            # A loader that can never fill one batch would iterate forever
            # yielding nothing (num_epochs=None) — fail at construction.
            raise ValueError(
                f"source yields 0 batches/epoch: per-process records "
                f"< host batch size {self.host_batch_size} "
                f"({len(source)} records over {self.process_count} "
                "processes); shrink the batch or grow the source")

    def _epoch_order(self, epoch: int) -> np.ndarray:
        if self.config.shard_policy == "file":
            # FILE autoshard: this process's records are its whole files;
            # shuffle is within the shard (matching tf.data
            # shard-then-shuffle under FILE policy).
            own = self._file_shards[self.process_index]
            if not self.config.shuffle:
                return own
            rng = np.random.default_rng(
                np.random.SeedSequence([self.config.seed, epoch])
            )
            return own[rng.permutation(len(own))]
        n = len(self.source)
        if self.config.shuffle:
            rng = np.random.default_rng(
                np.random.SeedSequence([self.config.seed, epoch])
            )
            order = rng.permutation(n)
        else:
            order = np.arange(n)
        # Index-stride autoshard: process p takes order[p::P]. Same
        # permutation on every host keeps global batches consistent.
        return order[self.process_index :: self.process_count]

    def _epoch_orders(self) -> Iterator[np.ndarray]:
        """Per-epoch index streams, sized to exactly whole batches —
        truncated (drop_remainder=True) or PADDED with repeats of the
        final index (False; the repeats are masked to weight 0 via
        ``sample_weight`` downstream, so they are NOT distinct records).

        Batch count must be identical on every process or multi-host SPMD
        deadlocks at the epoch boundary (one process enters the collective
        step while another's iterator is exhausted) — so it derives from
        globally-known sizes via ``steps_per_epoch``, never from this
        process's shard length.  Single source of epoch/order/sizing logic
        for both the Python and native batch paths.
        """
        epoch = 0
        while self.config.num_epochs is None or epoch < self.config.num_epochs:
            yield self._padded_order(epoch)
            epoch += 1

    def _augmentation_frozen(self) -> bool:
        """True when the native stager serves ``__iter__`` over an
        epoch-aware source: the stager packs transformed records once, so
        augmentation is frozen at epoch 0 — and ``iter_from`` (always the
        Python path) must ALSO fetch epoch 0, or a preemption restart
        would diverge from the uninterrupted stream."""
        if not self.config.use_native:
            return False
        if not getattr(self.source, "epoch_aware", False):
            return False
        from tensorflow_train_distributed_tpu.native.staging import (
            NativeBatchStager,
        )

        return NativeBatchStager.available()

    def _padded_order(self, epoch: int) -> np.ndarray:
        """Epoch index stream sized to exactly steps_per_epoch batches:
        truncated (drop_remainder) or padded by repeating the final index
        (pad rows get sample_weight 0 downstream — repeating real records
        keeps every model's input distribution valid, unlike zeros)."""
        order = np.asarray(self._epoch_order(epoch))
        want = self.steps_per_epoch() * self.host_batch_size
        if self.config.drop_remainder or len(order) == want:
            return order[:want]
        filler = order[-1:] if len(order) else np.zeros(1, np.int64)
        return np.concatenate(
            [order, np.repeat(filler, want - len(order))])

    def _with_sample_weight(self, batch: dict, in_epoch_batch: int) -> dict:
        """Attach the pad-row mask (drop_remainder=False contract)."""
        if "sample_weight" in batch:
            raise ValueError(
                "source records already have a 'sample_weight' key; the "
                "drop_remainder=False pad mask would clobber it")
        b0 = in_epoch_batch * self.host_batch_size
        w = ((np.arange(self.host_batch_size) + b0)
             < self._shard_len()).astype(np.float32)
        return dict(batch, sample_weight=w)

    def iter_from(self, global_step: int) -> Iterator[dict[str, np.ndarray]]:
        """Iterator positioned after ``global_step`` optimizer steps.

        The reference's mid-epoch resume (``BackupAndRestore``,
        ``tf_keras/src/callbacks.py:1755``) checkpoints iterator state; here
        the loader is deterministic by construction — a seeded per-epoch
        permutation — so "iterator state" is just (epoch, offset) index
        math, identical on every host, with nothing to serialize beyond the
        step already in the train state.
        """
        spe = self.steps_per_epoch()
        if spe == 0:
            return iter(())
        epoch, offset = divmod(global_step, spe)
        if self.config.num_epochs is not None and epoch >= self.config.num_epochs:
            return iter(())

        frozen = self._augmentation_frozen()

        def _resumed():
            first = True
            e = epoch
            while self.config.num_epochs is None or e < self.config.num_epochs:
                order = self._padded_order(e)
                start = offset * self.host_batch_size if first else 0
                first = False
                for b in range(start // self.host_batch_size, spe):
                    idx = order[b * self.host_batch_size:
                                (b + 1) * self.host_batch_size]
                    records = [fetch_record(self.source, int(i),
                                            0 if frozen else e)
                               for i in idx]
                    batch = {k: np.stack([r[k] for r in records])
                             for k in records[0]}
                    if not self.config.drop_remainder:
                        batch = self._with_sample_weight(batch, b)
                    yield batch
                e += 1

        return _resumed()

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        if self.config.use_native:
            from tensorflow_train_distributed_tpu.native.staging import (
                NativeBatchStager, native_batch_iterator, pack_for_staging,
            )

            if NativeBatchStager.available():
                if getattr(self.source, "epoch_aware", False):
                    import warnings

                    warnings.warn(
                        "use_native packs transformed records ONCE, so a "
                        "per-epoch augmentation transform is frozen at its "
                        "epoch-0 crops; use the in-process or data-service "
                        "path for fresh-per-epoch augmentation",
                        stacklevel=2)
                if self._native_packed is None:
                    # Pack once per loader: re-created iterators (periodic
                    # eval, preemption restart) reuse the flattened matrix
                    # instead of re-copying the dataset every time.
                    self._native_packed = pack_for_staging(self.source)
                it = native_batch_iterator(
                    self.source, self._epoch_orders(), self.host_batch_size,
                    num_threads=self.config.native_threads,
                    packed=self._native_packed,
                )
                if self.config.drop_remainder:
                    yield from it
                else:
                    spe = self.steps_per_epoch()
                    for i, batch in enumerate(it):
                        yield self._with_sample_weight(batch, i % spe)
                return
            # No toolchain/library: fall through to the Python path.
        for epoch, order in enumerate(self._epoch_orders()):
            for b in range(len(order) // self.host_batch_size):
                idx = order[b * self.host_batch_size : (b + 1) * self.host_batch_size]
                records = [fetch_record(self.source, int(i), epoch)
                           for i in idx]
                batch = {
                    k: np.stack([r[k] for r in records])
                    for k in records[0]
                }
                if not self.config.drop_remainder:
                    batch = self._with_sample_weight(batch, b)
                yield batch

    def _shard_len(self) -> int:
        """This process's record count for one epoch (pre-padding)."""
        if self.config.shard_policy == "file":
            return len(self._file_shards[self.process_index])
        n, p = len(self.source), self.process_index
        return (n - p + self.process_count - 1) // self.process_count

    def steps_per_epoch(self) -> int:
        """Identical on every process (SPMD deadlock otherwise): derived
        from globally-known sizes, never this process's shard length.
        drop_remainder=True floors to whole batches over the SMALLEST
        shard; False ceils over the LARGEST (shorter shards pad)."""
        if self.config.shard_policy == "file":
            sizes = [len(s) for s in self._file_shards]
            per_host = (min(sizes) if self.config.drop_remainder
                        else max(sizes))
        else:
            n = len(self.source)
            per_host = (n // self.process_count
                        if self.config.drop_remainder
                        else (n + self.process_count - 1)
                        // self.process_count)
        if self.config.drop_remainder:
            return per_host // self.host_batch_size
        return ((per_host + self.host_batch_size - 1)
                // self.host_batch_size)

    def as_device_iterator(self, mesh: Mesh) -> Iterator[Any]:
        """Prefetched device iterator using ``config.prefetch`` buffers."""
        return prefetch_to_device(iter(self), mesh, size=self.config.prefetch)


def prefetch_to_device(
    batches: Iterator[dict[str, np.ndarray]],
    mesh: Mesh,
    *,
    size: int = 2,
    spec: Optional[PartitionSpec] = None,
) -> Iterator[Any]:
    """Async host→device prefetch of globally-sharded batches.

    A background thread stages up to ``size`` batches on device (via
    ``shard_batch``: NamedSharding over the mesh's DP axes, or ``spec`` if
    given — e.g. ``P(None, ("data",))`` when dim 0 is a steps_per_execution
    scan axis) while compute consumes them — the tf.data
    ``prefetch_to_device`` analog, hiding host→HBM transfer behind the step.
    """
    if size < 1:
        raise ValueError("prefetch size must be >= 1")
    q: queue_lib.Queue = queue_lib.Queue(maxsize=size)
    _END = object()
    err: list[BaseException] = []
    stop = threading.Event()

    def _producer():
        try:
            for batch in batches:
                staged = shard_batch(mesh, batch, spec=spec)
                while not stop.is_set():
                    try:
                        q.put(staged, timeout=0.1)
                        break
                    except queue_lib.Full:
                        continue
                if stop.is_set():
                    return
        except BaseException as e:  # propagate into consumer
            err.append(e)
        finally:
            # Deliver _END even when the queue is momentarily full, but give
            # up if the consumer has already stopped (nobody will drain).
            while not stop.is_set():
                try:
                    q.put(_END, timeout=0.1)
                    break
                except queue_lib.Full:
                    continue

    t = threading.Thread(target=_producer, daemon=True, name="ttd-prefetch")
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                if err:
                    raise err[0]
                return
            yield item
    finally:
        # Consumer stopped early (break / exception / GeneratorExit): release
        # the producer and drop staged batches so HBM isn't pinned.
        stop.set()
        while True:
            try:
                q.get_nowait()
            except queue_lib.Empty:
                break
        t.join(timeout=5)
