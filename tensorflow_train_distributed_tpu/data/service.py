"""Out-of-process input workers — the tf.data service rebuilt host-side.

Reference surface (SURVEY.md §2.2 "tf.data service", §3.5): a dispatcher
plus out-of-process workers (``data/experimental/service/server_lib.py:
131,349``) feeding trainers over gRPC via ``.distribute(...)``
(``data_service_ops.py:578``) — moving input-pipeline CPU off the training
process.  Here the same shape: ``DataServiceDispatcher`` spawns N worker
processes, each producing one autoshard slice of the global batch
(``HostDataLoader`` with ``process_index=w``); ``DataServiceClient``
streams slices over local TCP and concatenates them into full global
batches for the trainer.  Transport is a length-prefixed JSON-header +
raw-buffer frame (no pickle on the wire).

When to use: heavy host-side record work (decode/augment) that would
otherwise steal cycles from the training process's dispatch thread.  The
in-process ``HostDataLoader`` (optionally with the native C++ stager)
remains the default.
"""

from __future__ import annotations

import dataclasses
import json
import multiprocessing as mp
import socket
import struct
import threading
from typing import Iterator, Optional

import numpy as np

from tensorflow_train_distributed_tpu.data.pipeline import DataConfig

_LEN = struct.Struct("<Q")


def _send_frame(sock: socket.socket, header: dict, payload: bytes = b""):
    hdr = json.dumps(header).encode()
    sock.sendall(_LEN.pack(len(hdr)) + hdr + _LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("input worker closed the connection")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> tuple[dict, bytes]:
    hdr_len = _LEN.unpack(_recv_exact(sock, _LEN.size))[0]
    header = json.loads(_recv_exact(sock, hdr_len))
    pay_len = _LEN.unpack(_recv_exact(sock, _LEN.size))[0]
    return header, _recv_exact(sock, pay_len) if pay_len else b""


def _encode_batch(batch: dict[str, np.ndarray]) -> tuple[dict, bytes]:
    fields, chunks, offset = [], [], 0
    for name in sorted(batch):
        arr = np.ascontiguousarray(batch[name])
        fields.append({"name": name, "dtype": arr.dtype.str,
                       "shape": arr.shape, "offset": offset,
                       "nbytes": arr.nbytes})
        chunks.append(arr.tobytes())
        offset += arr.nbytes
    return {"kind": "batch", "fields": fields}, b"".join(chunks)


def _decode_batch(header: dict, payload: bytes) -> dict[str, np.ndarray]:
    out = {}
    for f in header["fields"]:
        raw = payload[f["offset"]:f["offset"] + f["nbytes"]]
        out[f["name"]] = np.frombuffer(raw, dtype=np.dtype(f["dtype"])) \
            .reshape(f["shape"]).copy()
    return out


@dataclasses.dataclass(frozen=True)
class SourceSpec:
    """Picklable description of a dataset (registry name + kwargs)."""

    dataset: str
    kwargs: dict = dataclasses.field(default_factory=dict)

    def build(self):
        from tensorflow_train_distributed_tpu.data.datasets import get_dataset

        return get_dataset(self.dataset, **self.kwargs)


def _worker_main(spec: SourceSpec, config: DataConfig, shard_index: int,
                 shard_count: int, port_queue):
    """Worker process: serve this shard's batches over a local socket."""
    from tensorflow_train_distributed_tpu.data.pipeline import HostDataLoader

    loader = HostDataLoader(spec.build(), config,
                            process_index=shard_index,
                            process_count=shard_count)
    server = socket.socket()
    server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    server.bind(("127.0.0.1", 0))
    server.listen(1)
    port_queue.put(server.getsockname()[1])
    conn, _ = server.accept()
    it = iter(loader)
    try:
        while True:
            header, _ = _recv_frame(conn)
            cmd = header.get("cmd")
            if cmd == "NEXT":
                try:
                    batch = next(it)
                except StopIteration:
                    _send_frame(conn, {"kind": "end"})
                    continue
                _send_frame(conn, *_encode_batch(batch))
            elif cmd == "STOP":
                _send_frame(conn, {"kind": "bye"})
                return
            else:
                _send_frame(conn, {"kind": "error",
                                   "message": f"unknown cmd {cmd!r}"})
    except (ConnectionError, BrokenPipeError):
        pass
    finally:
        conn.close()
        server.close()


class DataServiceDispatcher:
    """Owns this host's worker fleet; hands out a connected client.

    ``num_workers`` workers each produce
    ``global_batch/(host_count*num_workers)`` examples per step (the
    per-worker rebatch rule, ``batch_sizes_for_worker``); the client
    reassembles this HOST's share (``global_batch/host_count`` rows), so
    the trainer sees exactly the per-process loader contract.

    Multi-host (the reference's tf.data service over a worker cluster):
    every host runs its own dispatcher with its ``host_index``; worker w
    of host h autoshard-slices the corpus as process h·W+w of H·W.  The
    union over all hosts' workers covers each epoch exactly once and
    every host draws the same number of batches — the SPMD contract —
    though the record→host assignment differs from the in-process
    loader's h-of-H striding (same property the reference's
    ``distribute`` has: sharding granularity follows the worker fleet).
    """

    def __init__(self, spec: SourceSpec, config: DataConfig,
                 num_workers: int = 2, *, host_index: int = 0,
                 host_count: int = 1):
        shards = host_count * num_workers
        if config.global_batch_size % shards:
            raise ValueError(
                f"global_batch_size={config.global_batch_size} not "
                f"divisible by host_count*num_workers={shards}")
        if not 0 <= host_index < host_count:
            raise ValueError(
                f"host_index={host_index} outside [0, {host_count})")
        self.spec = spec
        self.config = config
        self.num_workers = num_workers
        self.host_index = host_index
        self.host_count = host_count
        self._procs: list[mp.process.BaseProcess] = []
        self.ports: list[int] = []

    def start(self) -> "DataServiceDispatcher":
        import queue as queue_lib

        ctx = mp.get_context("spawn")  # never fork a live XLA runtime
        queues = [ctx.Queue() for _ in range(self.num_workers)]
        for w in range(self.num_workers):
            p = ctx.Process(
                target=_worker_main,
                args=(self.spec, self.config,
                      self.host_index * self.num_workers + w,
                      self.host_count * self.num_workers,
                      queues[w]),
                daemon=True,
            )
            p.start()
            self._procs.append(p)
        self.ports = []
        for w, (q, p) in enumerate(zip(queues, self._procs)):
            # Poll liveness while waiting: a worker that crashes in
            # source build/loader init would otherwise stall the full
            # timeout and surface as a bare queue.Empty.
            deadline = 60.0
            while True:
                try:
                    self.ports.append(q.get(timeout=0.5))
                    break
                except queue_lib.Empty:
                    deadline -= 0.5
                    if not p.is_alive():
                        rc = p.exitcode
                        self.stop()
                        raise RuntimeError(
                            f"input worker {w} died during startup "
                            f"(exit code {rc}) — bad SourceSpec/DataConfig?"
                        ) from None
                    if deadline <= 0:
                        self.stop()
                        raise TimeoutError(
                            f"input worker {w} did not report a port")
        return self

    def client(self) -> "DataServiceClient":
        return DataServiceClient(self.ports)

    def stop(self) -> None:
        for p in self._procs:
            if p.is_alive():
                p.terminate()
            p.join(timeout=10)
        self._procs.clear()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class DataServiceClient:
    """Iterates this host's batch share assembled from its workers'
    slices (the full global batch on a single-host cluster)."""

    def __init__(self, ports: list[int], host: str = "127.0.0.1"):
        self._socks = []
        self._consumed = False
        for port in ports:
            s = socket.create_connection((host, port), timeout=60)
            # The 60s budget is for connect only; batch production may
            # legitimately take longer (heavy decode/augment), so reads
            # block without a deadline.
            s.settimeout(None)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._socks.append(s)

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        # Single-use: the STOP/close in the finally block tears down the
        # worker connections, so a second pass cannot be served.
        if self._consumed:
            raise RuntimeError(
                "DataServiceClient is single-use (its sockets close when "
                "iteration ends); call .client() on the service for a "
                "fresh iterator")
        self._consumed = True
        try:
            while True:
                shards = []
                # Request all workers first, then read all replies — the
                # workers assemble their slices concurrently.
                for s in self._socks:
                    _send_frame(s, {"cmd": "NEXT"})
                ended = False
                for s in self._socks:
                    header, payload = _recv_frame(s)
                    if header["kind"] == "end":
                        ended = True
                    elif header["kind"] == "batch":
                        shards.append(_decode_batch(header, payload))
                    else:
                        raise RuntimeError(
                            f"input worker error: {header}")
                if ended:
                    return
                yield {
                    k: np.concatenate([sh[k] for sh in shards])
                    for k in shards[0]
                }
        finally:
            self.close()

    def close(self) -> None:
        for s in self._socks:
            try:
                _send_frame(s, {"cmd": "STOP"})
                s.close()
            except OSError:
                pass
        self._socks = []
