"""Host-side image decode + augmentation (the reference's tf.image stage).

The reference's ImageNet config is fed by tf.data pipelines that decode
JPEG on the host CPU and apply random-resized-crop + horizontal flip for
training, resize-short-side + center-crop for evaluation (SURVEY §2.1
"tf.data input pipelines", §3.5) — the standard ImageNet recipe.  This
module is that stage for the rebuild: pure per-record numpy/PIL
functions registered under ``filesource.TRANSFORMS`` string names, so
they run wherever records are read — in-process loaders, the native
stager's producer, or the out-of-process data-service workers (the name
travels in the picklable ``SourceSpec``; the CPU cost lands on the
workers, exactly where the reference puts it).

Determinism: the augmentation rng is seeded from ``(crc32(encoded
bytes), epoch)``, so a given record augments identically on every
worker and restart within an epoch but draws a FRESH crop/flip each
epoch — the reference's per-epoch tf.data augmentation diversity with
reproducibility by construction (tf.data's stateful rng has neither
property without careful seeding).  The epoch arrives with each fetch
via ``filesource.fetch_record`` (``HostDataLoader`` threads it through
the ``filesource``/``tfrecord`` sources; epoch-unaware callers get the
epoch-0 crop).

Record schema: the reference's ImageNet TFRecords carry
``image/encoded`` (JPEG bytes) and ``image/class/label``; bare
``jpeg`` + ``label`` names are accepted too, so hand-rolled corpora
need no renaming.  (``image`` is NOT an accepted bytes key — elsewhere
in the package it denotes a decoded pixel array.)
"""

from __future__ import annotations

import io
import re
import zlib
from functools import partial

import numpy as np

# ImageNet channel statistics (the torchvision/MLPerf convention).
MEAN_RGB = np.asarray([0.485, 0.456, 0.406], np.float32)
STDDEV_RGB = np.asarray([0.229, 0.224, 0.225], np.float32)

# NOT "image": elsewhere in the package that key is a DECODED pixel
# array (u8_image_to_f32's convention) — treating it as encoded bytes
# would fail deep inside PIL instead of with a schema error here.
_ENCODED_KEYS = ("image/encoded", "jpeg")
_LABEL_KEYS = ("image/class/label", "label")


def _encoded_bytes(rec: dict) -> bytes:
    for k in _ENCODED_KEYS:
        v = rec.get(k)
        if v is None:
            continue
        if isinstance(v, (list, tuple)):  # raw TFRecord bytes_list
            v = v[0]
        if isinstance(v, np.ndarray):
            v = v.tobytes()
        if isinstance(v, (bytes, bytearray)):
            return bytes(v)
    raise KeyError(
        f"record has no encoded image under any of {_ENCODED_KEYS} "
        f"(keys: {sorted(rec)})")


def _label(rec: dict) -> np.int32:
    for k in _LABEL_KEYS:
        v = rec.get(k)
        if v is not None:
            return np.int32(np.asarray(v).ravel()[0])
    raise KeyError(
        f"record has no label under any of {_LABEL_KEYS} "
        f"(keys: {sorted(rec)})")


def _pil_image():
    try:
        from PIL import Image
    except ImportError as e:
        raise ImportError(
            "Pillow is required for JPEG decode / ImageNet augmentation "
            "(the imagenet_* transforms); install the optional extra: "
            "pip install 'tensorflow_train_distributed_tpu[image]'"
        ) from e
    return Image


_NATIVE_JPEG_OK: "bool | None" = None


def _native_jpeg_parity_ok() -> bool:
    """One-time self-check: the system libjpeg the native decoder links
    must produce the SAME pixels as PIL's bundled one on a
    chroma-subsampled probe, or the crc32-seeded augmentation contract
    ("identical on every worker and restart") would silently break on
    fleets with heterogeneous libjpeg variants — mismatch falls back to
    PIL everywhere."""
    global _NATIVE_JPEG_OK
    if _NATIVE_JPEG_OK is None:
        try:
            from tensorflow_train_distributed_tpu.native import (
                jpeg as njpeg,
            )

            Image = _pil_image()
            y, x = np.mgrid[0:48, 0:64]
            probe = np.stack(
                [y * 5 % 256, x * 3 % 256, (y + x) * 7 % 256],
                axis=-1).astype(np.uint8)
            buf = io.BytesIO()
            Image.fromarray(probe).save(buf, "JPEG", quality=85)
            data = buf.getvalue()
            with Image.open(io.BytesIO(data)) as im:
                pil = np.asarray(im.convert("RGB"), np.uint8)
            _NATIVE_JPEG_OK = np.array_equal(njpeg.decode_rgb(data), pil)
        except Exception:
            _NATIVE_JPEG_OK = False
    return _NATIVE_JPEG_OK


def decode_image(data: bytes) -> np.ndarray:
    """Encoded image bytes (JPEG/PNG/...) → uint8 [H, W, 3] RGB.

    JPEGs take the native libjpeg path when built AND bit-identical to
    PIL on a runtime probe (``_native_jpeg_parity_ok`` — both stacks are
    libjpeg underneath, but heterogeneous fleets could link different
    variants); PNG/exotic color spaces/missing toolchain fall back to
    PIL.  Batch consumers wanting GIL-free threaded decode use
    ``native.jpeg.decode_batch`` directly.
    """
    if data[:2] == b"\xff\xd8":  # JPEG SOI marker
        from tensorflow_train_distributed_tpu.native import jpeg as njpeg

        if njpeg.available() and _native_jpeg_parity_ok():
            try:
                return njpeg.decode_rgb(data)
            except ValueError:
                pass  # CMYK/YCCK or corrupt: let PIL decide
    Image = _pil_image()

    with Image.open(io.BytesIO(data)) as im:
        return np.asarray(im.convert("RGB"), np.uint8)


def _normalize(img_u8: np.ndarray) -> np.ndarray:
    return ((img_u8.astype(np.float32) / 255.0) - MEAN_RGB) / STDDEV_RGB


def random_resized_crop(img: np.ndarray, size: int,
                        rng: np.random.Generator,
                        *, area_range=(0.08, 1.0),
                        ratio_range=(3 / 4, 4 / 3),
                        attempts: int = 10) -> np.ndarray:
    """Inception-style crop: sample area+aspect, fall back to center."""
    Image = _pil_image()

    h, w = img.shape[:2]
    area = h * w
    for _ in range(attempts):
        target = area * rng.uniform(*area_range)
        log_ratio = np.log(ratio_range)
        ratio = np.exp(rng.uniform(*log_ratio))
        cw = int(round(np.sqrt(target * ratio)))
        ch = int(round(np.sqrt(target / ratio)))
        if 0 < cw <= w and 0 < ch <= h:
            top = int(rng.integers(0, h - ch + 1))
            left = int(rng.integers(0, w - cw + 1))
            crop = img[top:top + ch, left:left + cw]
            return np.asarray(
                Image.fromarray(crop).resize((size, size),
                                             Image.BILINEAR), np.uint8)
    return center_crop(img, size)


def center_crop(img: np.ndarray, size: int,
                *, crop_padding: int = 32) -> np.ndarray:
    """Resize-short-side then central crop (the eval convention)."""
    Image = _pil_image()

    h, w = img.shape[:2]
    scale = (size + crop_padding) / min(h, w)
    nh, nw = max(size, int(round(h * scale))), max(size,
                                                   int(round(w * scale)))
    resized = np.asarray(
        Image.fromarray(img).resize((nw, nh), Image.BILINEAR), np.uint8)
    top = (nh - size) // 2
    left = (nw - size) // 2
    return resized[top:top + size, left:left + size]


def _train_crop_u8(data: bytes, size: int, epoch: int) -> np.ndarray:
    """Shared decode/crop/flip core: JPEG bytes → augmented uint8 crop."""
    rng = np.random.default_rng(
        np.random.SeedSequence([zlib.crc32(data), int(epoch)]))
    img = random_resized_crop(decode_image(data), size, rng)
    if rng.random() < 0.5:
        img = img[:, ::-1]
    return img


def imagenet_train_record(rec: dict, *, size: int = 224,
                          epoch: int = 0) -> dict:
    """JPEG record → augmented training record (decode/crop/flip/norm).

    ``epoch`` folds into the rng seed so every epoch draws a fresh
    crop/flip (reference tf.data semantics) while staying deterministic
    across workers and restarts; sources pass it per fetch
    (``filesource.fetch_record`` / ``transform_is_epoch_aware``).
    """
    data = _encoded_bytes(rec)
    return {"image": np.ascontiguousarray(
                _normalize(_train_crop_u8(data, size, epoch))),
            "label": _label(rec)}


def imagenet_train_record_u8(rec: dict, *, size: int = 224,
                             epoch: int = 0) -> dict:
    """Like ``imagenet_train_record`` but ships RAW uint8 pixels —
    normalization happens ON DEVICE (``models.resnet`` normalizes uint8
    inputs with the ImageNet constants; XLA fuses it into the stem
    conv).  4x less host→device transfer and no host-side f32 math —
    the TPU-first layout for input-bound hosts (tools/bench_input.py
    measures the delta)."""
    data = _encoded_bytes(rec)
    return {"image": np.ascontiguousarray(_train_crop_u8(
                data, size, epoch)),
            "label": _label(rec)}


def imagenet_eval_record(rec: dict, *, size: int = 224) -> dict:
    """JPEG record → deterministic eval record (decode/center-crop/norm)."""
    img = center_crop(decode_image(_encoded_bytes(rec)), size)
    return {"image": _normalize(img), "label": _label(rec)}


def imagenet_eval_record_u8(rec: dict, *, size: int = 224) -> dict:
    """Uint8 twin of ``imagenet_eval_record`` (device-side normalize)."""
    img = center_crop(decode_image(_encoded_bytes(rec)), size)
    return {"image": np.ascontiguousarray(img), "label": _label(rec)}


_NAME_RE = re.compile(r"imagenet_(train|eval)(_u8)?_(\d+)$")


def ensure_registered(name: str) -> None:
    """Register ``imagenet_(train|eval)[_u8]_{SIZE}`` for ANY size on
    demand — the size is encoded in the name, so no fixed list gates
    resolutions (``_u8`` ships raw pixels for device-side normalize)."""
    m = _NAME_RE.fullmatch(name)
    if m is None:
        return
    from tensorflow_train_distributed_tpu.data.filesource import TRANSFORMS

    if m.group(2):  # _u8
        fn = (imagenet_train_record_u8 if m.group(1) == "train"
              else imagenet_eval_record_u8)
    else:
        fn = (imagenet_train_record if m.group(1) == "train"
              else imagenet_eval_record)
    TRANSFORMS.setdefault(name, partial(fn, size=int(m.group(3))))


def register_transforms() -> None:
    """Pre-install the common names into ``filesource.TRANSFORMS`` (other
    sizes resolve on demand via ``ensure_registered``)."""
    for size in (224, 32):
        ensure_registered(f"imagenet_train_{size}")
        ensure_registered(f"imagenet_eval_{size}")


register_transforms()
