"""TFRecord + tf.train.Example interop — read the reference's corpora.

The reference's input pipelines read TFRecord files of ``tf.train.Example``
protos (the tf.data convention its builders assume, SURVEY.md §2.1/§3.5).
A reference user migrating here brings that data; this module reads and
writes it with **zero TensorFlow/protobuf dependency** — the framing
(length + masked crc32c) and the three-message Example schema are small
enough to implement directly:

- ``TFRecordWriter`` / ``read_records``: the on-wire framing
  (`uint64 length | crc(length) | payload | crc(payload)`, crc32c masked
  with the TF rotation constant).
- ``encode_example`` / ``decode_example``: hand-rolled proto codec for
  ``Example { Features { map<string, Feature> } }`` with
  BytesList/FloatList/Int64List (packed and unpacked accepted).
- ``TFRecordSource``: a ``RandomAccessSource`` over one or more ``.tfrecord``
  files — builds an offset index in one sequential pass (TFRecord itself is
  stream-oriented; the index restores the random access the SPMD input
  pipeline needs), then serves ``{field: np.ndarray}`` records through a
  ``FixedLenFeature``-style spec.

Sequential-proto decode is NOT the hot path (that is the mmap format in
``data.filesource``); ``convert_to_shards`` does the one-time migration.
"""

from __future__ import annotations

import gzip
import io
import json
import logging
import struct
from pathlib import Path
from typing import Optional, Sequence, Union

import numpy as np

from tensorflow_train_distributed_tpu.data.filesource import (
    TransformedRecordMixin,
    read_with_retries,
)
from tensorflow_train_distributed_tpu.runtime import faults

logger = logging.getLogger(__name__)

# id1+id2+deflate method: 3 bytes, not 2 — a plain TFRecord whose first
# record is exactly 0x8B1F bytes long starts with 1f 8b too, but its third
# byte is a length byte, not 0x08.
_GZIP_MAGIC = b"\x1f\x8b\x08"


def _is_gzip(path: Union[str, Path]) -> bool:
    """Sniff the gzip magic — TF writes ``.gz`` TFRecords as one gzip
    stream over the whole file (TFRecordOptions GZIP), and extension
    conventions vary, so content beats suffix."""
    with open(path, "rb") as f:
        return f.read(3) == _GZIP_MAGIC

# --- crc32c (Castagnoli), table-driven, with TF's masking -------------------

_CRC_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ 0x82F63B78 if _c & 1 else _c >> 1
    _CRC_TABLE.append(_c)


def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ _CRC_TABLE[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# --- varint / proto primitives ----------------------------------------------


def _write_varint(out: bytearray, value: int) -> None:
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def _tag(field: int, wire: int) -> int:
    return (field << 3) | wire


def _write_len_delimited(out: bytearray, field: int, payload: bytes) -> None:
    _write_varint(out, _tag(field, 2))
    _write_varint(out, len(payload))
    out.extend(payload)


def _skip_field(buf: bytes, pos: int, wire: int) -> int:
    if wire == 0:
        _, pos = _read_varint(buf, pos)
        return pos
    if wire == 1:
        return pos + 8
    if wire == 2:
        n, pos = _read_varint(buf, pos)
        return pos + n
    if wire == 5:
        return pos + 4
    raise ValueError(f"unsupported wire type {wire}")


# --- tf.train.Example codec -------------------------------------------------


def encode_example(features: dict[str, np.ndarray]) -> bytes:
    """Encode ``{name: array}`` as a serialized ``tf.train.Example``.

    dtype mapping (the tf.train convention): floating → FloatList (f32),
    integer/bool → Int64List, bytes/str objects → BytesList.
    """
    feats = bytearray()
    for name in sorted(features):
        arr = features[name]
        body = bytearray()
        if isinstance(arr, (bytes, str)):
            values = [arr.encode() if isinstance(arr, str) else arr]
            inner = bytearray()
            for v in values:
                _write_len_delimited(inner, 1, v)
            _write_len_delimited(body, 1, bytes(inner))  # bytes_list
        else:
            arr = np.asarray(arr)
            if np.issubdtype(arr.dtype, np.floating):
                packed = np.ascontiguousarray(
                    arr.reshape(-1), np.float32).tobytes()
                inner = bytearray()
                _write_len_delimited(inner, 1, packed)  # packed floats
                _write_len_delimited(body, 2, bytes(inner))  # float_list
            elif (np.issubdtype(arr.dtype, np.integer)
                  or arr.dtype == np.bool_):
                inner = bytearray()
                packed = bytearray()
                for v in arr.reshape(-1).astype(np.int64).tolist():
                    _write_varint(packed, v & 0xFFFFFFFFFFFFFFFF)
                _write_len_delimited(inner, 1, bytes(packed))
                _write_len_delimited(body, 3, bytes(inner))  # int64_list
            else:
                raise TypeError(
                    f"field {name!r}: unsupported dtype {arr.dtype}")
        # map entry: key = field 1 (string), value = field 2 (Feature)
        entry = bytearray()
        _write_len_delimited(entry, 1, name.encode())
        _write_len_delimited(entry, 2, bytes(body))
        _write_len_delimited(feats, 1, bytes(entry))
    example = bytearray()
    _write_len_delimited(example, 1, bytes(feats))  # Example.features
    return bytes(example)


def _decode_float_list(buf: bytes) -> list[float]:
    out: list[float] = []
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if field == 1 and wire == 2:  # packed
            n, pos = _read_varint(buf, pos)
            out.extend(struct.unpack(f"<{n // 4}f", buf[pos:pos + n]))
            pos += n
        elif field == 1 and wire == 5:  # unpacked
            out.append(struct.unpack("<f", buf[pos:pos + 4])[0])
            pos += 4
        else:
            pos = _skip_field(buf, pos, wire)
    return out


def _decode_int64_list(buf: bytes) -> list[int]:
    out: list[int] = []
    pos = 0

    def _signed(v: int) -> int:
        return v - (1 << 64) if v >= (1 << 63) else v

    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if field == 1 and wire == 2:  # packed
            n, pos = _read_varint(buf, pos)
            end = pos + n
            while pos < end:
                v, pos = _read_varint(buf, pos)
                out.append(_signed(v))
        elif field == 1 and wire == 0:  # unpacked
            v, pos = _read_varint(buf, pos)
            out.append(_signed(v))
        else:
            pos = _skip_field(buf, pos, wire)
    return out


def _decode_bytes_list(buf: bytes) -> list[bytes]:
    out: list[bytes] = []
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if field == 1 and wire == 2:
            n, pos = _read_varint(buf, pos)
            out.append(buf[pos:pos + n])
            pos += n
        else:
            pos = _skip_field(buf, pos, wire)
    return out


def _decode_feature(buf: bytes):
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 2 and field in (1, 2, 3):
            n, pos = _read_varint(buf, pos)
            payload = buf[pos:pos + n]
            pos += n
            if field == 1:
                return _decode_bytes_list(payload)
            if field == 2:
                return np.asarray(_decode_float_list(payload), np.float32)
            return np.asarray(_decode_int64_list(payload), np.int64)
        pos = _skip_field(buf, pos, wire)
    return np.asarray([], np.float32)  # empty Feature


def decode_example(data: bytes) -> dict[str, object]:
    """Serialized ``tf.train.Example`` → ``{name: ndarray | [bytes]}``
    (flat values; apply shapes via ``TFRecordSource``'s feature spec)."""
    out: dict[str, object] = {}
    pos = 0
    while pos < len(data):
        tag, pos = _read_varint(data, pos)
        field, wire = tag >> 3, tag & 7
        if field == 1 and wire == 2:  # Example.features
            n, pos = _read_varint(data, pos)
            feats = data[pos:pos + n]
            pos += n
            fpos = 0
            while fpos < len(feats):
                ftag, fpos = _read_varint(feats, fpos)
                ffield, fwire = ftag >> 3, ftag & 7
                if ffield == 1 and fwire == 2:  # map entry
                    en, fpos = _read_varint(feats, fpos)
                    entry = feats[fpos:fpos + en]
                    fpos += en
                    key, value = None, None
                    epos = 0
                    while epos < len(entry):
                        etag, epos = _read_varint(entry, epos)
                        efield, ewire = etag >> 3, etag & 7
                        if ewire == 2:
                            vn, epos = _read_varint(entry, epos)
                            payload = entry[epos:epos + vn]
                            epos += vn
                            if efield == 1:
                                key = payload.decode()
                            elif efield == 2:
                                value = _decode_feature(payload)
                        else:
                            epos = _skip_field(entry, epos, ewire)
                    if key is not None:
                        out[key] = value
                else:
                    fpos = _skip_field(feats, fpos, fwire)
        else:
            pos = _skip_field(data, pos, wire)
    return out


# --- record-level IO --------------------------------------------------------


class TFRecordWriter:
    """Write raw records in TFRecord framing (context-manager friendly).

    A ``.gz`` path (or ``compress=True``) streams through gzip — the
    TFRecordOptions GZIP wire format, readable by tf.data with
    ``compression_type="GZIP"`` and by ``TFRecordSource`` here.
    """

    def __init__(self, path: Union[str, Path],
                 compress: Optional[bool] = None):
        if compress is None:
            compress = str(path).endswith(".gz")
        self._f = gzip.open(path, "wb") if compress else open(path, "wb")

    def write(self, record: bytes) -> None:
        header = struct.pack("<Q", len(record))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc(header)))
        self._f.write(record)
        self._f.write(struct.pack("<I", _masked_crc(record)))

    def write_example(self, features: dict[str, np.ndarray]) -> None:
        self.write(encode_example(features))

    def close(self) -> None:
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_records(path: Union[str, Path], *, verify_crc: bool = True,
                 on_corrupt: str = "raise",
                 stats: Optional[dict] = None):
    """Yield raw record payloads from one TFRecord file (gzip-aware).

    ``on_corrupt`` (with ``verify_crc``): ``"raise"`` keeps the
    historical fail-mid-stream behavior; ``"skip"`` drops records whose
    *payload* crc fails (the framing is intact, so the stream resyncs
    cleanly at the next record) and counts them in
    ``stats["skipped_records"]``.  A corrupt *length* crc leaves no
    trustworthy framing to resync on — skip mode abandons the rest of
    the file loudly instead of misparsing garbage as records.
    """
    if on_corrupt not in ("raise", "skip"):
        raise ValueError(
            f"on_corrupt must be 'raise' or 'skip', got {on_corrupt!r}")

    def _skip_tail(what: str) -> bool:
        # Truncation mid-record = crashed-writer tail: in skip mode it
        # is dropped (counted + logged) instead of raised — nothing
        # after it is parseable either way.
        if on_corrupt != "skip":
            return False
        if stats is not None:
            stats["skipped_records"] = stats.get("skipped_records", 0) + 1
        logger.error("%s: %s; dropping the file tail (crashed writer)",
                     path, what)
        return True

    opener = gzip.open if _is_gzip(path) else open
    with opener(path, "rb") as f:
        while True:
            header = f.read(8)
            if not header:
                return
            if len(header) != 8:
                if _skip_tail("truncated length header"):
                    return
                raise ValueError(f"{path}: truncated length header")
            (length,) = struct.unpack("<Q", header)
            crc_bytes = f.read(4)
            if len(crc_bytes) != 4:
                if _skip_tail("truncated length crc"):
                    return
                raise ValueError(f"{path}: truncated length crc")
            (len_crc,) = struct.unpack("<I", crc_bytes)
            if verify_crc and _masked_crc(header) != len_crc:
                if on_corrupt == "skip":
                    if stats is not None:
                        stats["skipped_records"] = (
                            stats.get("skipped_records", 0) + 1)
                    logger.error(
                        "%s: corrupt length crc — framing lost, "
                        "abandoning the rest of the file", path)
                    return
                raise ValueError(f"{path}: corrupt length crc")
            payload = f.read(length)
            if len(payload) != length:
                if _skip_tail("truncated record"):
                    return
                raise ValueError(f"{path}: truncated record")
            crc_bytes = f.read(4)
            if len(crc_bytes) != 4:
                if _skip_tail("truncated record crc"):
                    return
                raise ValueError(f"{path}: truncated record crc")
            (crc,) = struct.unpack("<I", crc_bytes)
            if verify_crc and _masked_crc(payload) != crc:
                if on_corrupt == "skip":
                    if stats is not None:
                        stats["skipped_records"] = (
                            stats.get("skipped_records", 0) + 1)
                    continue
                raise ValueError(f"{path}: corrupt record crc")
            yield payload


def _index_stream(f, size: int, name: str, *, on_corrupt: str = "raise",
                  stats: Optional[dict] = None) -> list[tuple[int, int]]:
    """One sequential pass → [(payload_offset, payload_length)].

    Bounds-checks every record against the stream size so a file
    truncated mid-record (crashed writer) fails loudly at open time, not
    as an opaque decode error mid-training.

    ``on_corrupt="skip"`` additionally verifies both crcs (reading every
    payload — the price of screening) and LEAVES OUT corrupt records,
    counting them in ``stats["skipped_records"]``: training then never
    meets them mid-epoch.  The default ``"raise"`` pass stays seek-only
    (no payload reads, no crc cost).
    """
    index = []
    pos = 0
    while True:
        header = f.read(8)
        if not header:
            return index
        if len(header) != 8:
            if on_corrupt == "skip":
                if stats is not None:
                    stats["skipped_records"] = (
                        stats.get("skipped_records", 0) + 1)
                logger.error(
                    "%s: truncated length header at offset %d; dropping "
                    "it (crashed writer tail)", name, pos)
                return index
            raise ValueError(f"{name}: truncated length header")
        (length,) = struct.unpack("<Q", header)
        end = pos + 12 + length + 4
        if end > size:
            if on_corrupt == "skip":
                if stats is not None:
                    stats["skipped_records"] = (
                        stats.get("skipped_records", 0) + 1)
                logger.error(
                    "%s: truncated record at offset %d; dropping it "
                    "(crashed writer tail)", name, pos)
                return index
            raise ValueError(
                f"{name}: truncated record at offset {pos} "
                f"(needs {end} bytes, stream has {size})")
        if on_corrupt == "skip":
            (len_crc,) = struct.unpack("<I", f.read(4))
            payload = f.read(length)
            (crc,) = struct.unpack("<I", f.read(4))
            if (_masked_crc(header) != len_crc
                    or _masked_crc(payload) != crc):
                if stats is not None:
                    stats["skipped_records"] = (
                        stats.get("skipped_records", 0) + 1)
                if _masked_crc(header) != len_crc:
                    # Framing itself is untrustworthy: the next "record"
                    # boundary came from a corrupt length. Stop here
                    # rather than index garbage offsets.
                    logger.error(
                        "%s: corrupt length crc at offset %d — framing "
                        "lost, abandoning the rest of the file",
                        name, pos)
                    return index
                pos = end
                continue
        index.append((pos + 12, length))
        pos = end
        f.seek(pos)


def _index_file(path: Union[str, Path], *, on_corrupt: str = "raise",
                stats: Optional[dict] = None) -> list[tuple[int, int]]:
    size = Path(path).stat().st_size
    with open(path, "rb") as f:
        return _index_stream(f, size, str(path), on_corrupt=on_corrupt,
                             stats=stats)


class TFRecordSource:
    """Random access over TFRecord file(s) of ``tf.train.Example`` protos.

    ``features``: FixedLenFeature-style spec ``{name: (shape, dtype)}`` —
    flat Example values are reshaped/cast per field.  ``None`` returns the
    raw decoded dict (flat arrays / byte lists).  Multiple paths act as
    one concatenated dataset whose file boundaries are the FILE-autoshard
    units (wrap in ``pipeline.ConcatSource`` semantics via ``as_parts``).
    """

    def __init__(self, paths: Union[str, Path, Sequence[Union[str, Path]]],
                 features: Optional[dict[str, tuple]] = None,
                 max_gz_cached: int = 4, on_corrupt: str = "raise"):
        if isinstance(paths, (str, Path)):
            paths = [paths]
        if on_corrupt not in ("raise", "skip"):
            raise ValueError(
                f"on_corrupt must be 'raise' or 'skip', got {on_corrupt!r}")
        self.paths = [Path(p) for p in paths]
        if not self.paths:
            raise ValueError("TFRecordSource needs at least one path")
        self.features = features
        self.on_corrupt = on_corrupt
        # Pipeline-stats surface (``stats()``): corrupt-crc records the
        # "skip" policy screened out at open — loud, countable, and
        # never met mid-epoch.
        self._stats = {"skipped_records": 0}
        self._index: list[tuple[int, int, int]] = []  # (file, offset, len)
        self._file_counts: list[int] = []
        # Gzip TFRecords are one stream (no per-record seek): serve random
        # access from a decompressed in-memory copy, LRU-bounded like the
        # fd cache below — a 100-shard gzip corpus must not pin the whole
        # decompressed corpus in RAM.  Re-decompression on miss is the
        # cold-path price; the mmap format is the hot path for anything
        # throughput-critical (module docstring).
        self._gz_files: set[int] = set()
        self._gz_cache: dict[int, bytes] = {}
        self._max_gz_cached = max(1, int(max_gz_cached))
        self._gz_decompressed: set[int] = set()  # shards decompressed once
        self._warned_gz_thrash = False
        for fi, p in enumerate(self.paths):
            if _is_gzip(p):
                self._gz_files.add(fi)
                data = self._gz_bytes(fi)
                entries = _index_stream(io.BytesIO(data), len(data),
                                        str(p), on_corrupt=on_corrupt,
                                        stats=self._stats)
            else:
                entries = _index_file(p, on_corrupt=on_corrupt,
                                      stats=self._stats)
            self._file_counts.append(len(entries))
            for off, length in entries:
                self._index.append((fi, off, length))
        if self._stats["skipped_records"]:
            logger.warning(
                "TFRecordSource: skipped %d corrupt record(s) across %d "
                "file(s) (on_corrupt='skip'); stats() has the count",
                self._stats["skipped_records"], len(self.paths))
        # Indexing above decompressed every gzip shard once — that's
        # construction cost, not read-pattern thrash.  Reads start fresh.
        self._gz_decompressed.clear()
        # LRU-bounded handle cache: big corpora (1000s of shard files)
        # must not exhaust the process fd limit.
        self._handles: "dict[int, object]" = {}
        self._max_handles = 64

    def __len__(self) -> int:
        return len(self._index)

    def _gz_bytes(self, fi: int) -> bytes:
        data = self._gz_cache.pop(fi, None)
        if data is None:
            if fi in self._gz_decompressed and not self._warned_gz_thrash:
                # Evicted-then-refetched: the access pattern (e.g. global
                # shuffle over many gzip shards) is thrashing the cache —
                # each miss re-decompresses a whole shard.  Warn once; a
                # strictly sequential pass never hits this.
                self._warned_gz_thrash = True
                import warnings

                warnings.warn(
                    f"re-decompressing gzip shard "
                    f"{self.paths[fi].name}: {len(self._gz_files)} gzip "
                    f"shards exceed the {self._max_gz_cached}-shard "
                    f"decompressed cache (max_gz_cached) under a "
                    f"non-sequential access pattern — raise max_gz_cached "
                    f"or convert to the uncompressed/mmap format for "
                    f"shuffled throughput-critical reads",
                    stacklevel=3)
            self._gz_decompressed.add(fi)
            if len(self._gz_cache) >= self._max_gz_cached:
                self._gz_cache.pop(next(iter(self._gz_cache)))  # LRU out
            with gzip.open(self.paths[fi], "rb") as f:
                data = f.read()
        self._gz_cache[fi] = data  # re-insert → most recently used
        return data

    def _handle(self, fi: int):
        if fi in self._gz_files:  # in-memory; no fd to manage
            return io.BytesIO(self._gz_bytes(fi))
        f = self._handles.pop(fi, None)
        if f is None:
            if len(self._handles) >= self._max_handles:
                lru = next(iter(self._handles))  # least recently used
                self._handles.pop(lru).close()
            f = open(self.paths[fi], "rb")
        self._handles[fi] = f  # re-insert → most recently used
        return f

    def stats(self) -> dict:
        """Pipeline stats: record counts + corrupt records screened out
        by ``on_corrupt='skip'`` (0 under the default policy, which
        raises instead)."""
        return {"records": len(self._index), "files": len(self.paths),
                "skipped_records": self._stats["skipped_records"]}

    def __getitem__(self, idx: int) -> dict[str, np.ndarray]:
        if idx < 0 or idx >= len(self._index):
            raise IndexError(idx)
        fi, off, length = self._index[idx]

        def _read():
            if faults.ARMED:
                faults.on_data_read(idx)
            f = self._handle(fi)
            f.seek(off)
            return f.read(length)

        raw = read_with_retries(
            _read, f"{self.paths[fi]} record {idx}")
        try:
            rec = decode_example(raw)
        except (ValueError, IndexError) as e:
            raise ValueError(
                f"{self.paths[fi]}: record {idx} failed to decode "
                f"({e}) — corrupt payload; re-open with "
                "on_corrupt='skip' to screen such records out") from e
        if self.features is None:
            return rec
        out = {}
        for name, (shape, dtype) in self.features.items():
            if name not in rec:
                raise KeyError(
                    f"record {idx} missing feature {name!r}; has "
                    f"{sorted(rec)}")
            out[name] = np.asarray(rec[name]).reshape(shape).astype(dtype)
        return out

    def as_parts(self):
        """Per-file views for FILE autoshard (``ConcatSource(parts)``).

        Views, not new sources: all parts share this source's index and
        LRU-bounded handle cache, so a 5000-file corpus still holds at
        most ``_max_handles`` fds process-wide.
        """
        parts, start = [], 0
        for count in self._file_counts:
            parts.append(_SourceSlice(self, start, count))
            start += count
        return parts


class _SourceSlice:
    """Contiguous view into a ``RandomAccessSource`` (one file's records)."""

    def __init__(self, source, start: int, count: int):
        self.source, self.start, self.count = source, start, count

    def __len__(self) -> int:
        return self.count

    def __getitem__(self, idx: int) -> dict[str, np.ndarray]:
        if idx < 0 or idx >= self.count:
            raise IndexError(idx)
        return self.source[self.start + idx]


FEATURES_SIDECAR = "features.json"

_DTYPES = {"float32": np.float32, "float64": np.float64,
           "int32": np.int32, "int64": np.int64, "uint8": np.uint8,
           "bool": np.bool_}


def write_features_sidecar(root: Union[str, Path],
                           features: Optional[dict[str, tuple]]) -> Path:
    """Persist a feature spec as ``features.json`` next to the tfrecords,
    so directory-level opens (CLI ``--data-dir``) need no Python spec.

    ``features=None`` writes the RAW marker: records decode as the
    Example's raw flat arrays/byte lists with no fixed-shape spec — the
    variable-shape case (JPEG corpora, varlen token docs), where a
    per-record ``transform`` produces the fixed-shape training record.
    """
    root = Path(root)
    out = root / FEATURES_SIDECAR
    if features is None:
        out.write_text(json.dumps({"raw": True}))
        return out
    spec = {name: {"shape": list(shape), "dtype": np.dtype(dtype).name}
            for name, (shape, dtype) in features.items()}
    out.write_text(json.dumps({"features": spec}))
    return out


def read_features_sidecar(root: Union[str, Path]
                          ) -> Optional[dict[str, tuple]]:
    """Feature spec from ``features.json``; None for the RAW marker."""
    spec = json.loads((Path(root) / FEATURES_SIDECAR).read_text())
    if spec.get("raw"):
        return None
    out = {}
    for name, f in spec["features"].items():
        dtype = f["dtype"]
        if dtype not in _DTYPES:
            raise ValueError(
                f"{FEATURES_SIDECAR}: feature {name!r} has unsupported "
                f"dtype {dtype!r}; supported: {sorted(_DTYPES)}")
        out[name] = (tuple(f["shape"]), _DTYPES[dtype])
    return out


def open_tfrecord_dir(root: Union[str, Path],
                      features: Optional[dict[str, tuple]] = None,
                      transform=None, on_corrupt: str = "raise"):
    """Open a directory of ``*.tfrecord``(.gz) files as a ``ConcatSource``.

    Each file is one FILE-autoshard part (``DataConfig(shard_policy=
    "file")`` hands whole files to processes — the reference's FILE policy
    unit, SURVEY.md §3.5).  The feature spec comes from ``features`` or a
    ``features.json`` sidecar; ``transform`` is a callable or a
    ``filesource.TRANSFORMS`` name applied per record.
    """
    from tensorflow_train_distributed_tpu.data.filesource import (
        resolve_transform,
    )
    from tensorflow_train_distributed_tpu.data.pipeline import ConcatSource

    root = Path(root)
    paths = sorted([*root.glob("*.tfrecord"), *root.glob("*.tfrecord.gz")])
    if not paths:
        raise FileNotFoundError(
            f"no *.tfrecord / *.tfrecord.gz files under {root}")
    if features is None:
        if not (root / FEATURES_SIDECAR).is_file():
            raise FileNotFoundError(
                f"{root} has no {FEATURES_SIDECAR}; pass features= or "
                "write one with write_features_sidecar()")
        features = read_features_sidecar(root)
    transform = resolve_transform(transform)
    if features is None and transform is None:
        # RAW records are variable-shape (byte lists, varlen arrays) —
        # batching would np.stack them into garbage or crash downstream.
        # Fail at open with the actionable fix instead.
        raise ValueError(
            f"{root} is a RAW corpus (features.json marks no fixed "
            "schema) — a per-record transform must produce the "
            "fixed-shape training record; pass --data-transform (e.g. "
            "imagenet_train_224) or open with transform=")
    # ONE source over all files (shared index + LRU handle cache), exposed
    # as per-file views so FILE autoshard still hands whole files out —
    # per-file sources would each cache fds and defeat the LRU bound.
    source = TFRecordSource(paths, features, on_corrupt=on_corrupt)
    parts = source.as_parts()
    if transform is not None:
        parts = [_TransformedSource(p, transform) for p in parts]
    return ConcatSource(parts)


class _TransformedSource(TransformedRecordMixin):
    """Apply a record transform over any ``RandomAccessSource``."""

    def __init__(self, source, transform):
        self.source = source
        self._init_transform(transform)

    def __len__(self) -> int:
        return len(self.source)

    def _raw(self, idx: int) -> dict[str, np.ndarray]:
        return self.source[idx]


def convert_to_shards(tfrecord_paths, out_root, features,
                      num_shards: int):
    """One-time migration: TFRecord corpus → the mmap hot-path format."""
    from tensorflow_train_distributed_tpu.data.filesource import write_shards

    src = TFRecordSource(tfrecord_paths, features)
    return write_shards(out_root, src, num_shards)
