"""Input pipeline: per-host sharding, batching, host→device prefetch.

TPU-native replacement for the reference's distributed-input stack:
``strategy.experimental_distribute_dataset`` → ``DistributedDataset``
autoshard/rebatch (``tensorflow/python/distribute/input_lib.py:729``,
``data/ops/options.py:89``, ``data/experimental/ops/distribute.py:219``) and
the tf.data C++ runtime.  Here the pipeline is host-side Python/numpy over
random-access sources, sharded per process, with double-buffered transfer to
device — the "host-side prefetch-to-device" the reference's north star
prescribes.
"""

from tensorflow_train_distributed_tpu.data.pipeline import (  # noqa: F401
    ConcatSource,
    MixtureSource,
    DataConfig,
    HostDataLoader,
    prefetch_to_device,
)
from tensorflow_train_distributed_tpu.data.datasets import (  # noqa: F401
    SliceSource,
    SyntheticBlobs,
    SyntheticImageNet,
    SyntheticLM,
    SyntheticMLM,
    SyntheticMNIST,
    SyntheticWMT,
    get_dataset,
    train_val_split,
)
from tensorflow_train_distributed_tpu.data.filesource import (  # noqa: F401
    MmapArraySource,
    open_sharded,
    write_shards,
)
from tensorflow_train_distributed_tpu.data.tfrecord import (  # noqa: F401
    TFRecordSource,
    TFRecordWriter,
    open_tfrecord_dir,
)
