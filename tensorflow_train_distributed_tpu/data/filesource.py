"""On-disk record ingestion: memory-mapped columnar shards.

The reference's input layer reads real corpora through tf.data file
formats (TFRecord readers behind ``tf.data`` builders, SURVEY.md §2.1 /
§3.5); its FILE autoshard policy (``data/ops/options.py:89``) hands whole
files to workers.  The TPU-native equivalent here is a *columnar
memory-mapped* layout rather than a sequential proto stream:

- a corpus is a directory of ``part-NNNNN/`` shard dirs — the FILE
  autoshard unit, loaded as a ``ConcatSource``;
- each shard dir holds one ``<field>.npy`` per record field plus a
  ``manifest.json``; fields are ``np.load(..., mmap_mode="r")``'d, so
  random access is an O(1) page-fault read with zero deserialization —
  exactly what the native batch stager and host→device prefetch want
  (record bytes flow mmap page → packed batch → HBM, no proto decode on
  the hot path).

Records of one shard are fixed-shape (the SPMD static-shape contract the
pipeline already enforces); variable-length data is padded at corpus-write
time, the same trade tf.data's ``padded_batch`` makes per step but paid
once.
"""

from __future__ import annotations

import inspect
import json
import logging
import time
from pathlib import Path
from typing import Callable, Optional, Union

import numpy as np

from tensorflow_train_distributed_tpu.data.pipeline import (
    ConcatSource,
    fetch_record,  # noqa: F401  (re-export: the record-fetch protocol)
)
from tensorflow_train_distributed_tpu.runtime import faults

logger = logging.getLogger(__name__)

MANIFEST = "manifest.json"

# Bounded retry for transient record-read IO (flaky NFS/GCS-fuse mounts,
# injected faults): N attempts with doubling backoff, then the error
# propagates — a *persistently* failing disk must kill the job loudly,
# not spin forever feeding the trainer nothing.
IO_RETRY_ATTEMPTS = 3
IO_RETRY_BACKOFF_S = 0.05


def read_with_retries(fn: Callable[[], dict], what: str,
                      *, attempts: int = None, backoff_s: float = None,
                      sleep=time.sleep) -> dict:
    """Run a record-read thunk with bounded retry on ``OSError``.

    Only ``OSError`` (the transient-IO family, including
    ``faults.InjectedTransientIO``) retries; decode/shape errors are
    data corruption, not weather, and propagate immediately.
    """
    attempts = IO_RETRY_ATTEMPTS if attempts is None else attempts
    backoff_s = IO_RETRY_BACKOFF_S if backoff_s is None else backoff_s
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    for attempt in range(attempts):
        try:
            return fn()
        except OSError as e:
            if attempt + 1 >= attempts:
                raise
            delay = backoff_s * 2 ** attempt
            logger.warning(
                "transient IO reading %s (%s); retry %d/%d in %.2fs",
                what, e, attempt + 1, attempts - 1, delay)
            sleep(delay)

# Named record transforms, so configs/CLI can reference them as strings
# (e.g. storage-efficient uint8 images decoded to the model's f32 input).
TRANSFORMS: dict[str, Callable[[dict], dict]] = {
    "u8_image_to_f32": lambda rec: {
        **rec, "image": np.asarray(rec["image"], np.float32) / 255.0,
    },
}


def resolve_transform(
    transform: Union[Callable[[dict], dict], str, None],
) -> Optional[Callable[[dict], dict]]:
    """Resolve a ``TRANSFORMS`` name (or pass a callable/None through)."""
    if isinstance(transform, str):
        if transform not in TRANSFORMS:
            # Image decode/augment names register on demand — lazy, so
            # loading a text corpus never imports PIL, and any
            # imagenet_(train|eval)_{SIZE} resolves without a fixed list.
            from tensorflow_train_distributed_tpu.data import image

            image.ensure_registered(transform)
        if transform not in TRANSFORMS:
            raise ValueError(
                f"Unknown transform {transform!r}; available: "
                f"{sorted(TRANSFORMS)}")
        return TRANSFORMS[transform]
    return transform


def transform_is_epoch_aware(fn) -> bool:
    """Does ``fn`` accept an ``epoch`` keyword (fresh-per-epoch
    augmentation, e.g. ``image.imagenet_train_record``)?  Sources call
    epoch-aware transforms as ``fn(rec, epoch=e)`` with the epoch the
    loader passes to ``get_record``; everything else keeps the 1-arg
    call."""
    if fn is None:
        return False
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    p = sig.parameters.get("epoch")
    return p is not None and p.kind in (
        inspect.Parameter.KEYWORD_ONLY,
        inspect.Parameter.POSITIONAL_OR_KEYWORD)


class TransformedRecordMixin:
    """Leaf-source helper: raw record + optional (epoch-aware) transform.

    Subclasses implement ``_raw(idx)`` and call ``_init_transform`` once;
    the mixin provides the ``get_record``/``__getitem__`` pair with the
    epoch threaded into transforms that accept it."""

    def _init_transform(self, transform) -> None:
        self.transform = resolve_transform(transform)
        self.epoch_aware = transform_is_epoch_aware(self.transform)

    def get_record(self, idx: int, epoch: int = 0) -> dict:
        rec = self._raw(idx)
        if self.transform is None:
            return rec
        if self.epoch_aware:
            return self.transform(rec, epoch=epoch)
        return self.transform(rec)

    def __getitem__(self, idx: int) -> dict:
        return self.get_record(idx, 0)


class MmapArraySource(TransformedRecordMixin):
    """One shard dir of ``.npy`` columns, memory-mapped; random access.

    ``transform`` (callable or ``TRANSFORMS`` name) maps the raw stored
    record to the training record — storage dtype and model dtype need
    not match.
    """

    def __init__(self, path: Union[str, Path],
                 transform: Union[Callable[[dict], dict], str, None] = None):
        self.path = Path(path)
        manifest_path = self.path / MANIFEST
        if not manifest_path.is_file():
            raise FileNotFoundError(
                f"{self.path} is not a record shard (no {MANIFEST})")
        manifest = json.loads(manifest_path.read_text())
        self.columns: dict[str, np.ndarray] = {}
        n = int(manifest["num_records"])
        for name in manifest["fields"]:
            arr = np.load(self.path / f"{name}.npy", mmap_mode="r")
            if arr.shape[0] != n:
                raise ValueError(
                    f"{self.path}/{name}.npy has {arr.shape[0]} records, "
                    f"manifest says {n}")
            self.columns[name] = arr
        self._n = n
        self._init_transform(transform)

    def __len__(self) -> int:
        return self._n

    def _raw(self, idx: int) -> dict[str, np.ndarray]:
        if idx < 0 or idx >= self._n:
            raise IndexError(idx)

        def _read():
            if faults.ARMED:
                faults.on_data_read(idx)
            # np.asarray materializes the mmap'd row — the page-fault
            # read that a flaky mount turns into an OSError.
            return {k: np.asarray(v[idx]) for k, v in self.columns.items()}

        return read_with_retries(_read, f"{self.path} record {idx}")


def write_shards(root: Union[str, Path], source, num_shards: int) -> Path:
    """Write a ``RandomAccessSource`` as ``part-NNNNN/`` mmap shard dirs.

    Contiguous record ranges per shard (shard boundaries = file boundaries,
    the FILE-autoshard unit).  Storage dtype is whatever the source yields
    — pre-quantize (e.g. images to uint8) before writing and decode with a
    ``transform`` at read time.
    """
    root = Path(root)
    n = len(source)
    if num_shards < 1 or n < num_shards:
        raise ValueError(f"cannot write {n} records as {num_shards} shards")
    root.mkdir(parents=True, exist_ok=True)
    written = set()
    # Balanced split (sizes differ by at most 1) — a ceil-based split can
    # leave trailing shards empty.
    for s, idx in enumerate(np.array_split(np.arange(n), num_shards)):
        records = [source[int(i)] for i in idx]
        part = root / f"part-{s:05d}"
        part.mkdir(exist_ok=True)
        written.add(part.name)
        fields = sorted(records[0])
        for name in fields:
            np.save(part / f"{name}.npy",
                    np.stack([r[name] for r in records]))
        (part / MANIFEST).write_text(json.dumps(
            {"num_records": len(records), "fields": fields}))
    # Rewriting with fewer shards must not leave stale parts behind —
    # open_sharded globs part-* and would silently concatenate them.
    for stale in root.glob("part-*"):
        if stale.is_dir() and stale.name not in written:
            for f in stale.iterdir():
                f.unlink()
            stale.rmdir()
    return root


def open_sharded(root: Union[str, Path],
                 transform: Union[Callable[[dict], dict], str, None] = None,
                 ) -> ConcatSource:
    """Open a ``write_shards`` corpus as a ``ConcatSource`` of mmap parts.

    Use with ``DataConfig(shard_policy="file")`` for whole-file-per-worker
    autoshard, or the default DATA policy for index-stride sharding.
    """
    root = Path(root)
    parts = sorted(p for p in root.glob("part-*") if p.is_dir())
    if not parts:
        raise FileNotFoundError(f"no part-* shard dirs under {root}")
    return ConcatSource([MmapArraySource(p, transform) for p in parts])
